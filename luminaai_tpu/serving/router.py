"""Resilient multi-replica serving plane: the data-plane router.

One ChatServer process caps out at `num_slots` concurrent decode lanes;
a fleet of them is only a serving plane if individual replica loss is
invisible to clients. This router is that tier — a thin HTTP data plane
fronting N ChatServer replicas, where robustness is the contract:

  - **Replica registry + active health probing.** `probe_all()` polls
    each replica's `/healthz` (and `/slo`, best-effort) on an injectable
    clock. Warming and draining replicas receive no new admissions, but
    their in-flight streams drain cleanly — the router never severs a
    stream it already joined. A refused/failed probe marks the replica
    down and trips its breaker immediately: probes are cheap and a dead
    TCP endpoint is unambiguous, so the breaker opens within one probe
    interval of a SIGKILL.
  - **Per-replica circuit breaker.** closed → open on a consecutive-
    failure or error-rate threshold → half-open single probe after the
    cooldown → closed on success. Transitions are booked as flight
    events (`breaker_open` / `breaker_half_open` / `breaker_close`) and
    mirrored in the `router_breaker_state{replica}` gauge (0 closed,
    1 half-open, 2 open).
  - **Prefix-hash-affine dispatch.** Requests rendezvous-hash on the
    prompt prefix so shared prompts land where their radix-cache pages
    already live; when the affine target is open/draining/shedding the
    request falls back to the least-loaded live replica.
  - **Bounded failover.** Idempotent non-stream requests retry on the
    next candidate with backoff+jitter (delays from utils/retry.py's
    RetryPolicy, sleep injectable). Streams that die pre-first-token
    fail over transparently; streams that die mid-generation surface an
    SSE error frame carrying the original `request_id` — re-dispatching
    would silently replay tokens the client already consumed.
  - **Shed as a routing signal.** A replica 503 with Retry-After puts
    that replica on shed-cooldown and the request moves to the next
    candidate; the client only sees 503 (with the max Retry-After) when
    every candidate is shedding.
  - **Hedged dispatch.** Optionally, short non-stream requests fire a
    second replica after a p95-based hedge delay; first answer wins and
    the loser's connection is cancelled. A hedge budget caps hedges to
    a fixed fraction of non-stream traffic so tail-chasing can never
    double the fleet's load.

Pure host-side Python, stdlib HTTP only (same constraint as server.py):
zero jax imports, zero device executables. The clock, sleep, RNG and
the replica transport are all injectable, so every failure contract
above is pinned in tests/test_router.py with no wall-clock sleeps.

`lumina route` runs this standalone; `lumina serve --replicas N` spawns
a local fleet for dev. docs/serving.md "Replica router" has the
operator story; docs/observability.md tables the `router_*` series and
events.
"""

from __future__ import annotations

import collections
import http.client
import json
import hashlib
import logging
import queue
import random
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from luminaai_tpu.monitoring.events import get_recorder
from luminaai_tpu.monitoring.telemetry import (
    DEFAULT_LATENCY_BUCKETS,
    MetricsRegistry,
)
from luminaai_tpu.serving.server import (
    MAX_BODY_BYTES,
    REQUEST_ID_RX,
    new_request_id,
)
from luminaai_tpu.utils.retry import RetryPolicy

logger = logging.getLogger(__name__)

__all__ = [
    "CircuitBreaker",
    "Replica",
    "Router",
    "HttpTransport",
    "wait_ready",
    "run_router",
]

# Breaker state as exported in router_breaker_state{replica}.
_BREAKER_GAUGE = {"closed": 0, "half_open": 1, "open": 2}

# Transport failures that mean "this replica, this attempt" — not the
# request. Everything here is retryable on the next candidate.
TRANSPORT_ERRORS = (OSError, http.client.HTTPException)


class CircuitBreaker:
    """Per-replica closed → open → half-open → closed state machine.

    Failures are counted two ways: `failures` consecutive failures open
    the breaker, and so does an error-rate >= `error_rate` over the
    last `window` outcomes once `min_requests` of them exist (a replica
    that alternates ok/5xx never trips the consecutive counter but is
    still unusable). After `cooldown_s` an open breaker admits exactly
    one probe request (half-open); its success closes the breaker, its
    failure re-opens it for another cooldown. `trip()` force-opens —
    the probe loop uses it when a replica's TCP endpoint is dead, which
    needs no statistical evidence.

    The clock is injectable; `on_transition(breaker, old, new, reason)`
    books the gauge + flight event without this class knowing about
    either."""

    def __init__(
        self,
        name: str,
        failures: int = 3,
        error_rate: float = 0.5,
        min_requests: int = 8,
        window: int = 16,
        cooldown_s: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
        on_transition: Optional[Callable[..., None]] = None,
    ):
        self.name = name
        self.failures = max(1, int(failures))
        self.error_rate = float(error_rate)
        self.min_requests = max(1, int(min_requests))
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._on_transition = on_transition
        self._lock = threading.Lock()
        self.state = "closed"
        self._consecutive = 0
        self._outcomes: collections.deque = collections.deque(
            maxlen=max(self.min_requests, int(window))
        )
        self._opened_at: Optional[float] = None
        self._probe_started: Optional[float] = None

    def _transition(self, new: str, reason: str) -> None:
        old, self.state = self.state, new
        if new == "open":
            self._opened_at = self._clock()
            self._probe_started = None
        if old != new and self._on_transition is not None:
            self._on_transition(self, old, new, reason)

    def allow(self) -> bool:
        """May a request be sent to this replica right now? Half-open
        admits ONE probe at a time; a probe lost without a verdict
        (caller died) re-arms after another cooldown."""
        with self._lock:
            if self.state == "closed":
                return True
            now = self._clock()
            if self.state == "open":
                if now - (self._opened_at or now) < self.cooldown_s:
                    return False
                self._transition("half_open", "cooldown elapsed")
                self._probe_started = now
                return True
            # half_open: one in-flight probe owns the slot.
            if (
                self._probe_started is not None
                and now - self._probe_started < self.cooldown_s
            ):
                return False
            self._probe_started = now
            return True

    def record_success(self) -> None:
        with self._lock:
            self._consecutive = 0
            self._outcomes.append(1)
            if self.state != "closed":
                self._transition("closed", "probe succeeded")

    def record_failure(self, reason: str = "request failed") -> None:
        with self._lock:
            self._consecutive += 1
            self._outcomes.append(0)
            if self.state == "half_open":
                self._transition("open", f"probe failed: {reason}")
                return
            if self.state != "closed":
                return
            n = len(self._outcomes)
            rate = (n - sum(self._outcomes)) / n if n else 0.0
            if self._consecutive >= self.failures:
                self._transition(
                    "open", f"{self._consecutive} consecutive failures"
                )
            elif n >= self.min_requests and rate >= self.error_rate:
                self._transition("open", f"error rate {rate:.2f}")

    def trip(self, reason: str) -> None:
        """Force-open (dead endpoint seen by the prober): no threshold
        arithmetic, the evidence is total."""
        with self._lock:
            if self.state != "open":
                self._transition("open", reason)
            else:
                self._opened_at = self._clock()  # extend the cooldown


class Replica:
    """One ChatServer as the router sees it: identity, probed health,
    breaker, load, and the shed/latency bookkeeping routing reads."""

    def __init__(self, name: str, url: str, breaker: CircuitBreaker):
        self.name = name
        self.url = url.rstrip("/")
        self.breaker = breaker
        self.status = "unknown"  # ok|degraded|warming|draining|down|unknown
        self.health: Dict[str, Any] = {}
        self.slo: Optional[Dict[str, Any]] = None
        self.inflight = 0
        self.requests = 0
        self.failures = 0
        self.shed_until = 0.0
        self.probe_failures = 0
        self.latencies: collections.deque = collections.deque(maxlen=128)
        self.lock = threading.Lock()

    def p95_s(self) -> Optional[float]:
        if not self.latencies:
            return None
        xs = sorted(self.latencies)
        return xs[min(len(xs) - 1, int(0.95 * len(xs)))]


class _Cancel:
    """Cancellation handle for a hedged attempt: closing the underlying
    connection aborts the loser's blocking read mid-flight."""

    def __init__(self):
        self._lock = threading.Lock()
        self._conn: Optional[http.client.HTTPConnection] = None
        self.cancelled = False

    def attach(self, conn: http.client.HTTPConnection) -> None:
        with self._lock:
            self._conn = conn
            if self.cancelled:
                conn.close()

    def cancel(self) -> None:
        with self._lock:
            self.cancelled = True
            if self._conn is not None:
                try:
                    self._conn.close()
                except OSError:  # pragma: no cover - close is best-effort
                    pass


class HttpTransport:
    """Blocking stdlib HTTP to one replica. The Router only ever talks
    through this seam, so tests swap in an in-memory fake and drive every
    failure mode without sockets."""

    def __init__(self, connect_timeout_s: float = 5.0):
        self.connect_timeout_s = float(connect_timeout_s)

    def _connect(self, base_url: str, timeout_s: Optional[float]):
        u = urllib.parse.urlsplit(base_url)
        return http.client.HTTPConnection(
            u.hostname, u.port or 80,
            timeout=timeout_s or self.connect_timeout_s,
        )

    def request(
        self,
        base_url: str,
        method: str,
        path: str,
        body: Optional[Dict[str, Any]] = None,
        headers: Optional[Dict[str, str]] = None,
        timeout_s: Optional[float] = None,
        cancel: Optional[_Cancel] = None,
    ) -> Tuple[int, Dict[str, str], Dict[str, Any]]:
        """One JSON round-trip: (status, headers, payload). Raises
        TRANSPORT_ERRORS on connect/read failure."""
        conn = self._connect(base_url, timeout_s)
        if cancel is not None:
            cancel.attach(conn)
        try:
            payload = json.dumps(body).encode() if body is not None else None
            conn.request(method, path, body=payload, headers={
                "Content-Type": "application/json", **(headers or {}),
            })
            resp = conn.getresponse()
            raw = resp.read()
            try:
                doc = json.loads(raw) if raw else {}
            except json.JSONDecodeError:
                doc = {"error": raw.decode(errors="replace")[:200]}
            return resp.status, dict(resp.getheaders()), doc
        finally:
            conn.close()

    def stream(
        self,
        base_url: str,
        path: str,
        body: Dict[str, Any],
        headers: Optional[Dict[str, str]] = None,
        timeout_s: Optional[float] = None,
    ):
        """Open an SSE stream. Returns (status, headers, payload, frames):
        on a non-200, frames is None and payload is the error body; on
        200, payload is None and frames yields each `data:` payload
        string (the `[DONE]` sentinel is consumed, not yielded — the
        router's handler writes its own terminator). Closing the frames
        generator closes the connection."""
        conn = self._connect(base_url, timeout_s)
        try:
            conn.request("POST", path, body=json.dumps(body).encode(),
                         headers={"Content-Type": "application/json",
                                  **(headers or {})})
            resp = conn.getresponse()
        except BaseException:
            conn.close()
            raise
        ctype = resp.getheader("Content-Type", "")
        if resp.status != 200 or "text/event-stream" not in ctype:
            try:
                raw = resp.read()
                try:
                    doc = json.loads(raw) if raw else {}
                except json.JSONDecodeError:
                    doc = {"error": raw.decode(errors="replace")[:200]}
                return resp.status, dict(resp.getheaders()), doc, None
            finally:
                conn.close()

        def frames() -> Iterator[str]:
            try:
                while True:
                    line = resp.readline()
                    if not line:
                        # EOF without [DONE]: the replica died mid-frame.
                        raise ConnectionError(
                            "stream ended without [DONE]"
                        )
                    line = line.strip()
                    if not line or not line.startswith(b"data: "):
                        continue
                    data = line[len(b"data: "):].decode(errors="replace")
                    if data == "[DONE]":
                        return
                    yield data
            finally:
                conn.close()

        return resp.status, dict(resp.getheaders()), None, frames()


class Router:
    """Health-aware data-plane router over N ChatServer replicas.

    Everything time-like is injectable (`clock`, `sleep`, `rng`) and all
    replica I/O goes through `transport`, so the failure contracts are
    testable with zero wall-clock cost. `probe_all()` is the prober's
    synchronous core; `start_probing()` wraps it in a background thread
    for real deployments."""

    def __init__(
        self,
        replicas,
        transport: Optional[Any] = None,
        registry: Optional[MetricsRegistry] = None,
        recorder=None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        rng: Optional[random.Random] = None,
        probe_interval_s: float = 2.0,
        probe_timeout_s: float = 2.0,
        breaker_failures: int = 3,
        breaker_error_rate: float = 0.5,
        breaker_min_requests: int = 8,
        breaker_cooldown_s: float = 5.0,
        max_failovers: int = 2,
        failover_base_delay_s: float = 0.05,
        failover_max_delay_s: float = 0.5,
        request_timeout_s: Optional[float] = None,
        hedge: bool = False,
        hedge_delay_s: Optional[float] = None,
        hedge_budget: float = 0.1,
        hedge_max_tokens: int = 32,
        affinity_prefix_chars: int = 256,
        flight_dir: Optional[str] = None,
        page_index_capacity: int = 65536,
    ):
        self.transport = transport or HttpTransport()
        self._clock = clock
        self._sleep = sleep
        self._rng = rng or random.Random()
        self.probe_interval_s = float(probe_interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.max_failovers = max(0, int(max_failovers))
        self.request_timeout_s = request_timeout_s
        self.hedge = bool(hedge)
        self.hedge_delay_s = hedge_delay_s
        self.hedge_budget = float(hedge_budget)
        self.hedge_max_tokens = int(hedge_max_tokens)
        self.affinity_prefix_chars = max(1, int(affinity_prefix_chars))
        self.flight_dir = flight_dir
        self._recorder = recorder
        self._probe_stop: Optional[threading.Event] = None
        self._nonstream_total = 0
        self._hedges_fired = 0
        self._stats_lock = threading.Lock()

        # Failover backoff delays come from the SAME policy durable I/O
        # uses (utils/retry.py): exponential with jitter, injectable
        # sleep. Only delay_for_attempt is used — the attempt loop here
        # owns candidate selection, which .call() can't express.
        self._backoff = RetryPolicy(
            max_attempts=self.max_failovers + 1,
            base_delay_s=failover_base_delay_s,
            max_delay_s=failover_max_delay_s,
            sleep=sleep, clock=clock, rng=self._rng,
            registry=registry or MetricsRegistry(),
        )

        self.registry = registry or MetricsRegistry()
        self._m_requests = self.registry.counter(
            "router_requests_total",
            "Requests dispatched to a replica, by outcome code "
            "('error' = transport failure)",
            labelnames=("replica", "code"),
        )
        self._m_failovers = self.registry.counter(
            "router_failovers_total",
            "Dispatch attempts moved to the next candidate after a "
            "replica failure, by kind (request | stream)",
            labelnames=("kind",),
        )
        self._m_sheds = self.registry.counter(
            "router_sheds_total",
            "Replica 503/Retry-After responses absorbed as a routing "
            "signal (failover, not client-visible)",
            labelnames=("replica",),
        )
        self._m_shed_returned = self.registry.counter(
            "router_shed_returned_total",
            "503s returned to clients because EVERY candidate was "
            "shedding",
        )
        self._m_hedges = self.registry.counter(
            "router_hedges_total",
            "Hedged dispatches fired (second replica engaged)",
        )
        self._m_hedge_wins = self.registry.counter(
            "router_hedge_wins_total",
            "Hedged dispatches won by the hedge replica",
        )
        self._m_breaker_state = self.registry.gauge(
            "router_breaker_state",
            "Per-replica circuit breaker state "
            "(0 closed, 1 half-open, 2 open)",
            labelnames=("replica",),
        )
        self._m_breaker_transitions = self.registry.counter(
            "router_breaker_transitions_total",
            "Breaker state transitions, by replica and target state",
            labelnames=("replica", "to"),
        )
        self._m_stream_errors = self.registry.counter(
            "router_stream_errors_total",
            "Streams that died mid-generation and surfaced an SSE "
            "error frame",
        )
        self._m_latency = self.registry.histogram(
            "router_request_seconds",
            "Router-side latency of successful non-stream dispatches",
            buckets=DEFAULT_LATENCY_BUCKETS,
        )
        self._m_replicas = self.registry.gauge(
            "router_replicas_total", "Registered replicas"
        )
        self._m_available = self.registry.gauge(
            "router_replicas_available",
            "Replicas currently accepting new admissions",
        )

        # Fleet page index (ISSUE 20): chain key -> owning replica URL,
        # fed by replica harvest reports (POST /pages/report) and read
        # by replica cold admissions (POST /pages/lookup). Keys and
        # URLs only — page BYTES move replica-to-replica. FIFO-bounded:
        # a lost entry costs one missed sharing opportunity, and a
        # stale one costs one failed pull that degrades to a local
        # recompute, so the index needs no consistency protocol.
        self.page_index_capacity = max(0, int(page_index_capacity))
        self._page_index: "collections.OrderedDict[str, str]" = (
            collections.OrderedDict()
        )
        self._page_reports: Dict[str, int] = {}
        self._page_lock = threading.Lock()
        self._m_page_index = self.registry.gauge(
            "router_page_index_keys",
            "Chain keys currently in the fleet page index",
        )
        self._m_page_reports = self.registry.counter(
            "router_page_reports_total",
            "Chain-key ownership reports accepted into the fleet index",
        )
        self._m_page_lookups = self.registry.counter(
            "router_page_lookups_total",
            "Fleet page-index lookups, by result",
            labelnames=("result",),
        )

        self.replicas: List[Replica] = []
        for i, rep in enumerate(replicas):
            name, url = (
                rep if isinstance(rep, (tuple, list))
                else (f"r{i}", rep)
            )
            breaker = CircuitBreaker(
                name,
                failures=breaker_failures,
                error_rate=breaker_error_rate,
                min_requests=breaker_min_requests,
                cooldown_s=breaker_cooldown_s,
                clock=clock,
                on_transition=self._book_transition,
            )
            self.replicas.append(Replica(name, url, breaker))
            self._m_breaker_state.labels(replica=name).set(0)
        self._m_replicas.set(len(self.replicas))
        self._m_available.set(len(self.replicas))

    # -- bookkeeping -------------------------------------------------------
    def _emit(self, etype: str, **fields) -> None:
        rec = self._recorder or get_recorder()
        rec.emit(etype, **fields)

    def _book_transition(self, breaker: CircuitBreaker, old: str,
                         new: str, reason: str) -> None:
        self._m_breaker_state.labels(replica=breaker.name).set(
            _BREAKER_GAUGE[new]
        )
        self._m_breaker_transitions.labels(
            replica=breaker.name, to=new
        ).inc()
        event = {
            "open": "breaker_open",
            "half_open": "breaker_half_open",
            "closed": "breaker_close",
        }[new]
        self._emit(event, replica=breaker.name, from_state=old,
                   reason=reason)
        logger.warning("breaker %s: %s -> %s (%s)",
                       breaker.name, old, new, reason)

    # -- health probing ----------------------------------------------------
    def probe_once(self, replica: Replica) -> None:
        """One probe round-trip for one replica: GET /healthz (+ /slo,
        best-effort). Updates status + breaker. Synchronous so tests
        drive it on a fake clock."""
        try:
            status, _, payload = self.transport.request(
                replica.url, "GET", "/healthz",
                timeout_s=self.probe_timeout_s,
            )
        except TRANSPORT_ERRORS as e:
            replica.probe_failures += 1
            prev = replica.status
            replica.status = "down"
            replica.slo = None
            replica.breaker.trip(f"probe failed: {type(e).__name__}")
            if prev != "down":
                self._emit("replica_state", replica=replica.name,
                           from_state=prev, to_state="down",
                           reason=str(e)[:200])
            return
        replica.probe_failures = 0
        new_status = str(payload.get("status") or
                         ("warming" if status == 503 else "ok"))
        prev = replica.status
        replica.status = new_status
        replica.health = payload
        if prev != new_status:
            self._emit("replica_state", replica=replica.name,
                       from_state=prev, to_state=new_status)
        if new_status not in ("warming",) and status == 200:
            # The endpoint answered sanely: let an open breaker walk its
            # half-open → closed recovery on probe traffic, not only on
            # live requests.
            if replica.breaker.state != "closed" and replica.breaker.allow():
                replica.breaker.record_success()
        try:
            s_code, _, s_doc = self.transport.request(
                replica.url, "GET", "/slo",
                timeout_s=self.probe_timeout_s,
            )
            replica.slo = s_doc if s_code == 200 else None
        except TRANSPORT_ERRORS:
            replica.slo = None  # health already booked the failure

    def probe_all(self) -> None:
        for r in self.replicas:
            self.probe_once(r)
        self._m_available.set(
            sum(1 for r in self.replicas if self._skip_reason(r) is None)
        )

    def start_probing(self) -> threading.Thread:
        """Background prober for real deployments (tests call probe_all
        directly on a fake clock instead)."""
        self._probe_stop = threading.Event()

        def loop():
            while not self._probe_stop.wait(self.probe_interval_s):
                try:
                    self.probe_all()
                except Exception:  # pragma: no cover - belt and braces
                    logger.exception("probe round failed")

        t = threading.Thread(target=loop, daemon=True,
                             name="router-prober")
        t.start()
        return t

    def stop_probing(self) -> None:
        if self._probe_stop is not None:
            self._probe_stop.set()

    # -- fleet page index (ISSUE 20) ---------------------------------------
    def _replica_by_url(self, url: str) -> Optional[Replica]:
        url = str(url).rstrip("/")
        for r in self.replicas:
            if r.url.rstrip("/") == url:
                return r
        return None

    def handle_page_report(self, body: Dict[str, Any]) -> Dict[str, Any]:
        """POST /pages/report core: a replica advertises chain keys
        whose page bytes are arena-resident on it. Only registered
        replicas are indexed (an unknown URL could otherwise poison
        every lookup); last reporter wins per key."""
        url = str(body.get("replica", "")).rstrip("/")
        keys = [
            k for k in (body.get("keys") or [])
            if isinstance(k, str) and k
        ]
        if self._replica_by_url(url) is None:
            return {"indexed": 0, "known": False}
        with self._page_lock:
            for key in keys:
                self._page_index[key] = url
                self._page_index.move_to_end(key)
            while len(self._page_index) > self.page_index_capacity:
                self._page_index.popitem(last=False)
            self._page_reports[url] = (
                self._page_reports.get(url, 0) + len(keys)
            )
            self._m_page_index.set(len(self._page_index))
        if keys:
            self._m_page_reports.inc(len(keys))
        return {"indexed": len(keys), "known": True}

    def handle_page_lookup(self, body: Dict[str, Any]) -> Dict[str, Any]:
        """POST /pages/lookup core: given a chain (and how many leading
        pages the asker already holds), name ONE live replica owning a
        contiguous run from position `have`, plus the covered prefix of
        the chain. Owners that are down, draining, breaker-open or the
        asker itself are invisible — a lookup must never send a puller
        at a replica the router would not route a request to."""
        keys = [
            k for k in (body.get("keys") or [])
            if isinstance(k, str) and k
        ]
        exclude = str(body.get("exclude", "")).rstrip("/")
        have = max(0, min(int(body.get("have", 0) or 0), len(keys)))
        if have >= len(keys):
            self._m_page_lookups.labels(result="miss").inc()
            return {"owner": None, "keys": []}
        with self._page_lock:
            owner = self._page_index.get(keys[have])
        rep = self._replica_by_url(owner) if owner else None
        if (
            owner is None
            or owner == exclude
            or rep is None
            or rep.status in ("down", "draining", "warming")
            or rep.breaker.state == "open"
        ):
            self._m_page_lookups.labels(result="miss").inc()
            return {"owner": None, "keys": []}
        matched = list(keys[:have + 1])
        with self._page_lock:
            for key in keys[have + 1:]:
                if self._page_index.get(key) != owner:
                    break
                matched.append(key)
        self._m_page_lookups.labels(result="hit").inc()
        return {"owner": owner, "keys": matched}

    def _page_index_counts(self) -> Dict[str, int]:
        with self._page_lock:
            counts: Dict[str, int] = {}
            for url in self._page_index.values():
                counts[url] = counts.get(url, 0) + 1
            return counts

    # -- candidate selection -----------------------------------------------
    def _affinity_key(self, path: str, body: Dict[str, Any]) -> str:
        """The prompt prefix is the cache identity: requests sharing a
        system prompt / few-shot template hash together, landing where
        the radix cache already holds their pages. The keying rule
        itself lives in serving/page_share.py (single source of truth,
        shared with the cache's chain ownership — ISSUE 20)."""
        from luminaai_tpu.serving.page_share import affinity_key

        return affinity_key(path, body, self.affinity_prefix_chars)

    def _ordered(self, key: str) -> List[Replica]:
        """Affine target first (rendezvous hash: stable under fleet
        membership change), then the rest by ascending load."""
        def score(r: Replica) -> int:
            h = hashlib.sha1(
                (key + "\x00" + r.name).encode()
            ).digest()
            return int.from_bytes(h[:8], "big")

        ordered = sorted(self.replicas, key=score, reverse=True)
        head, rest = ordered[0], ordered[1:]
        rest.sort(key=lambda r: (r.inflight, r.name))
        return [head] + rest

    def _skip_reason(self, r: Replica,
                     now: Optional[float] = None) -> Optional[str]:
        """Why a candidate gets no NEW admissions right now (None = send).
        NOTE: a half-open breaker's allow() consumes the probe slot, so
        only call this when the caller will actually dispatch."""
        now = self._clock() if now is None else now
        if r.status in ("warming", "draining"):
            return r.status
        if now < r.shed_until:
            return "shed"
        if not r.breaker.allow():
            return "open"
        return None

    @staticmethod
    def _retry_after(headers: Dict[str, str],
                     payload: Dict[str, Any]) -> float:
        for source in (payload.get("retry_after"),
                       (headers or {}).get("Retry-After")):
            try:
                if source is not None:
                    return max(0.0, float(source))
            except (TypeError, ValueError):
                pass
        return 1.0  # shed without a hint: brief cooldown beats a hot loop

    def _fwd_headers(self, headers: Optional[Dict[str, str]],
                     request_id: str) -> Dict[str, str]:
        out = {"X-Request-Id": request_id}
        auth = (headers or {}).get("Authorization")
        if auth:
            out["Authorization"] = auth
        return out

    # -- non-stream dispatch -----------------------------------------------
    def _attempt(self, replica: Replica, path: str, body: Dict[str, Any],
                 headers: Optional[Dict[str, str]], request_id: str,
                 cancel: Optional[_Cancel] = None) -> Tuple:
        """One replica, one try. Returns one of
        ("ok", status, payload) — includes 4xx: client errors are the
        client's, retrying them elsewhere can't help;
        ("shed", retry_after_s); ("fail", reason)."""
        t0 = self._clock()
        with replica.lock:
            replica.inflight += 1
        try:
            status, hdrs, payload = self.transport.request(
                replica.url, "POST", path, body,
                headers=self._fwd_headers(headers, request_id),
                timeout_s=self.request_timeout_s, cancel=cancel,
            )
        except TRANSPORT_ERRORS as e:
            with replica.lock:
                replica.failures += 1
            replica.breaker.record_failure(type(e).__name__)
            self._m_requests.labels(replica=replica.name,
                                    code="error").inc()
            return ("fail", f"{type(e).__name__}: {e}")
        finally:
            with replica.lock:
                replica.inflight -= 1
        self._m_requests.labels(replica=replica.name,
                                code=str(status)).inc()
        if status == 503:
            retry_after = self._retry_after(hdrs, payload)
            replica.shed_until = self._clock() + retry_after
            self._m_sheds.labels(replica=replica.name).inc()
            return ("shed", retry_after)
        if status >= 500:
            with replica.lock:
                replica.failures += 1
            replica.breaker.record_failure(f"http {status}")
            return ("fail", f"http {status}")
        replica.breaker.record_success()
        dt = self._clock() - t0
        with replica.lock:
            replica.requests += 1
            replica.latencies.append(dt)
        self._m_latency.observe(dt)
        return ("ok", status, payload)

    def _hedge_delay(self) -> float:
        if self.hedge_delay_s is not None:
            return float(self.hedge_delay_s)
        p95s = [p for p in (r.p95_s() for r in self.replicas)
                if p is not None]
        return max(p95s) if p95s else 0.05

    def _hedge_eligible(self, body: Dict[str, Any]) -> bool:
        if not self.hedge or body.get("stream"):
            return False
        want = body.get("max_new_tokens")
        try:
            if want is not None and int(want) > self.hedge_max_tokens:
                return False
        except (TypeError, ValueError):
            return False
        with self._stats_lock:
            # Budget: hedges may never exceed hedge_budget of non-stream
            # traffic (+1 lets the very first request hedge).
            return (self._hedges_fired + 1) <= self.hedge_budget * (
                self._nonstream_total + 1
            )

    def _hedged(self, primary: Replica, secondary: Replica, path: str,
                body: Dict[str, Any], headers, request_id: str) -> Tuple:
        """Fire primary; if no answer within the hedge delay, fire the
        secondary; first verdict wins and the loser is cancelled. Returns
        an _attempt()-shaped tuple (plus the winner's name for events)."""
        results: "queue.Queue" = queue.Queue()
        cancels = {primary.name: _Cancel(), secondary.name: _Cancel()}

        def run(rep: Replica) -> None:
            out = self._attempt(rep, path, body, headers, request_id,
                                cancel=cancels[rep.name])
            results.put((rep, out))

        threading.Thread(target=run, args=(primary,), daemon=True).start()
        try:
            rep, out = results.get(timeout=max(1e-4, self._hedge_delay()))
            return out  # primary answered inside the delay: no hedge
        except queue.Empty:
            pass
        with self._stats_lock:
            self._hedges_fired += 1
        self._m_hedges.inc()
        self._emit("router_hedge", request_id=request_id,
                   primary=primary.name, hedge=secondary.name)
        threading.Thread(target=run, args=(secondary,),
                         daemon=True).start()
        rep, out = results.get()
        if out[0] != "ok":
            # First verdict was a failure: the slower twin may still win.
            rep, out = results.get()
        for name, c in cancels.items():
            if name != rep.name:
                c.cancel()
        if rep is secondary and out[0] == "ok":
            self._m_hedge_wins.inc()
        return out

    def dispatch(self, path: str, body: Dict[str, Any],
                 headers: Optional[Dict[str, str]] = None) -> Tuple[
                     int, Dict[str, Any]]:
        """Route one non-stream generation POST. Returns (status,
        payload); payload carries request_id (and retry_after on an
        all-shed 503) like ChatServer's contract."""
        request_id = self._inbound_request_id(headers) or new_request_id()
        with self._stats_lock:
            self._nonstream_total += 1
        order = self._ordered(self._affinity_key(path, body))
        attempts = 0
        sheds: List[float] = []
        last_fail: Optional[str] = None
        prev_name: Optional[str] = None
        for replica in order:
            if attempts > self.max_failovers:
                break
            skip = self._skip_reason(replica)
            if skip == "shed":
                sheds.append(replica.shed_until - self._clock())
                continue
            if skip is not None:
                continue
            if attempts > 0:
                self._m_failovers.labels(kind="request").inc()
                self._emit("router_failover", request_id=request_id,
                           from_replica=prev_name, to_replica=replica.name,
                           reason=last_fail or "shed", kind="request")
                self._sleep(self._backoff.delay_for_attempt(attempts))
            attempts += 1
            prev_name = replica.name
            hedge_partner = self._hedge_partner(order, replica)
            if attempts == 1 and hedge_partner is not None and \
                    self._hedge_eligible(body):
                out = self._hedged(replica, hedge_partner, path, body,
                                   headers, request_id)
            else:
                out = self._attempt(replica, path, body, headers,
                                    request_id)
            if out[0] == "ok":
                _, status, payload = out
                if isinstance(payload, dict):
                    payload.setdefault("request_id", request_id)
                return status, payload
            if out[0] == "shed":
                sheds.append(out[1])
                continue
            last_fail = out[1]
        if sheds and last_fail is None:
            retry_after = max(sheds)
            self._m_shed_returned.inc()
            self._emit("router_shed_all", request_id=request_id,
                       retry_after=round(retry_after, 3))
            return 503, {
                "error": "all replicas shedding load; retry shortly",
                "retry_after": max(1, int(round(retry_after))),
                "request_id": request_id,
            }
        self._emit("router_no_replica", request_id=request_id,
                   reason=last_fail or "no admittable replica")
        return 502, {
            "error": "no replica available"
                     + (f" (last: {last_fail})" if last_fail else ""),
            "request_id": request_id,
        }

    def _hedge_partner(self, order: List[Replica],
                       primary: Replica) -> Optional[Replica]:
        if not self.hedge:
            return None
        for r in order:
            if r is primary:
                continue
            # Peek without consuming a half-open probe slot: hedging is
            # opportunistic, never a breaker probe.
            if (r.status not in ("warming", "draining")
                    and r.breaker.state == "closed"
                    and self._clock() >= r.shed_until):
                return r
        return None

    @staticmethod
    def _inbound_request_id(
        headers: Optional[Dict[str, str]]
    ) -> Optional[str]:
        rid = (headers or {}).get("X-Request-Id", "")
        return rid if rid and REQUEST_ID_RX.fullmatch(rid) else None

    # -- stream dispatch ---------------------------------------------------
    def open_stream(self, path: str, body: Dict[str, Any],
                    headers: Optional[Dict[str, str]] = None):
        """Route one SSE generation. Returns (error_tuple | None,
        frame_iterator | None) — ChatServer.start_stream's shape, with
        frames as raw `data:` payload strings ready to forward."""
        request_id = self._inbound_request_id(headers) or new_request_id()
        order = self._ordered(self._affinity_key(path, body))
        state = {"idx": 0, "attempts": 0, "prev": None}
        sheds: List[float] = []
        fails: List[str] = []

        def next_conn():
            """Advance to the next live candidate and open its stream.
            Returns ("ok", replica, frames) | ("client_error", (code,
            payload)) | ("exhausted", None)."""
            while (state["idx"] < len(order)
                   and state["attempts"] <= self.max_failovers):
                replica = order[state["idx"]]
                state["idx"] += 1
                skip = self._skip_reason(replica)
                if skip == "shed":
                    sheds.append(replica.shed_until - self._clock())
                    continue
                if skip is not None:
                    continue
                if state["attempts"] > 0:
                    self._m_failovers.labels(kind="stream").inc()
                    self._emit(
                        "router_failover", request_id=request_id,
                        from_replica=state["prev"],
                        to_replica=replica.name,
                        reason=(fails[-1] if fails else "shed"),
                        kind="stream",
                    )
                    self._sleep(
                        self._backoff.delay_for_attempt(state["attempts"])
                    )
                state["attempts"] += 1
                state["prev"] = replica.name
                try:
                    status, hdrs, payload, frames = self.transport.stream(
                        replica.url, path, body,
                        headers=self._fwd_headers(headers, request_id),
                        timeout_s=self.request_timeout_s,
                    )
                except TRANSPORT_ERRORS as e:
                    with replica.lock:
                        replica.failures += 1
                    replica.breaker.record_failure(type(e).__name__)
                    self._m_requests.labels(replica=replica.name,
                                            code="error").inc()
                    fails.append(f"{type(e).__name__}: {e}")
                    continue
                if status == 503:
                    retry_after = self._retry_after(hdrs, payload)
                    replica.shed_until = self._clock() + retry_after
                    self._m_sheds.labels(replica=replica.name).inc()
                    sheds.append(retry_after)
                    continue
                if status >= 500:
                    with replica.lock:
                        replica.failures += 1
                    replica.breaker.record_failure(f"http {status}")
                    self._m_requests.labels(replica=replica.name,
                                            code=str(status)).inc()
                    fails.append(f"http {status}")
                    continue
                if frames is None:  # 4xx: the client's error, no retry
                    replica.breaker.record_success()
                    if isinstance(payload, dict):
                        payload.setdefault("request_id", request_id)
                    return ("client_error", (status, payload))
                return ("ok", replica, frames)
            return ("exhausted", None)

        first = next_conn()
        if first[0] == "client_error":
            return first[1], None
        if first[0] == "exhausted":
            if sheds and not fails:
                retry_after = max(sheds)
                self._m_shed_returned.inc()
                self._emit("router_shed_all", request_id=request_id,
                           retry_after=round(retry_after, 3))
                return (503, {
                    "error": "all replicas shedding load; retry shortly",
                    "retry_after": max(1, int(round(retry_after))),
                    "request_id": request_id,
                }), None
            return (502, {
                "error": "no replica available"
                         + (f" (last: {fails[-1]})" if fails else ""),
                "request_id": request_id,
            }), None

        def gen():
            _, replica, frames = first
            sent_any = False
            while True:
                try:
                    try:
                        for frame in frames:
                            sent_any = True
                            yield frame
                    finally:
                        close = getattr(frames, "close", None)
                        if close is not None:
                            close()
                    replica.breaker.record_success()
                    with replica.lock:
                        replica.requests += 1
                    self._m_requests.labels(replica=replica.name,
                                            code="200").inc()
                    return
                except TRANSPORT_ERRORS as e:
                    with replica.lock:
                        replica.failures += 1
                    replica.breaker.record_failure(type(e).__name__)
                    self._m_requests.labels(replica=replica.name,
                                            code="error").inc()
                    fails.append(f"{type(e).__name__}: {e}")
                    if sent_any:
                        # Tokens already reached the client: a replay
                        # would duplicate them. Surface the death with
                        # the original id so the client can correlate.
                        self._m_stream_errors.inc()
                        self._emit("router_stream_error",
                                   request_id=request_id,
                                   replica=replica.name,
                                   reason=str(e)[:200])
                        yield json.dumps({
                            "error": "replica failed mid-stream",
                            "replica": replica.name,
                            "request_id": request_id,
                        })
                        return
                    nxt = next_conn()
                    if nxt[0] != "ok":
                        self._m_stream_errors.inc()
                        self._emit("router_stream_error",
                                   request_id=request_id,
                                   replica=replica.name,
                                   reason="no surviving candidate")
                        yield json.dumps({
                            "error": "no replica available",
                            "request_id": request_id,
                        })
                        return
                    _, replica, frames = nxt

        return None, gen()

    # -- fleet / health surfaces -------------------------------------------
    def _replica_out(self, r: Replica) -> bool:
        return (r.breaker.state == "open"
                or r.status in ("down", "warming"))

    def health_payload(self) -> Tuple[int, Dict[str, Any]]:
        """Aggregate /healthz: degraded if ANY breaker is open, down
        (503) only when EVERY replica is out — one dead replica must not
        get the whole plane pulled from rotation."""
        out = sum(1 for r in self.replicas if self._replica_out(r))
        open_breakers = sum(
            1 for r in self.replicas if r.breaker.state != "closed"
        )
        total = len(self.replicas)
        if total and out == total:
            status, code = "down", 503
        elif out or open_breakers:
            status, code = "degraded", 200
        else:
            status, code = "ok", 200
        return code, {
            "status": status,
            "replicas": total,
            "available": total - out,
            "breakers_open": open_breakers,
        }

    def fleet_payload(self) -> Dict[str, Any]:
        """Per-replica verdict table (GET /fleet; rendered by
        `lumina top --url <router>`)."""
        now = self._clock()
        page_counts = self._page_index_counts()
        reps = []
        for r in self.replicas:
            slo_summary = None
            if isinstance(r.slo, dict) and r.slo.get("objectives"):
                slo_summary = {
                    "alerting": list(r.slo.get("alerting") or []),
                    "objectives": {
                        name: v.get("state")
                        for name, v in r.slo["objectives"].items()
                    },
                }
            p95 = r.p95_s()
            reps.append({
                "replica": r.name,
                "url": r.url,
                "status": r.status,
                "breaker": r.breaker.state,
                "inflight": r.inflight,
                "requests": r.requests,
                "failures": r.failures,
                "shed_for_s": round(max(0.0, r.shed_until - now), 3),
                "p95_s": round(p95, 4) if p95 is not None else None,
                "slo": slo_summary,
                # Shared-index view: chain keys the fleet index credits
                # to this replica + how many it has ever reported.
                "shared_pages": page_counts.get(r.url.rstrip("/"), 0),
                "page_reports": self._page_reports.get(
                    r.url.rstrip("/"), 0
                ),
            })
        code, health = self.health_payload()
        return {**health, "http_status": code, "replicas": reps}

    # -- HTTP surface ------------------------------------------------------
    def make_handler(self):
        router = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                logger.info("%s %s", self.address_string(), fmt % args)

            def _reply(self, code: int, payload: Dict[str, Any]) -> None:
                data = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                if isinstance(payload, dict):
                    if "retry_after" in payload:
                        self.send_header(
                            "Retry-After",
                            str(int(payload["retry_after"])),
                        )
                    if payload.get("request_id"):
                        self.send_header("X-Request-Id",
                                         str(payload["request_id"]))
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _headers(self) -> Dict[str, str]:
                out = {}
                for key in ("Authorization", "X-Request-Id"):
                    v = self.headers.get(key)
                    if v:
                        out[key] = v
                return out

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/healthz":
                    self._reply(*router.health_payload())
                    return
                if path == "/fleet":
                    self._reply(200, router.fleet_payload())
                    return
                if path == "/metrics":
                    data = router.registry.render_prometheus().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                    return
                self._reply(404, {"error": f"no route GET {path}"})

            def do_POST(self):
                path = self.path.split("?", 1)[0]
                if path not in ("/v1/generate", "/v1/chat",
                                "/pages/report", "/pages/lookup"):
                    self._reply(404, {"error": f"no route POST {path}"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    if n > MAX_BODY_BYTES:
                        self._reply(413, {"error": "body too large"})
                        return
                    body = json.loads(self.rfile.read(n) or b"{}")
                    if not isinstance(body, dict):
                        raise ValueError("body must be a JSON object")
                except (ValueError, json.JSONDecodeError) as e:
                    self._reply(400, {"error": f"bad request: {e}"})
                    return
                if path == "/pages/report":
                    self._reply(200, router.handle_page_report(body))
                    return
                if path == "/pages/lookup":
                    self._reply(200, router.handle_page_lookup(body))
                    return
                headers = self._headers()
                try:
                    if body.get("stream"):
                        err, frames = router.open_stream(
                            path, body, headers
                        )
                        if err is not None:
                            self._reply(*err)
                        else:
                            self._reply_sse(frames)
                        return
                    code, payload = router.dispatch(path, body, headers)
                except Exception as e:  # surface as 502, keep routing
                    logger.exception("router dispatch failed")
                    code, payload = 502, {"error": str(e)}
                self._reply(code, payload)

            def _reply_sse(self, frames) -> None:
                try:
                    self.send_response(200)
                    self.send_header("Content-Type", "text/event-stream")
                    self.send_header("Cache-Control", "no-cache")
                    self.send_header("Connection", "close")
                    self.end_headers()
                    for frame in frames:
                        self.wfile.write(
                            b"data: " + frame.encode() + b"\n\n"
                        )
                        self.wfile.flush()
                    self.wfile.write(b"data: [DONE]\n\n")
                except (BrokenPipeError, ConnectionResetError):
                    logger.info("stream client disconnected")
                    frames.close()  # stop the upstream pull too
                except Exception as e:
                    logger.exception("router stream failed")
                    try:
                        self.wfile.write(
                            b"data: "
                            + json.dumps({"error": str(e)}).encode()
                            + b"\n\ndata: [DONE]\n\n"
                        )
                    except OSError:
                        pass
                    frames.close()

        return Handler

    def serve_forever(self, host: str = "127.0.0.1",
                      port: int = 8000) -> None:
        httpd = ThreadingHTTPServer((host, port), self.make_handler())

        def _graceful(sig, frame):  # pragma: no cover - signal-driven
            logger.warning("signal %s: router shutting down", sig)
            threading.Thread(target=httpd.shutdown, daemon=True).start()

        import signal as _signal

        try:
            _signal.signal(_signal.SIGTERM, _graceful)
            _signal.signal(_signal.SIGINT, _graceful)
        except ValueError:  # pragma: no cover - non-main thread (tests)
            pass
        logger.info("routing on http://%s:%d over %d replica(s)",
                    host, port, len(self.replicas))
        try:
            httpd.serve_forever()
        finally:
            httpd.server_close()
            self.stop_probing()
            if self.flight_dir:
                rec = self._recorder or get_recorder()
                try:
                    rec.dump_to_dir(self.flight_dir, reason="router_exit")
                except OSError:  # pragma: no cover - dump best-effort
                    logger.exception("flight dump failed")


def wait_ready(urls: List[str], timeout_s: float = 120.0,
               poll_s: float = 0.25) -> None:
    """Block until every url answers /healthz with 200 (replica warmed).
    Raises TimeoutError naming the stragglers."""
    transport = HttpTransport()
    deadline = time.monotonic() + timeout_s
    pending = list(urls)
    while pending:
        still = []
        for url in pending:
            try:
                status, _, _ = transport.request(
                    url, "GET", "/healthz", timeout_s=2.0
                )
                if status != 200:
                    still.append(url)
            except TRANSPORT_ERRORS:
                still.append(url)
        pending = still
        if pending:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"replicas never became ready: {pending}"
                )
            time.sleep(poll_s)


def run_router(replica_urls: List[str], host: str = "127.0.0.1",
               port: int = 8000, probing: bool = True,
               **kwargs) -> None:
    """CLI `lumina route` entry: build, probe once so /fleet is warm
    before the first request, then serve."""
    router = Router(replica_urls, **kwargs)
    router.probe_all()
    if probing:
        router.start_probing()
    router.serve_forever(host, port)
