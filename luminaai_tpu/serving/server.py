"""HTTP serving for chat/completion — stdlib only.

The reference deploys its chat model behind an HTTP backend
(ref: Dockerfile.backend — Flask server on :5001 with a /health check,
docker-compose.dev.yml wiring; the Electron desktop app in package.json
talks to it). This is that surface, TPU-side: a ThreadingHTTPServer wrapping
GenerationEngine. Generation requests ride CONTINUOUS BATCHING: a
ContinuousScheduler owns a step-wise decode loop over a slot-paged KV
pool (engine.make_stepwise), admitting queued requests into slots freed
by finished ones at every token step — no lane ever idles behind a
slower request, and mixed max_new_tokens workloads share one decode
executable. Engines without the step-wise API (and continuous=False)
fall back to the legacy MicroBatcher, which groups same-parameter
requests into run-to-completion generate_batch calls. The security stack
(auth, rate limiting, input validation) is optional on the same
endpoints either way.

Endpoints:
  GET  /health            liveness + model info (ref HEALTHCHECK contract)
  GET  /healthz           readiness: 503 while the engine is warming/
                          compiling, 200 + scheduler state once serving
                          (the Dockerfile HEALTHCHECK target)
  GET  /metrics           Prometheus text exposition of the process
                          registry (serving histograms, KV-pool gauges,
                          training counters when colocated)
  GET  /stats             session counters
  POST /v1/generate       {"prompt": str, "max_new_tokens"?, "temperature"?,
                           "top_p"?, "top_k"?} → {"text", "tokens", ...}
  POST /v1/chat           {"messages": [{"role","content"},...]} or
                           {"message": str} → {"reply", ...}
  POST /v1/auth           {"user","password"} → {"token"} (secure mode)

Both generation endpoints accept {"stream": true} and then respond as
text/event-stream: one `data: {"token", "delta"}` frame per generated
token, a final `data: {"done": true, <text|reply>, tokens, latency_s,
stopped}` frame, and a `data: [DONE]` terminator (engine.generate_stream's
chunked decode; scripts/serve_load.py drives both modes under load).
{"speculative": true} composes with both shapes on greedy requests: the
JSON path runs generate_speculative, the SSE path streams the
draft/verify loop (generate_stream_speculative, tokens in
accepted-prefix bursts, verify stats on the done frame); ineligible or
slot-starved requests silently take the normal path.

No flask/fastapi in the image — http.server keeps the component
dependency-free and testable in-process.
"""

from __future__ import annotations

import contextlib
import inspect
import json
import logging
import math
import queue
import re
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

from luminaai_tpu.monitoring.events import FlightRecorder, get_recorder
from luminaai_tpu.monitoring.slo import SLOEngine, build_slo_stack
from luminaai_tpu.monitoring.timeseries import (
    TimeSeriesRing,
    get_history,
    set_history,
)
from luminaai_tpu.monitoring.watchdog import HangWatchdog, StepTimeSentinel
from luminaai_tpu.monitoring.telemetry import (
    DEFAULT_LATENCY_BUCKETS,
    MetricsRegistry,
    get_registry,
    register_build_info,
    weak_callback,
)
from luminaai_tpu.monitoring.tracing import NULL_TRACER, SpanTracer
from luminaai_tpu.security.auth import ANON_TENANT, tenant_hash

logger = logging.getLogger(__name__)

MAX_BODY_BYTES = 1 << 20  # 1MB request cap (input_validator also re-checks)

# Shape an inbound X-Request-Id must match to be honored (router-minted
# ids are 12 hex chars; anything else sane is fine, garbage is not).
REQUEST_ID_RX = re.compile(r"[A-Za-z0-9_-]{1,64}")

# Chain keys are sha256 hex (inference/prefix_cache.page_chain_keys);
# the page-export route rejects anything else before touching the cache.
PAGE_KEY_RX = re.compile(r"[0-9a-f]{64}")


def new_request_id() -> str:
    """Per-request correlation id: short enough for log lines and SSE
    frames, random enough to never collide within a flight record."""
    return uuid.uuid4().hex[:12]


class RequestTimeout(Exception):
    """A request's deadline passed before it finished: the scheduler
    evicted its lane (or refused admission). Blocking submits surface it
    as HTTP 504; SSE streams get an error frame (docs/resilience.md)."""


class MicroBatcher:
    """Collects concurrent generation requests into one batched decode.

    Handler threads `submit()` and block; a single worker thread pulls the
    first request, waits up to `window_ms` for more with IDENTICAL
    sampling parameters (the decode loop compiles per parameter set), and
    runs them through `engine.generate_batch` — one chip step then serves
    every stream's next token instead of one. Mismatched-parameter
    requests are requeued for the next cycle, so nothing starves.
    """

    def __init__(self, engine, max_batch: int = 8, window_ms: float = 15.0,
                 recorder: Optional[FlightRecorder] = None,
                 telemetry: bool = True):
        self.engine = engine
        self.max_batch = max(1, int(max_batch))
        self.window = max(0.0, float(window_ms)) / 1000.0
        self.q: "queue.Queue" = queue.Queue()
        self.batches = 0
        self.max_batch_seen = 0
        self._busy = False  # a batch is being generated right now
        # Identity-aware accounting parity with the continuous path:
        # submit() strips the request_id/tenant riders the server
        # attaches (they must never reach generate_batch) and emits the
        # same admitted/completed lifecycle events, so /metrics
        # per-tenant series and the flight trail stay honest when the
        # fallback path (--no-continuous) is serving.
        self.telemetry = bool(telemetry)
        self.recorder = recorder if recorder is not None else get_recorder()
        self._worker = threading.Thread(target=self._loop, daemon=True)
        self._worker.start()

    def queue_depth(self) -> int:
        return self.q.qsize()

    def idle(self) -> bool:
        """Nothing queued and nothing generating (drain completion)."""
        return self.q.empty() and not self._busy

    def submit(
        self, prompt_tokens: List[int], gen_kwargs: Dict[str, Any]
    ) -> Tuple[List[int], Dict[str, Any]]:
        # Identity riders are host metadata, never engine kwargs (the
        # same strip-before-compile-key contract the continuous
        # scheduler's _make_request applies).
        gen_kwargs = dict(gen_kwargs)
        request_id = gen_kwargs.pop("request_id", None)
        tenant = gen_kwargs.pop("tenant", None) or ANON_TENANT
        gen_kwargs.pop("timeout_s", None)  # run-to-completion path
        t0 = time.time()
        if self.telemetry and request_id is not None:
            self.recorder.emit(
                "request_admitted", request_id=request_id, tenant=tenant,
                scheduler="micro_batch",
                prompt_tokens=len(prompt_tokens),
            )
        ev = threading.Event()
        slot: Dict[str, Any] = {}
        resolve = getattr(self.engine, "_resolve_gen_key", None)
        if resolve is not None:
            # Group by the RESOLVED compile key, so a request passing an
            # explicit config-default value still batches with one that
            # omitted it.
            key = resolve(
                gen_kwargs.get("max_new_tokens"),
                gen_kwargs.get("temperature"),
                gen_kwargs.get("top_p"),
                gen_kwargs.get("top_k"),
                gen_kwargs.get("repetition_penalty"),
            )
        else:  # duck-typed engines without the helper
            key = tuple(sorted(gen_kwargs.items()))
        self.q.put((prompt_tokens, key, gen_kwargs, ev, slot))
        ev.wait()
        if "error" in slot:
            raise slot["error"]
        tokens, stats = slot["result"]
        if request_id is not None:
            # The reply payload correlates on these like the continuous
            # path's stats do.
            stats = {**stats, "request_id": request_id, "tenant": tenant}
            if self.telemetry:
                self.recorder.emit(
                    "request_completed", request_id=request_id,
                    tenant=tenant, scheduler="micro_batch",
                    tokens=len(tokens),
                    seconds=round(time.time() - t0, 3),
                    stopped=stats.get("stopped"),
                )
        return tokens, stats

    def _loop(self) -> None:
        while True:
            first = self.q.get()
            self._busy = True
            batch = [first]
            requeue = []
            deadline = time.time() + self.window
            while len(batch) < self.max_batch:
                left = deadline - time.time()
                if left <= 0:
                    break
                try:
                    nxt = self.q.get(timeout=left)
                except queue.Empty:
                    break
                if nxt[1] == first[1]:
                    batch.append(nxt)
                else:
                    requeue.append(nxt)
            for item in requeue:
                self.q.put(item)
            try:
                results = self.engine.generate_batch(
                    [item[0] for item in batch], **batch[0][2]
                )
                for item, res in zip(batch, results):
                    item[4]["result"] = res
            except Exception as e:  # deliver, don't kill the worker
                logger.exception("batched generation failed")
                for item in batch:
                    item[4]["error"] = e
            finally:
                self.batches += 1
                self.max_batch_seen = max(self.max_batch_seen, len(batch))
                for item in batch:
                    item[3].set()
                self._busy = False


class _ContinuousRequest:
    """One in-flight request inside the ContinuousScheduler: its prompt,
    resolved budgets, and the sink its tokens stream into (a Queue for
    SSE streams, an Event + result for blocking submits)."""

    def __init__(self, prompt, max_new, sample_key, seed, stream,
                 deadline=None, request_id=None, tenant=ANON_TENANT):
        self.prompt = list(prompt)
        self.max_new = int(max_new)
        self.sample_key = sample_key
        self.seed = seed
        self.deadline = deadline  # absolute wall time; None = no limit
        # Identity for the wide-event trail and per-tenant accounting:
        # every lifecycle event this request produces carries both.
        self.request_id = request_id or new_request_id()
        self.tenant = tenant or ANON_TENANT
        self.stream = bool(stream)
        self.sink: "queue.Queue" = queue.Queue() if stream else None
        self.event = None if stream else threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.tokens: List[int] = []
        self.cancelled = False
        self.done = False
        self.slot: Optional[int] = None
        self.prompt_tokens = 0
        self.admitted_step: Optional[int] = None
        self.t0 = time.time()


class ContinuousScheduler:
    """Continuous (in-flight) batching over a slot-paged KV pool.

    Replaces the MicroBatcher's run-to-completion batches for engines
    exposing the step-wise decode API (GenerationEngine.make_stepwise):
    a single worker owns the decode loop, and EVERY step it (1) frees the
    slots of finished lanes, (2) admits queued requests into freed slots
    (prefill-then-join), and (3) advances all active lanes one token in
    one jit call. Early finishers stop costing chip steps the moment they
    stop, p50 latency decouples from the slowest request in flight, and —
    because max_new is host state, not a compile key — mixed-length
    workloads share one decode executable instead of splitting into
    per-length micro-batches.

    Sampling parameters DO remain a compile key (the sampling math traces
    them), so one "generation" admits only requests with an identical
    resolved sampling key; a mismatched request parks in `_pending`, new
    admissions pause, the active lanes drain, and the scheduler switches
    keys — bounded-latency FIFO across keys rather than starvation.

    Tokens stream out per-slot as they decode: `submit()` blocks like the
    MicroBatcher, `submit_stream()` returns a generator with the engine
    generate_stream contract (ints, then a stats dict) that the existing
    SSE path consumes unchanged; closing it cancels the lane at the next
    step, so a gone client stops costing decode immediately.
    """

    def __init__(
        self,
        engine,
        num_slots: int = 8,
        page_size: int = 128,
        admission_window_ms: float = 0.0,
        max_slot_tokens: Optional[int] = None,
        decoder=None,
        registry: Optional[MetricsRegistry] = None,
        tracer: Optional[SpanTracer] = None,
        telemetry: bool = True,
        latency_buckets=DEFAULT_LATENCY_BUCKETS,
        request_timeout_s: Optional[float] = None,
        recorder: Optional[FlightRecorder] = None,
        max_tenants: int = 64,
        tick_every: int = 16,
        prefill_chunk_tokens: Optional[int] = None,
        prefix_cache_pages: Optional[int] = None,
        prefix_cache_tenant_quota: Optional[int] = None,
        tenant_weights: Optional[Dict[str, int]] = None,
        watchdog: Optional[HangWatchdog] = None,
        page_share=None,
    ):
        self.engine = engine
        # Hang watchdog (monitoring/watchdog.py): armed per generation,
        # beaten once per decode step — a stuck decode executable fires
        # hang_suspected + serving_hangs_total and dumps forensics
        # (abort semantics are the watchdog's, not the scheduler's).
        self.watchdog = watchdog
        # Default per-request deadline; a request's own timeout_s can only
        # shorten it. None = no deadline unless the request asks for one.
        self.request_timeout_s = request_timeout_s
        if decoder is None:
            kw = dict(
                num_slots=num_slots,
                page_size=page_size,
                max_slot_tokens=max_slot_tokens,
            )
            # Duck-typed engines may predate the chunked-prefill /
            # prefix-cache kwargs: inspect the signature instead of
            # catching TypeError, which would also swallow genuine
            # constructor errors.
            try:
                accepted = set(
                    inspect.signature(engine.make_stepwise).parameters
                )
            except (TypeError, ValueError):
                accepted = set()
            if "prefill_chunk_tokens" in accepted:
                kw["prefill_chunk_tokens"] = prefill_chunk_tokens
            if "prefix_cache_pages" in accepted:
                kw["prefix_cache_pages"] = prefix_cache_pages
                kw["prefix_cache_tenant_quota"] = prefix_cache_tenant_quota
            decoder = engine.make_stepwise(**kw)
        self.decoder = decoder
        # Cross-replica page plane (serving/page_share.py): inject the
        # client into the decoder so cold admissions consult the fleet
        # index; only meaningful when the decoder actually has a prefix
        # cache to land pulled pages in.
        self.page_share = page_share
        if page_share is not None and (
            getattr(decoder, "prefix_cache", None) is not None
        ):
            decoder.page_share = page_share
        # Whether the decoder's chunked admission accepts the tenant
        # rider (the prefix cache attributes pages per tenant).
        try:
            self._prefill_takes_tenant = "tenant" in inspect.signature(
                decoder.start_prefill
            ).parameters
        except (AttributeError, TypeError, ValueError):
            self._prefill_takes_tenant = False
        # Fair-share admission (tenant QoS): queued requests park in
        # per-tenant FIFOs and are dequeued WEIGHTED ROUND-ROBIN across
        # tenants, so one hot tenant flooding the intake cannot starve
        # the rest. tenant_weights maps tenant LABEL (hashed identity) ->
        # dequeues per round (priority lanes: weight n tenants drain up
        # to n requests per rotation); default weight 1.
        self.tenant_weights: Dict[str, int] = {
            str(k): max(1, int(v))
            for k, v in (tenant_weights or {}).items()
        }
        self._tq: Dict[str, Any] = {}  # tenant -> deque of requests
        self._rr: List[str] = []  # round-robin rotation order
        self._credits: Dict[str, int] = {}  # WRR dequeues used this turn
        # The worker owns _tq's CONTENTS, but queue_depth() iterates it
        # from request threads (_shed) and /metrics scrapes — guard the
        # dict's shape so a new tenant's insert can never crash a
        # concurrent depth read with "dict changed size during
        # iteration".
        self._tq_lock = threading.Lock()
        # Admissions mid-prefill: slot -> (request, decoder chunk state,
        # admission timestamp). The worker advances ONE chunk per loop
        # tick, interleaved with decode steps, so a long prompt cannot
        # stall concurrent lanes for more than ~one chunk's step time.
        self._prefilling: Dict[int, Tuple[Any, Any, float, float]] = {}
        self.q: "queue.Queue" = queue.Queue()
        self.window = max(0.0, float(admission_window_ms)) / 1000.0
        # Stat names shared with MicroBatcher so /stats stays stable:
        # batches = generations (one sampling key each), max_batch_seen =
        # peak concurrent lanes.
        self.batches = 0
        self.max_batch_seen = 0
        self.requests_served = 0
        self._pending: List[_ContinuousRequest] = []
        self._busy = False  # a generation cycle is running right now
        # Submit-to-terminal request count: covers the dequeue→prefill
        # window where a request is in neither the queue nor a lane.
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        # Wide-event flight recorder (monitoring/events.py): request
        # lifecycle events keyed by request_id + tenant. Rides the same
        # off switch as the metrics so the overhead A/B stays honest.
        self.recorder = recorder if recorder is not None else get_recorder()
        self.max_tenants = max(1, int(max_tenants))
        self.tick_every = max(1, int(tick_every))
        # Liveness stamp for /healthz staleness: wall ts of the last
        # completed decode step. None until the first tick (an idle
        # scheduler is not stale — only a busy one that stopped ticking).
        self.last_tick_ts: Optional[float] = None
        self._init_telemetry(registry, tracer, telemetry, latency_buckets)
        self._worker = threading.Thread(target=self._loop, daemon=True)
        self._worker.start()

    def _init_telemetry(self, registry, tracer, telemetry, buckets) -> None:
        """Registry wiring: per-request latency histograms (recorded on
        the hot path only when `telemetry` — the off switch is the A/B
        for the overhead budget test) plus pull-time KV-pool gauges,
        which cost nothing until /metrics is scraped."""
        self.telemetry = bool(telemetry)
        self.registry = registry or get_registry()
        self.tracer = tracer or NULL_TRACER
        r = self.registry
        self._m_queue_wait = r.histogram(
            "serve_queue_wait_seconds",
            "Submit-to-admission wait (slot contention + key parking)",
            buckets=buckets,
        )
        self._m_prefill = r.histogram(
            "serve_prefill_seconds",
            "prefill_into_slot duration (prompt KV write + first token)",
            buckets=buckets,
        )
        self._m_ttft = r.histogram(
            "serve_ttft_seconds",
            "Submit-to-first-token latency per request",
            buckets=buckets,
        )
        self._m_step = r.histogram(
            "serve_decode_step_seconds",
            "One scheduler decode step (all active lanes, one jit call)",
            buckets=buckets,
        )
        self._m_token = r.histogram(
            "serve_token_latency_seconds",
            "Per-token decode latency (step duration, one observation "
            "per lane that produced a token)",
            buckets=buckets,
        )
        # Step-time anomaly sentinel (docs/observability.md "Goodput &
        # sentinels"): robust rolling median/MAD over decode-step
        # durations — serve_decode_step_seconds_{median,mad} gauges plus
        # step_anomaly events when one step blows past the distribution.
        self._sentinel = StepTimeSentinel(
            registry=r,
            recorder=self.recorder if self.telemetry else None,
            prefix="serve_decode_step_seconds",
            program="serve",
        )
        self._m_admissions = r.counter(
            "serve_admissions_total", "Requests admitted into a KV slot"
        )
        self._m_evictions = r.counter(
            "serve_evictions_total",
            "Slots released (finished, cancelled, or failed lanes)",
        )
        self._m_generations = r.counter(
            "serve_generations_total",
            "Generations started (one sampling key each)",
        )
        self._m_decode_steps = r.counter(
            "serve_decode_steps_total", "Scheduler decode steps executed"
        )
        self._m_timeouts = r.counter(
            "serving_requests_timed_out_total",
            "Requests evicted (or refused admission) because their "
            "deadline passed before completion",
        )
        self._m_prefill_chunks = r.counter(
            "serving_prefill_chunks_total",
            "Prefill chunks executed by the scheduler (chunked prefill "
            "interleaves these with decode steps)",
        )
        # Per-tenant accounting (bounded: max_tenants distinct labels,
        # then the registry's `_overflow` bucket — a tenant label can
        # never explode /metrics).
        self._m_tenant_ttft = r.histogram(
            "tenant_ttft_seconds",
            "Submit-to-first-token latency per tenant",
            buckets=buckets,
            labelnames=("tenant",),
            max_label_values=self.max_tenants,
        )
        self._m_tenant_timeouts = r.counter(
            "tenant_requests_timed_out_total",
            "Deadline-evicted (or admission-refused) requests per tenant",
            labelnames=("tenant",),
            max_label_values=self.max_tenants,
        )
        # Callback gauges hold WEAK refs: the process registry outlives
        # any one scheduler, and a strong closure would pin a replaced
        # scheduler's whole KV pool and export its stale state forever.
        r.gauge(
            "serve_active_lanes", "Lanes currently decoding"
        ).set_function(weak_callback(self, lambda s: s._active_lanes))
        r.gauge(
            "serve_queue_depth",
            "Requests waiting for admission (queued + key-parked)",
        ).set_function(weak_callback(self, lambda s: s.queue_depth()))
        self._active_lanes = 0
        pool = getattr(self.decoder, "pool", None)
        if pool is not None and hasattr(pool, "stats"):
            def pool_gauge(name, help_text, key):
                r.gauge(name, help_text).set_function(
                    weak_callback(pool, lambda p: p.stats().get(key, 0))
                )

            pool_gauge("kv_pool_slots_in_use", "KV pool slots allocated",
                       "in_use")
            pool_gauge("kv_pool_slots_free", "KV pool slots free", "free")
            pool_gauge("kv_pool_slot_reuses_total",
                       "Times a previously-used slot was re-issued",
                       "reuses")
            pool_gauge("kv_pool_pages_in_use",
                       "Pages holding live KV rows", "pages_in_use")
            pool_gauge("kv_pool_pages_total", "Total pool pages",
                       "pages_total")
            pool_gauge(
                "kv_pool_fragmentation_rows",
                "Rows lost to page rounding (allocated but not live)",
                "fragmentation_rows",
            )
        # Prefix cache (inference/prefix_cache.py): hit/miss/savings
        # counters observed at admission, plus pull-time occupancy /
        # refcount / eviction gauges straight off the cache's stats.
        self._m_prefix_hits = r.counter(
            "serve_prefix_cache_hits_total",
            "Admissions that spliced at least one cached prefix page",
        )
        self._m_prefix_misses = r.counter(
            "serve_prefix_cache_misses_total",
            "Chunked admissions that found no cached prefix",
        )
        self._m_prefix_saved = r.counter(
            "serve_prefill_tokens_saved_total",
            "Prompt tokens whose prefill was skipped via cached prefix "
            "pages",
        )
        self._m_prefix_remote_hits = r.counter(
            "serve_prefix_remote_hits_total",
            "Admissions whose prefix hit rode pages pulled from another "
            "replica (cross-replica page sharing)",
        )
        # Tenant-keyed cache residency rides under the same label budget
        # as every other tenant series (`lumina analyze` LX009 enforces
        # the max_label_values declaration).
        self._m_tenant_prefix_pages = r.gauge(
            "tenant_prefix_cache_pages",
            "Arena pages currently cached per owning tenant",
            labelnames=("tenant",),
            max_label_values=self.max_tenants,
        )
        cache = getattr(self.decoder, "prefix_cache", None)
        if cache is not None:
            # prefix_evict flight events ride the scheduler's recorder,
            # honoring the same telemetry off switch.
            cache.recorder = self.recorder if self.telemetry else None

            def cache_gauge(name, help_text, key):
                r.gauge(name, help_text).set_function(
                    weak_callback(cache, lambda c: c.stats().get(key, 0))
                )

            cache_gauge("prefix_cache_pages_cached",
                        "Arena pages holding cached prefix KV",
                        "pages_cached")
            cache_gauge("prefix_cache_pages_free",
                        "Arena pages free for harvest", "pages_free")
            cache_gauge("prefix_cache_page_refs",
                        "Live lane references onto cached pages "
                        "(sharing fan-out)", "page_refs")
            cache_gauge("prefix_cache_evictions",
                        "Cached pages LRU-evicted since start",
                        "evictions")
            cache_gauge("prefix_cache_pages_budget",
                        "Configured arena page budget "
                        "(--prefix-cache-pages)", "capacity_pages")

    def queue_depth(self) -> int:
        with self._tq_lock:
            parked = sum(len(d) for d in self._tq.values())
        return self.q.qsize() + len(self._pending) + parked

    # -- fair-share tenant queues (worker thread only) ---------------------
    def _enqueue_tenant(self, req: "_ContinuousRequest") -> None:
        from collections import deque

        t = req.tenant or ANON_TENANT
        dq = self._tq.get(t)
        if dq is None:
            with self._tq_lock:
                dq = self._tq[t] = deque()
            self._rr.append(t)
        dq.append(req)

    def _drain_intake(self) -> None:
        """Move everything waiting on the intake queue into the
        per-tenant FIFOs (worker thread only — submit() threads touch
        only self.q)."""
        while True:
            try:
                self._enqueue_tenant(self.q.get_nowait())
            except queue.Empty:
                return

    def _next_queued(self) -> Optional["_ContinuousRequest"]:
        """Weighted round-robin dequeue across tenant queues: each
        rotation visits tenants in arrival order, draining up to
        `tenant_weights[t]` (default 1) requests before moving on —
        a tenant with 50 queued requests and a tenant with 1 alternate
        instead of the flood going first (contract-tested: the starved
        tenant's queue keeps draining under a hot-tenant flood)."""
        if not self._rr:
            return None
        # One WRR credit per call: rotate to the next tenant with work,
        # respecting per-tenant weight via a running credit counter.
        for _ in range(len(self._rr)):
            t = self._rr[0]
            dq = self._tq.get(t)
            if not dq:
                # Empty queue: drop the tenant from the rotation (it
                # re-registers on its next submit).
                self._rr.pop(0)
                with self._tq_lock:
                    self._tq.pop(t, None)
                self._credits.pop(t, None)
                continue
            used = self._credits.get(t, 0)
            if used + 1 >= self.tenant_weights.get(t, 1):
                # Weight exhausted after this dequeue: rotate.
                self._credits[t] = 0
                self._rr.append(self._rr.pop(0))
            else:
                self._credits[t] = used + 1
            return dq.popleft()
        return None

    def idle(self) -> bool:
        """No request anywhere between submit and its terminal
        finish/fail (drain completion). Counted submit-to-terminal, so
        the dequeue→prefill window — where a request is in neither the
        queue nor a lane — can never make drain() declare completion and
        shut the server down on top of the request it exists to
        protect."""
        with self._inflight_lock:
            return self._inflight == 0 and not self._busy

    def _track(self, req: _ContinuousRequest) -> _ContinuousRequest:
        with self._inflight_lock:
            self._inflight += 1
        return req

    def _untrack(self) -> None:
        with self._inflight_lock:
            self._inflight -= 1

    # -- public API --------------------------------------------------------
    def submit(
        self, prompt_tokens: List[int], gen_kwargs: Dict[str, Any]
    ) -> Tuple[List[int], Dict[str, Any]]:
        req = self._track(
            self._make_request(prompt_tokens, gen_kwargs, stream=False)
        )
        self.q.put(req)
        req.event.wait()
        if req.error is not None:
            raise req.error
        return req.result

    def submit_stream(
        self, prompt_tokens: List[int], gen_kwargs: Dict[str, Any]
    ):
        """Generator with the generate_stream contract: token ints as the
        lane decodes them, then one final stats dict. Closing it flags the
        request cancelled; the worker frees the slot at the next step."""
        req = self._track(
            self._make_request(prompt_tokens, gen_kwargs, stream=True)
        )
        self.q.put(req)

        def events():
            try:
                while True:
                    item = req.sink.get()
                    if isinstance(item, BaseException):
                        raise item
                    yield item
                    if isinstance(item, dict):
                        return
            finally:
                req.cancelled = True

        return events()

    def stats(self) -> Dict[str, Any]:
        out = {
            "scheduler": "continuous",
            "batches": self.batches,
            "max_batch_seen": self.max_batch_seen,
            "decode_steps": int(getattr(self.decoder, "steps", 0)),
            "active_lanes": self._active_lanes,
            "queue_depth": self.queue_depth(),
            "prefilling": len(self._prefilling),
        }
        pool = getattr(self.decoder, "pool", None)
        if pool is not None and hasattr(pool, "stats"):
            out["kv_pool"] = pool.stats()
        cache = getattr(self.decoder, "prefix_cache", None)
        if cache is not None:
            out["prefix_cache"] = cache.stats()
        return out

    # -- internals ---------------------------------------------------------
    def _make_request(self, prompt_tokens, gen_kwargs, stream):
        # Identity riders are host metadata, never compile keys: strip
        # them before sampling-key resolution so two tenants' otherwise
        # identical requests still share one decode executable.
        gen_kwargs = dict(gen_kwargs)
        request_id = gen_kwargs.pop("request_id", None)
        tenant = gen_kwargs.pop("tenant", None)
        resolve = getattr(self.engine, "_resolve_gen_key", None)
        if resolve is not None:
            key = resolve(
                gen_kwargs.get("max_new_tokens"),
                gen_kwargs.get("temperature"),
                gen_kwargs.get("top_p"),
                gen_kwargs.get("top_k"),
                gen_kwargs.get("repetition_penalty"),
            )
            max_new, sample_key = key[0], tuple(key[1:])
        else:  # duck-typed engines without the helper
            max_new = int(gen_kwargs.get("max_new_tokens") or 16)
            sample_key = tuple(
                sorted(
                    (k, v)
                    for k, v in gen_kwargs.items()
                    if k not in ("max_new_tokens", "seed", "timeout_s")
                )
            )
        cap = int(
            getattr(self.decoder, "token_capacity", 0)
            or getattr(self.decoder, "slot_tokens", 0)
        ) or None
        if cap:
            # A slot must hold prompt tail + budget; the prompt trims but
            # the budget can only clamp. token_capacity (not the page-
            # rounded slot size) keeps decode inside the engine's
            # max_context contract.
            max_new = max(1, min(max_new, cap - 1))
        timeout = gen_kwargs.get("timeout_s") or self.request_timeout_s
        if timeout and self.request_timeout_s:
            timeout = min(float(timeout), float(self.request_timeout_s))
        return _ContinuousRequest(
            prompt_tokens, max_new, sample_key,
            gen_kwargs.get("seed"), stream,
            deadline=(time.time() + float(timeout)) if timeout else None,
            request_id=request_id, tenant=tenant,
        )

    def _emit(self, req: _ContinuousRequest, token: int) -> None:
        req.tokens.append(int(token))
        if req.stream:
            req.sink.put(int(token))

    def _event(self, type: str, req: Optional[_ContinuousRequest] = None,
               **fields) -> None:
        """Append one lifecycle event to the flight recorder, stamped
        with the request's identity. Same off switch as the metrics."""
        if not self.telemetry:
            return
        if req is not None:
            fields.setdefault("request_id", req.request_id)
            fields.setdefault("tenant", req.tenant)
        self.recorder.emit(type, **fields)

    def _finish(self, req: _ContinuousRequest, stopped: str) -> None:
        if req.done:
            return  # terminal already delivered
        dt = time.time() - req.t0
        n = len(req.tokens)
        stats = {
            "tokens_generated": n,
            "seconds": round(dt, 3),
            "tokens_per_second": round(n / max(dt, 1e-9), 1),
            "prompt_tokens": req.prompt_tokens,
            "stopped": stopped,
            "slot": req.slot,
            "admitted_step": req.admitted_step,
            "finished_step": int(getattr(self.decoder, "steps", 0)),
            "scheduler": "continuous",
            "request_id": req.request_id,
            "tenant": req.tenant,
        }
        self.requests_served += 1
        req.done = True
        self._untrack()
        self._event(
            "request_completed", req,
            slot=req.slot, tokens=n, prompt_tokens=req.prompt_tokens,
            seconds=round(dt, 3), stopped=stopped,
            step=int(getattr(self.decoder, "steps", 0)),
        )
        if req.stream:
            req.sink.put(stats)
        else:
            req.result = (req.tokens, stats)
            req.event.set()

    def _fail(self, req: _ContinuousRequest, err: BaseException) -> None:
        if req.done:
            return  # terminal already delivered
        req.done = True
        self._untrack()
        self._event(
            "request_evicted", req,
            slot=req.slot, tokens=len(req.tokens),
            reason=(
                "timeout" if isinstance(err, RequestTimeout) else "error"
            ),
            error=str(err)[:200],
        )
        if req.stream:
            req.sink.put(err)
        else:
            req.error = err
            req.event.set()

    def _timeout(self, req: _ContinuousRequest, where: str) -> None:
        """Deadline enforcement: a lane past its deadline stops costing
        decode steps NOW (eviction frees the slot for queued work) and the
        client gets an explicit timeout instead of an open-ended wait."""
        if self.telemetry:
            self._m_timeouts.inc()
            self._m_tenant_timeouts.labels(tenant=req.tenant).inc()
        waited = time.time() - req.t0
        self._fail(req, RequestTimeout(
            f"deadline exceeded after {waited:.1f}s ({where}; "
            f"{len(req.tokens)} tokens generated)"
        ))

    def _release_slot(self, slot: int) -> None:
        """Single choke point for giving a slot back: the decoder free +
        the eviction count must never drift apart across the four
        release sites."""
        self.decoder.release_slot(slot)
        if self.telemetry:
            self._m_evictions.inc()

    def _release(self, req: _ContinuousRequest, active: dict) -> None:
        self._release_slot(req.slot)
        active.pop(req.slot, None)
        self._active_lanes = len(active)

    def _admit(self, req: _ContinuousRequest, active: dict) -> None:
        """Prefill-then-join: the request's prompt KV lands in a freed
        slot and its first token streams out immediately; the lane joins
        the shared decode from the next step."""
        if req.cancelled:
            self._finish(req, "cancelled")
            return
        if req.deadline is not None and time.time() > req.deadline:
            # Expired while queued (slot contention / key parking): refuse
            # admission rather than spend prefill on a dead request.
            self._timeout(req, "while queued")
            return
        with self._wd_pause():
            self._admit_paused(req, active)

    def _admit_paused(self, req: _ContinuousRequest, active: dict) -> None:
        """_admit's body, under the watchdog pause: the prefill below can
        hit a first-use XLA compile (new prompt bucket) that dwarfs the
        rolling decode-step stats. The pause lives HERE — exactly where
        prefill work happens — not per tick: pausing on a merely-nonempty
        queue would exclude every interval on a saturated server and
        starve the warmup, leaving real decode hangs undetectable."""
        slot = self.decoder.acquire_slot()
        t_admit = time.perf_counter()
        queue_wait = max(0.0, time.time() - req.t0)
        if self.telemetry:
            # Queue wait = submit to slot acquisition: covers both slot
            # contention and sampling-key parking.
            self._m_queue_wait.observe(queue_wait)
            self._m_admissions.inc()
        self._event(
            "request_admitted", req,
            slot=slot, queue_wait_s=round(queue_wait, 4),
            prompt_tokens=len(req.prompt),
            step=int(getattr(self.decoder, "steps", 0)),
        )
        start = getattr(self.decoder, "start_prefill", None)
        if start is not None and getattr(self.decoder, "prefill_chunk", 0):
            try:
                st = start(
                    slot,
                    req.prompt,
                    max_new_tokens=req.max_new,
                    sample_key=req.sample_key,
                    seed=req.seed,
                    # Tenant rider: the prefix cache attributes harvested
                    # pages per tenant (quota enforcement).
                    **(
                        {"tenant": req.tenant}
                        if self._prefill_takes_tenant
                        else {}
                    ),
                )
            except Exception as e:
                logger.exception("start-prefill failed")
                self._release_slot(slot)
                self._fail(req, e)
                return
            if st is not None:
                # Chunks run from the worker loop, one per tick,
                # interleaved with decode steps (_advance_prefills). The
                # trailing 0.0 accumulates per-chunk compute seconds so
                # serve_prefill_seconds stays a prefill-cost histogram
                # rather than absorbing every interleaved decode tick.
                self._prefilling[slot] = (req, st, t_admit, 0.0)
                return
        try:
            with self.tracer.span(
                "prefill", slot=slot, prompt_tokens=len(req.prompt)
            ):
                info = self.decoder.prefill_into_slot(
                    slot,
                    req.prompt,
                    max_new_tokens=req.max_new,
                    sample_key=req.sample_key,
                    seed=req.seed,
                )
        except Exception as e:
            logger.exception("prefill-into-slot failed")
            self._release_slot(slot)
            self._fail(req, e)
            return
        self._prefill_done(req, slot, info, t_admit, active)

    def _prefill_done(self, req, slot, info, t_admit, active,
                      prefill_s=None) -> None:
        """Shared prompt-prefilled tail for the whole-prompt and chunked
        admission paths: TTFT booking, first-token emission, lane
        activation (or immediate finish). `prefill_s` is the prefill
        COMPUTE time — the chunked path passes its per-chunk sum so the
        histogram keeps one meaning across both admission paths (the
        monolithic path's admission-to-done wall time IS its compute)."""
        ttft = max(0.0, time.time() - req.t0)
        if prefill_s is None:
            prefill_s = time.perf_counter() - t_admit
        if self.telemetry:
            self._m_prefill.observe(prefill_s)
            # First token is sampled inside prefill, so TTFT lands here.
            self._m_ttft.observe(ttft)
            self._m_tenant_ttft.labels(tenant=req.tenant).observe(ttft)
        self._event(
            "request_prefill", req, slot=slot,
            prefill_s=round(prefill_s, 4),
            prompt_tokens=int(info.get("prompt_tokens", 0)),
        )
        self._event("request_first_token", req, slot=slot,
                    ttft_s=round(ttft, 4))
        prefix = info.get("prefix") if isinstance(info, dict) else None
        if prefix is not None:
            if self.telemetry:
                if prefix.get("hit_pages"):
                    self._m_prefix_hits.inc()
                else:
                    self._m_prefix_misses.inc()
                saved = int(prefix.get("tokens_saved", 0))
                if saved:
                    self._m_prefix_saved.inc(saved)
                cache = getattr(self.decoder, "prefix_cache", None)
                if cache is not None:
                    t = prefix.get("tenant") or req.tenant
                    self._m_tenant_prefix_pages.labels(tenant=t).set(
                        cache.tenant_pages(t)
                    )
            if prefix.get("hit_pages"):
                self._event(
                    "prefix_hit", req, slot=slot,
                    pages=int(prefix["hit_pages"]),
                    tokens_saved=int(prefix.get("tokens_saved", 0)),
                )
            remote = prefix.get("remote")
            if isinstance(remote, dict) and remote.get("pulled"):
                if self.telemetry:
                    self._m_prefix_remote_hits.inc()
                self._event(
                    "prefix_remote_hit", req, slot=slot,
                    owner=remote.get("owner"),
                    pages=int(remote.get("pulled", 0)),
                    tokens=int(remote.get("tokens", 0)),
                    bytes=int(remote.get("bytes", 0)),
                    degraded=bool(remote.get("failed")),
                )
        req.slot = slot
        req.prompt_tokens = int(info.get("prompt_tokens", 0))
        req.admitted_step = int(getattr(self.decoder, "steps", 0))
        if info.get("is_stop"):
            self._finish(req, "eos")
            self._release_slot(slot)
            return
        self._emit(req, info["token"])
        if req.max_new <= 1:
            self._finish(req, "length")
            self._release_slot(slot)
            return
        active[slot] = req
        self._active_lanes = len(active)
        self.max_batch_seen = max(self.max_batch_seen, len(active))

    def _admit_queued(self, key, active: dict) -> None:
        """Admit queued same-key requests into free slots, dequeued
        FAIR-SHARE (weighted round-robin across tenant queues — one hot
        tenant's flood cannot starve the rest; docs/serving.md "Prefix
        cache + tenant QoS"). Once a MISMATCHED-key request is waiting,
        admission pauses so the active lanes drain and the scheduler can
        switch keys (no starvation across sampling keys either)."""
        self._drain_intake()
        while self.decoder.has_free_slot() and not self._pending:
            nxt = self._next_queued()
            if nxt is None:
                break
            if nxt.sample_key == key:
                self._admit(nxt, active)
            else:
                self._pending.append(nxt)

    def _flush_harvests(self) -> None:
        """One bulk device copy for every harvest queued this tick
        (StepwiseDecoder.flush_harvests; no-op without a prefix cache
        or an empty queue). With page sharing on, chain keys whose
        bytes just landed (this flush or a remote pull) are reported
        to the router's fleet index off-thread."""
        flush = getattr(self.decoder, "flush_harvests", None)
        if flush is not None:
            flush()
        if self.page_share is not None:
            drain = getattr(self.decoder, "drain_landed_keys", None)
            if drain is not None:
                keys = drain()
                if keys:
                    self.page_share.report_async(keys)

    def _advance_prefills(self, active: dict) -> None:
        """Advance ONE chunk of ONE mid-prefill admission (round-robin
        in admission order). Called once per scheduler tick, so prefill
        work interleaves with decode steps instead of stalling them —
        the chunked-prefill latency contract (docs/serving.md).

        Dedup followers parked behind an in-flight identical prefix
        (decoder `waiting` states) re-check for free but must NOT eat
        the tick's single chunk advance — otherwise K parked followers
        would slow their own leader's prefill (and every queued one)
        (K+1)x. A parked re-check cycles to the ring's tail and the
        scan moves on to the first runnable admission; only real chunk
        compute (or a resolution running its first chunk) ends the
        tick."""
        if not self._prefilling:
            return
        with self._wd_pause():
            self._advance_prefills_paused(active)

    def _advance_prefills_paused(self, active: dict) -> None:
        """_advance_prefills' body, watchdog-paused like _admit_paused:
        a chunk advance can compile its executable on first use. Guarded
        by the `_prefilling` check above, so steady decode-only ticks
        never pause and the rolling stats keep warming."""
        for _ in range(max(1, len(self._prefilling))):
            if not self._prefilling:
                return
            slot, (req, st, t_admit, spent) = next(
                iter(self._prefilling.items())
            )
            del self._prefilling[slot]
            if req.cancelled:
                self._finish(req, "cancelled")
                self._release_slot(slot)
                return
            if req.deadline is not None and time.time() > req.deadline:
                self._timeout(req, "mid-prefill")
                self._release_slot(slot)
                return
            was_waiting = bool(st.get("waiting"))
            try:
                t_chunk = time.perf_counter()
                with self.tracer.span("prefill_chunk", slot=slot):
                    info = self.decoder.advance_prefill(st)
                spent += time.perf_counter() - t_chunk
                # A chunk advance is real progress: stamp liveness here
                # too, or a prefill-only window (huge prompt, no active
                # decode lanes) would read as stale to /healthz while
                # the scheduler is genuinely working.
                self.last_tick_ts = time.time()
            except Exception as e:
                logger.exception("chunked prefill failed")
                self._release_slot(slot)
                self._fail(req, e)
                return
            if info is None and was_waiting and st.get("waiting"):
                # Still parked: host-only bookkeeping, no chunk ran and
                # no prefill_chunk event/counter — keep scanning for
                # runnable work this tick.
                self._prefilling[slot] = (req, st, t_admit, spent)
                continue
            if self.telemetry:
                self._m_prefill_chunks.inc()
            self._event(
                "prefill_chunk", req, slot=slot,
                chunk=int(st["next"]), chunks=int(st["n_chunks"]),
                # Rows RESIDENT, spliced prefix included — must agree with
                # the decoder's own residency booking for a prefix hit.
                rows=int(min(
                    int(st.get("start_rows", 0)) + st["next"] * st["chunk"],
                    st["length"],
                )),
            )
            if info is None:
                # More chunks pending: back of the round-robin ring.
                self._prefilling[slot] = (req, st, t_admit, spent)
                return
            self._prefill_done(req, slot, info, t_admit, active,
                               prefill_s=spent)
            return

    def _loop(self) -> None:
        while True:
            if self._pending:
                req = self._pending.pop(0)
            else:
                self._drain_intake()
                req = self._next_queued()
                if req is None:
                    # Nothing parked anywhere: block for the next submit,
                    # then run it through the same fair-share path.
                    self._enqueue_tenant(self.q.get())
                    self._drain_intake()
                    req = self._next_queued()
            self._busy = True
            try:
                self._run_generation(req)
            except Exception as e:  # never kill the worker
                logger.exception("continuous scheduler generation failed")
                if not req.done:  # the client must never hang on a bug
                    self._fail(req, e)
            finally:
                self._busy = False

    def _run_generation(self, first: _ContinuousRequest) -> None:
        self.batches += 1
        if self.telemetry:
            self._m_generations.inc()
        if self.watchdog is not None:
            # Watch only while a generation is live: an idle scheduler
            # parked on q.get() must never read as hung.
            self.watchdog.arm()
        try:
            self._run_generation_inner(first)
        finally:
            if self.watchdog is not None:
                self.watchdog.disarm()

    def _wd_pause(self):
        """Watchdog pause for the compile-prone host work between decode
        steps (admission prefills, chunk advances — first-use XLA
        compiles of new prompt/chunk buckets): the trainer's
        skip_next-on-recompile guard, serving-shaped. Callers apply it
        exactly around REAL prefill work, never per tick — pausing every
        tick would exclude every beat interval and starve the rolling
        stats. No-op without a watchdog."""
        if self.watchdog is None:
            return contextlib.nullcontext()
        return self.watchdog.pause()

    def _run_generation_inner(self, first: _ContinuousRequest) -> None:
        key = first.sample_key
        active: Dict[int, _ContinuousRequest] = {}
        self._admit(first, active)
        # Optional admission window: wait briefly for same-key peers so
        # the first step already carries a batch (a latency/throughput
        # knob, NOT required for joining — lanes join at any later step).
        # Peers are dequeued through the same fair-share WRR path as
        # steady-state admission, so requests already parked in tenant
        # queues go first and a burst inside the window cannot jump them.
        deadline = time.time() + self.window
        while (
            self.window > 0
            and self.decoder.has_free_slot()
            and not self._pending
        ):
            left = deadline - time.time()
            if left <= 0:
                break
            self._drain_intake()
            nxt = self._next_queued()
            if nxt is None:
                try:
                    self._enqueue_tenant(self.q.get(timeout=left))
                except queue.Empty:
                    break
                continue
            if nxt.sample_key == key:
                self._admit(nxt, active)
            else:
                self._pending.append(nxt)
        # Decode-tick accumulator: one SUMMARY event per tick_every steps
        # (per-step events would be all the ring buffer ever holds).
        tick_steps = tick_tokens = 0
        tick_t0 = time.perf_counter()
        while active or self._prefilling:
            self._admit_queued(key, active)
            # One prefill chunk per tick: a long admission progresses
            # without ever costing the decode batch more than one
            # chunk-sized forward between steps (_admit/_advance_prefills
            # pause the watchdog internally, exactly around real prefill
            # work — never on a merely-busy queue).
            self._advance_prefills(active)
            # Harvest batching (ROADMAP item 2): every prefix-cache
            # harvest that landed this tick rides ONE jitted bulk page
            # copy instead of one pool-copy dispatch per admission.
            self._flush_harvests()
            if not active:
                if self._prefilling:
                    continue
                break
            try:
                t_step = time.perf_counter()
                toks, produced, eos = self.decoder.decode_step(key)
                step_dt = time.perf_counter() - t_step
            except Exception as e:
                logger.exception("decode step failed")
                for r in list(active.values()):
                    self._fail(r, e)
                    self._release(r, active)
                for slot, (r, *_) in list(self._prefilling.items()):
                    self._fail(r, e)
                    self._release_slot(slot)
                self._prefilling.clear()
                return
            if self.watchdog is not None:
                self.watchdog.beat()
            self.last_tick_ts = time.time()
            n_produced = sum(1 for slot in active if produced[slot])
            if self.telemetry:
                self._m_step.observe(step_dt)
                self._sentinel.observe(
                    step_dt, step=int(getattr(self.decoder, "steps", 0))
                )
                self._m_decode_steps.inc()
                # Per-token decode latency: the step IS the inter-token
                # gap for every lane that emitted this step.
                self._m_token.observe(step_dt, count=max(0, n_produced))
            tick_steps += 1
            tick_tokens += max(0, n_produced)
            if tick_steps >= self.tick_every:
                dt_tick = time.perf_counter() - tick_t0
                self._event(
                    "decode_tick",
                    step=int(getattr(self.decoder, "steps", 0)),
                    steps=tick_steps, tokens=tick_tokens,
                    active_lanes=len(active),
                    queue_depth=self.queue_depth(),
                    tokens_per_sec=round(
                        tick_tokens / max(dt_tick, 1e-9), 1
                    ),
                )
                tick_steps = tick_tokens = 0
                tick_t0 = time.perf_counter()
            now = time.time()
            for slot, r in list(active.items()):
                if r.cancelled:
                    self._finish(r, "cancelled")
                    self._release(r, active)
                    continue
                if r.deadline is not None and now > r.deadline:
                    # Overdue lane (slow/stuck decode or an oversized
                    # budget): evict so the slot serves queued work.
                    self._timeout(r, "mid-decode")
                    self._release(r, active)
                    continue
                if eos[slot]:
                    self._finish(r, "eos")
                    self._release(r, active)
                    continue
                if produced[slot]:
                    self._emit(r, int(toks[slot]))
                    full = getattr(self.decoder, "lane_full", None)
                    if len(r.tokens) >= r.max_new or (
                        full is not None and full(slot)
                    ):
                        self._finish(r, "length")
                        self._release(r, active)
        # A harvest landing on the generation's last tick must not wait
        # for the next admission's defensive flush.
        self._flush_harvests()


class _SlotStream:
    """Event-stream wrapper that releases its concurrency slot exactly
    once — on exhaustion, error, or close(). A plain generator's finally
    block never runs if the generator is closed before its first next()
    (e.g. the handler's header write fails for an already-gone client),
    which would slowly leak stream slots into permanent 503s."""

    def __init__(self, inner, release):
        self._inner = inner
        self._release = release
        self._released = False

    def _release_once(self) -> None:
        if not self._released:
            self._released = True
            self._release()

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self._inner)
        except BaseException:
            self._release_once()  # StopIteration included
            raise

    def close(self) -> None:
        try:
            self._inner.close()
        finally:
            self._release_once()


class ChatServer:
    """Owns the engine + optional security stack; builds the handler class."""

    def __init__(
        self,
        engine,
        secure: bool = False,
        bootstrap_user: Optional[tuple] = None,
        users_path: str = "users.json",
        max_new_tokens_cap: int = 2048,
        max_batch: int = 8,
        batch_window_ms: float = 15.0,
        max_streams: int = 4,
        continuous: Any = "auto",
        num_slots: int = 8,
        page_size: int = 128,
        admission_window_ms: float = 0.0,
        registry: Optional[MetricsRegistry] = None,
        tracer: Optional[SpanTracer] = None,
        telemetry: bool = True,
        latency_buckets=DEFAULT_LATENCY_BUCKETS,
        warmup: bool = False,
        request_timeout_s: Optional[float] = None,
        max_queue_depth: int = 128,
        drain_grace_s: float = 30.0,
        flight_dir: Optional[str] = None,
        max_tenants: int = 64,
        recorder: Optional[FlightRecorder] = None,
        prefill_chunk_tokens: Optional[int] = None,
        prefix_cache_pages: Optional[int] = None,
        prefix_cache_tenant_quota: Optional[int] = None,
        tenant_weights: Optional[Dict[str, int]] = None,
        tenant_rate_per_s: Optional[float] = None,
        tenant_burst: Optional[int] = None,
        watchdog: Any = "auto",
        watchdog_abort: bool = False,
        watchdog_k: Optional[float] = None,
        watchdog_floor_s: Optional[float] = None,
        slo: bool = True,
        slo_config: Optional[str] = None,
        healthz_stale_after_s: Optional[float] = None,
        page_share: Optional[str] = None,
        page_share_self_url: Optional[str] = None,
        page_pull_timeout_s: float = 2.0,
        page_share_max_inflight: int = 2,
    ):
        self.engine = engine
        self.telemetry = bool(telemetry)
        self.registry = registry or get_registry()
        self.tracer = tracer or NULL_TRACER
        # Wide-event trail (monitoring/events.py): request identity is
        # minted at the HTTP layer, lifecycle events come from the
        # scheduler, and drain dumps the ring into flight_dir for
        # `lumina events` (crash forensics; docs/observability.md).
        self.recorder = recorder if recorder is not None else get_recorder()
        self.flight_dir = flight_dir
        self.max_tenants = max(1, int(max_tenants))
        # Graceful degradation (docs/resilience.md): deadlines evict
        # overdue lanes, queue-depth overload sheds with 503+Retry-After,
        # and SIGTERM drains in-flight work before shutdown.
        self.request_timeout_s = request_timeout_s
        self.max_queue_depth = max(0, int(max_queue_depth))
        self.drain_grace_s = float(drain_grace_s)
        self._draining = False
        # Readiness gate for /healthz: a container probe must see 503
        # while XLA is still compiling the prefill/decode executables
        # (minutes for real models) and flip to 200 the moment requests
        # can actually be served. warmup=True (the `serve` entrypoint)
        # drives a tiny generation through the real batcher path in the
        # background and sets the gate when it completes; in-process
        # embedders/tests default to immediately-ready.
        self._ready = threading.Event()
        # Continuous batching (step-level admission over a slot-paged KV
        # pool) whenever the engine exposes the step-wise decode API;
        # duck-typed engines without it keep the legacy MicroBatcher
        # (continuous=False forces the legacy path for A/B).
        self.continuous = bool(
            continuous is True
            or (continuous == "auto" and hasattr(engine, "make_stepwise"))
        )
        if self.continuous:
            # Serving hang watchdog: "auto" builds one over the flight
            # dir (hang forensics land next to the drain dumps); pass
            # None/False to disable, or a configured HangWatchdog to
            # control thresholds (tests do).
            if watchdog == "auto":
                wd_kw = {}
                if watchdog_k is not None:
                    wd_kw["k"] = float(watchdog_k)
                if watchdog_floor_s is not None:
                    # --watchdog-floor: on cold fleets, raise above the
                    # worst-case decode compile before enabling abort.
                    wd_kw["floor_s"] = float(watchdog_floor_s)
                watchdog = HangWatchdog(
                    kind="serving",
                    registry=self.registry,
                    recorder=self.recorder,
                    dump_dir=flight_dir,
                    abort=watchdog_abort,
                    **wd_kw,
                )
            self.watchdog = watchdog or None
            # Operator-supplied tenant weights are keyed by RAW identity
            # (or the literal "anon"); hash them here so raw identities
            # never live in scheduler state — the same tenant_hash the
            # gate resolves request identities through.
            weights = {
                (k if k == ANON_TENANT else tenant_hash(str(k))): v
                for k, v in (tenant_weights or {}).items()
            }
            # Cross-replica page sharing (serving/page_share.py):
            # `page_share` is the ROUTER url; the client reports
            # harvested chain keys there and pulls indexed pages
            # replica-to-replica. self_url is how peers reach THIS
            # replica — serve() fills it from host/port; tests binding
            # port 0 set client.self_url after the listener exists.
            self.page_share = None
            if page_share:
                from luminaai_tpu.serving.page_share import (
                    PageShareClient,
                )

                self.page_share = PageShareClient(
                    router_url=str(page_share),
                    self_url=page_share_self_url or "",
                    timeout_s=page_pull_timeout_s,
                    max_inflight=page_share_max_inflight,
                    registry=self.registry if telemetry else None,
                    recorder=self.recorder if telemetry else None,
                )
            self.batcher = ContinuousScheduler(
                engine,
                num_slots=num_slots,
                page_size=page_size,
                admission_window_ms=admission_window_ms,
                registry=self.registry,
                tracer=self.tracer,
                telemetry=telemetry,
                latency_buckets=latency_buckets,
                request_timeout_s=request_timeout_s,
                recorder=self.recorder,
                max_tenants=self.max_tenants,
                prefill_chunk_tokens=prefill_chunk_tokens,
                prefix_cache_pages=prefix_cache_pages,
                prefix_cache_tenant_quota=prefix_cache_tenant_quota,
                tenant_weights=weights,
                watchdog=self.watchdog,
                page_share=self.page_share,
            )
        else:
            self.watchdog = None
            self.page_share = None
            self.batcher = MicroBatcher(
                engine, max_batch=max_batch, window_ms=batch_window_ms,
                recorder=self.recorder, telemetry=telemetry,
            )
        # Build identity for fleet debugging (docs/observability.md):
        # which commit/jax/config answers this /metrics.
        register_build_info(self.registry, config=engine.config)
        # /healthz staleness: a wedged-but-alive process (decode loop
        # stuck inside a sync) keeps answering probes — with a stale
        # threshold set, a busy scheduler whose last decode tick is
        # older than this flips status to "degraded" (still 200) so
        # external probes catch it before the watchdog aborts.
        if healthz_stale_after_s is not None and not (
            float(healthz_stale_after_s) > 0
        ):
            # A falsy-zero check here would silently DISABLE the probe
            # the flag exists for; reject loudly instead.
            raise ValueError(
                "healthz_stale_after_s must be positive, got "
                f"{healthz_stale_after_s!r}"
            )
        self.healthz_stale_after_s = (
            float(healthz_stale_after_s)
            if healthz_stale_after_s is not None
            else None
        )
        # SLO layer (docs/observability.md "SLOs & burn rate"): windowed
        # registry history in a fixed-memory ring + burn-rate alerts
        # over the serve objectives (TTFT p95, decode p50, error rate),
        # targets from the engine's Config slo_* knobs (or a
        # --slo-config JSON override). GET /metrics/history and
        # GET /slo read these; `lumina top --url` draws them.
        self.history: Optional[TimeSeriesRing] = None
        self.slo: Optional[SLOEngine] = None
        cfg = engine.config
        if self.telemetry and slo and getattr(cfg, "slo", True):
            self.history, self.slo = build_slo_stack(
                cfg, registry=self.registry, recorder=self.recorder,
                program="serve", slo_config=slo_config,
            )
            self._installed_history = get_history() is None
            if self._installed_history:
                set_history(self.history)
            self.history.start()
        else:
            self._installed_history = False
        # Per-tenant token-bucket admission (rate_limiter.py): every
        # generation request costs one token from its tenant's bucket —
        # burst-tolerant, steady-state rate-bounded. Applies in _gate
        # whenever configured (secure or not; unauthenticated traffic
        # shares the anon tenant's bucket). Keys are ALWAYS hashed
        # tenants, never raw identities.
        self.tenant_bucket = None
        if tenant_rate_per_s:
            from luminaai_tpu.security.rate_limiter import (
                TokenBucketLimiter,
            )

            self.tenant_bucket = TokenBucketLimiter(
                rate_per_s=float(tenant_rate_per_s),
                burst=int(tenant_burst or max(1, int(tenant_rate_per_s))),
            )
        r = self.registry
        self._m_http = r.counter(
            "serve_http_requests_total",
            "HTTP requests by route and status code",
            labelnames=("route", "code"),
        )
        self._m_request = r.histogram(
            "serve_request_seconds",
            "Non-streaming generation request latency (parse to reply)",
            buckets=latency_buckets,
        )
        self._m_stream = r.histogram(
            "serve_stream_duration_seconds",
            "SSE stream duration (first event to close/abort)",
            buckets=latency_buckets,
        )
        self._m_tokens_out = r.counter(
            "serve_tokens_out_total", "Generated tokens returned to clients"
        )
        self._m_overload = r.counter(
            "serving_overload_rejections_total",
            "Generation requests shed with 503 + Retry-After because the "
            "admission queue was at max_queue_depth",
        )
        # Per-tenant request accounting (the substrate ROADMAP item 2's
        # fair-share admission prices QoS against). Bounded cardinality:
        # max_tenants distinct labels, then `_overflow`.
        tk = dict(labelnames=("tenant",), max_label_values=self.max_tenants)
        self._m_tenant_requests = r.counter(
            "tenant_requests_total",
            "Generation requests accepted for processing, per tenant",
            **tk,
        )
        self._m_tenant_tokens_in = r.counter(
            "tenant_tokens_in_total",
            "Prompt tokens submitted, per tenant", **tk,
        )
        self._m_tenant_tokens_out = r.counter(
            "tenant_tokens_out_total",
            "Generated tokens returned, per tenant", **tk,
        )
        self._m_tenant_shed = r.counter(
            "tenant_requests_shed_total",
            "Requests rejected 503 (drain or overload), per tenant", **tk,
        )
        r.gauge(
            "serve_ready",
            "1 once the engine is warmed and serving, 0 while compiling",
        ).set_function(
            weak_callback(self, lambda s: float(s._ready.is_set()))
        )
        r.gauge(
            "serve_draining",
            "1 while the server is draining (admissions stopped, in-flight "
            "generations finishing before shutdown)",
        ).set_function(
            weak_callback(self, lambda s: float(s._draining))
        )
        if warmup:
            threading.Thread(target=self._warmup, daemon=True).start()
        else:
            self._ready.set()
        # Streams bypass the MicroBatcher, so each holds its own KV cache
        # + decode loop on the device; unlike the single-worker batched
        # path they'd be unbounded without a cap (ThreadingHTTPServer is
        # thread-per-connection).
        self._stream_slots = threading.Semaphore(max(1, int(max_streams)))
        # Auth/limiter/counter state is shared across handler threads;
        # SecurityManager and RateLimiter are not thread-safe themselves.
        self.state_lock = threading.Lock()
        self.t0 = time.time()
        self.requests = 0
        self.tokens_out = 0
        self.max_new_tokens_cap = max_new_tokens_cap
        self.secure = secure
        self.security = None
        self.limiter = None
        self.validator = None
        if secure:
            from luminaai_tpu.security.auth import SecurityManager
            from luminaai_tpu.security.input_validator import InputValidator
            from luminaai_tpu.security.rate_limiter import RateLimiter

            self.security = SecurityManager(persist_path=users_path)
            self.limiter = RateLimiter()
            self.validator = InputValidator()
            if bootstrap_user is not None:
                user, password = bootstrap_user
                self.security.create_user(user, password)

    # -- readiness ---------------------------------------------------------
    def mark_ready(self) -> None:
        self._ready.set()

    # -- graceful shutdown (docs/resilience.md) ----------------------------
    def begin_drain(self) -> None:
        """Stop admitting generation requests. /healthz stays 200 (the
        process is healthy) but advertises `draining` in the body and the
        serve_draining gauge; in-flight lanes keep decoding to completion."""
        if not self._draining:
            self._draining = True
            if self.telemetry:
                self.recorder.emit(
                    "drain_started", queue_depth=self._queue_depth()
                )
            logger.warning(
                "drain started: new generations rejected, in-flight work "
                "finishing (queue_depth=%d)", self._queue_depth(),
            )

    def _idle(self) -> bool:
        idle = getattr(self.batcher, "idle", None)
        return bool(idle()) if callable(idle) else True

    def drain(self, timeout_s: Optional[float] = None) -> bool:
        """begin_drain + wait (bounded) for in-flight generations to
        finish. Returns True when the scheduler went idle inside the
        grace window; False means the deadline expired with lanes still
        active (the caller shuts down anyway — bounded beats hung)."""
        self.begin_drain()
        deadline = time.time() + (
            self.drain_grace_s if timeout_s is None else float(timeout_s)
        )
        idle = False
        while time.time() < deadline:
            if self._idle():
                logger.info("drain complete: scheduler idle")
                idle = True
                break
            time.sleep(0.05)
        if not idle:
            idle = self._idle()
            if not idle:
                logger.warning(
                    "drain grace expired with work still in flight; "
                    "shutting down anyway"
                )
        if self.telemetry:
            self.recorder.emit("drain_finished", idle=idle)
        # Crash forensics: the event trail survives the shutdown as a
        # flightrec-*.jsonl dump next to the checkpoints (lumina events
        # replays it; docs/observability.md "Flight recorder").
        self.dump_flight_record("drain")
        # The server is done serving: stop the watchdog's monitor thread
        # (Trainer.close does the same) — a drained server must not keep
        # a poller alive in embedding processes that cycle servers. The
        # history sampler stops for the same reason.
        if getattr(self, "watchdog", None) is not None:
            self.watchdog.close()
        if self.history is not None:
            self.history.stop()
            if self._installed_history and get_history() is self.history:
                set_history(None)
        return idle

    def dump_flight_record(self, reason: str) -> Optional[str]:
        """Dump the wide-event ring buffer into flight_dir (no-op without
        one), plus the time-series history when SLO retention is on
        (`lumina top <dir>` replays it). Never raises — it rides
        shutdown paths."""
        if not self.flight_dir:
            return None
        if self.history is not None:
            self.history.dump_to_dir(
                self.flight_dir, reason,
                slo=self.slo.verdicts() if self.slo is not None else None,
            )
        return self.recorder.dump_to_dir(self.flight_dir, reason)

    def _queue_depth(self) -> int:
        qd = getattr(self.batcher, "queue_depth", None)
        return int(qd()) if callable(qd) else 0

    def _shed(self):
        """Load-shedding gate for generation endpoints: draining servers
        and full admission queues answer 503 + Retry-After immediately
        instead of queuing unboundedly (clients retry against a replica)."""
        if self._draining:
            return 503, {
                "error": "server draining; retry against another replica",
                "retry_after": 2,
            }
        depth = self._queue_depth()
        if self.max_queue_depth and depth >= self.max_queue_depth:
            if self.telemetry:
                self._m_overload.inc()
            # Rough time-to-queue-space: a slot's worth of queued work.
            slots = getattr(self.batcher, "max_batch", None) or getattr(
                getattr(self.batcher, "decoder", None), "num_slots", 8
            )
            return 503, {
                "error": f"overloaded: admission queue at {depth}; "
                         "retry later",
                "retry_after": max(1, depth // max(1, int(slots or 8))),
            }
        return None

    def _effective_timeout(self, body: Dict[str, Any]) -> Optional[float]:
        """Per-request deadline: the request's timeout_s can only SHORTEN
        the server's request_timeout_s cap (a client must not be able to
        pin a lane past the operator's bound)."""
        cap = self.request_timeout_s
        t = body.get("timeout_s")
        try:
            t = float(t) if t is not None else None
        except (TypeError, ValueError):
            t = None
        if t is not None and t <= 0:
            t = None
        if t is None:
            return cap
        return min(t, cap) if cap else t

    def _warmup(self) -> None:
        """Compile-priming generation through the real batcher path (the
        same executables production requests hit), then open the /healthz
        gate. A warmup failure still opens the gate — a server that can
        answer SOME requests beats one a probe kills forever — but logs
        loudly and leaves the failure visible in the health payload."""
        self._warmup_error: Optional[str] = None
        t0 = time.time()
        try:
            encode = getattr(
                getattr(self.engine.tokenizer, "backend", None),
                "encode", None,
            )
            prompt = encode("warmup") if callable(encode) else [1, 2, 3]
            with self.tracer.span("warmup"):
                self.batcher.submit(
                    list(prompt) or [1],
                    {"max_new_tokens": 2, "temperature": 0.0},
                )
            logger.info("warmup generation done in %.1fs", time.time() - t0)
        except Exception as e:
            logger.exception("warmup generation failed; serving anyway")
            self._warmup_error = f"{type(e).__name__}: {e}"
        finally:
            self._ready.set()

    def _scheduler_state(self) -> Dict[str, Any]:
        """Live scheduler occupancy for /healthz and /stats consumers."""
        if self.continuous:
            st = self.batcher.stats()
            return {
                "scheduler": "continuous",
                "active_lanes": st.get("active_lanes", 0),
                "queue_depth": st.get("queue_depth", 0),
                "slots_free": st.get("kv_pool", {}).get("free"),
                "decode_steps": st.get("decode_steps", 0),
            }
        return {
            "scheduler": "micro_batch",
            "queue_depth": self.batcher.q.qsize(),
            "batches": self.batcher.batches,
        }

    def _staleness(self) -> Dict[str, Any]:
        """Liveness ages for /healthz: seconds since the scheduler's
        last decode tick and (when a trainer shares the process
        registry) since the last train step. `stale` is True only when
        a threshold is configured AND the process has work it is not
        advancing — an idle scheduler is quiet, not stale."""
        out: Dict[str, Any] = {}
        now = time.time()
        busy = False
        if self.continuous:
            last = getattr(self.batcher, "last_tick_ts", None)
            if last is not None:
                out["last_decode_tick_age_seconds"] = round(now - last, 3)
            st = self._scheduler_state()
            busy = bool(
                st.get("active_lanes") or st.get("queue_depth")
                or getattr(self.batcher, "_prefilling", None)
            )
        fam = self.registry.get("train_last_step_ts")
        if fam is not None:
            try:
                ts = float(fam.value)
            except (TypeError, ValueError):
                ts = float("nan")
            if ts == ts and ts > 0:  # NaN-safe: live train loop only
                out["last_step_age_seconds"] = round(now - ts, 3)
        thr = self.healthz_stale_after_s
        if thr:
            decode_stale = (
                busy
                and out.get("last_decode_tick_age_seconds") is not None
                and out["last_decode_tick_age_seconds"] > thr
            )
            train_stale = (
                out.get("last_step_age_seconds") is not None
                and out["last_step_age_seconds"] > thr
            )
            out["stale"] = bool(decode_stale or train_stale)
            out["stale_after_s"] = thr
        return out

    def history_route(
        self, seconds: Optional[float] = None,
        max_points: Optional[int] = None,
    ) -> tuple:
        """GET /metrics/history -> (status, payload): the ring's JSON
        snapshot. ONE implementation behind both entries — handle()
        (in-process, no query) and do_GET (parses ?seconds=&max_points=).
        Budget-guarded twice over: the ring's own capacity/series budget
        bounds the worst case, and the query params tighten a single
        response."""
        if self.history is None:
            return 404, {
                "error": "history ring disabled "
                         "(--no-slo or telemetry off)"
            }
        # Query values come off the wire: float() accepts nan/inf, and
        # int(nan) raises — a curl probe must get the full view, not a
        # handler traceback. Non-finite/non-positive -> unclamped.
        if seconds is not None and not (
            math.isfinite(float(seconds)) and seconds > 0
        ):
            seconds = None
        if max_points is not None:
            mp = float(max_points)
            max_points = (
                max(1, min(int(mp), 10_000))
                if math.isfinite(mp) and mp > 0
                else None
            )
        return 200, self.history.snapshot(
            window_s=seconds, max_points=max_points
        )

    def render_metrics(self) -> str:
        return self.registry.render_prometheus()

    # -- request handling --------------------------------------------------
    def handle(self, method: str, path: str, body: Dict[str, Any],
               token: Optional[str],
               request_id: Optional[str] = None) -> tuple:
        """Returns (status_code, payload dict). Pure-ish: no socket I/O.

        `request_id` is an inbound `X-Request-Id` (already validated by
        the HTTP handler): a fronting router minted it, and honoring it
        here means one id correlates the request across the router's and
        this replica's flight rings (`lumina events --request <id>`).
        Absent, we mint as before."""
        if method == "GET" and path == "/healthz":
            # Readiness (vs /health's liveness): 503 while the engine is
            # compiling/warming so orchestrators hold traffic, 200 with
            # scheduler occupancy once serving. The Dockerfile
            # HEALTHCHECK curls this route.
            if not self._ready.is_set():
                return 503, {
                    "status": "warming",
                    "uptime_s": round(time.time() - self.t0, 1),
                }
            # Draining stays 200: the process is healthy and finishing
            # in-flight work — a 5xx here would get it killed mid-drain.
            # Observers that care read `status` or the serve_draining
            # gauge (docker-compose.dev.yml's curl healthcheck tolerates
            # the drain window by construction). Staleness: ages since
            # the last decode tick / train step ride the body, and past
            # --healthz-stale-after a BUSY-but-silent process reports
            # "degraded" (still 200 — probes distinguish wedged from
            # dead; the watchdog owns aborting).
            status = "draining" if self._draining else "ok"
            out = {
                "uptime_s": round(time.time() - self.t0, 1),
                **self._scheduler_state(),
            }
            stale = self._staleness()
            out.update(stale)
            if status == "ok" and stale.get("stale"):
                status = "degraded"
            out["status"] = status
            warm_err = getattr(self, "_warmup_error", None)
            if warm_err:
                out["warmup_error"] = warm_err
            return 200, out
        if method == "GET" and path == "/slo":
            if self.slo is None:
                return 404, {
                    "error": "slo engine disabled "
                             "(--no-slo or telemetry off)"
                }
            return 200, self.slo.verdicts()
        if method == "GET" and path == "/metrics/history":
            return self.history_route()
        if method == "GET" and path == "/health":
            cfg = self.engine.config
            return 200, {
                "status": "ok",
                "uptime_s": round(time.time() - self.t0, 1),
                "model": {
                    "hidden_size": cfg.hidden_size,
                    "num_layers": cfg.num_layers,
                    "vocab_size": cfg.vocab_size,
                    "moe": bool(cfg.use_moe),
                },
                "secure": self.secure,
            }
        if method == "GET" and path == "/stats":
            out = {
                "requests": self.requests,
                "tokens_out": self.tokens_out,
                "uptime_s": round(time.time() - self.t0, 1),
                "batches": self.batcher.batches,
                "max_batch_seen": self.batcher.max_batch_seen,
                "scheduler": (
                    "continuous" if self.continuous else "micro_batch"
                ),
            }
            if self.continuous:
                out.update(self.batcher.stats())
            return 200, out
        if method == "POST" and path == "/v1/auth":
            if not self.secure:
                return 400, {"error": "server not in secure mode"}
            with self.state_lock:
                token = self.security.authenticate(
                    str(body.get("user", "")), str(body.get("password", ""))
                )
            if token is None:
                return 401, {"error": "authentication failed"}
            return 200, {"token": token}
        if method == "POST" and path in ("/v1/generate", "/v1/chat"):
            request_id = request_id or new_request_id()
            shed = self._shed()  # drain/overload: reject before auth work
            if shed is not None:
                self._count_shed(request_id, token, path)
                shed[1]["request_id"] = request_id
                return shed
            with self.state_lock:
                err, tenant = self._gate(body, token)
            if err is not None:
                return err
            return self._run_model(
                path, body, request_id=request_id, tenant=tenant
            )
        return 404, {"error": f"no route {method} {path}"}

    def _tenant_of(self, token: Optional[str]) -> str:
        """Tenant label outside the gate (shed accounting): hashed
        session identity or the shared anon tenant. One HMAC, no
        password work — cheap enough for the overload path."""
        if not self.secure or not token:
            return ANON_TENANT
        with self.state_lock:
            sess = self.security.validate_session(token)
        return tenant_hash(sess["username"]) if sess else ANON_TENANT

    def _count_shed(self, request_id: str, token: Optional[str],
                    route: str) -> None:
        # Same off switch as the scheduler's _event: telemetry off means
        # no accounting work at all (including the session-HMAC tenant
        # resolution), so the overhead A/B stays honest.
        if not self.telemetry:
            return
        tenant = self._tenant_of(token)
        self._m_tenant_shed.labels(tenant=tenant).inc()
        self.recorder.emit(
            "request_shed", request_id=request_id, tenant=tenant,
            route=route,
            reason="drain" if self._draining else "overload",
        )

    def _gate(self, body: Dict[str, Any], token: Optional[str]):
        """Admission checks: session token, per-tenant rate limiting,
        input validation. Returns (error_tuple | None, tenant_label) —
        the tenant is the hashed authenticated identity, so accounting,
        events AND limiter state never carry raw usernames.

        Two limiter layers compose here: the secure-mode sliding-window
        limiter (legacy request-count policy) and the optional per-tenant
        TOKEN BUCKET (--tenant-rate/--tenant-burst), which also applies
        to unauthenticated traffic via the shared anon tenant."""
        tenant = ANON_TENANT
        if self.secure:
            session = self.security.validate_session(token or "")
            if session is None:
                return (
                    (401, {"error": "missing or invalid token"}),
                    ANON_TENANT,
                )
            user = session.get("username", "anonymous")
            tenant = tenant_hash(user)
            # Limiter state is keyed by the HASHED tenant — the limiter's
            # bucket dict is introspectable (and dumpable in bug
            # reports), so raw identities must never appear in its keys.
            if not self.limiter.is_allowed(tenant, "chat"):
                return (429, {"error": "rate limit exceeded"}), tenant
        if self.tenant_bucket is not None and not self.tenant_bucket.allow(
            tenant
        ):
            retry = self.tenant_bucket.retry_after(tenant)
            return (
                429,
                {
                    "error": "tenant rate limit exceeded",
                    "retry_after": max(1, int(retry + 0.999)),
                },
            ), tenant
        if not self.secure:
            return None, tenant
        text = body.get("prompt") or body.get("message") or ""
        if not text and body.get("messages"):
            text = " ".join(
                str(m.get("content", "")) for m in body["messages"]
            )
        verdict = self.validator.validate_user_input(str(text))
        if not verdict.valid:
            return (400, {
                "error": f"input rejected: {'; '.join(verdict.errors)}"
            }), tenant
        return None, tenant

    # (name, clamp) — requests cannot push sampling params outside sane
    # bounds; max_new_tokens is capped so one request can't hold the decode
    # lock arbitrarily long (the rate limiter counts requests, not tokens).
    _OVERRIDE_CLAMPS = {
        "max_new_tokens": lambda v, cap: max(1, min(int(v), cap)),
        "temperature": lambda v, _: min(max(float(v), 0.0), 10.0),
        "top_p": lambda v, _: min(max(float(v), 0.0), 1.0),
        "top_k": lambda v, _: max(0, min(int(v), 10_000)),
        "repetition_penalty": lambda v, _: min(max(float(v), 0.5), 5.0),
    }

    def _parse_request(self, path: str, body: Dict[str, Any]):
        """Shared request parsing for the batched and streaming paths.

        Returns (error_tuple | None, prompt_ids, overrides, reply_key)."""
        overrides = {}
        for k, clamp in self._OVERRIDE_CLAMPS.items():
            if k in body:
                try:
                    overrides[k] = clamp(body[k], self.max_new_tokens_cap)
                except (TypeError, ValueError):
                    return (400, {"error": f"bad value for {k}"}), None, None, None
        if path == "/v1/chat":
            messages = body.get("messages")
            if not messages:
                msg = str(body.get("message", ""))
                if not msg:
                    return (400, {"error": "message(s) required"}), None, None, None
                messages = [{"role": "user", "content": msg}]
            for m in messages:
                if (
                    not isinstance(m, dict)
                    or not isinstance(m.get("role"), str)
                    or not isinstance(m.get("content"), str)
                ):
                    return (
                        400,
                        {
                            "error": "each message needs string "
                                     "'role' and 'content'"
                        },
                    ), None, None, None
            prompt_ids = self.engine.encode_chat(messages)
            reply_key = "reply"
        else:
            prompt = str(body.get("prompt", ""))
            if not prompt:
                return (400, {"error": "prompt required"}), None, None, None
            prompt_ids = self.engine.tokenizer.backend.encode(prompt)
            reply_key = "text"
        return None, prompt_ids, overrides, reply_key

    def _run_model(self, path: str, body: Dict[str, Any],
                   request_id: Optional[str] = None,
                   tenant: str = ANON_TENANT) -> tuple:
        t0 = time.time()
        request_id = request_id or new_request_id()
        err, prompt_ids, overrides, reply_key = self._parse_request(path, body)
        if err is not None:
            return err
        self._account_request(request_id, tenant, path, len(prompt_ids),
                              stream=False)
        if body.get("speculative"):
            out = self._run_speculative(
                prompt_ids, overrides, reply_key, t0,
                request_id=request_id, tenant=tenant,
            )
            if out is not None:
                return out
            # Not eligible (sampling params / engine support): fall
            # through to the batched path silently — speculation is an
            # accelerator hint, not a contract.
        # Concurrent requests with the same sampling params ride one
        # batched decode (MicroBatcher); sampling overrides go as generate
        # kwargs, so there is no config mutation to serialize.
        timeout_s = self._effective_timeout(body)
        # Identity riders ride BOTH schedulers' submit (each strips them
        # before its compile key / engine kwargs), so per-tenant series
        # and the flight trail stay honest on the --no-continuous
        # fallback path too. The deadline is a continuous-scheduler
        # contract (step-level eviction); MicroBatcher drops it.
        overrides = {
            **overrides, "request_id": request_id, "tenant": tenant,
        }
        if timeout_s:
            overrides["timeout_s"] = timeout_s
        try:
            tokens, stats = self.batcher.submit(prompt_ids, overrides)
        except RequestTimeout as e:
            return 504, {
                "error": str(e), "request_id": request_id, "tenant": tenant,
            }
        return self._reply_payload(
            tokens, stats, reply_key, t0,
            request_id=request_id, tenant=tenant,
        )

    def _account_request(self, request_id, tenant, route, prompt_tokens,
                         stream) -> None:
        """Per-tenant admission accounting + the request_received event
        (one choke point for the JSON and SSE paths). Rides the same
        off switch as the metrics."""
        if not self.telemetry:
            return
        self._m_tenant_requests.labels(tenant=tenant).inc()
        self._m_tenant_tokens_in.labels(tenant=tenant).inc(
            int(prompt_tokens)
        )
        self.recorder.emit(
            "request_received", request_id=request_id, tenant=tenant,
            route=route, stream=bool(stream),
            prompt_tokens=int(prompt_tokens),
        )

    def _reply_payload(self, tokens, stats, reply_key, t0,
                       request_id: Optional[str] = None,
                       tenant: Optional[str] = None, **extra) -> tuple:
        """Shared response building + stats booking for the batched and
        speculative generation paths."""
        out = {reply_key: self.engine.tokenizer.decode(tokens)}
        n_tok = int(stats.get("tokens_generated", 0))
        with self.state_lock:
            self.requests += 1
            self.tokens_out += n_tok
        if self.telemetry:
            self._m_request.observe(time.time() - t0)
            self._m_tokens_out.inc(n_tok)
            if tenant:
                self._m_tenant_tokens_out.labels(tenant=tenant).inc(n_tok)
        self.mark_ready()  # a served request is proof of readiness
        out.update(
            tokens=n_tok,
            latency_s=round(time.time() - t0, 3),
            stopped=stats.get("stopped"),
            **extra,
        )
        if request_id is not None:
            # Correlation contract: the id in this reply matches the
            # request's server-side events and /metrics tenant series.
            out["request_id"] = request_id
            out["tenant"] = tenant or ANON_TENANT
        return 200, out

    def _speculative_eligible(self, overrides) -> bool:
        """Whether a {"speculative": true} hint can be honored for these
        request params. Eligibility is judged on the RESOLVED params
        (config defaults fill omitted fields — a request without
        temperature usually samples): greedy, no repetition penalty.
        Shared by the JSON and SSE paths so the hint means one thing."""
        resolve = getattr(self.engine, "_resolve_gen_key", None)
        if resolve is None:
            return False
        key = resolve(
            overrides.get("max_new_tokens"),
            overrides.get("temperature"),
            overrides.get("top_p"),
            overrides.get("top_k"),
            overrides.get("repetition_penalty"),
        )
        return key[1] <= 0.0 and key[4] == 1.0

    def _run_speculative(self, prompt_ids, overrides, reply_key, t0,
                         request_id=None, tenant=None):
        """Greedy requests with {"speculative": true} run the engine's
        prompt-lookup speculative decode (exactly the greedy sequence,
        several tokens per device call on repetitive text). Single-stream
        like SSE, so it borrows the stream slot cap instead of the
        MicroBatcher; returns None when not eligible (sampling requested
        or the engine lacks the method) so the caller falls back."""
        if not hasattr(self.engine, "generate_speculative"):
            return None
        if not self._speculative_eligible(overrides):
            return None
        if not self._stream_slots.acquire(blocking=False):
            # All slots busy: fall back to the batched path rather than
            # erroring — the hint must never make a servable request fail.
            return None
        try:
            tokens, stats = self.engine.generate_speculative(
                prompt_ids,
                max_new_tokens=overrides.get("max_new_tokens"),
            )
        finally:
            self._stream_slots.release()
        return self._reply_payload(
            tokens, stats, reply_key, t0,
            request_id=request_id, tenant=tenant,
            speculative={
                "verify_calls": stats.get("verify_calls"),
                "tokens_per_verify": stats.get("tokens_per_verify"),
            },
        )

    # -- streaming (SSE) ---------------------------------------------------
    def start_stream(self, path: str, body: Dict[str, Any],
                     token: Optional[str],
                     request_id: Optional[str] = None):
        """Begin a streamed generation. Returns (error_tuple | None,
        events_generator | None). Streaming runs the engine's chunked
        decode directly (one stream per request thread) rather than the
        MicroBatcher — each stream owns its decode cadence; batched SSE
        would couple every client's latency to the slowest stream.
        An inbound `X-Request-Id` (router-minted) is honored like
        handle()'s, so stream events correlate across tiers."""
        request_id = request_id or new_request_id()
        shed = self._shed()  # drain/overload applies to streams too
        if shed is not None:
            self._count_shed(request_id, token, path)
            shed[1]["request_id"] = request_id
            return shed, None
        with self.state_lock:
            err, tenant = self._gate(body, token)
        if err is not None:
            return err, None
        if not self.continuous and not hasattr(
            self.engine, "generate_stream"
        ):
            return (501, {"error": "engine does not support streaming"}), None
        err, prompt_ids, overrides, reply_key = self._parse_request(path, body)
        if err is not None:
            return err, None
        self._account_request(request_id, tenant, path, len(prompt_ids),
                              stream=True)
        timeout_s = self._effective_timeout(body)
        if (
            body.get("speculative")
            and hasattr(self.engine, "generate_stream_speculative")
            and self._speculative_eligible(overrides)
            and self._stream_slots.acquire(blocking=False)
        ):
            # Greedy SSE with {"speculative": true}: the draft/verify
            # loop composes with the streaming contract — tokens arrive
            # in accepted-prefix bursts (engine
            # generate_stream_speculative). Single-stream like the JSON
            # speculative path, so it borrows the stream slot cap even
            # under the continuous scheduler; slots busy or sampled
            # params fall through to the normal stream — the hint never
            # makes a servable request fail. The per-request deadline
            # applies: speculative streams run outside the continuous
            # scheduler's overdue-lane eviction, so the engine's decode
            # loop enforces it instead (stopped='timeout').
            if timeout_s:
                overrides = {**overrides, "timeout_s": timeout_s}
            return None, _SlotStream(
                self._stream_events(
                    prompt_ids, overrides, reply_key, speculative=True,
                    request_id=request_id, tenant=tenant,
                ),
                self._stream_slots.release,
            )
        if self.continuous:
            # Identity riders for the scheduler's lifecycle events
            # (stripped before the compile key) + the deadline.
            overrides = {
                **overrides, "request_id": request_id, "tenant": tenant,
            }
            if timeout_s:
                overrides["timeout_s"] = timeout_s
            # Streams ride the shared continuous decode loop like any
            # other request — concurrency is bounded by the KV pool's
            # slots (excess queues), so the legacy per-stream slot cap
            # does not apply. Closing the generator cancels the lane.
            return None, self._stream_events(
                prompt_ids, overrides, reply_key,
                request_id=request_id, tenant=tenant,
            )
        if not self._stream_slots.acquire(blocking=False):
            return (
                503,
                {"error": "too many concurrent streams; retry shortly"},
            ), None
        return None, _SlotStream(
            self._stream_events(prompt_ids, overrides, reply_key,
                                request_id=request_id, tenant=tenant),
            self._stream_slots.release,
        )

    def _stream_events(self, prompt_ids, overrides, reply_key,
                       speculative: bool = False,
                       request_id: Optional[str] = None,
                       tenant: str = ANON_TENANT):
        """Yield SSE event dicts: {'token','delta'} per token, then a
        final {'done': True, <reply_key>: full_text, ...stats}.

        Deltas decode only the tokens since the last clean flush (O(1)
        amortized, not a full re-decode per token); a decode ending
        mid-codepoint (trailing U+FFFD from a split multi-byte char) is
        HELD — the empty delta is emitted now and the held tokens flush
        with the next clean boundary, so concatenated deltas reproduce
        the final text instead of baking replacement chars in. The done
        frame's text is authoritative (one decode of all tokens), and it
        carries a final 'delta' flushing any still-held tokens so the
        delta contract survives a stream that ENDS mid-codepoint.
        Aborted streams (client gone -> GeneratorExit) still count their
        streamed tokens into /stats via the finally block, which also
        releases the concurrency slot acquired in start_stream."""
        t0 = time.time()
        tok = self.engine.tokenizer
        tokens: List[int] = []
        base = 0  # tokens[:base] are flushed into deltas already
        counted = False
        stream_span = self.tracer.span("sse_stream", route=reply_key)
        span = stream_span.__enter__()

        def count(n: int) -> None:
            nonlocal counted
            if counted:
                return
            counted = True
            with self.state_lock:
                self.requests += 1
                self.tokens_out += n
            if self.telemetry:
                self._m_stream.observe(time.time() - t0)
                self._m_tokens_out.inc(n)
                if tenant:
                    self._m_tenant_tokens_out.labels(tenant=tenant).inc(n)
            span.set(tokens=n)
            self.mark_ready()

        # Continuous mode streams per-slot out of the shared scheduler
        # loop; legacy engines run their own chunked decode; speculative
        # greedy streams run the engine's draft/verify loop directly.
        # Every source honors the same contract (token ints, then a
        # stats dict).
        if speculative:
            src = self.engine.generate_stream_speculative(
                prompt_ids,
                max_new_tokens=overrides.get("max_new_tokens"),
                timeout_s=overrides.get("timeout_s"),
            )
        elif self.continuous:
            src = self.batcher.submit_stream(prompt_ids, overrides)
        else:
            src = self.engine.generate_stream(prompt_ids, **overrides)
        try:
            for item in src:
                if isinstance(item, dict):  # final stats yield
                    count(int(item.get("tokens_generated", 0)))
                    done_frame = {
                        "done": True,
                        reply_key: tok.decode(tokens),
                        # Flush tokens still held by the mid-codepoint
                        # delta hold (empty when the stream ended clean).
                        "delta": (
                            tok.decode(tokens[base:])
                            if base < len(tokens)
                            else ""
                        ),
                        "tokens": int(item.get("tokens_generated", 0)),
                        "latency_s": round(time.time() - t0, 3),
                        "stopped": item.get("stopped"),
                        # Correlation contract (docs/serving.md): the
                        # done frame carries the same id/tenant as the
                        # server-side events and /metrics series.
                        "request_id": (
                            request_id or item.get("request_id")
                        ),
                        "tenant": item.get("tenant", tenant),
                    }
                    if item.get("verify_calls") is not None:
                        # Speculative stream: the done frame carries the
                        # acceptance stats the JSON path reports.
                        done_frame["speculative"] = {
                            "verify_calls": item.get("verify_calls"),
                            "tokens_per_verify": item.get(
                                "tokens_per_verify"
                            ),
                        }
                    yield done_frame
                    return
                tokens.append(int(item))
                delta = tok.decode(tokens[base:])
                if delta and (
                    not delta.endswith("�")
                    # A genuinely invalid byte would hold forever — flush
                    # after 4 held tokens (a UTF-8 codepoint spans ≤4).
                    or len(tokens) - base >= 4
                ):
                    base = len(tokens)
                else:
                    delta = ""
                yield {"token": int(item), "delta": delta}
        except Exception as e:
            # Mid-stream failures (deadline eviction, decode error)
            # become a CORRELATABLE error frame — request_id + tenant —
            # instead of the handler's anonymous fallback frame. The
            # [DONE] terminator still follows from _reply_sse.
            # GeneratorExit (client gone) is BaseException: untouched.
            logger.warning("stream %s failed: %s", request_id, e)
            yield {
                "error": str(e),
                "request_id": request_id,
                "tenant": tenant,
            }
            return
        finally:
            count(len(tokens))
            stream_span.__exit__(None, None, None)
            close = getattr(src, "close", None)
            if close is not None:
                close()  # continuous: flags the lane cancelled

    # -- socket layer ------------------------------------------------------
    def export_page_by_key(self, key: str) -> Optional[bytes]:
        """Serve one cached page's framed bytes for a remote puller
        (GET /pages/<key>). None = not servable right now (not
        resident, bytes still in the deferred harvest queue, or no
        prefix cache) — the puller books a failure and degrades to
        local prefill, so refusing is always safe. The page is
        refcount-pinned across the device_get so eviction pressure
        cannot reassign its arena slot mid-serialization."""
        decoder = getattr(self.batcher, "decoder", None)
        cache = getattr(decoder, "prefix_cache", None)
        pool = getattr(decoder, "pool", None)
        if cache is None or pool is None or pool.caches is None:
            return None
        pid = cache.pin_key(key)
        if pid is None:
            return None
        try:
            if pid in getattr(decoder, "_queued_dst", ()):
                # Inserted but the bulk copy has not executed: the
                # arena bytes are still the previous occupant's.
                return None
            return pool.export_page(pid)
        except Exception:
            logger.exception("page export failed for %s", key[:16])
            return None
        finally:
            cache.release([pid])

    def make_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # route to logging, not stderr
                logger.info("%s %s", self.address_string(), fmt % args)

            _KNOWN_ROUTES = (
                "/", "/chat", "/health", "/healthz", "/metrics",
                "/metrics/history", "/slo", "/stats",
                "/v1/generate", "/v1/chat", "/v1/auth", "/pages",
            )

            def _count(self, code: int) -> None:
                if server.telemetry:
                    # Unknown paths collapse into one label value: a
                    # scanner probing random routes must not be able to
                    # mint unbounded label cardinality.
                    route = self.path.split("?", 1)[0]
                    if route.startswith("/pages/"):
                        # One label for every per-key page fetch.
                        route = "/pages"
                    elif route not in self._KNOWN_ROUTES:
                        route = "<other>"
                    server._m_http.labels(
                        route=route, code=str(code)
                    ).inc()

            def _reply(self, code: int, payload: Dict[str, Any]) -> None:
                self._count(code)
                data = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                if isinstance(payload, dict) and "retry_after" in payload:
                    # Overload/drain 503s carry the standard header so
                    # off-the-shelf clients and LBs back off correctly.
                    self.send_header(
                        "Retry-After", str(int(payload["retry_after"]))
                    )
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _reply_text(self, code: int, text: str,
                            content_type: str) -> None:
                self._count(code)
                data = text.encode()
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _token(self) -> Optional[str]:
                auth = self.headers.get("Authorization", "")
                return auth[7:] if auth.startswith("Bearer ") else None

            def _request_id(self) -> Optional[str]:
                # Inbound X-Request-Id (router-minted). Validated so a
                # hostile client can't inject log/JSONL garbage into two
                # tiers of flight rings; anything dubious is ignored and
                # the server mints its own as before.
                rid = self.headers.get("X-Request-Id", "")
                return rid if REQUEST_ID_RX.fullmatch(rid) else None

            def do_GET(self):
                # Health probes often add query strings (cache busting);
                # route on the bare path.
                path, _, query = self.path.partition("?")
                if path == "/metrics/history":
                    # Windowed-history query params (?seconds=&max_points=)
                    # parse here — handle() stays query-string-free; the
                    # route logic itself lives once, in history_route().
                    from urllib.parse import parse_qs

                    qs = parse_qs(query)

                    def _num(key):
                        try:
                            return float(qs[key][0]) if key in qs else None
                        except (TypeError, ValueError):
                            return None

                    self._reply(*server.history_route(
                        seconds=_num("seconds"),
                        max_points=_num("max_points"),
                    ))
                    return
                if path == "/metrics":
                    # Prometheus text exposition: the one non-JSON API
                    # route. Rendered outside handle() so a scrape can
                    # never be confused with a model request.
                    self._reply_text(
                        200,
                        server.render_metrics(),
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                    return
                if path in ("/", "/chat"):
                    # Built-in chat page (the ref's Electron app role —
                    # serving/webui.py). Static: auth gates the API calls
                    # the page makes, not the page itself.
                    from luminaai_tpu.serving.webui import PAGE

                    data = PAGE.encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "text/html; charset=utf-8"
                    )
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                    return
                if path.startswith("/pages/"):
                    # Cross-replica page export (serving/page_share.py).
                    # Raw framed bytes, not JSON: the payload is a KV
                    # page image, and the puller's parser validates the
                    # LPG1 frame itself.
                    key = path[len("/pages/"):]
                    if not PAGE_KEY_RX.fullmatch(key):
                        self._reply(404, {"error": "bad page key"})
                        return
                    payload = server.export_page_by_key(key)
                    if payload is None:
                        self._reply(404, {"error": "page not available"})
                        return
                    self._count(200)
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "application/octet-stream"
                    )
                    self.send_header("Content-Length", str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                    return
                code, payload = server.handle(
                    "GET", path, {}, self._token()
                )
                self._reply(code, payload)

            def _reply_sse(self, events) -> None:
                """Server-sent events: one `data: <json>` frame per event,
                closing with `data: [DONE]` (the OpenAI-style stream
                terminator clients already know how to parse)."""
                try:
                    # Header writes live INSIDE the try: a client gone
                    # before headers raises BrokenPipeError, and the
                    # handler below must still events.close() or the
                    # stream slot leaks (permanent 503s at the cap).
                    self._count(200)
                    self.send_response(200)
                    self.send_header("Content-Type", "text/event-stream")
                    self.send_header("Cache-Control", "no-cache")
                    self.send_header("Connection", "close")
                    self.end_headers()
                    for ev in events:
                        self.wfile.write(
                            b"data: " + json.dumps(ev).encode() + b"\n\n"
                        )
                        self.wfile.flush()
                    self.wfile.write(b"data: [DONE]\n\n")
                except (BrokenPipeError, ConnectionResetError):
                    logger.info("stream client disconnected")
                    events.close()  # stop decoding for a gone client
                except Exception as e:
                    # Headers are already sent: a raised-through error
                    # would make do_POST write a second status line into
                    # the open SSE body. Emit an error frame instead.
                    logger.exception("stream failed mid-flight")
                    try:
                        self.wfile.write(
                            b"data: "
                            + json.dumps({"error": str(e)}).encode()
                            + b"\n\ndata: [DONE]\n\n"
                        )
                    except OSError:
                        pass
                    events.close()

            def do_POST(self):
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    if n > MAX_BODY_BYTES:
                        self._reply(413, {"error": "body too large"})
                        return
                    body = json.loads(self.rfile.read(n) or b"{}")
                    if not isinstance(body, dict):
                        raise ValueError("body must be a JSON object")
                except (ValueError, json.JSONDecodeError) as e:
                    self._reply(400, {"error": f"bad request: {e}"})
                    return
                path = self.path.split("?", 1)[0]
                try:
                    with server.tracer.span(
                        "http_request", route=path,
                        stream=bool(body.get("stream")),
                    ):
                        if (
                            body.get("stream")
                            and path in ("/v1/generate", "/v1/chat")
                        ):
                            err, events = server.start_stream(
                                path, body, self._token(),
                                request_id=self._request_id(),
                            )
                            if err is not None:
                                self._reply(*err)
                            else:
                                self._reply_sse(events)
                            return
                        code, payload = server.handle(
                            "POST", path, body, self._token(),
                            request_id=self._request_id(),
                        )
                except Exception as e:  # surface as 500, keep serving
                    logger.exception("request failed")
                    code, payload = 500, {"error": str(e)}
                self._reply(code, payload)

        return Handler

    def serve_forever(self, host: str = "127.0.0.1", port: int = 5001):
        httpd = ThreadingHTTPServer((host, port), self.make_handler())

        def _graceful(sig, frame):  # pragma: no cover - signal-driven
            logger.warning(
                "signal %s: draining (grace %.0fs) before shutdown",
                sig, self.drain_grace_s,
            )

            def _stop():
                self.drain()
                httpd.shutdown()

            # shutdown() must not run on the serve_forever thread (it
            # joins the poll loop), and a signal handler must return fast.
            threading.Thread(target=_stop, daemon=True).start()

        import signal as _signal

        try:
            _signal.signal(_signal.SIGTERM, _graceful)
            _signal.signal(_signal.SIGINT, _graceful)
        except ValueError:  # pragma: no cover - non-main thread (tests)
            pass
        logger.info("serving on http://%s:%d (secure=%s)", host, port,
                    self.secure)
        try:
            httpd.serve_forever()
        finally:
            httpd.server_close()


def serve(
    checkpoint: Optional[str] = None,
    host: str = "127.0.0.1",
    port: int = 5001,
    secure: bool = False,
    bootstrap_user: Optional[tuple] = None,
    quantize: Optional[str] = None,
    adapter: Optional[str] = None,
    kv_cache_dtype: Optional[str] = None,
    num_slots: int = 8,
    page_size: int = 128,
    continuous: Any = "auto",
    admission_window_ms: float = 0.0,
    telemetry: bool = True,
    trace_jsonl: Optional[str] = None,
    trace_jax: bool = False,
    latency_buckets=None,
    request_timeout_s: Optional[float] = None,
    max_queue_depth: int = 128,
    drain_grace_s: float = 30.0,
    flight_dir: Optional[str] = None,
    max_tenants: int = 64,
    prefill_chunk_tokens: Optional[int] = None,
    prefix_cache_pages: Optional[int] = None,
    prefix_cache_tenant_quota: Optional[int] = None,
    tenant_rate_per_s: Optional[float] = None,
    tenant_burst: Optional[int] = None,
    watchdog: bool = True,
    watchdog_abort: bool = False,
    watchdog_k: Optional[float] = None,
    watchdog_floor_s: Optional[float] = None,
    slo: bool = True,
    slo_config: Optional[str] = None,
    healthz_stale_after_s: Optional[float] = None,
    page_share: Optional[str] = None,
    page_share_self_url: Optional[str] = None,
    page_pull_timeout_s: float = 2.0,
    page_share_max_inflight: int = 2,
):
    """Build an engine from a checkpoint and serve it (CLI `serve`)."""
    from luminaai_tpu.inference.chat import ChatInterface

    chat = ChatInterface(
        checkpoint_dir=checkpoint, quantize=quantize, adapter=adapter,
        kv_cache_dtype=kv_cache_dtype
    )
    if page_share and not page_share_self_url:
        # Peers reach this replica at the address it serves on; an
        # explicit --page-share-self overrides (NAT, name-based LBs).
        page_share_self_url = f"http://{host}:{port}"
    tracer = NULL_TRACER
    if trace_jsonl or trace_jax:
        tracer = SpanTracer(
            jsonl_path=trace_jsonl, use_jax_profiler=trace_jax
        )
    ChatServer(
        chat.engine, secure=secure, bootstrap_user=bootstrap_user,
        continuous=continuous, num_slots=num_slots, page_size=page_size,
        admission_window_ms=admission_window_ms,
        prefill_chunk_tokens=prefill_chunk_tokens,
        prefix_cache_pages=prefix_cache_pages,
        prefix_cache_tenant_quota=prefix_cache_tenant_quota,
        tenant_rate_per_s=tenant_rate_per_s,
        tenant_burst=tenant_burst,
        telemetry=telemetry,
        tracer=tracer,
        request_timeout_s=request_timeout_s,
        max_queue_depth=max_queue_depth,
        drain_grace_s=drain_grace_s,
        # Drain dumps the wide-event ring next to the checkpoint (or the
        # working dir) so a SIGTERM'd server leaves a queryable trail.
        flight_dir=flight_dir or checkpoint or ".",
        max_tenants=max_tenants,
        # Hang watchdog over the decode loop (--no-watchdog disables;
        # --watchdog-abort exits 75 on a confirmed stall so the
        # orchestrator restarts the replica; --watchdog-k/--watchdog-floor
        # tune the robust threshold).
        watchdog=("auto" if watchdog else None),
        watchdog_abort=watchdog_abort,
        watchdog_k=watchdog_k,
        watchdog_floor_s=watchdog_floor_s,
        # SLO engine + history ring (--no-slo disables; --slo-config
        # replaces the default objectives; --healthz-stale-after flips
        # /healthz to "degraded" on a busy-but-silent decode loop).
        slo=slo,
        slo_config=slo_config,
        healthz_stale_after_s=healthz_stale_after_s,
        # Cross-replica page sharing (--page-share <router-url>): the
        # replica reports harvested chain keys to the router and pulls
        # indexed pages from sibling replicas on cold admissions.
        page_share=page_share,
        page_share_self_url=page_share_self_url,
        page_pull_timeout_s=page_pull_timeout_s,
        page_share_max_inflight=page_share_max_inflight,
        latency_buckets=(
            tuple(latency_buckets)
            if latency_buckets
            else DEFAULT_LATENCY_BUCKETS
        ),
        # Real checkpoints compile for minutes: gate /healthz behind a
        # background warmup generation so probes hold traffic until the
        # executables exist.
        warmup=True,
    ).serve_forever(host, port)
