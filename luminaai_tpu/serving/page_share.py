"""Cross-replica KV page sharing: keying rule + page transfer client.

PR 19's router lands shared prompts on one replica via prefix-hash
affinity, but the radix prefix cache (PR 9/10) is process-local: on
failover or rebalance every other replica re-runs prefill from token
zero for pages the fleet already computed. This module is the host-side
plane that closes that gap (ROADMAP item 1, shared page index half).

Topology — ROUTER-CENTERED INDEX, DIRECT PAGE PULLS (the simpler of the
two topologies ISSUE 20 offers; gossip would add a membership protocol
for no extra information):

    replica A ──POST /pages/report──▶ router     (harvest landed: keys)
    replica B ──POST /pages/lookup──▶ router     (cold chain: who owns?)
    replica B ──GET  /pages/<key>───▶ replica A  (page bytes, framed)

The router only ever holds chain keys and owner URLs — never page
bytes — so the index is a few MB for tens of thousands of chains and
the bulk transfer goes replica-to-replica exactly once per pull. A
transfer failure is degraded to a local recompute by the puller
(`StepwiseDecoder._try_remote_pull`): a dead owner can cost at most one
pull deadline, never a wedged admission.

THE SHARED KEYING RULE (single source of truth; the router's affinity
hash and the cache's chain ownership both import it from here):

  The cache keys whole token pages with a hash chained over the prefix
  (inference/prefix_cache.page_chain_keys) — a partial tail page is
  never keyed. The router cannot tokenize (it is model-blind), so it
  mirrors the same shape at the character level: extract the request's
  prefix text (`prompt`, else the FIRST chat message — the system
  prompt, the stable shared prefix), NFKC-normalize, cap at the
  configured prefix budget, then keep only WHOLE
  `AFFINITY_BLOCK_CHARS`-char blocks, dropping the partial tail block.
  Two requests sharing a cached chain share at least one whole token
  page, hence (approximately) at least one whole char block, hence the
  same affinity key; a prompt too short to fill one block also has no
  cacheable chain, so it keys on its raw normalized text purely for
  load spread. Char blocks approximate token pages — the router hashes
  text, not tokens — which is exactly as aligned as a model-blind tier
  can be; the fleet page index (exact sha256 chain keys) is the
  authoritative owner map when they disagree.

Stdlib HTTP only, zero jax imports (same constraint as router.py). The
byte-fetch seam (`fetch_page`) is where testing/faults.py injects dead
and slow owners.
"""

from __future__ import annotations

import http.client
import json
import logging
import threading
import time
import unicodedata
import urllib.parse
from typing import Any, Dict, List, Optional, Sequence, Tuple

from luminaai_tpu.utils.retry import RetryPolicy

logger = logging.getLogger(__name__)

__all__ = [
    "AFFINITY_BLOCK_CHARS",
    "request_prefix_text",
    "affinity_key",
    "PageShareClient",
]

# Char-level analog of the cache's token page_size (pages are 16-64
# tokens in practice; one block ~ one short page of English text).
AFFINITY_BLOCK_CHARS = 64

# A page payload is page_size rows of every KV leaf — generously bounded
# so a confused owner can never balloon the puller's memory.
MAX_PAGE_PAYLOAD_BYTES = 64 * 1024 * 1024


def request_prefix_text(body: Dict[str, Any]) -> str:
    """The request text whose prefix identifies its cache chain:
    `prompt` verbatim, else the FIRST chat message (system prompt —
    the part shared across a template's requests), else `message`."""
    if "prompt" in body:
        return str(body.get("prompt", ""))
    msgs = body.get("messages")
    if isinstance(msgs, list) and msgs:
        return json.dumps(msgs[0], sort_keys=True, default=str)
    return str(body.get("message", ""))


def affinity_key(path: str, body: Dict[str, Any],
                 prefix_chars: int = 256) -> str:
    """Routing identity under the shared keying rule (module docstring).
    Whole-block truncation mirrors `page_chain_keys` never keying a
    partial tail page; sub-block prompts (uncacheable anyway) keep
    their raw text so short unrelated prompts still spread."""
    text = unicodedata.normalize(
        "NFKC", request_prefix_text(body)
    )[: max(0, int(prefix_chars))]
    whole = (len(text) // AFFINITY_BLOCK_CHARS) * AFFINITY_BLOCK_CHARS
    if whole > 0:
        text = text[:whole]
    return path + "\x00" + text


class PageShareClient:
    """One replica's handle on the fleet page plane.

    Owns the three replica-side conversations (report, lookup, fetch)
    plus their telemetry. All I/O is stdlib HTTP against injectable
    seams: `post_json` for the router control conversations and
    `fetch_page` for the owner byte pull (the faults.py injection
    point). Every failure mode degrades to "not shared": a dead router
    means cold admissions, never errors.

    `self_url` is how OTHER replicas reach this one — the advertised
    URL sent with reports and excluded from lookups. Servers binding
    port 0 set it after the listener exists.
    """

    def __init__(
        self,
        router_url: str,
        self_url: str = "",
        timeout_s: float = 2.0,
        max_inflight: int = 2,
        registry: Any = None,
        recorder: Any = None,
        retry: Optional[RetryPolicy] = None,
        clock=time.monotonic,
    ):
        self.router_url = str(router_url).rstrip("/")
        self.self_url = str(self_url).rstrip("/")
        self.timeout_s = max(0.05, float(timeout_s))
        self.recorder = recorder
        self._clock = clock
        # Pull concurrency bound: a replica mid-rebalance must not turn
        # into a page-transfer firehose; an admission that cannot take
        # a pull slot RIGHT NOW just prefills locally (non-blocking).
        self._inflight = threading.BoundedSemaphore(
            max(1, int(max_inflight))
        )
        # Per-page fetch retry, bounded by the overall pull deadline the
        # decoder enforces; transfer failure must never be worse than a
        # cache miss, so the ladder is short.
        self.retry = retry or RetryPolicy(
            max_attempts=2, base_delay_s=0.05, max_delay_s=0.2,
            timeout_s=self.timeout_s, registry=None, recorder=None,
        )
        # Counters survive a None registry as no-ops via _Null.
        self._m_pulls = _metric(
            registry, "counter", "serve_prefix_remote_pulls_total",
            "Remote page pulls attempted (per page)")
        self._m_pull_failures = _metric(
            registry, "counter",
            "serve_prefix_remote_pull_failures_total",
            "Remote page pulls that failed (per page; admission "
            "degraded to local prefill)")
        self._m_bytes = _metric(
            registry, "counter", "serve_page_transfer_bytes_total",
            "Bytes of KV page payload pulled from other replicas")
        self._m_pull_s = _metric(
            registry, "histogram", "serve_page_pull_seconds",
            "Per-page remote pull latency (fetch + parse)")
        self._m_reports = _metric(
            registry, "counter", "serve_page_reports_total",
            "Harvest ownership reports posted to the router")

    # -- low-level transport (stdlib; both methods are test seams) -------
    def post_json(
        self, base_url: str, path: str, body: Dict[str, Any],
        timeout_s: Optional[float] = None,
    ) -> Tuple[int, Dict[str, Any]]:
        u = urllib.parse.urlsplit(base_url)
        conn = http.client.HTTPConnection(
            u.hostname, u.port or 80,
            timeout=timeout_s or self.timeout_s,
        )
        try:
            conn.request(
                "POST", path, body=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            raw = resp.read()
            try:
                doc = json.loads(raw) if raw else {}
            except json.JSONDecodeError:
                doc = {}
            return resp.status, doc
        finally:
            conn.close()

    def get_bytes(
        self, base_url: str, path: str,
        timeout_s: Optional[float] = None,
    ) -> Tuple[int, bytes]:
        u = urllib.parse.urlsplit(base_url)
        conn = http.client.HTTPConnection(
            u.hostname, u.port or 80,
            timeout=timeout_s or self.timeout_s,
        )
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            return resp.status, resp.read(MAX_PAGE_PAYLOAD_BYTES + 1)
        finally:
            conn.close()

    # -- control plane ---------------------------------------------------
    def report(self, keys: Sequence[str]) -> bool:
        """Tell the router this replica owns these chain keys. Best
        effort: ownership is a hint, not a ledger — a lost report costs
        one missed sharing opportunity, so errors are swallowed."""
        keys = [str(k) for k in keys]
        if not keys or not self.self_url:
            return False
        try:
            status, _ = self.post_json(
                self.router_url, "/pages/report",
                {"replica": self.self_url, "keys": keys},
            )
        except OSError as e:
            logger.debug("page report failed: %s", e)
            return False
        if status == 200:
            self._m_reports.inc(len(keys))
            return True
        return False

    def report_async(self, keys: Sequence[str]) -> None:
        """report() off the scheduler tick (daemon thread): index
        freshness is worth zero decode latency."""
        keys = [str(k) for k in keys]
        if not keys or not self.self_url:
            return
        threading.Thread(
            target=self.report, args=(keys,),
            name="page-share-report", daemon=True,
        ).start()

    def lookup(
        self, keys: Sequence[str], have: int = 0
    ) -> Tuple[Optional[str], List[str]]:
        """Ask the router who owns this chain beyond the `have` pages
        already resident locally. Returns (owner url, covered prefix
        of `keys`) or (None, []) — on ANY failure the admission just
        proceeds cold."""
        keys = [str(k) for k in keys]
        if not keys:
            return None, []
        try:
            status, doc = self.post_json(
                self.router_url, "/pages/lookup",
                {"keys": keys, "have": int(have),
                 "exclude": self.self_url},
            )
        except OSError as e:
            logger.debug("page lookup failed: %s", e)
            return None, []
        if status != 200 or not doc.get("owner"):
            return None, []
        owned = [k for k in doc.get("keys", []) if isinstance(k, str)]
        # The owner's chain must be a prefix of ours — anything else is
        # a stale/garbled index entry and pulling it would splice the
        # wrong bytes.
        if owned != keys[: len(owned)]:
            return None, []
        return str(doc["owner"]).rstrip("/"), owned

    # -- pull slots ------------------------------------------------------
    def try_begin_pull(self) -> bool:
        """Non-blocking pull-slot acquire; False = at max_inflight, the
        caller treats the admission as a plain miss."""
        return self._inflight.acquire(blocking=False)

    def end_pull(self) -> None:
        self._inflight.release()

    # -- data plane ------------------------------------------------------
    def fetch_page(self, owner_url: str, key: str,
                   timeout_s: Optional[float] = None) -> bytes:
        """Pull ONE page's framed bytes from its owner. Raises OSError
        on transport failure / non-200 / oversize — the caller books
        the failure and falls back to local prefill. Fault injectors
        (`testing/faults.drop_page_pulls`) wrap exactly this method."""
        t = self.timeout_s if timeout_s is None else max(
            0.05, float(timeout_s)
        )
        t0 = self._clock()
        try:
            status, payload = self.retry.call(
                self.get_bytes, owner_url, f"/pages/{key}",
                timeout_s=t, op="page_pull",
            )
        except Exception:
            self._observe_pull(key, owner_url, t0, ok=False, nbytes=0)
            raise
        if status != 200:
            self._observe_pull(key, owner_url, t0, ok=False, nbytes=0)
            raise OSError(f"page owner answered {status} for {key[:16]}")
        if len(payload) > MAX_PAGE_PAYLOAD_BYTES:
            self._observe_pull(key, owner_url, t0, ok=False, nbytes=0)
            raise OSError("page payload exceeds size bound")
        self._observe_pull(key, owner_url, t0, ok=True,
                           nbytes=len(payload))
        return payload

    def _observe_pull(self, key: str, owner: str, t0: float,
                      ok: bool, nbytes: int) -> None:
        dt = max(0.0, self._clock() - t0)
        self._m_pulls.inc()
        if ok:
            self._m_bytes.inc(nbytes)
        else:
            self._m_pull_failures.inc()
        self._m_pull_s.observe(dt)
        if self.recorder is not None:
            self.recorder.emit(
                "page_pull", key=key[:16], owner=owner, ok=ok,
                bytes=nbytes, seconds=round(dt, 4),
            )


class _Null:
    """No-op metric stand-in for a None registry (telemetry off)."""

    def inc(self, amount: float = 1.0) -> None:
        pass

    def observe(self, value: float, count: int = 1) -> None:
        pass


def _metric(registry: Any, kind: str, name: str, help_text: str):
    if registry is None:
        return _Null()
    return getattr(registry, kind)(name, help_text)
