"""Built-in chat web UI, served at GET / by the HTTP server.

The reference ships an Electron desktop chat app (ref package.json
"lumina-ai-desktop"; its renderer talks to the Flask backend on :5001 —
docker-compose.dev.yml:12). The app's main.js/renderer sources are absent
from the reference repo, so the parity target is the CONTRACT: a chat
client over the HTTP backend. Here that's a single dependency-free HTML
page speaking the same /v1/chat endpoint — with SSE streaming, sampling
controls, and session stats — so `lumina serve` is a complete chat
deployment with zero extra installs (open the URL in any browser).
"""

PAGE = """<!doctype html>
<html lang="en">
<head>
<meta charset="utf-8">
<meta name="viewport" content="width=device-width, initial-scale=1">
<title>LuminaAI TPU Chat</title>
<style>
  :root { --bg:#101419; --panel:#1a2028; --text:#e6e9ee; --dim:#8a94a3;
          --accent:#4f9cf9; --user:#243247; --bot:#1f2733; }
  * { box-sizing: border-box; }
  body { margin:0; background:var(--bg); color:var(--text);
         font:15px/1.5 system-ui, sans-serif; display:flex;
         flex-direction:column; height:100vh; }
  header { padding:10px 16px; background:var(--panel);
           display:flex; gap:16px; align-items:center; }
  header h1 { font-size:16px; margin:0; }
  header .stat { color:var(--dim); font-size:12px; }
  #log { flex:1; overflow-y:auto; padding:16px; }
  .msg { max-width:72ch; margin:8px 0; padding:10px 14px;
         border-radius:10px; white-space:pre-wrap; }
  .user { background:var(--user); margin-left:auto; }
  .bot  { background:var(--bot); }
  .meta { color:var(--dim); font-size:11px; margin-top:4px; }
  form { display:flex; gap:8px; padding:12px 16px; background:var(--panel); }
  textarea { flex:1; resize:none; background:var(--bg); color:var(--text);
             border:1px solid #2a3340; border-radius:8px; padding:10px;
             font:inherit; height:52px; }
  button { background:var(--accent); border:0; color:#fff; padding:0 22px;
           border-radius:8px; font:inherit; cursor:pointer; }
  button:disabled { opacity:.5; cursor:default; }
  details { padding:4px 16px; background:var(--panel); color:var(--dim);
            font-size:13px; }
  details input { width:70px; background:var(--bg); color:var(--text);
                  border:1px solid #2a3340; border-radius:4px;
                  padding:2px 6px; margin:0 12px 0 4px; }
</style>
</head>
<body>
<header>
  <h1>LuminaAI TPU</h1>
  <span class="stat" id="model"></span>
  <span class="stat" id="speed"></span>
</header>
<div id="log"></div>
<details>
  <summary>sampling</summary>
  max_new_tokens <input id="maxnew" type="number" value="256">
  temperature <input id="temp" type="number" step="0.05" value="0.8">
  top_p <input id="topp" type="number" step="0.05" value="0.9">
</details>
<form id="f">
  <textarea id="box" placeholder="Message… (Enter to send)"></textarea>
  <button id="send" type="submit">Send</button>
</form>
<script>
const log = document.getElementById('log');
const box = document.getElementById('box');
const send = document.getElementById('send');
const history = [];
let token = sessionStorage.getItem('lumina_token') || null;

async function login() {
  // Secure-mode servers gate /v1/chat behind /v1/auth Bearer tokens.
  const user = prompt('username');
  if (user === null) return false;
  const pass = prompt('password');
  if (pass === null) return false;
  const r = await fetch('/v1/auth', {
    method: 'POST', headers: {'Content-Type': 'application/json'},
    body: JSON.stringify({user: user, password: pass}),
  });
  if (!r.ok) { alert('login failed'); return false; }
  token = (await r.json()).token;
  sessionStorage.setItem('lumina_token', token);
  return true;
}

fetch('/health').then(r => r.json()).then(h => {
  const m = h.model || {};
  document.getElementById('model').textContent =
    `${m.num_layers}L x ${m.hidden_size}h` + (m.moe ? ' MoE' : '');
}).catch(() => {});

function add(cls, text) {
  const d = document.createElement('div');
  d.className = 'msg ' + cls;
  d.textContent = text;
  log.appendChild(d);
  log.scrollTop = log.scrollHeight;
  return d;
}

async function chat(text) {
  history.push({role: 'user', content: text});
  add('user', text);
  const bot = add('bot', '');
  send.disabled = true;
  try {
    const body = {
      messages: history, stream: true,
      max_new_tokens: +document.getElementById('maxnew').value || 256,
      temperature: +document.getElementById('temp').value,
      top_p: +document.getElementById('topp').value,
    };
    const hdrs = {'Content-Type': 'application/json'};
    if (token) hdrs['Authorization'] = 'Bearer ' + token;
    let r = await fetch('/v1/chat', {
      method: 'POST', headers: hdrs, body: JSON.stringify(body),
    });
    if (r.status === 401) {          // secure mode: log in, retry once
      if (await login()) {
        hdrs['Authorization'] = 'Bearer ' + token;
        r = await fetch('/v1/chat', {
          method: 'POST', headers: hdrs, body: JSON.stringify(body),
        });
      }
    }
    if (!r.ok || !(r.headers.get('content-type') || '')
        .startsWith('text/event-stream')) {
      const err = await r.json().catch(() => ({}));
      bot.textContent = 'error: ' + (err.error || r.status);
      return;
    }
    const reader = r.body.getReader();
    const dec = new TextDecoder();
    let buf = '';
    for (;;) {
      const {done, value} = await reader.read();
      if (done) break;
      buf += dec.decode(value, {stream: true});
      let idx;
      while ((idx = buf.indexOf('\\n\\n')) >= 0) {
        const frame = buf.slice(0, idx); buf = buf.slice(idx + 2);
        if (!frame.startsWith('data: ')) continue;
        const data = frame.slice(6);
        if (data === '[DONE]') continue;
        const ev = JSON.parse(data);
        if (ev.error) { bot.textContent += '\\n[error: ' + ev.error + ']'; }
        else if (ev.done) {
          // The done frame's reply is authoritative (full decode).
          if (ev.reply !== undefined) bot.textContent = ev.reply;
          history.push({role: 'assistant', content: bot.textContent});
          const tps = ev.latency_s > 0
            ? (ev.tokens / ev.latency_s).toFixed(1) : '?';
          document.getElementById('speed').textContent =
            `${ev.tokens} tok in ${ev.latency_s}s (${tps} tok/s)`;
          const meta = document.createElement('div');
          meta.className = 'meta';
          meta.textContent = `${ev.tokens} tokens - ${ev.stopped}`;
          bot.appendChild(meta);
        } else if (ev.delta) {
          bot.textContent += ev.delta;
          log.scrollTop = log.scrollHeight;
        }
      }
    }
  } catch (e) {
    bot.textContent += '\\n[connection error: ' + e + ']';
  } finally {
    send.disabled = false;
    box.focus();
  }
}

document.getElementById('f').addEventListener('submit', e => {
  e.preventDefault();
  const t = box.value.trim();
  if (t) { box.value = ''; chat(t); }
});
box.addEventListener('keydown', e => {
  if (e.key === 'Enter' && !e.shiftKey) {
    e.preventDefault();
    document.getElementById('f').requestSubmit();
  }
});
box.focus();
</script>
</body>
</html>
"""
