"""Security subsystem: auth, sessions, rate limiting, input validation
(ref: Src/Main_Scripts/security/)."""

from luminaai_tpu.security.auth import (
    SecurityManager,
    Session,
    User,
    tenant_hash,
)
from luminaai_tpu.security.input_validator import (
    InputValidator,
    ValidationResult,
)
from luminaai_tpu.security.rate_limiter import (
    RateLimiter,
    SecureChatSession,
    TokenBucket,
    TokenBucketLimiter,
)

__all__ = [
    "SecurityManager",
    "tenant_hash",
    "Session",
    "User",
    "InputValidator",
    "ValidationResult",
    "RateLimiter",
    "SecureChatSession",
    "TokenBucket",
    "TokenBucketLimiter",
]
