"""Security subsystem: auth, sessions, rate limiting, input validation
(ref: Src/Main_Scripts/security/)."""

from luminaai_tpu.security.auth import SecurityManager, Session, User
from luminaai_tpu.security.input_validator import (
    InputValidator,
    ValidationResult,
)
from luminaai_tpu.security.rate_limiter import (
    RateLimiter,
    SecureChatSession,
)

__all__ = [
    "SecurityManager",
    "Session",
    "User",
    "InputValidator",
    "ValidationResult",
    "RateLimiter",
    "SecureChatSession",
]
