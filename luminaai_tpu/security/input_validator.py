"""User-input and conversation-payload validation.

Covers the reference InputValidator (ref: Src/Main_Scripts/security/
input_validator.py:17 — conversation/message/content checks, sanitization,
user-input screening). Additions specific to this framework: chat-template
smuggling detection — raw role tags like <|im_start|> inside user content
would let a user forge assistant/system turns in the token stream.
"""

from __future__ import annotations

import re
import unicodedata
from dataclasses import dataclass, field
from typing import Any, Dict, List

MAX_CONTENT_CHARS = 32_768
MAX_MESSAGES = 256
VALID_ROLES = ("system", "user", "assistant", "tool")

# Chat-template special tags must never arrive via user text.
_TEMPLATE_TAGS = re.compile(r"<\|[a-z_]+\|>", re.IGNORECASE)
# Control chars except \n\t\r.
_CONTROL = re.compile(r"[\x00-\x08\x0b\x0c\x0e-\x1f\x7f]")
# Crude script/injection probes (ref input_validator.py suspicious patterns).
_SUSPICIOUS = re.compile(
    r"(<script\b|javascript:|data:text/html|\beval\s*\(|\bexec\s*\()",
    re.IGNORECASE,
)


@dataclass
class ValidationResult:
    """(ref input_validator.py:9)"""

    valid: bool
    errors: List[str] = field(default_factory=list)
    warnings: List[str] = field(default_factory=list)
    sanitized: Any = None

    def merge(self, other: "ValidationResult") -> None:
        self.valid = self.valid and other.valid
        self.errors.extend(other.errors)
        self.warnings.extend(other.warnings)


class InputValidator:
    """Structural + content validation with sanitization (ref :17)."""

    def __init__(
        self,
        max_content_chars: int = MAX_CONTENT_CHARS,
        max_messages: int = MAX_MESSAGES,
        strip_template_tags: bool = True,
    ):
        self.max_content_chars = max_content_chars
        self.max_messages = max_messages
        self.strip_template_tags = strip_template_tags

    # -- conversations (ref :45) ------------------------------------------
    def validate_conversation(
        self, conversation: Dict[str, Any]
    ) -> ValidationResult:
        result = ValidationResult(valid=True)
        if not isinstance(conversation, dict):
            return ValidationResult(False, errors=["conversation not a dict"])
        msgs = conversation.get("messages")
        if not isinstance(msgs, list) or not msgs:
            return ValidationResult(False, errors=["missing/empty messages"])
        if len(msgs) > self.max_messages:
            result.valid = False
            result.errors.append(f"too many messages (> {self.max_messages})")
            return result
        sanitized_msgs = []
        for i, msg in enumerate(msgs):
            mr = self._validate_message(msg)
            if not mr.valid:
                mr.errors = [f"message {i}: {e}" for e in mr.errors]
            result.merge(mr)
            if mr.sanitized is not None:
                sanitized_msgs.append(mr.sanitized)
        result.sanitized = {**conversation, "messages": sanitized_msgs}
        return result

    def _validate_message(self, message: Any) -> ValidationResult:
        """(ref :86)"""
        if not isinstance(message, dict):
            return ValidationResult(False, errors=["not a dict"])
        role = message.get("role")
        if role not in VALID_ROLES:
            return ValidationResult(False, errors=[f"bad role {role!r}"])
        content = message.get("content")
        if not isinstance(content, str):
            return ValidationResult(False, errors=["content not a string"])
        cr = self._validate_content(content)
        if cr.sanitized is not None:
            cr.sanitized = {**message, "content": cr.sanitized}
        return cr

    def _validate_content(self, content: str) -> ValidationResult:
        """(ref :127)"""
        result = ValidationResult(valid=True)
        if len(content) > self.max_content_chars:
            result.valid = False
            result.errors.append(
                f"content too long ({len(content)} > {self.max_content_chars})"
            )
            return result
        if _TEMPLATE_TAGS.search(content):
            result.warnings.append("template tags stripped from content")
        if _SUSPICIOUS.search(content):
            result.warnings.append("suspicious pattern in content")
        result.sanitized = self.sanitize(content)
        return result

    # -- free-form user input (ref :172) ----------------------------------
    def validate_user_input(self, user_input: Any) -> ValidationResult:
        if not isinstance(user_input, str):
            return ValidationResult(False, errors=["input not a string"])
        if not user_input.strip():
            return ValidationResult(False, errors=["empty input"])
        return self._validate_content(user_input)

    # -- sanitization (ref :158) ------------------------------------------
    def sanitize(self, content: str) -> str:
        content = unicodedata.normalize("NFC", content)
        content = _CONTROL.sub("", content)
        if self.strip_template_tags:
            content = _TEMPLATE_TAGS.sub("", content)
        return content
