"""Request rate limiting + the secured chat wrapper.

Covers the reference RateLimiter and SecureConversationalChat (ref:
Src/Main_Scripts/security/rate_limiter.py:8,107 — sliding-window limits
per identifier/action with remaining/reset introspection; a chat facade
that requires authentication, validates every input, rate-limits message
traffic, and audit-logs the session).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from luminaai_tpu.security.auth import SecurityManager
from luminaai_tpu.security.input_validator import InputValidator

logger = logging.getLogger(__name__)

# action -> (max requests, window seconds) (ref rate_limiter.py:11)
DEFAULT_LIMITS: Dict[str, Tuple[int, float]] = {
    "chat_message": (30, 60.0),
    "login": (10, 60.0),
    "generate": (20, 60.0),
}


class RateLimiter:
    """Sliding-window limiter keyed by (identifier, action) (ref :8)."""

    def __init__(
        self, limits: Optional[Dict[str, Tuple[int, float]]] = None
    ):
        self.limits = dict(DEFAULT_LIMITS)
        if limits:
            self.limits.update(limits)
        self._events: Dict[Tuple[str, str], List[float]] = {}

    def _window(self, key: Tuple[str, str], window: float, now: float):
        events = [t for t in self._events.get(key, []) if now - t < window]
        self._events[key] = events
        return events

    def is_allowed(
        self,
        identifier: str,
        action: str,
        custom_limit: Optional[Tuple[int, float]] = None,
    ) -> bool:
        """(ref :25)"""
        limit, window = custom_limit or self.limits.get(action, (60, 60.0))
        now = time.time()
        key = (identifier, action)
        events = self._window(key, window, now)
        if len(events) >= limit:
            return False
        events.append(now)
        return True

    def get_remaining_requests(self, identifier: str, action: str) -> int:
        """(ref :47)"""
        limit, window = self.limits.get(action, (60, 60.0))
        events = self._window((identifier, action), window, time.time())
        return max(0, limit - len(events))

    def get_reset_time(self, identifier: str, action: str) -> Optional[float]:
        """Seconds until a blocked identifier can act again (ref :62)."""
        limit, window = self.limits.get(action, (60, 60.0))
        events = self._window((identifier, action), window, time.time())
        if len(events) < limit:
            return None
        return max(0.0, events[0] + window - time.time())

    def cleanup_old_buckets(self) -> int:
        """Drop empty windows; returns surviving bucket count (ref :75)."""
        now = time.time()
        for key in list(self._events):
            action = key[1]
            _, window = self.limits.get(action, (60, 60.0))
            if not self._window(key, window, now):
                del self._events[key]
        return len(self._events)


class TokenBucket:
    """One tenant's token bucket: capacity `burst`, refilled continuously
    at `rate_per_s`. The clock is INJECTED (defaults to time.monotonic)
    so refill timing is testable without sleeps and immune to wall-clock
    jumps."""

    def __init__(
        self,
        rate_per_s: float,
        burst: float,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.rate = float(rate_per_s)
        self.burst = float(burst)
        self.clock = clock
        self.tokens = float(burst)
        self._last = clock()

    def _refill(self) -> None:
        now = self.clock()
        elapsed = max(0.0, now - self._last)
        self._last = now
        self.tokens = min(self.burst, self.tokens + elapsed * self.rate)

    def allow(self, cost: float = 1.0) -> bool:
        self._refill()
        if self.tokens >= cost:
            self.tokens -= cost
            return True
        return False

    def retry_after(self, cost: float = 1.0) -> float:
        """Seconds until `cost` tokens will be available (0 if now)."""
        self._refill()
        missing = cost - self.tokens
        if missing <= 0:
            return 0.0
        return missing / max(self.rate, 1e-9)


class TokenBucketLimiter:
    """Per-tenant token-bucket admission for the serving gate
    (ChatServer._gate): one bucket per tenant LABEL. Callers must pass
    HASHED tenants (security.auth.tenant_hash) — bucket keys are
    introspectable state and raw identities must never appear in them
    (tier-1 contract-tested). Thread-safe: the server gates under its
    state lock, but /stats-style readers may race emitters."""

    def __init__(
        self,
        rate_per_s: float = 10.0,
        burst: int = 20,
        clock: Callable[[], float] = time.monotonic,
        max_buckets: int = 4096,
    ):
        self.rate = float(rate_per_s)
        self.burst = float(burst)
        self.clock = clock
        # Bounded state, same discipline LX009 enforces on tenant metric
        # labels: rotating identities must not grow server memory
        # without bound. At the cap, idle (fully-refilled) buckets are
        # swept first — dropping one is semantically a no-op, a fresh
        # bucket starts full anyway — then oldest-touched.
        self.max_buckets = max(1, int(max_buckets))
        self._buckets: Dict[str, TokenBucket] = {}
        self._lock = threading.Lock()

    def _bucket(self, tenant: str) -> TokenBucket:
        b = self._buckets.get(tenant)
        if b is None:
            if len(self._buckets) >= self.max_buckets:
                self._prune()
            b = self._buckets[tenant] = TokenBucket(
                self.rate, self.burst, clock=self.clock
            )
        return b

    def _prune(self) -> None:
        idle = [
            k for k, b in self._buckets.items()
            if b.tokens + (self.clock() - b._last) * b.rate >= b.burst
        ]
        for k in idle:
            del self._buckets[k]
        while len(self._buckets) >= self.max_buckets:
            oldest = min(self._buckets, key=lambda k: self._buckets[k]._last)
            del self._buckets[oldest]

    def allow(self, tenant: str, cost: float = 1.0) -> bool:
        with self._lock:
            return self._bucket(tenant).allow(cost)

    def retry_after(self, tenant: str, cost: float = 1.0) -> float:
        with self._lock:
            return self._bucket(tenant).retry_after(cost)

    def remaining(self, tenant: str) -> float:
        """Pure read: never allocates a bucket (an introspection call
        for an unseen tenant must not trigger the cap's prune and evict
        a live bucket). Unseen tenants report a full bucket — that is
        exactly what allow() would start them with."""
        with self._lock:
            b = self._buckets.get(tenant)
            if b is None:
                return self.burst
            b._refill()
            return b.tokens


class SecureChatSession:
    """Authenticated, validated, rate-limited chat facade (ref :107
    SecureConversationalChat).

    Wraps anything exposing `respond(text) -> (reply, stats)` — the
    ChatInterface, or a bare GenerationEngine adapter. All security
    decisions happen here so the inference stack stays policy-free.
    """

    def __init__(
        self,
        respond_fn: Callable[[str], Tuple[str, Dict[str, Any]]],
        security: Optional[SecurityManager] = None,
        rate_limiter: Optional[RateLimiter] = None,
        validator: Optional[InputValidator] = None,
    ):
        self.respond_fn = respond_fn
        self.security = security or SecurityManager()
        self.rate_limiter = rate_limiter or RateLimiter()
        self.validator = validator or InputValidator()
        self.stats = {"messages": 0, "rejected": 0}

    # -- account/session passthrough (ref :123,224,228) --------------------
    def create_user(self, username: str, password: str, permissions=None):
        return self.security.create_user(username, password, permissions)

    def authenticate(
        self, username: str, password: str, client_ip: str = ""
    ) -> Optional[str]:
        if not self.rate_limiter.is_allowed(client_ip or username, "login"):
            return None
        return self.security.authenticate(username, password, client_ip)

    def logout(self, token: str) -> bool:
        return self.security.logout(token)

    # -- the secured message path (ref :141) -------------------------------
    def secure_respond(
        self, user_input: str, session_token: str
    ) -> Dict[str, Any]:
        """Returns {ok, reply?, error?, stats?}. Order: session → permission
        → rate limit → validation → generate."""
        session = self.security.validate_session(session_token)
        if session is None:
            self.stats["rejected"] += 1
            return {"ok": False, "error": "invalid or expired session"}
        if not self.security.check_permission(session, "chat"):
            self.stats["rejected"] += 1
            return {"ok": False, "error": "permission denied"}
        user = session["username"]
        if not self.rate_limiter.is_allowed(user, "chat_message"):
            self.stats["rejected"] += 1
            reset = self.rate_limiter.get_reset_time(user, "chat_message")
            return {
                "ok": False,
                "error": "rate limit exceeded",
                "retry_after_sec": round(reset or 0.0, 1),
            }
        check = self.validator.validate_user_input(user_input)
        if not check.valid:
            self.stats["rejected"] += 1
            return {"ok": False, "error": "; ".join(check.errors)}
        reply, gen_stats = self.respond_fn(check.sanitized)
        self.stats["messages"] += 1
        return {
            "ok": True,
            "reply": reply,
            "stats": gen_stats,
            "warnings": check.warnings,
        }

    def get_security_status(self) -> Dict[str, Any]:
        """(ref :232)"""
        return {
            **self.security.get_security_status(),
            "session_stats": dict(self.stats),
        }
