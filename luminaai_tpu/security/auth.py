"""Authentication & session management.

Covers the reference SecurityManager (ref: Src/Main_Scripts/security/
auth.py:33 — salted password hashing, session tokens with expiry,
failed-attempt lockout, per-IP auth rate limiting, permission checks).
Design here: PBKDF2-HMAC-SHA256 with per-user salt, HMAC-signed opaque
session tokens (no server-side token table needed to reject forgeries),
monotonic-clock lockout windows, constant-time comparisons throughout.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import logging
import secrets
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

PBKDF2_ITERATIONS = 600_000
SALT_BYTES = 16
TOKEN_BYTES = 32

ANON_TENANT = "anon"


def tenant_hash(identity: Optional[str]) -> str:
    """Stable, non-reversible tenant label for telemetry and wide
    events: sha256 of the authenticated identity (username / API key),
    truncated to 12 hex chars. Raw identities must never become metric
    labels or event fields — /metrics and flightrec dumps travel to
    places the user database does not. None/empty (unauthenticated
    requests) map to the shared "anon" tenant."""
    if not identity:
        return ANON_TENANT
    return hashlib.sha256(str(identity).encode()).hexdigest()[:12]


@dataclass
class User:
    """Account record (ref auth.py:16)."""

    username: str
    password_hash: str
    salt: str
    permissions: List[str] = field(default_factory=lambda: ["chat"])
    created_at: float = field(default_factory=time.time)
    failed_attempts: int = 0
    locked_until: float = 0.0
    last_login: Optional[float] = None

    def to_dict(self) -> Dict[str, Any]:
        return dict(self.__dict__)


@dataclass
class Session:
    token_id: str
    username: str
    permissions: List[str]
    created_at: float
    expires_at: float
    client_ip: str = ""


class SecurityManager:
    """Users, sessions, lockout, auth-attempt rate limiting (ref auth.py:33)."""

    def __init__(
        self,
        max_failed_attempts: int = 5,
        lockout_seconds: float = 300.0,
        session_ttl_seconds: float = 3600.0,
        auth_rate_limit: int = 10,
        auth_rate_window: float = 60.0,
        min_password_length: int = 8,
        persist_path: Optional[str] = None,
        secret_key: Optional[bytes] = None,
    ):
        self.max_failed_attempts = max_failed_attempts
        self.lockout_seconds = lockout_seconds
        self.session_ttl = session_ttl_seconds
        self.auth_rate_limit = auth_rate_limit
        self.auth_rate_window = auth_rate_window
        self.min_password_length = min_password_length
        self.persist_path = Path(persist_path) if persist_path else None
        self._secret = secret_key or secrets.token_bytes(32)
        self.users: Dict[str, User] = {}
        self.sessions: Dict[str, Session] = {}
        self._auth_events: Dict[str, List[float]] = {}
        self.audit_log: List[Dict[str, Any]] = []
        if self.persist_path and self.persist_path.exists():
            self._load()

    # -- password primitives (ref auth.py:56) ------------------------------
    @staticmethod
    def _hash_password(password: str, salt: str) -> str:
        return hashlib.pbkdf2_hmac(
            "sha256", password.encode(), bytes.fromhex(salt),
            PBKDF2_ITERATIONS,
        ).hex()

    def _validate_username(self, username: str) -> bool:
        return (
            3 <= len(username) <= 64
            and username.replace("_", "").replace("-", "").isalnum()
        )

    def _validate_password(self, password: str) -> bool:
        if len(password) < self.min_password_length:
            return False
        has_alpha = any(c.isalpha() for c in password)
        has_digit = any(c.isdigit() for c in password)
        return has_alpha and has_digit

    # -- accounts (ref auth.py:69) -----------------------------------------
    def create_user(
        self,
        username: str,
        password: str,
        permissions: Optional[List[str]] = None,
    ) -> bool:
        if not self._validate_username(username):
            self._audit("create_user_rejected", username, "bad username")
            return False
        if not self._validate_password(password):
            self._audit("create_user_rejected", username, "weak password")
            return False
        if username in self.users:
            self._audit("create_user_rejected", username, "exists")
            return False
        salt = secrets.token_bytes(SALT_BYTES).hex()
        self.users[username] = User(
            username=username,
            password_hash=self._hash_password(password, salt),
            salt=salt,
            permissions=list(permissions or ["chat"]),
        )
        self._audit("user_created", username)
        self._save()
        return True

    # -- authentication (ref auth.py:98) -----------------------------------
    def authenticate(
        self, username: str, password: str, client_ip: str = ""
    ) -> Optional[str]:
        """Returns a session token, or None. Lockout and per-IP rate limits
        apply before any hash work (cheap rejection of brute force)."""
        now = time.time()
        if not self._check_auth_rate(client_ip or username, now):
            self._audit("auth_rate_limited", username, client_ip)
            return None
        user = self.users.get(username)
        if user is None:
            # Hash anyway: identical timing for unknown vs known users.
            self._hash_password(password, "00" * SALT_BYTES)
            self._audit("auth_failed", username, "unknown user")
            return None
        if user.locked_until > now:
            self._audit("auth_locked_out", username)
            return None
        expected = user.password_hash
        got = self._hash_password(password, user.salt)
        if not hmac.compare_digest(expected, got):
            user.failed_attempts += 1
            if user.failed_attempts >= self.max_failed_attempts:
                user.locked_until = now + self.lockout_seconds
                self._audit("account_locked", username)
            else:
                self._audit("auth_failed", username)
            self._save()
            return None
        user.failed_attempts = 0
        user.locked_until = 0.0
        user.last_login = now
        token = self._issue_token(user, client_ip, now)
        self._audit("auth_ok", username, client_ip)
        self._save()
        return token

    # -- sessions (ref auth.py:155,166,191) --------------------------------
    def _issue_token(self, user: User, client_ip: str, now: float) -> str:
        token_id = secrets.token_urlsafe(TOKEN_BYTES)
        sig = hmac.new(self._secret, token_id.encode(), "sha256").hexdigest()
        token = f"{token_id}.{sig}"
        self.sessions[token_id] = Session(
            token_id=token_id,
            username=user.username,
            permissions=list(user.permissions),
            created_at=now,
            expires_at=now + self.session_ttl,
            client_ip=client_ip,
        )
        return token

    def validate_session(self, token: str) -> Optional[Dict[str, Any]]:
        try:
            token_id, sig = token.rsplit(".", 1)
        except (ValueError, AttributeError):
            return None
        want = hmac.new(self._secret, token_id.encode(), "sha256").hexdigest()
        if not hmac.compare_digest(want, sig):
            self._audit("session_forged", token_id[:8])
            return None
        sess = self.sessions.get(token_id)
        if sess is None:
            return None
        if sess.expires_at < time.time():
            del self.sessions[token_id]
            self._audit("session_expired", sess.username)
            return None
        return {
            "username": sess.username,
            "permissions": sess.permissions,
            "expires_at": sess.expires_at,
        }

    def logout(self, token: str) -> bool:
        info = self.validate_session(token)
        if info is None:
            return False
        token_id = token.rsplit(".", 1)[0]
        self.sessions.pop(token_id, None)
        self._audit("logout", info["username"])
        return True

    def check_permission(
        self, session_info: Optional[Dict[str, Any]], required: str
    ) -> bool:
        """(ref auth.py:264)"""
        if not session_info:
            return False
        perms = session_info.get("permissions", [])
        return required in perms or "admin" in perms

    # -- auth rate limiting (ref auth.py:237) ------------------------------
    def _check_auth_rate(self, identifier: str, now: float) -> bool:
        window = [
            t for t in self._auth_events.get(identifier, [])
            if now - t < self.auth_rate_window
        ]
        window.append(now)
        self._auth_events[identifier] = window
        return len(window) <= self.auth_rate_limit

    # -- audit + persistence ----------------------------------------------
    def _audit(self, event: str, *details: str) -> None:
        entry = {"event": event, "details": details, "time": time.time()}
        self.audit_log.append(entry)
        logger.info("security: %s %s", event, details)

    def get_security_status(self) -> Dict[str, Any]:
        now = time.time()
        return {
            "users": len(self.users),
            "active_sessions": sum(
                1 for s in self.sessions.values() if s.expires_at > now
            ),
            "locked_accounts": sum(
                1 for u in self.users.values() if u.locked_until > now
            ),
            "audit_events": len(self.audit_log),
        }

    def _save(self) -> None:
        if self.persist_path is None:
            return
        self.persist_path.parent.mkdir(parents=True, exist_ok=True)
        data = {u.username: u.to_dict() for u in self.users.values()}
        self.persist_path.write_text(json.dumps(data, indent=1))

    def _load(self) -> None:
        try:
            data = json.loads(self.persist_path.read_text())
            self.users = {k: User(**v) for k, v in data.items()}
        except Exception as e:  # pragma: no cover - corrupted store
            logger.warning("user store unreadable (%s); starting empty", e)
