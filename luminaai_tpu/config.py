"""Configuration system for the LuminaAI TPU-native framework.

Covers the reference's config surface (ref: Src/Main_Scripts/config/config_manager.py:15
``Config``, :759 ``ConfigPresets``, :1871 ``ConfigManager``) re-designed for TPU:
the DeepSpeed/NCCL fields are replaced by a `jax.sharding.Mesh` axis layout
(data / fsdp / tensor / expert / sequence parallelism).
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

try:
    import yaml

    _HAS_YAML = True
except Exception:  # pragma: no cover
    _HAS_YAML = False

MOE_PATTERNS = ("all", "every_3rd", "every_4th", "sandwich", "none")
LR_SCHEDULES = ("cosine", "linear", "constant", "wsd")
PRECISIONS = ("auto", "fp32", "bf16", "mixed_bf16", "fp16", "mixed_fp16")


@dataclass
class Config:
    """Single source of truth for model + training + runtime configuration.

    Field groups mirror the reference Config (config_manager.py:15) with
    TPU-native parallelism fields replacing the DeepSpeed group.
    """

    # --- Model architecture ---
    vocab_size: int = 50304
    hidden_size: int = 512
    num_layers: int = 8
    num_heads: int = 8
    num_kv_heads: Optional[int] = 4
    seq_length: int = 1024
    intermediate_size: Optional[int] = None  # auto: 8/3 * hidden, rounded
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    dropout: float = 0.0
    tie_word_embeddings: bool = True
    use_stable_embedding: bool = True
    init_std: float = 0.02
    use_flash_attention: bool = True
    flash_block_q: int = 1024
    flash_block_kv: int = 1024
    # RoPE rotation math: 'fp32' (exact tables; costs an fp32 [B,S,H,D]
    # round-trip per q/k projection, ~70ms/step at flagship scale) or
    # 'bf16' (rotation in the compute dtype; inputs/outputs are bf16-
    # quantized either way, only the products round differently).
    rope_dtype: str = "fp32"
    # Decode KV cache storage: 'bf16' (compute dtype) or 'int8' (per
    # position/head symmetric codes + fp32 scales — halves cache HBM, so
    # max batch·context doubles; the dequant convert fuses into the
    # attention dots). Quantization happens at insert; prefill/decode
    # math is otherwise unchanged.
    kv_cache_dtype: str = "bf16"
    # Serving attention backend for the length-aware (LaneMeta) decode/
    # prefill paths — scalar-offset decode, batched per-lane decode over
    # the slot-paged pool, and chunked prefill all dispatch through it
    # (ops/ragged_paged_attention.py):
    #   'dense'      legacy full-extent per-lane masking (parity oracle);
    #   'ragged_xla' pure-XLA length-masked reference — the serving
    #                default: bit-identical to 'dense' on resident rows,
    #                and the decode step slices K/V to the resident page
    #                extent so decode cost scales with tokens resident,
    #                not pool capacity;
    #   'ragged'     Pallas page-table-native decode kernel when eligible
    #                (ragged_eligible), ragged_xla otherwise. Compiled on
    #                TPU, interpret mode on CPU (slow — use for parity
    #                tests, not CPU serving).
    # Rolling (windowed O(window)) caches always take the dense path —
    # their slot arithmetic is mod-C, which LaneMeta does not describe.
    attention_backend: str = "ragged_xla"
    # Chunked prefill: prompts prefill in fixed chunks of this many
    # tokens — ONE executable for every prompt length (instead of a
    # power-of-two bucket ladder), and the serving scheduler interleaves
    # chunks with decode steps so a long admission cannot stall the
    # decode batch for more than ~one chunk's step time. 0 disables
    # (legacy bucketed prefill). Engines with a rolling windowed cache
    # ignore it (chunk writes are only defined on non-wrapping layouts).
    prefill_chunk_size: int = 64
    # Radix prefix cache over the serving KV pool (inference/
    # prefix_cache.py): budget of content-hash-keyed arena pages shared
    # copy-on-write across lanes — admissions splice the longest cached
    # prompt-prefix page chain into their page table and prefill only
    # the uncached suffix. 0 disables. Requires a ragged attention
    # backend (the dense mask cannot follow cross-slot aliases; the
    # decoder gates the cache off under 'dense') and chunked prefill.
    prefix_cache_pages: int = 0
    # Max arena pages one tenant may own (0 = unbounded): a hot tenant
    # at quota evicts its OWN pages, never everyone else's.
    prefix_cache_tenant_quota: int = 0
    # Sliding-window (local) attention: each position attends to at most
    # the `attention_window` most recent positions (itself included).
    # None = full causal. The flash kernels skip whole blocks outside the
    # band, so long-context attention cost becomes O(S·W) instead of
    # O(S²); ring sequence parallelism masks/skips the same band across
    # shards; decode runs a ROLLING KV cache (slot = pos % C, C ≈ W) so
    # serving cache HBM is O(window) instead of O(max_context). A
    # TPU-first capability beyond the reference's surface (its attention
    # is always full causal).
    attention_window: Optional[int] = None

    # --- MoE ---
    use_moe: bool = False
    num_experts: int = 8
    moe_top_k: int = 2
    capacity_factor: float = 1.25
    load_balancing_weight: float = 0.01
    router_z_loss_weight: float = 1e-3
    routing_temperature: float = 1.0
    routing_noise_std: float = 0.1
    # Whole-expert dropout during training: each step a Bernoulli mask
    # removes experts from routing, forcing load to spread (anti-collapse;
    # ref trainer.py:1495 enable_expert_dropout). 0 disables.
    expert_dropout_rate: float = 0.0
    moe_pattern: str = "all"
    dense_start_layers: int = 2
    dense_end_layers: int = 2
    expert_output_scaling: float = 1.0
    # 'sort' = scatter/gather dispatch via flat slot ids (linear memory);
    # 'gather' = same routing, but the expert buffers are filled by a row
    # GATHER through an inverted slot→token index table (the H-wide scatter
    # moves to the backward pass — TPUs execute row gathers much better);
    # 'einsum' = GShard one-hot dispatch (O(S·E·C) memory, MXU-only data
    # movement — useful for A/B in bench_ops);
    # 'a2a' = cross-host expert parallelism: tokens shard over
    # (data, fsdp, expert) and are ROUTED to their experts' shards via
    # the hierarchical (ici-then-dcn) all-to-all subsystem
    # (parallel/expert_dispatch.py) — padding-free bucket payloads, no
    # full-activation psum; requires an 'expert' mesh axis.
    moe_dispatch: str = "sort"
    # a2a only: how much of the expert axis spans the DCN tier (hosts).
    # expert_parallel_size must be divisible; 1 = single-stage fallback
    # (everything on ICI). The two-stage exchange sends few large
    # rail-aligned DCN messages per X-MoE (docs/parallelism.md).
    expert_dcn_size: int = 1
    # a2a only: split the bucket payload into this many chunks so each
    # chunk's stage-2 (DCN) exchange is data-independent of the other
    # chunks' expert FFN — XLA's latency-hiding scheduler overlaps
    # comms with grouped-matmul compute. 1 disables.
    moe_a2a_overlap_chunks: int = 2
    # Internal: explicit expert-axis activation constraints in MoELayer.
    # The pipeline builders flip this off inside the manual-pipe region
    # (XLA partitioner group-check crash); everywhere else leave True.
    moe_ep_constraints: bool = True
    # Internal: manual expert parallelism — tokens sharded over the
    # 'expert' mesh axis, explicit tiled all-to-alls around the expert
    # FFN. Set by the 1F1B pipeline builders (auto-SPMD ep cannot
    # partition inside the manual-pipe region); requires being inside a
    # shard_map with a manual 'expert' axis.
    moe_manual_ep: bool = False
    # Internal: call the ring-attention body directly (no nested
    # shard_map) — set by the 1F1B pipeline builders when sp > 1; requires
    # a manual 'sequence' axis in scope.
    ring_manual: bool = False
    # Internal: manual axes tokens are sharded over inside the pipeline
    # region; MoE routing stats pmean over these so aux/z losses use
    # global fractions.
    moe_stat_pmean_axes: tuple = ()

    # --- MoD (mixture of depths) ---
    use_mod: bool = False
    mod_capacity_factor: float = 0.5
    mod_routing_temperature: float = 1.0

    # --- Training ---
    batch_size: int = 8  # global batch (sequences)
    micro_batch_size: Optional[int] = None  # per grad-accum slice; auto
    gradient_accumulation_steps: int = 1
    learning_rate: float = 3e-4
    weight_decay: float = 0.01
    beta1: float = 0.9
    beta2: float = 0.95
    eps: float = 1e-8
    grad_clip_norm: float = 1.0
    num_epochs: int = 3
    max_steps: Optional[int] = None
    warmup_ratio: float = 0.15
    lr_scheduler: str = "cosine"
    use_lr_scheduler: bool = True
    min_lr: float = 1e-6
    precision: str = "auto"  # auto|fp32|bf16|mixed_bf16|mixed_fp16
    inference_precision: str = "auto"
    # Weight-only inference quantization (training/quantization.py):
    # None | 'int8' | 'int4' (ref trainer.py:575 QuantizationManager).
    quantization_method: Optional[str] = None
    quantization_bits: int = 8
    gradient_checkpointing: bool = True
    # nothing_saveable = recompute everything (min HBM);
    # save_outs = store each block's attention/FFN outputs (2 x [B,S,H]
    #   bf16 per layer) so the backward recomputes only the branch being
    #   differentiated — most of dots_saveable's win at ~1% of its HBM;
    # dots_saveable = store every matmul output; full = no remat.
    remat_policy: str = "nothing_saveable"  # nothing_saveable|save_outs|save_attn|dots_saveable|full
    # Adam first-moment dtype: None = fp32; 'bf16' halves mu's HBM
    # (2 bytes/param) — nu stays fp32 (variance needs the exponent range).
    adam_mu_dtype: Optional[str] = None
    # 'int8': both Adam moments as int8 codes + row-wise scales (1B/param/
    # moment vs 4; ref trainer.py:771 create_quantized_optimizer).
    adam_state_quantization: Optional[str] = None
    scan_layers: bool = False  # lax.scan over layers (homogeneous stacks)
    # Degrade scan_layers instead of crashing when its first compile dies
    # in the backend's remote-compile helper (the on-chip
    # `remote_compile HTTP 500: tpu_compile_helper subprocess exit code 1`
    # class — scripts/repro_scan500.py is the root-cause ladder): the
    # trainer rebuilds the step with scan_layers=False, logs the failure,
    # and counts train_recompiles_total{reason="scan500_fallback"}. Only
    # engages at step 0 on a single-stage config (pipeline parallelism
    # REQUIRES the scanned layout, so there it re-raises).
    scan_compile_fallback: bool = True
    donate_state: bool = True
    eval_every_n_batches: int = 500
    save_every_n_batches: int = 1000
    assistant_loss_weight: float = 1.5
    z_loss_weight: float = 0.0
    label_smoothing: float = 0.0
    # Fuse the LM head matmul into the CE loss, chunked over the sequence —
    # full [B,S,V] logits never materialize (ops/fused.py). The single
    # biggest HBM saving at large vocab; disable only for debugging.
    fused_lm_head_ce: bool = True
    loss_chunk_size: int = 256

    # --- Parallelism (replaces ref DeepSpeed/FSDP/ColossalAI group) ---
    # Axis order = physical torus placement: trailing axes land on the
    # innermost ICI ring, so the chattiest collectives (tensor) go last.
    mesh_axes: tuple = ("data", "pipe", "fsdp", "expert", "sequence", "tensor")
    data_parallel_size: int = -1  # -1 = infer remaining devices
    # GPipe pipeline parallelism over the scanned layer stack
    # (parallel/pipeline.py): stage p holds layers [p*L/P, (p+1)*L/P).
    pipeline_parallel_size: int = 1
    pipeline_microbatches: Optional[int] = None  # auto: = pipe size
    # '1f1b': fused fwd+bwd schedule, per-stage live activations bounded by
    # ~2P regardless of microbatch count (the PipeDream-flush memory
    # profile); 'gpipe': all-forward-then-autodiff (simpler, more live
    # activations — A/B and eval path).
    pipeline_schedule: str = "1f1b"
    fsdp_parallel_size: int = 1
    expert_parallel_size: int = 1
    tensor_parallel_size: int = 1
    sequence_parallel_size: int = 1
    use_ring_attention: bool = False  # required when sequence_parallel_size > 1
    # --- Gradient reduction across the data/fsdp axes ---------------------
    # 'flat' = whatever GSPMD emits: implicit all-reduces at full fp32
    # width, re-issued wherever the partitioner places them (invisible to
    # the comms auditor, and under grad accumulation free to psum inside
    # the scan). 'hierarchical' = the explicit shard_map gradient-sync
    # stage (parallel/grad_reduce.py): gradients accumulate shard-locally
    # in fp32 through the whole accumulation scan, then ONE post-scan
    # sync flattens them into size-bucketed chunks, reduce-scatters over
    # the ici tier, crosses DCN once per bucket, and all-gathers back —
    # the Scalable-pjit / X-MoE two-tier cure for cross-host reduction
    # (docs/parallelism.md "Hierarchical gradient reduction").
    grad_reduce: str = "flat"
    # hierarchical only: how much of the DATA axis spans the DCN tier
    # (hosts). data_parallel_size must be divisible; 1 = single-stage
    # fallback (one explicit reduce-scatter/all-gather, everything on
    # ICI). Mirrors expert_dcn_size for the a2a expert dispatch.
    gradient_dcn_size: int = 1
    # hierarchical only: target bucket size for the flattened-gradient
    # chunks. Smaller buckets start crossing DCN earlier (more overlap
    # with the optimizer's wait), bigger buckets amortize latency.
    grad_reduce_bucket_mb: float = 32.0
    # hierarchical only: minimum number of buckets, so bucket k's DCN
    # hop is data-independent of bucket k-1's all-gather and XLA's
    # latency-hiding scheduler overlaps them. 1 disables the floor
    # (bucket count then comes from grad_reduce_bucket_mb alone).
    grad_reduce_overlap_chunks: int = 2
    # hierarchical only: None = fp32 end to end; 'bf16' compresses the
    # DCN hop only (in-host accumulation stays fp32 — each shard's
    # scattered chunk is already the full in-host sum before it is cast
    # down). Parity-gated: the fp32 default is loss-trajectory-exact vs
    # the implicit path, bf16-over-DCN trades that for half the DCN
    # bytes (tests/test_grad_reduce.py pins both behaviours).
    grad_reduce_dcn_dtype: Optional[str] = None
    allow_split_physical_axes: bool = False
    multihost: bool = False  # call jax.distributed.initialize()
    coordinator_address: Optional[str] = None
    process_id: Optional[int] = None
    num_processes: Optional[int] = None

    # --- Data ---
    train_data_path: str = "data/train.jsonl"
    eval_data_path: str = "data/eval.jsonl"
    tokenizer_name: str = "byte"  # byte|bpe:PATH|tiktoken:NAME|hf:NAME
    num_workers: int = 2
    max_conversations_per_file: int = 10000
    streaming_threshold_gb: float = 10.0
    prefetch_batches: int = 2
    pack_sequences: bool = True
    use_native_dataloader: bool = True  # C++ memmap packer when available

    # --- Generation ---
    max_new_tokens: int = 512
    temperature: float = 0.8
    top_p: float = 0.9
    top_k: int = 50
    repetition_penalty: float = 1.05

    # --- Production / experiment ---
    experiment_name: Optional[str] = None
    output_dir: str = "experiments"
    # Capture a jax.profiler device trace (TensorBoard XPlane) for steps
    # [profile_start_step, profile_start_step + profile_num_steps) into
    # profile_dir (default output_dir/profile). 0 disables (SURVEY §5
    # tracing). After the window closes the trainer runs the attribution
    # classifier (monitoring/attribution.py) over the trace and exports
    # the per-subsystem breakdown as registry gauges + attribution.jsonl.
    profile_start_step: int = 0
    profile_num_steps: int = 3
    profile_dir: Optional[str] = None
    # AOT-query XLA's cost model for the compiled train step at first
    # compile (compiled_flops_per_step / bytes_accessed / HBM-footprint
    # gauges + the analytic-vs-compiled MFU cross-check). Off by default:
    # the AOT lower+compile is a second compile of the step program
    # (cheap only where the persistent compile cache is warm).
    compiled_cost_analysis: bool = False
    seed: int = 42
    log_level: str = "INFO"
    save_total_limit: int = 5
    early_stopping_patience: Optional[int] = None
    auto_resume: bool = True
    backup_every_n_hours: int = 6
    max_retries: int = 3
    enable_wandb: bool = False
    wandb_project: Optional[str] = None
    wandb_entity: Optional[str] = None

    # --- Monitoring / fault tolerance ---
    health_check_interval: int = 100
    loss_spike_threshold: float = 2.0
    grad_norm_threshold: float = 100.0
    expert_collapse_threshold: float = 0.05
    # Goodput ledger + hang watchdog + step-time anomaly sentinel
    # (docs/observability.md "Goodput & sentinels"). The ledger
    # attributes every second of the run to a cause and exports
    # training_goodput_fraction; the watchdog heartbeats at the
    # log-window sync and fires when a beat gap exceeds
    # watchdog_k x (rolling median + MAD), floored at watchdog_floor_s
    # — warmup-aware, so the first compile can never trip it. All
    # host-side wall clock: zero new syncs on the step path.
    goodput: bool = True
    watchdog: bool = True
    watchdog_k: float = 10.0
    watchdog_floor_s: float = 30.0
    watchdog_warmup: int = 3
    watchdog_poll_s: float = 1.0
    # Opt-in (--watchdog-abort): a confirmed stall exits 75 (resumable)
    # after dumping stacks + the flight ring, so orchestrators restart
    # the job instead of burning the reservation on a wedged sync.
    watchdog_abort: bool = False
    # Step-time anomaly sentinel: a logged window mean flagged when it
    # exceeds step_anomaly_k x rolling median (+ MAD significance
    # guard). step_anomaly=False silences a known-noisy workload
    # (no gauges, no events).
    step_anomaly: bool = True
    step_anomaly_k: float = 4.0
    # --- SLO engine (docs/observability.md "SLOs & burn rate") ---
    # A background sampler retains windowed history of the registry in a
    # fixed-memory ring (counters as deltas, histograms as windowed
    # quantiles) and the SLO engine judges declarative objectives over
    # fast/slow windows with Google-SRE burn-rate rules: a fast-window
    # burn >= slo_fast_burn pages, a slow-window burn >= slo_slow_burn
    # warns, transitions land in the flight recorder as slo_burn events.
    # slo_config points at a JSON file REPLACING the default objectives.
    # All host-side: zero new syncs on the step path.
    slo: bool = True
    slo_sample_interval_s: float = 5.0
    slo_ring_points: int = 720       # per series (~1h at the default 5s)
    slo_max_series: int = 256        # hard series budget (then _overflow)
    slo_fast_window_s: float = 60.0
    slo_slow_window_s: float = 600.0
    slo_fast_burn: float = 10.0
    slo_slow_burn: float = 2.0
    slo_budget: float = 0.1          # allowed violating-sample fraction
    # Default objective targets (objectives_for builds them from these):
    slo_ttft_p95_s: float = 2.0      # serve: p95 time-to-first-token
    slo_decode_p50_s: float = 0.5    # serve: median per-token latency
    slo_error_rate: float = 0.05     # serve: shed+timeout / admissions
    slo_goodput_fraction: float = 0.5  # train: productive/elapsed floor
    slo_step_time_factor: float = 2.0  # train: p95 vs rolling median
    slo_config: Optional[str] = None   # JSON override (--slo-config)
    # --- Durable I/O (docs/resilience.md "Durable I/O") ---
    # Storage ops (checkpoint save/restore, manifest writes, data opens/
    # reads) retry transient faults with exponential backoff + jitter:
    # io_retries total attempts per op, delays io_retry_base_s doubling
    # up to io_retry_max_s, the whole op bounded by io_timeout_s when
    # set. Retry waits accrue to the already-open goodput cause
    # (checkpoint / data_wait).
    io_retries: int = 4
    io_retry_base_s: float = 0.05
    io_retry_max_s: float = 2.0
    io_timeout_s: Optional[float] = None
    # Checkpoint integrity: restore verifies each step's sha256 manifest
    # — 'full' hashes every file, 'sample' hashes a deterministic subset
    # (sizes always checked; the fast mode for huge checkpoints), 'off'
    # disables. A mismatch walks back like any corrupt checkpoint.
    checkpoint_verify: str = "full"
    # Emergency saves fall back to this local directory when the primary
    # checkpoint dir is unwritable (None disables the tier).
    checkpoint_local_tier: Optional[str] = None
    # Degraded-mode data loading: corrupt/truncated records are
    # quarantined (counted + flight-evented, run continues) instead of
    # raising; a quarantine rate above the fence aborts so silent data
    # loss can't masquerade as health.
    data_quarantine: bool = True
    data_quarantine_max_rate: float = 0.05
    # --- Serving-plane router (docs/serving.md "Replica router") ---
    # The data-plane router fronting N ChatServer replicas
    # (serving/router.py): health probes every router_probe_interval_s;
    # a replica's circuit breaker opens after router_breaker_failures
    # consecutive failures (or the error-rate threshold) and re-probes
    # half-open after router_breaker_cooldown_s; a failed dispatch
    # retries on up to router_max_failovers other candidates with
    # backoff+jitter. Hedged dispatch (opt-in, `lumina route --hedge`)
    # fires a second replica for short (< router_hedge_max_tokens)
    # non-stream requests after a p95-based delay, capped at
    # router_hedge_budget of non-stream traffic.
    router_probe_interval_s: float = 2.0
    router_breaker_failures: int = 3
    router_breaker_cooldown_s: float = 5.0
    router_max_failovers: int = 2
    router_hedge_budget: float = 0.1
    router_hedge_max_tokens: int = 32
    # Cross-replica KV page sharing (docs/serving.md "Cross-replica
    # prefix sharing"): replicas report harvested prefix-chain keys to
    # the router's page index and pull indexed pages directly from the
    # owning sibling on a cold admission. page_share enables the plane
    # (the serve CLI takes the router URL); page_pull_timeout_s bounds
    # one whole pull (lookup + transfers) before degrading to local
    # prefill; page_share_max_inflight caps concurrent pulls per
    # replica so transfers can't starve the decode loop.
    page_share: bool = False
    page_pull_timeout_s: float = 2.0
    page_share_max_inflight: int = 2

    # --- Adaptive control (orchestrator) ---
    enable_adaptive_lr: bool = True
    allow_scheduler_override: bool = True
    min_override_threshold: float = 0.2
    emergency_override_enabled: bool = True
    log_lr_decisions: bool = True
    enable_architecture_evolution: bool = False
    # Runtime capacity-factor / routing-temperature tuning (each change
    # recompiles the step; ref trainer.py:1450,1471).
    enable_moe_routing_optimization: bool = True
    # Orchestrator may raise AdamW weight decay on a slow sustained loss
    # rise (ref trainer.py:1792 adjust_weight_decay's adaptive role).
    enable_adaptive_wd: bool = True
    # Gradient-noise-driven effective-batch growth (recompiles + reshapes
    # the data contract; opt-in; ref trainer.py:1626).
    enable_batch_size_optimization: bool = False
    # Phase-scheduled MoD compute ratio (ref Main.py mod_capacity_adaptation
    # + trainer.py:1559 adjust_mod_capacity): spend more FFN compute early
    # in training, taper as the model converges. Total steps split into
    # len(schedule) equal phases; each change recompiles the step.
    enable_mod_capacity_adaptation: bool = False
    mod_capacity_schedule: tuple = (0.7, 0.5, 0.3)
    # Learning-velocity curriculum (ref chinchilla_scaler.py:155
    # AdaptiveCurriculumManager): the orchestrator tracks per-step loss
    # reduction and forwards the recommended difficulty to any data loader
    # exposing set_difficulty (PackedDataset maps it to a doc-length
    # quantile; takes effect at the next epoch restart).
    enable_adaptive_curriculum: bool = False
    intervention_cooldown_steps: int = 200

    # --- Chinchilla scaling ---
    use_chinchilla_scaling: bool = False
    tokens_per_param: float = 20.0
    convergence_patience: int = 5

    # --- Memory ---
    max_memory_usage: float = 0.9
    host_offload_optimizer: bool = False  # ref cpu_offload_* analogue

    def __post_init__(self):
        # yaml/json roundtrips turn tuples into lists; normalize back so
        # to_dict() comparisons and static hashing stay stable.
        self.moe_stat_pmean_axes = tuple(self.moe_stat_pmean_axes)
        if self.num_kv_heads is None:
            self.num_kv_heads = self.num_heads
        if self.intermediate_size is None:
            # SwiGLU sizing: 8/3 * hidden, rounded up to a multiple of 128
            # (MXU lane width) — ref auto-calcs 4*hidden for plain FFN.
            raw = int(8 * self.hidden_size / 3)
            self.intermediate_size = ((raw + 127) // 128) * 128
        if self.micro_batch_size is None:
            self.micro_batch_size = max(
                1, self.batch_size // max(1, self.gradient_accumulation_steps)
            )
        elif (
            self.gradient_accumulation_steps == 1
            and 0 < self.micro_batch_size < self.batch_size
        ):
            # Explicit micro_batch_size drives the in-jit accumulation
            # split (the reference's dataloader-batch knob, ref
            # config_manager.py micro_batch_size).
            assert self.batch_size % self.micro_batch_size == 0, (
                "batch_size must be a multiple of micro_batch_size"
            )
            self.gradient_accumulation_steps = (
                self.batch_size // self.micro_batch_size
            )
        if isinstance(self.mesh_axes, list):
            self.mesh_axes = tuple(self.mesh_axes)
        if isinstance(self.mod_capacity_schedule, list):
            # yaml/json round-trips tuples as lists
            self.mod_capacity_schedule = tuple(self.mod_capacity_schedule)
        self.normalize_parallelism()
        self.validate()

    def normalize_parallelism(self) -> None:
        """Resolve axis-implied settings so a bare axis-size request is a
        complete, valid config. Runs in __post_init__ before validate(), so
        constructor/preset/file-loaded configs all get it (docs/
        parallelism.md):

          - sequence parallelism rides ring attention;
          - pipeline parallelism slices the scanned layer stack, and grad
            accumulation folds into pipeline microbatches (same memory
            effect, no extra bubbles), capped to a divisor of the batch.
            micro_batch_size is cleared so __post_init__ cannot re-derive
            the accumulation this fold just removed.
        """
        if self.sequence_parallel_size > 1 and not self.use_ring_attention:
            self.use_ring_attention = True
        if self.pipeline_parallel_size > 1:
            if not self.scan_layers:
                self.scan_layers = True
            if self.gradient_accumulation_steps > 1:
                n_micro = (
                    self.pipeline_microbatches or self.pipeline_parallel_size
                )
                cand = min(
                    n_micro * self.gradient_accumulation_steps,
                    self.batch_size,
                )
                # Loop exits with cand dividing batch_size, or cand ==
                # n_micro (whose divisibility validate() then checks).
                while cand > n_micro and self.batch_size % cand != 0:
                    cand -= 1
                self.pipeline_microbatches = cand
                self.gradient_accumulation_steps = 1
                self.micro_batch_size = self.batch_size

    # -- validation ------------------------------------------------------
    def validate(self) -> None:
        assert self.hidden_size % self.num_heads == 0, (
            "hidden_size must be divisible by num_heads"
        )
        assert self.num_heads % self.num_kv_heads == 0, (
            "num_heads must be divisible by num_kv_heads"
        )
        assert self.precision in PRECISIONS, f"invalid precision {self.precision}"
        assert self.rope_dtype in ("fp32", "bf16"), (
            f"invalid rope_dtype {self.rope_dtype}"
        )
        assert self.kv_cache_dtype in ("bf16", "int8"), (
            f"invalid kv_cache_dtype {self.kv_cache_dtype}"
        )
        assert self.attention_backend in ("dense", "ragged_xla", "ragged"), (
            f"invalid attention_backend {self.attention_backend}"
        )
        assert self.prefill_chunk_size >= 0, (
            "prefill_chunk_size must be >= 0 (0 disables chunked prefill)"
        )
        assert self.prefix_cache_pages >= 0, (
            "prefix_cache_pages must be >= 0 (0 disables the prefix cache)"
        )
        assert self.prefix_cache_tenant_quota >= 0, (
            "prefix_cache_tenant_quota must be >= 0 (0 = unbounded)"
        )
        if self.attention_window is not None:
            assert self.attention_window > 0, (
                f"attention_window must be positive, got "
                f"{self.attention_window}"
            )
            # Composes with ring attention (r5): the ring body masks the
            # global band, skips whole out-of-band chunks, and merges the
            # far-edge straddling chunk by lse (ops/ring_attention.py).
        assert self.lr_scheduler in LR_SCHEDULES, (
            f"invalid lr_scheduler {self.lr_scheduler}"
        )
        assert self.watchdog_k > 0, "watchdog_k must be positive"
        assert self.watchdog_floor_s > 0, "watchdog_floor_s must be positive"
        assert self.watchdog_warmup >= 1, "watchdog_warmup must be >= 1"
        assert self.watchdog_poll_s > 0, "watchdog_poll_s must be positive"
        assert self.step_anomaly_k > 1, "step_anomaly_k must be > 1"
        assert self.slo_sample_interval_s > 0, (
            "slo_sample_interval_s must be positive"
        )
        assert self.slo_ring_points >= 2, "slo_ring_points must be >= 2"
        assert self.slo_max_series >= 1, "slo_max_series must be >= 1"
        assert 0 < self.slo_fast_window_s < self.slo_slow_window_s, (
            "slo windows must satisfy 0 < fast < slow"
        )
        assert self.slo_fast_burn >= 1, "slo_fast_burn must be >= 1"
        assert self.slo_slow_burn >= 1, "slo_slow_burn must be >= 1"
        assert 0 < self.slo_budget <= 1, "slo_budget must be in (0, 1]"
        assert self.slo_ttft_p95_s > 0, "slo_ttft_p95_s must be positive"
        assert self.slo_decode_p50_s > 0, "slo_decode_p50_s must be positive"
        assert 0 < self.slo_error_rate <= 1, (
            "slo_error_rate must be in (0, 1]"
        )
        assert 0 < self.slo_goodput_fraction <= 1, (
            "slo_goodput_fraction must be in (0, 1]"
        )
        assert self.slo_step_time_factor > 1, (
            "slo_step_time_factor must be > 1"
        )
        assert self.io_retries >= 1, "io_retries must be >= 1 (1 = no retry)"
        assert self.io_retry_base_s > 0, "io_retry_base_s must be positive"
        assert self.io_retry_max_s >= self.io_retry_base_s, (
            "io_retry_max_s must be >= io_retry_base_s"
        )
        if self.io_timeout_s is not None:
            assert self.io_timeout_s > 0, "io_timeout_s must be positive"
        assert self.checkpoint_verify in ("full", "sample", "off"), (
            f"invalid checkpoint_verify {self.checkpoint_verify!r} "
            "(one of full/sample/off)"
        )
        assert 0.0 < self.data_quarantine_max_rate <= 1.0, (
            "data_quarantine_max_rate must be in (0, 1]"
        )
        assert self.router_probe_interval_s > 0, (
            "router_probe_interval_s must be positive"
        )
        assert self.router_breaker_failures >= 1, (
            "router_breaker_failures must be >= 1"
        )
        assert self.router_breaker_cooldown_s > 0, (
            "router_breaker_cooldown_s must be positive"
        )
        assert self.router_max_failovers >= 0, (
            "router_max_failovers must be >= 0"
        )
        assert 0.0 <= self.router_hedge_budget <= 1.0, (
            "router_hedge_budget must be in [0, 1]"
        )
        assert self.router_hedge_max_tokens >= 1, (
            "router_hedge_max_tokens must be >= 1"
        )
        assert self.page_pull_timeout_s > 0, (
            "page_pull_timeout_s must be positive"
        )
        assert self.page_share_max_inflight >= 1, (
            "page_share_max_inflight must be >= 1"
        )
        if self.use_moe:
            assert self.moe_top_k <= self.num_experts, "moe_top_k must be <= num_experts"
            assert self.moe_pattern in MOE_PATTERNS, (
                f"invalid moe_pattern {self.moe_pattern}"
            )
            assert self.capacity_factor > 0
            assert self.moe_dispatch in (
                "sort", "gather", "einsum", "gmm", "a2a"
            ), f"invalid moe_dispatch {self.moe_dispatch}"
            if self.moe_dispatch == "a2a":
                # Cross-host expert parallelism routes tokens over the
                # 'expert' mesh axis (parallel/expert_dispatch.py): the
                # axis must exist, and the dcn tier must factor it.
                assert self.expert_parallel_size > 1, (
                    "moe_dispatch='a2a' requires an expert mesh axis "
                    "(expert_parallel_size > 1) — token routing needs "
                    "shards to route between; use 'gmm' on a single-"
                    "host/no-ep mesh"
                )
                assert (
                    self.expert_parallel_size % self.expert_dcn_size == 0
                ), (
                    f"expert_dcn_size ({self.expert_dcn_size}) must "
                    f"divide expert_parallel_size "
                    f"({self.expert_parallel_size})"
                )
                assert self.moe_a2a_overlap_chunks >= 1, (
                    "moe_a2a_overlap_chunks must be >= 1"
                )
                for name, size in (
                    ("pipeline", self.pipeline_parallel_size),
                    ("sequence", self.sequence_parallel_size),
                ):
                    assert size == 1, (
                        f"moe_dispatch='a2a' composes with data/fsdp/"
                        f"expert/tensor mesh axes only ({name}_parallel_"
                        f"size={size}); use 'gather' or 'sort' there"
                    )
                if self.tensor_parallel_size > 1:
                    assert (
                        self.intermediate_size % self.tensor_parallel_size
                        == 0
                    ), (
                        "moe_dispatch='a2a' with tensor parallelism "
                        "needs intermediate_size divisible by tensor_"
                        f"parallel_size ({self.intermediate_size} % "
                        f"{self.tensor_parallel_size})"
                    )
            assert self.expert_dcn_size >= 1, (
                "expert_dcn_size must be >= 1"
            )
            if self.moe_dispatch == "gmm":
                # The megablox grouped-matmul kernel is a Pallas custom
                # call GSPMD cannot partition, so gmm runs under shard_map
                # (models/moe.py _gmm_path): tokens shard over data/fsdp,
                # experts over 'expert', and (r6) the expert FFN dims over
                # 'tensor' — gate/up column-parallel, wo row-parallel —
                # with partial outputs psum'd over ('expert', 'tensor').
                # sequence/pipe would split the kernel's sorted row
                # dimension itself — not expressible; use 'gather' there.
                for name, size in (
                    ("pipeline", self.pipeline_parallel_size),
                    ("sequence", self.sequence_parallel_size),
                ):
                    assert size == 1, (
                        f"moe_dispatch='gmm' composes with data/fsdp/"
                        f"expert/tensor mesh axes only ({name}_parallel_"
                        f"size={size}); use 'gather' or 'sort' there"
                    )
                if self.tensor_parallel_size > 1:
                    assert (
                        self.intermediate_size % self.tensor_parallel_size
                        == 0
                    ), (
                        "moe_dispatch='gmm' with tensor parallelism needs "
                        "intermediate_size divisible by tensor_parallel_"
                        f"size ({self.intermediate_size} % "
                        f"{self.tensor_parallel_size})"
                    )
                # num_experts % expert_parallel_size is enforced by the
                # unconditional expert-parallel check below.
            assert 0.0 <= self.expert_dropout_rate <= 0.5, (
                "expert_dropout_rate must be in [0, 0.5]"
            )
        if self.use_mod:
            assert 0.0 < self.mod_capacity_factor <= 1.0, (
                "mod_capacity_factor must be in (0, 1]"
            )
            assert self.mod_capacity_schedule and all(
                0.0 < c <= 1.0 for c in self.mod_capacity_schedule
            ), (
                "mod_capacity_schedule entries must be in (0, 1] "
                f"(got {self.mod_capacity_schedule})"
            )
        if self.sequence_parallel_size > 1:
            assert self.seq_length % self.sequence_parallel_size == 0
            assert self.use_ring_attention, (
                "sequence_parallel_size > 1 requires use_ring_attention=True "
                "(without it every device re-gathers the full sequence, "
                "defeating sequence parallelism)"
            )
        assert self.loss_chunk_size > 0, "loss_chunk_size must be positive"
        assert self.remat_policy in (
            "nothing_saveable", "save_outs", "save_attn", "dots_saveable",
            "full",
        ), f"invalid remat_policy {self.remat_policy}"
        assert self.adam_mu_dtype in (None, "bf16"), (
            f"invalid adam_mu_dtype {self.adam_mu_dtype}"
        )
        assert self.adam_state_quantization in (None, "int8"), (
            f"invalid adam_state_quantization {self.adam_state_quantization}"
        )
        assert not (
            self.adam_state_quantization and self.adam_mu_dtype
        ), "adam_state_quantization supersedes adam_mu_dtype; set one"
        for axis in ("fsdp", "expert", "tensor", "sequence", "pipeline"):
            size = getattr(self, f"{axis}_parallel_size")
            assert size >= 1, f"{axis}_parallel_size must be >= 1"
        if self.pipeline_parallel_size > 1:
            assert self.pipeline_schedule in ("1f1b", "gpipe"), (
                f"invalid pipeline_schedule {self.pipeline_schedule}"
            )
            assert self.scan_layers, (
                "pipeline_parallel_size > 1 requires scan_layers=True "
                "(stages slice the stacked layer axis)"
            )
            assert self.num_layers % self.pipeline_parallel_size == 0, (
                "num_layers must divide evenly over pipeline stages"
            )
            n_micro = self.pipeline_microbatches or self.pipeline_parallel_size
            assert self.batch_size % n_micro == 0, (
                "batch_size must divide into pipeline_microbatches"
            )
            assert self.gradient_accumulation_steps == 1, (
                "pipeline parallelism replaces grad accumulation: raise "
                "pipeline_microbatches instead (same memory effect, no "
                "extra pipeline bubbles)"
            )
            # pp composes with every axis: data/fsdp/tensor are automatic
            # under the partial-manual shard_map; expert and sequence join
            # the manual region under the 1F1B schedule (tokens shard over
            # them, tiled all-to-alls / in-region ring attention — see
            # parallel/pipeline.py).
            if (
                self.expert_parallel_size > 1
                or self.sequence_parallel_size > 1
            ):
                assert self.pipeline_schedule == "1f1b", (
                    "pp x ep / pp x sp require pipeline_schedule='1f1b' "
                    "(manual expert/sequence parallelism lives in the "
                    "1F1B region)"
                )
                # MoD composes too: its BCE aux pmean's over the token
                # axes (models/mod.py apply_mod stat_pmean_axes); routing
                # is per local chunk with total capacity conserved.
            if self.expert_parallel_size > 1:
                assert (
                    self.batch_size // n_micro
                ) % self.expert_parallel_size == 0, (
                    "microbatch size must divide over expert_parallel_size "
                    "under pipeline parallelism (tokens shard over the "
                    "expert axis inside the pipe region)"
                )
        if self.expert_parallel_size > 1 and self.use_moe:
            assert self.num_experts % self.expert_parallel_size == 0, (
                "num_experts must divide evenly over expert_parallel_size"
            )
        assert self.grad_reduce in ("flat", "hierarchical"), (
            f"invalid grad_reduce {self.grad_reduce}"
        )
        assert self.gradient_dcn_size >= 1, "gradient_dcn_size must be >= 1"
        assert self.grad_reduce_overlap_chunks >= 1, (
            "grad_reduce_overlap_chunks must be >= 1"
        )
        assert self.grad_reduce_bucket_mb > 0, (
            "grad_reduce_bucket_mb must be positive"
        )
        assert self.grad_reduce_dcn_dtype in (None, "bf16"), (
            f"invalid grad_reduce_dcn_dtype {self.grad_reduce_dcn_dtype}"
        )
        if self.grad_reduce == "hierarchical":
            # The explicit sync runs the WHOLE grad computation inside a
            # partial-auto shard_map manual over (data, fsdp). Nested
            # manual regions over other axes (the gmm/a2a expert
            # dispatches, ring attention's sequence shard_map, the 1F1B
            # pipe region) cannot nest inside it on this jax line — the
            # auto-GSPMD dispatch modes (sort/gather/einsum) and auto
            # tensor/expert axes compose fine.
            for name, size in (
                ("pipeline", self.pipeline_parallel_size),
                ("sequence", self.sequence_parallel_size),
            ):
                assert size == 1, (
                    f"grad_reduce='hierarchical' composes with data/fsdp/"
                    f"expert/tensor mesh axes only ({name}_parallel_size="
                    f"{size}); use grad_reduce='flat' there"
                )
            if self.use_moe:
                assert self.moe_dispatch not in ("gmm", "a2a"), (
                    f"grad_reduce='hierarchical' cannot nest the "
                    f"moe_dispatch='{self.moe_dispatch}' shard_map inside "
                    "its manual (data, fsdp) region; use 'sort'/'gather'/"
                    "'einsum' dispatch or grad_reduce='flat'"
                )
            if self.data_parallel_size > 0:
                assert (
                    self.data_parallel_size % self.gradient_dcn_size == 0
                ), (
                    f"gradient_dcn_size ({self.gradient_dcn_size}) must "
                    f"divide data_parallel_size "
                    f"({self.data_parallel_size})"
                )

    # -- derived quantities (ref config_manager.py:234,505,572) ----------
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    def estimate_parameters(self) -> int:
        """Total parameter count (ref core/model.py:91 estimate_parameters)."""
        h, v, L = self.hidden_size, self.vocab_size, self.num_layers
        inter = self.intermediate_size
        kv_dim = self.num_kv_heads * self.head_dim()
        embed = v * h if self.tie_word_embeddings else 2 * v * h
        attn = h * h + 2 * h * kv_dim + h * h  # q, k, v, o
        ffn_dense = 3 * h * inter  # gate, up, down
        per_layer_norms = 2 * h
        total = embed + L * (attn + per_layer_norms) + h  # final norm
        moe_layers = self.num_moe_layers()
        dense_layers = L - moe_layers
        total += dense_layers * ffn_dense
        total += moe_layers * (self.num_experts * ffn_dense + h * self.num_experts)
        if self.use_mod:
            total += L * h  # MoD routers
        return total

    def estimate_active_parameters(self) -> int:
        """Active (per-token) params (ref core/model.py:1808)."""
        total = self.estimate_parameters()
        if not self.use_moe:
            return total
        h, inter = self.hidden_size, self.intermediate_size
        ffn_dense = 3 * h * inter
        moe_layers = self.num_moe_layers()
        inactive = moe_layers * (self.num_experts - self.moe_top_k) * ffn_dense
        return total - inactive

    def num_moe_layers(self) -> int:
        if not self.use_moe:
            return 0
        return sum(1 for i in range(self.num_layers) if self.is_moe_layer(i))

    def is_moe_layer(self, layer_idx: int) -> bool:
        """MoE layer placement pattern (ref core/model.py:1545 _should_use_moe)."""
        if not self.use_moe or self.moe_pattern == "none":
            return False
        if self.moe_pattern == "all":
            return True
        if self.moe_pattern == "every_3rd":
            return layer_idx % 3 == 2
        if self.moe_pattern == "every_4th":
            return layer_idx % 4 == 3
        if self.moe_pattern == "sandwich":
            return (
                self.dense_start_layers <= layer_idx
                < self.num_layers - self.dense_end_layers
            )
        return False

    def memory_estimate_gb(self) -> Dict[str, float]:
        """Rough HBM footprint estimate (ref config_manager.py:572)."""
        params = self.estimate_parameters()
        bytes_per = 2 if "bf16" in self.resolve_precision() else 4
        param_gb = params * bytes_per / 1e9
        # Adam: fp32 master copy + 2 moments whose width the config picks
        # (fp32 default; bf16 mu; int8 codes + row scales ≈ 1B each).
        if self.adam_state_quantization == "int8":
            moment_bytes = 2  # mu + nu codes; scales are ~1/last_dim extra
        elif self.adam_mu_dtype == "bf16":
            moment_bytes = 6  # bf16 mu + fp32 nu
        else:
            moment_bytes = 8
        opt_gb = params * (4 + moment_bytes) / 1e9
        act_gb = (
            self.micro_batch_size
            * self.seq_length
            * self.hidden_size
            * self.num_layers
            * bytes_per
            * (2 if not self.gradient_checkpointing else 0.25)
        ) / 1e9
        total = param_gb + opt_gb + act_gb
        return {
            "parameters_gb": round(param_gb, 3),
            "optimizer_gb": round(opt_gb, 3),
            "activations_gb": round(act_gb, 3),
            "total_gb": round(total, 3),
        }

    def resolve_precision(self, for_inference: bool = False) -> str:
        p = self.inference_precision if for_inference else self.precision
        if p == "auto":
            return "bf16" if for_inference else "mixed_bf16"
        # fp16 is a CUDA legacy (ref GradScaler machinery); TPU MXUs take
        # bf16 natively with fp32 range, so fp16 modes alias to bf16.
        if p == "fp16":
            return "bf16"
        if p == "mixed_fp16":
            return "mixed_bf16"
        return p

    def total_mesh_size(self) -> int:
        return (
            max(1, self.data_parallel_size)
            * self.fsdp_parallel_size
            * self.expert_parallel_size
            * self.tensor_parallel_size
            * self.sequence_parallel_size
        )

    # -- serialization (ref config_manager.py:616,637) --------------------
    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["mesh_axes"] = list(self.mesh_axes)
        return d

    def save(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        d = self.to_dict()
        with open(path, "w") as f:
            if path.endswith((".yaml", ".yml")) and _HAS_YAML:
                yaml.safe_dump(d, f, sort_keys=False)
            else:
                json.dump(d, f, indent=2)

    @classmethod
    def load(cls, path: str) -> "Config":
        with open(path) as f:
            if path.endswith((".yaml", ".yml")) and _HAS_YAML:
                d = yaml.safe_load(f)
            else:
                d = json.load(f)
        known = {f.name for f in dataclasses.fields(cls)}
        d = {k: v for k, v in d.items() if k in known}
        return cls(**d)


class ConfigPresets:
    """Model-size presets following the reference's 8x-MoE pattern
    (ref config_manager.py:759). Sizes name the *active* parameter count."""

    @staticmethod
    def debug() -> Config:
        return Config(
            vocab_size=1024,
            hidden_size=128,
            num_layers=2,
            num_heads=2,
            num_kv_heads=1,
            seq_length=256,
            intermediate_size=256,
            batch_size=2,
            micro_batch_size=1,
            gradient_accumulation_steps=2,
            num_epochs=1,
            learning_rate=5e-5,
            use_moe=True,
            num_experts=8,
            moe_top_k=2,
            capacity_factor=1.1,
            load_balancing_weight=0.005,
            eval_every_n_batches=50,
            save_every_n_batches=100,
            experiment_name="debug_run",
            log_level="DEBUG",
            health_check_interval=10,
            save_total_limit=3,
            gradient_checkpointing=False,
            scan_layers=False,
        )

    @staticmethod
    def debug_200m() -> Config:
        return Config(
            vocab_size=50304,
            hidden_size=768,
            num_layers=12,
            num_heads=12,
            num_kv_heads=4,
            seq_length=2048,
            batch_size=32,
            gradient_accumulation_steps=4,
            use_moe=False,
            use_mod=True,
            mod_capacity_factor=0.5,
            experiment_name="debug_200m",
        )

    @staticmethod
    def debug_300m() -> Config:
        return Config(
            vocab_size=50304,
            hidden_size=768,
            num_layers=6,
            num_heads=4,
            num_kv_heads=2,
            seq_length=1024,
            batch_size=16,
            use_moe=True,
            num_experts=8,
            moe_top_k=2,
            experiment_name="debug_300m",
        )

    @staticmethod
    def moe_stress_test() -> Config:
        return Config(
            vocab_size=50304,
            hidden_size=512,
            num_layers=8,
            num_heads=8,
            num_kv_heads=4,
            seq_length=1024,
            batch_size=8,
            use_moe=True,
            num_experts=32,
            moe_top_k=2,
            capacity_factor=1.1,
            routing_noise_std=0.2,
            expert_parallel_size=1,
            experiment_name="moe_stress_test",
        )

    @staticmethod
    def b1() -> Config:
        return Config(
            vocab_size=50304,
            hidden_size=2048,
            num_layers=16,
            num_heads=16,
            num_kv_heads=4,
            seq_length=2048,
            batch_size=128,
            gradient_accumulation_steps=8,
            use_moe=True,
            num_experts=8,
            moe_top_k=2,
            fsdp_parallel_size=8,
            experiment_name="b1",
        )

    @staticmethod
    def b7() -> Config:
        return Config(
            vocab_size=50304,
            hidden_size=4096,
            num_layers=32,
            num_heads=32,
            num_kv_heads=8,
            seq_length=2048,
            batch_size=512,
            gradient_accumulation_steps=16,
            learning_rate=1.5e-4,
            use_moe=True,
            num_experts=8,
            moe_top_k=2,
            fsdp_parallel_size=8,
            expert_parallel_size=8,
            scan_layers=True,
            experiment_name="b7",
        )

    @staticmethod
    def b14() -> Config:
        return Config(
            vocab_size=50304,
            hidden_size=5120,
            num_layers=40,
            num_heads=40,
            num_kv_heads=8,
            seq_length=4096,
            batch_size=512,
            gradient_accumulation_steps=16,
            learning_rate=1.2e-4,
            use_moe=True,
            num_experts=8,
            moe_top_k=2,
            fsdp_parallel_size=16,
            expert_parallel_size=8,
            scan_layers=True,
            experiment_name="b14",
        )

    @staticmethod
    def b30() -> Config:
        return Config(
            vocab_size=50304,
            hidden_size=6656,
            num_layers=48,
            num_heads=52,
            num_kv_heads=13,
            seq_length=4096,
            batch_size=1024,
            gradient_accumulation_steps=32,
            learning_rate=1e-4,
            use_moe=True,
            num_experts=8,
            moe_top_k=2,
            fsdp_parallel_size=32,
            expert_parallel_size=8,
            scan_layers=True,
            experiment_name="b30",
        )

    @staticmethod
    def b50() -> Config:
        return Config(
            vocab_size=50304,
            hidden_size=8192,
            num_layers=48,
            num_heads=64,
            num_kv_heads=8,
            seq_length=4096,
            batch_size=1024,
            gradient_accumulation_steps=32,
            learning_rate=8e-5,
            use_moe=True,
            num_experts=16,
            moe_top_k=2,
            fsdp_parallel_size=32,
            expert_parallel_size=16,
            scan_layers=True,
            experiment_name="b50",
        )

    @staticmethod
    def b75() -> Config:
        return Config(
            vocab_size=50304,
            hidden_size=8192,
            num_layers=64,
            num_heads=64,
            num_kv_heads=8,
            seq_length=8192,
            batch_size=1024,
            gradient_accumulation_steps=32,
            learning_rate=7e-5,
            use_moe=True,
            num_experts=16,
            moe_top_k=2,
            fsdp_parallel_size=64,
            expert_parallel_size=16,
            use_ring_attention=True,
            sequence_parallel_size=1,
            scan_layers=True,
            experiment_name="b75",
        )

    @staticmethod
    def b100() -> Config:
        return Config(
            vocab_size=50304,
            hidden_size=10240,
            num_layers=64,
            num_heads=80,
            num_kv_heads=8,
            seq_length=8192,
            batch_size=2048,
            gradient_accumulation_steps=64,
            learning_rate=6e-5,
            use_moe=True,
            num_experts=32,
            moe_top_k=2,
            fsdp_parallel_size=64,
            expert_parallel_size=32,
            use_ring_attention=True,
            scan_layers=True,
            experiment_name="b100",
        )

    @staticmethod
    def b200() -> Config:
        return Config(
            vocab_size=50304,
            hidden_size=12288,
            num_layers=80,
            num_heads=96,
            num_kv_heads=8,
            seq_length=8192,
            batch_size=2048,
            gradient_accumulation_steps=64,
            learning_rate=5e-5,
            use_moe=True,
            num_experts=64,
            moe_top_k=2,
            fsdp_parallel_size=128,
            expert_parallel_size=64,
            use_ring_attention=True,
            scan_layers=True,
            experiment_name="b200",
        )

    @staticmethod
    def b300() -> Config:
        return Config(
            vocab_size=50304,
            hidden_size=16384,
            num_layers=80,
            num_heads=128,
            num_kv_heads=16,
            seq_length=8192,
            batch_size=4096,
            gradient_accumulation_steps=128,
            learning_rate=4e-5,
            use_moe=True,
            num_experts=64,
            moe_top_k=2,
            fsdp_parallel_size=128,
            expert_parallel_size=64,
            tensor_parallel_size=2,
            use_ring_attention=True,
            scan_layers=True,
            experiment_name="b300",
        )

    _PRESETS = (
        "debug",
        "debug_200m",
        "debug_300m",
        "moe_stress_test",
        "b1",
        "b7",
        "b14",
        "b30",
        "b50",
        "b75",
        "b100",
        "b200",
        "b300",
    )

    @classmethod
    def available(cls) -> List[str]:
        return list(cls._PRESETS)

    @classmethod
    def get(cls, name: str) -> Config:
        if name not in cls._PRESETS:
            raise ValueError(f"Unknown preset: {name}. Available: {cls.available()}")
        return getattr(cls, name)()

    @classmethod
    def get_preset_info(cls) -> Dict[str, Dict[str, Any]]:
        """Comparison table across presets (ref config_manager.py:1670)."""
        info = {}
        for name in cls._PRESETS:
            c = cls.get(name)
            info[name] = {
                "hidden_size": c.hidden_size,
                "num_layers": c.num_layers,
                "total_params": c.estimate_parameters(),
                "active_params": c.estimate_active_parameters(),
                "use_moe": c.use_moe,
                "num_experts": c.num_experts if c.use_moe else 0,
                "use_mod": c.use_mod,
                "seq_length": c.seq_length,
                "memory_gb": c.memory_estimate_gb()["total_gb"],
            }
        return info


class ConfigManager:
    """Create, validate, tune, persist configs (ref config_manager.py:1871)."""

    @staticmethod
    def create_config(preset: str = "b7", **overrides) -> Config:
        config = ConfigPresets.get(preset)
        config = dataclasses.replace(config, **overrides)
        return config

    @staticmethod
    def validate_config(config: Config, strict: bool = False) -> List[str]:
        """Returns a list of warnings; raises on hard errors (via validate())."""
        config.validate()
        warnings = []
        if config.batch_size % max(1, config.micro_batch_size) != 0:
            warnings.append("batch_size is not a multiple of micro_batch_size")
        if config.use_moe and config.capacity_factor < 1.0:
            warnings.append("capacity_factor < 1.0 will drop tokens aggressively")
        if config.seq_length % 128 != 0:
            warnings.append("seq_length not a multiple of 128 (TPU lane width)")
        if config.hidden_size % 128 != 0:
            warnings.append("hidden_size not a multiple of 128 (MXU tiling)")
        mem = config.memory_estimate_gb()["total_gb"]
        shards = config.fsdp_parallel_size * config.tensor_parallel_size
        if mem / max(1, shards) > 90:
            warnings.append(
                f"~{mem / max(1, shards):.0f}GB/chip estimated — exceeds v5p HBM"
            )
        if strict and warnings:
            raise ValueError("; ".join(warnings))
        return warnings

    @staticmethod
    def optimize_for_hardware(config: Config, n_devices: Optional[int] = None) -> Config:
        """Pick a mesh layout for the *detected* devices
        (ref config_manager.py:1921 optimize_for_hardware). Uses real device
        introspection (utils.environment): per-chip HBM decides how much
        model sharding (fsdp/tp) is needed; leftover devices become data
        parallelism."""
        from luminaai_tpu.utils.environment import get_device_info

        dev = get_device_info()
        n = n_devices or dev["device_count"]
        hbm_gb = dev.get("memory_per_device_gb") or 16.0
        updates: Dict[str, Any] = {}
        # Shard experts first (cheap all-to-all on ICI), then FSDP the rest.
        ep = 1
        if config.use_moe:
            ep = math.gcd(config.num_experts, n)
        remaining = n // ep
        updates["expert_parallel_size"] = ep
        updates["data_parallel_size"] = 1
        # State per chip: bf16/fp32 params + Adam moments ≈ 12 bytes/param,
        # divided across the model-sharding axes. Grow tp while one chip
        # can't hold its shard (norm+embed replicas bound fsdp's reach).
        state_gb = config.estimate_parameters() * 12 / 1e9
        shards = max(1, remaining)  # model-parallel ways left after ep
        tp = 1

        def per_chip_gb(tp_size: int) -> float:
            # ~75% of state is fsdp-shardable everywhere; ~25% (embeddings,
            # fused projections) only truly shards across tp. Monotonically
            # decreasing in tp at fixed total shards, so the loop below
            # terminates at the minimal tp that fits (or the caps).
            fsdp = max(1, shards // tp_size)
            return state_gb * (0.75 / (tp_size * fsdp) + 0.25 / tp_size)

        while (
            per_chip_gb(tp) > hbm_gb * 0.5
            and tp * 2 <= shards
            and tp < 8
            and config.num_heads % (tp * 2) == 0
        ):
            tp *= 2
        updates["tensor_parallel_size"] = tp
        updates["fsdp_parallel_size"] = shards // tp
        return dataclasses.replace(config, **updates)

    @staticmethod
    def save_config_with_metadata(config: Config, path: str) -> None:
        d = config.to_dict()
        d["_metadata"] = {
            "total_params": config.estimate_parameters(),
            "active_params": config.estimate_active_parameters(),
            "memory_estimate": config.memory_estimate_gb(),
            "framework": "luminaai_tpu",
        }
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            if path.endswith((".yaml", ".yml")) and _HAS_YAML:
                yaml.safe_dump(d, f, sort_keys=False)
            else:
                json.dump(d, f, indent=2)
