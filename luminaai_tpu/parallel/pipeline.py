"""GPipe pipeline parallelism over the scanned layer stack.

The fifth model-parallel dimension (with fsdp/tensor/expert/sequence):
stage p of P holds layers [p·L/P, (p+1)·L/P) — under `scan_layers=True`
the stacked parameters carry a leading L axis, so a stage's weights are
just that axis sharded over the 'pipe' mesh axis (sharding.py maps the
'layers' logical axis to 'pipe'). The batch is split into microbatches
that flow through stages with `lax.ppermute` hops under
`jax.shard_map(axis_names={'pipe'})` — manual collectives over the pipe
axis only, while data/fsdp sharding on every tensor stays automatic.

Schedule (forward): tick t gives stage p microbatch (t - p); valid work
happens for 0 <= t - p < n_micro (the classic (P-1)-tick bubble at each
end). Bubble lanes compute on zeros and are masked out of outputs and
metrics; their gradient contribution is exactly zero because nothing they
produce reaches the loss. The backward pass is plain autodiff through the
schedule (scan + ppermute transpose to the reverse schedule), so grads,
clipping, and the optimizer reuse the standard train-step machinery.

Embedding, final norm, and the fused LM-head CE run outside the
pipelined region, replicated over 'pipe' (they are a few percent of step
FLOPs; the layer stack is what pipelining is for).

The reference has no pipeline engine of its own (DeepSpeed's sat unused
behind its config); this is TPU-first coverage of the driver's
tp/pp/dp/sp/ep contract.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn
from jax.sharding import Mesh, PartitionSpec as P

from luminaai_tpu.config import Config
from luminaai_tpu.models.transformer import TransformerBlock, scan_segments
from luminaai_tpu.parallel.sharding import TrainState
from luminaai_tpu.parallel.train_step import (
    _ce,
    _shifted_mask_weights,
    shift_labels,
)

Batch = Dict[str, jax.Array]


def pipeline_compatible(config: Config) -> Tuple[bool, str]:
    """Whether the config's layer stack can be pipelined: one homogeneous
    scan segment (uniform block kind) that divides evenly over stages."""
    if config.pipeline_parallel_size <= 1:
        return False, "pipeline_parallel_size is 1"
    segs = scan_segments(config)
    if len(segs) != 1 or len(segs[0][1]) != 1:
        return False, (
            f"layer stack is not one homogeneous segment (got {len(segs)} "
            "segments); use moe_pattern 'all' or 'none'"
        )
    return True, ""


def _stage_apply(
    config: Config,
    block: nn.Module,
    stack_local: Any,
    x: jax.Array,
    rng: jax.Array,
    n_local: int,
    first_global_layer: jax.Array,
):
    """Run this stage's n_local layers over x via lax.scan.

    stack_local: param tree with leading axis n_local (this stage's slice).
    Returns (x, metrics_summed_over_local_layers).
    """

    def body(carry, xs):
        layer_params, idx = xs
        layer_rng = jax.random.fold_in(rng, idx)
        out, _, metrics = block.apply(
            {"params": layer_params},
            carry,
            rngs={"routing": layer_rng, "dropout": jax.random.fold_in(layer_rng, 1)},
        )
        return out, metrics

    if config.gradient_checkpointing:
        from luminaai_tpu.models.transformer import REMAT_POLICIES

        body = jax.checkpoint(
            body,
            policy=REMAT_POLICIES.get(config.remat_policy),
            prevent_cse=False,
        )
    idxs = first_global_layer + jnp.arange(n_local)
    x, metrics = jax.lax.scan(body, x, (stack_local, idxs))
    metrics = jax.tree.map(lambda m: m.sum(axis=0), metrics)
    return x, metrics


def make_pipeline_loss_fn(
    config: Config, model, mesh: Mesh, deterministic: bool = False
) -> Callable:
    """Loss over the GPipe schedule; drop-in signature for the train step.

    model: the LuminaTransformer whose scanned params this runs against
    (used for dtype/config; its param tree layout is what init produced).
    deterministic=True gives the eval path (no routing noise/dropout).
    """
    ok, why = pipeline_compatible(config)
    if not ok:
        raise ValueError(f"config not pipeline-compatible: {why}")
    assert config.fused_lm_head_ce, (
        "pipeline train step requires fused_lm_head_ce (the LM head runs "
        "outside the pipelined region on hidden states)"
    )
    Pn = config.pipeline_parallel_size
    L = config.num_layers
    n_local = L // Pn
    n_micro = config.pipeline_microbatches or Pn
    dtype = model.dtype
    # Representative block: homogeneity was checked, so layer 0's kind
    # (and param structure) matches every layer.
    block = TransformerBlock(
        config, layer_idx=0, dtype=dtype, deterministic=deterministic
    )

    from luminaai_tpu.models.layers import Embedder, RMSNorm

    embedder = Embedder(config, dtype=dtype, name=None)
    final_norm = RMSNorm(config.rms_norm_eps, dtype=dtype)

    def pipe_body(stack_local, x, rng):
        """Manual over 'pipe' (shard_map): stack_local is this stage's
        [n_local, ...] slice; x and rng are pipe-replicated."""
        p = jax.lax.axis_index("pipe")
        B = x.shape[0]
        mb = B // n_micro
        mbs = x.reshape(n_micro, mb, *x.shape[1:])
        ticks = n_micro + Pn - 1
        perm = [(i, (i + 1) % Pn) for i in range(Pn)]
        first_layer = p * n_local

        def one_tick(carry, t):
            state, outs, macc = carry
            recv = jax.lax.ppermute(state, "pipe", perm)
            mb_idx = jnp.clip(t, 0, n_micro - 1)
            feed = jax.lax.dynamic_index_in_dim(
                mbs, mb_idx, axis=0, keepdims=False
            )
            x_in = jnp.where(p == 0, feed, recv)
            my_mb = t - p  # microbatch this stage works on this tick
            out, metrics = _stage_apply(
                config, block, stack_local, x_in,
                jax.random.fold_in(rng, my_mb), n_local,
                first_layer,
            )
            valid = (my_mb >= 0) & (my_mb < n_micro)
            # Collect finished microbatches on the last stage.
            out_idx = jnp.clip(t - (Pn - 1), 0, n_micro - 1)
            collect = valid & (p == Pn - 1)
            outs = jnp.where(
                collect,
                jax.lax.dynamic_update_index_in_dim(outs, out, out_idx, 0),
                outs,
            )
            macc = jax.tree.map(
                lambda a, m: a + jnp.where(valid, m, 0.0), macc, metrics
            )
            return (out, outs, macc), None

        varying = lambda a: jax.lax.pcast(a, ("pipe",), to="varying")
        state0 = varying(jnp.zeros((mb, *x.shape[1:]), x.dtype))
        outs0 = varying(jnp.zeros((n_micro, mb, *x.shape[1:]), x.dtype))
        # Metric zeros with the right structure: one dry stage application
        # under eval_shape costs nothing and avoids hand-listing keys.
        m_shape = jax.eval_shape(
            lambda: _stage_apply(
                config, block, stack_local, state0, rng, n_local, first_layer
            )[1]
        )
        macc0 = jax.tree.map(
            lambda s: varying(jnp.zeros(s.shape, jnp.float32)), m_shape
        )
        (_, outs, macc), _ = jax.lax.scan(
            one_tick, (state0, outs0, macc0), jnp.arange(ticks)
        )
        # Replicate results over the pipe axis: outputs live on the last
        # stage, each stage's metric sums cover its own layers.
        outs = jax.lax.psum(
            jnp.where(p == Pn - 1, outs, jnp.zeros_like(outs)), "pipe"
        )
        macc = jax.lax.psum(macc, "pipe")
        return outs.reshape(B, *x.shape[1:]), macc

    def loss_fn(params, batch: Batch, rng: jax.Array):
        ids = batch["input_ids"]
        x = embedder.apply(
            {"params": params["embedder"]}, ids, method="encode"
        )
        stack = params["scan_0"]["block_0"]
        sharded = jax.shard_map(
            pipe_body,
            mesh=mesh,
            axis_names=frozenset({"pipe"}),
            in_specs=(P("pipe"), P(), P()),
            out_specs=(P(), P()),
        )
        hidden, metrics_sum = sharded(stack, x, rng)
        hidden = final_norm.apply({"params": params["final_norm"]}, hidden)

        labels, valid = shift_labels(batch)
        mask, weights = _shifted_mask_weights(batch, valid)
        loss, metrics = _ce(
            config, params, hidden, labels, mask, weights,
            z_loss_weight=config.z_loss_weight,
            label_smoothing=config.label_smoothing,
        )
        # Per-layer mean diagnostics + summed aux losses, matching the
        # non-pipelined metric reduction (transformer._reduce_metrics).
        aux_total = jnp.float32(0.0)
        for key, v in metrics_sum.items():
            if key.endswith("_loss"):
                per_mb_sum = v / n_micro  # each microbatch crossed L layers
                metrics[key] = per_mb_sum
                aux_total = aux_total + per_mb_sum
            else:
                metrics[key] = v / (L * n_micro)
        total = loss + aux_total
        metrics["loss"] = total
        metrics["aux_loss"] = aux_total
        return total, metrics

    return loss_fn


def make_pipeline_train_step(
    config: Config,
    model,
    state_shardings: TrainState,
    mesh: Mesh,
    schedule: Optional[optax.Schedule],
    tx: optax.GradientTransformation,
):
    """Donated, sharded, jitted GPipe train step.

    Same contract as parallel.train_step.make_train_step — in fact it IS
    that step builder with the GPipe loss injected (grad accumulation is
    validated to 1 under pp, so the shared body's accumulation path
    degenerates to a single value_and_grad; clipping, donation, and metric
    reporting stay single-sourced).
    """
    from luminaai_tpu.parallel.train_step import make_train_step

    return make_train_step(
        config, model, state_shardings, mesh, schedule, tx,
        loss_fn=make_pipeline_loss_fn(config, model, mesh),
    )


def make_pipeline_eval_step(
    config: Config,
    model,
    state_shardings: TrainState,
    mesh: Mesh,
):
    """Forward-only eval over the GPipe schedule (deterministic routing) —
    the non-pipelined eval step would all-gather every stage's layers onto
    every device per scan iteration. Reuses make_eval_step's wrapper with
    the GPipe loss injected (mirror of the train-step delegation)."""
    from luminaai_tpu.parallel.train_step import make_eval_step

    pipe_loss = make_pipeline_loss_fn(config, model, mesh, deterministic=True)
    fixed_rng = jax.random.key(0)  # deterministic path ignores it

    def eval_loss(params, batch):
        _, metrics = pipe_loss(params, batch, fixed_rng)
        return metrics

    return make_eval_step(
        config, model, state_shardings, mesh, loss_fn=eval_loss
    )
