"""GPipe pipeline parallelism over the scanned layer stack.

The fifth model-parallel dimension (with fsdp/tensor/expert/sequence):
stage p of P holds layers [p·L/P, (p+1)·L/P) — under `scan_layers=True`
the stacked parameters carry a leading L axis, so a stage's weights are
just that axis sharded over the 'pipe' mesh axis (sharding.py maps the
'layers' logical axis to 'pipe'). The batch is split into microbatches
that flow through stages with `lax.ppermute` hops under
`jax.shard_map(axis_names={'pipe'})` — manual collectives over the pipe
axis only, while data/fsdp sharding on every tensor stays automatic.

Schedule (forward): tick t gives stage p microbatch (t - p); valid work
happens for 0 <= t - p < n_micro (the classic (P-1)-tick bubble at each
end). Bubble lanes compute on zeros and are masked out of outputs and
metrics; their gradient contribution is exactly zero because nothing they
produce reaches the loss. The backward pass is plain autodiff through the
schedule (scan + ppermute transpose to the reverse schedule), so grads,
clipping, and the optimizer reuse the standard train-step machinery.

Embedding, final norm, and the fused LM-head CE run outside the
pipelined region, replicated over 'pipe' (they are a few percent of step
FLOPs; the layer stack is what pipelining is for).

The reference has no pipeline engine of its own (DeepSpeed's sat unused
behind its config); this is TPU-first coverage of the driver's
tp/pp/dp/sp/ep contract.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn
from jax.sharding import Mesh, PartitionSpec as P

from luminaai_tpu.config import Config
from luminaai_tpu.models.transformer import TransformerBlock, scan_segments
from luminaai_tpu.parallel.mesh import shard_map
from luminaai_tpu.parallel.sharding import TrainState
from luminaai_tpu.parallel.train_step import (
    _ce,
    _shifted_mask_weights,
    shift_labels,
)

Batch = Dict[str, jax.Array]


def pipeline_compatible(config: Config) -> Tuple[bool, str]:
    """Whether the config's layer stack can be pipelined: one homogeneous
    scan segment (uniform block kind) that divides evenly over stages."""
    if config.pipeline_parallel_size <= 1:
        return False, "pipeline_parallel_size is 1"
    segs = scan_segments(config)
    if len(segs) != 1 or len(segs[0][1]) != 1:
        return False, (
            f"layer stack is not one homogeneous segment (got {len(segs)} "
            "segments); use moe_pattern 'all' or 'none'"
        )
    return True, ""


def _pipe_manual_axes(config: Config):
    """(manual_axes, token_axes) for the 1F1B region. token_axes are the
    manual axes tokens are sharded over (grad partials psum over them)."""
    manual = ["pipe"]
    token = []
    if config.expert_parallel_size > 1:
        manual.append("expert")
        token.append("expert")
    if config.sequence_parallel_size > 1:
        manual.append("sequence")
        token.append("sequence")
    return tuple(manual), tuple(token)


def _pipe_block_config(config: Config) -> Config:
    """Block config for tracing inside the manual region: auto expert
    constraints off, manual all-to-all MoE when ep>1, in-region ring
    attention when sp>1, routing stats pmean'd over the token axes."""
    _, token_axes = _pipe_manual_axes(config)
    return dataclasses.replace(
        config,
        moe_ep_constraints=False,
        moe_manual_ep=config.expert_parallel_size > 1,
        ring_manual=config.sequence_parallel_size > 1,
        moe_stat_pmean_axes=token_axes,
    )


def _is_expert_leaf(path) -> bool:
    """Stack-param leaves whose dim 1 (after the layer axis) is the expert
    dim — the MoE module's wi/wo. Everything else (attention — which has
    its own 'wo' — norms, router) is replicated over 'expert'."""
    name = getattr(path[-1], "key", None)
    parent = getattr(path[-2], "key", None) if len(path) >= 2 else None
    return parent == "moe" and name in ("wi", "wo")


def _stage_apply(
    config: Config,
    block: nn.Module,
    stack_local: Any,
    x: jax.Array,
    rng: jax.Array,
    n_local: int,
    first_global_layer: jax.Array,
    positions: Optional[jax.Array] = None,
):
    """Run this stage's n_local layers over x via lax.scan.

    stack_local: param tree with leading axis n_local (this stage's slice).
    positions: explicit RoPE positions (manual sequence parallelism passes
    this stage's global offsets; None = arange over local length).
    Returns (x, metrics_summed_over_local_layers).
    """

    def body(carry, xs):
        layer_params, idx = xs
        layer_rng = jax.random.fold_in(rng, idx)
        out, _, metrics = block.apply(
            {"params": layer_params},
            carry,
            positions=positions,
            rngs={"routing": layer_rng, "dropout": jax.random.fold_in(layer_rng, 1)},
        )
        return out, metrics

    if config.gradient_checkpointing:
        from luminaai_tpu.models.transformer import REMAT_POLICIES

        body = jax.checkpoint(
            body,
            policy=REMAT_POLICIES.get(config.remat_policy),
            prevent_cse=False,
        )
    idxs = first_global_layer + jnp.arange(n_local)
    x, metrics = jax.lax.scan(body, x, (stack_local, idxs))
    metrics = jax.tree.map(lambda m: m.sum(axis=0), metrics)
    return x, metrics


def make_pipeline_loss_fn(
    config: Config, model, mesh: Mesh, deterministic: bool = False
) -> Callable:
    """Loss over the GPipe schedule; drop-in signature for the train step.

    model: the LuminaTransformer whose scanned params this runs against
    (used for dtype/config; its param tree layout is what init produced).
    deterministic=True gives the eval path (no routing noise/dropout).
    """
    ok, why = pipeline_compatible(config)
    if not ok:
        raise ValueError(f"config not pipeline-compatible: {why}")
    assert config.fused_lm_head_ce, (
        "pipeline train step requires fused_lm_head_ce (the LM head runs "
        "outside the pipelined region on hidden states)"
    )
    Pn = config.pipeline_parallel_size
    L = config.num_layers
    n_local = L // Pn
    n_micro = config.pipeline_microbatches or Pn
    dtype = model.dtype
    # Representative block: homogeneity was checked, so layer 0's kind
    # (and param structure) matches every layer. Expert-axis activation
    # constraints are dropped inside the manual region (partitioner
    # group-check crash); the expert-sharded weights still partition the
    # expert einsums.
    block = TransformerBlock(
        dataclasses.replace(config, moe_ep_constraints=False),
        layer_idx=0, dtype=dtype, deterministic=deterministic,
    )

    from luminaai_tpu.models.layers import Embedder, RMSNorm

    embedder = Embedder(config, dtype=dtype, name=None)
    final_norm = RMSNorm(config.rms_norm_eps, dtype=dtype)

    def pipe_body(stack_local, x, rng):
        """Manual over 'pipe' (shard_map): stack_local is this stage's
        [n_local, ...] slice; x and rng are pipe-replicated."""
        p = jax.lax.axis_index("pipe")
        B = x.shape[0]
        mb = B // n_micro
        mbs = x.reshape(n_micro, mb, *x.shape[1:])
        ticks = n_micro + Pn - 1
        perm = [(i, (i + 1) % Pn) for i in range(Pn)]
        first_layer = p * n_local

        def one_tick(carry, t):
            state, outs, macc = carry
            recv = jax.lax.ppermute(state, "pipe", perm)
            mb_idx = jnp.clip(t, 0, n_micro - 1)
            feed = jax.lax.dynamic_index_in_dim(
                mbs, mb_idx, axis=0, keepdims=False
            )
            x_in = jnp.where(p == 0, feed, recv)
            my_mb = t - p  # microbatch this stage works on this tick
            out, metrics = _stage_apply(
                config, block, stack_local, x_in,
                jax.random.fold_in(rng, my_mb), n_local,
                first_layer,
            )
            valid = (my_mb >= 0) & (my_mb < n_micro)
            # Collect finished microbatches on the last stage.
            out_idx = jnp.clip(t - (Pn - 1), 0, n_micro - 1)
            collect = valid & (p == Pn - 1)
            outs = jnp.where(
                collect,
                jax.lax.dynamic_update_index_in_dim(outs, out, out_idx, 0),
                outs,
            )
            macc = jax.tree.map(
                lambda a, m: a + jnp.where(valid, m, 0.0), macc, metrics
            )
            return (out, outs, macc), None

        varying = lambda a: jax.lax.pcast(a, ("pipe",), to="varying")
        state0 = varying(jnp.zeros((mb, *x.shape[1:]), x.dtype))
        outs0 = varying(jnp.zeros((n_micro, mb, *x.shape[1:]), x.dtype))
        # Metric zeros with the right structure: one dry stage application
        # under eval_shape costs nothing and avoids hand-listing keys.
        m_shape = jax.eval_shape(
            lambda: _stage_apply(
                config, block, stack_local, state0, rng, n_local, first_layer
            )[1]
        )
        macc0 = jax.tree.map(
            lambda s: varying(jnp.zeros(s.shape, jnp.float32)), m_shape
        )
        (_, outs, macc), _ = jax.lax.scan(
            one_tick, (state0, outs0, macc0), jnp.arange(ticks)
        )
        # Replicate results over the pipe axis: outputs live on the last
        # stage, each stage's metric sums cover its own layers.
        outs = jax.lax.psum(
            jnp.where(p == Pn - 1, outs, jnp.zeros_like(outs)), "pipe"
        )
        macc = jax.lax.psum(macc, "pipe")
        return outs.reshape(B, *x.shape[1:]), macc

    def loss_fn(params, batch: Batch, rng: jax.Array):
        ids = batch["input_ids"]
        x = embedder.apply(
            {"params": params["embedder"]}, ids, method="encode"
        )
        stack = params["scan_0"]["block_0"]
        sharded = shard_map(
            pipe_body,
            mesh=mesh,
            axis_names=frozenset({"pipe"}),
            in_specs=(P("pipe"), P(), P()),
            out_specs=(P(), P()),
        )
        hidden, metrics_sum = sharded(stack, x, rng)
        hidden = final_norm.apply({"params": params["final_norm"]}, hidden)

        labels, valid = shift_labels(batch)
        mask, weights = _shifted_mask_weights(batch, valid)
        loss, metrics = _ce(
            config, params, hidden, labels, mask, weights,
            z_loss_weight=config.z_loss_weight,
            label_smoothing=config.label_smoothing,
        )
        # Per-layer mean diagnostics + summed aux losses, matching the
        # non-pipelined metric reduction (transformer._reduce_metrics).
        aux_total = jnp.float32(0.0)
        for key, v in metrics_sum.items():
            if key.endswith("_loss"):
                per_mb_sum = v / n_micro  # each microbatch crossed L layers
                metrics[key] = per_mb_sum
                aux_total = aux_total + per_mb_sum
            else:
                metrics[key] = v / (L * n_micro)
        total = loss + aux_total
        metrics["loss"] = total
        metrics["aux_loss"] = aux_total
        return total, metrics

    return loss_fn


def make_1f1b_loss_fn(config: Config, model, mesh: Mesh) -> Callable:
    """1F1B (PipeDream-flush) pipeline: fwd and bwd interleaved in ONE
    lockstep tick scan, gradients accumulated in the scan carry.

    Why not autodiff through the schedule (the GPipe path): reversing the
    tick scan keeps every microbatch's stage activations live until the
    backward replays, so per-stage memory grows with n_micro. Here the
    last stage computes the fused CE for each microbatch the moment it
    exits (the loss lives INSIDE the pipelined region), so its cotangent
    flows back up while later microbatches are still going forward; a
    stage input can be dropped after its bwd tick, bounding the saved-
    activation ring at min(n_micro, 2P-1) microbatch inputs per stage.

    Timetable (stage p, microbatch m, P stages): fwd at tick m+p, bwd at
    tick m + 2P-1-p; T = n_micro + 2P-1 ticks. Steady state does one fwd
    and one bwd per tick ("one forward, one backward"). Activations hop
    down (ppermute +1) and cotangents hop up (ppermute -1) every tick.
    Each bwd tick re-runs the stage forward under jax.vjp from the saved
    input (rematerialization), computing embed (stage 0), the stage
    layers, and final-norm + CE-sums (last stage) in one structurally
    uniform function — the p-dependent parts are selected by masks, so
    all stages trace the same graph and the dead branches contribute
    exact-zero gradients.

    Exactness: the CE is accumulated in token-SUM form and divided by the
    global weight total (precomputed from the full batch), and aux losses
    get cotangent 1/n_micro — identical math to the non-pipelined step,
    so losses and grads match it to numerics. The train step still calls
    jax.value_and_grad: a custom_vjp runs the fused schedule in its
    forward and stashes the already-computed grads as residuals.
    """
    ok, why = pipeline_compatible(config)
    if not ok:
        raise ValueError(f"config not pipeline-compatible: {why}")
    assert config.fused_lm_head_ce, (
        "pipeline train step requires fused_lm_head_ce"
    )
    from luminaai_tpu.ops.fused import fused_lm_head_ce_sums

    Pn = config.pipeline_parallel_size
    L = config.num_layers
    n_local = L // Pn
    n_micro = config.pipeline_microbatches or Pn
    R = min(n_micro, 2 * Pn - 1)  # saved-input ring slots per stage
    T = n_micro + 2 * Pn - 1
    zw = config.z_loss_weight
    dtype = model.dtype
    # Expert and sequence parallelism compose MANUALLY here: those axes
    # join the manual region; microbatch tokens shard over 'expert' (ep
    # borrows the data dimension) and the sequence dim shards over
    # 'sequence' (ring attention body runs in-region, RoPE positions get
    # per-shard global offsets).
    ep = config.expert_parallel_size
    sp = config.sequence_parallel_size
    manual_axes, token_axes = _pipe_manual_axes(config)
    block = TransformerBlock(
        _pipe_block_config(config),
        layer_idx=0, dtype=dtype, deterministic=False,
    )

    from luminaai_tpu.models.layers import Embedder, RMSNorm

    embedder = Embedder(config, dtype=dtype, name=None)
    final_norm = RMSNorm(config.rms_norm_eps, dtype=dtype)
    head_name = "embedding" if config.tie_word_embeddings else "lm_head"

    def schedule_body(stack_local, io, ids_mb, lab_mb, wts_mb, rng, w_total):
        """Manual over 'pipe'. ids/lab/wts arrive pre-split [n_micro, mb, S];
        w_total is the global CE weight sum (denominator)."""
        p = jax.lax.axis_index("pipe")
        is_last = p == Pn - 1
        first_layer = p * n_local
        mb, S = ids_mb.shape[1], ids_mb.shape[2]
        H = config.hidden_size
        fwd_perm = [(i, (i + 1) % Pn) for i in range(Pn)]
        bwd_perm = [(i, (i - 1) % Pn) for i in range(Pn)]
        # Manual sp: S here is the LOCAL chunk; RoPE needs global offsets.
        positions = None
        if sp > 1:
            positions = (
                jax.lax.axis_index("sequence") * S + jnp.arange(S)
            )[None, :]

        def full_fn(stack, io_, x_recv, ids, lab, wts, m_idx):
            """Embed (stage 0) → stage layers → final norm + CE sums (last
            stage). Uniform across stages; masks route the cotangents."""
            emb_x = embedder.apply(
                {"params": io_["embedder"]}, ids, method="encode"
            )
            x_in = jnp.where(p == 0, emb_x, x_recv)
            h, metrics = _stage_apply(
                config, block, stack, x_in,
                jax.random.fold_in(rng, m_idx), n_local, first_layer,
                positions=positions,
            )
            nh = final_norm.apply({"params": io_["final_norm"]}, h)
            emb_head = io_["embedder"][head_name]
            if isinstance(emb_head, nn.meta.AxisMetadata):
                emb_head = emb_head.unbox()
            nll_s, w_s, z_s, n_tok = fused_lm_head_ce_sums(
                nh, emb_head, lab, wts,
                label_smoothing=config.label_smoothing,
                chunk_size=config.loss_chunk_size,
            )
            ce_scalar = nll_s + zw * z_s
            # nll_s * 0: a zero carrying the same varying-axes type as the
            # CE outputs, so the cotangent types line up even when the
            # block metrics dict is empty (dense stacks).
            aux_scalar = nll_s * 0.0
            for key, v in metrics.items():
                if key.endswith("_loss"):
                    aux_scalar = aux_scalar + v
            return (h, ce_scalar, aux_scalar), (metrics, nll_s, w_s, z_s, n_tok)

        def fwd_only(stack, io_, x_recv, ids, m_idx):
            emb_x = embedder.apply(
                {"params": io_["embedder"]}, ids, method="encode"
            )
            x_in = jnp.where(p == 0, emb_x, x_recv)
            h, _ = _stage_apply(
                config, block, stack, x_in,
                jax.random.fold_in(rng, m_idx), n_local, first_layer,
                positions=positions,
            )
            return h

        def varying(a):
            """Upcast to varying over every manual axis (pcast rejects
            axes a value already varies over)."""
            need = tuple(
                ax for ax in manual_axes if ax not in jax.typeof(a).vma
            )
            return jax.lax.pcast(a, need, to="varying") if need else a

        vzeros = lambda tree: jax.tree.map(
            lambda x: varying(jnp.zeros(x.shape, jnp.float32)), tree
        )
        act0 = varying(jnp.zeros((mb, S, H), dtype))
        m_shape = jax.eval_shape(
            lambda: full_fn(
                stack_local, io, act0, ids_mb[0], lab_mb[0], wts_mb[0], 0
            )[1][0]
        )
        carry0 = dict(
            act_send=act0,
            g_send=varying(jnp.zeros((mb, S, H), jnp.float32)),
            saved=varying(jnp.zeros((R, mb, S, H), dtype)),
            g_stack=vzeros(stack_local),
            g_io=vzeros(io),
            ce={
                k: varying(jnp.float32(0.0))
                for k in ("nll", "w", "z", "n_tok")
            },
            macc=vzeros(m_shape),
        )

        def one_tick(carry, t):
            recv_act = jax.lax.ppermute(carry["act_send"], "pipe", fwd_perm)
            recv_g = jax.lax.ppermute(carry["g_send"], "pipe", bwd_perm)

            # ---- backward work (reads the ring BEFORE this tick's store)
            m_b = t - (2 * Pn - 1 - p)
            bwd_valid = (m_b >= 0) & (m_b < n_micro)
            mb_idx = jnp.clip(m_b, 0, n_micro - 1)
            x_saved = jax.lax.dynamic_index_in_dim(
                carry["saved"], mb_idx % R, axis=0, keepdims=False
            )
            ids_b = jax.lax.dynamic_index_in_dim(ids_mb, mb_idx, 0, False)
            lab_b = jax.lax.dynamic_index_in_dim(lab_mb, mb_idx, 0, False)
            wts_b = jax.lax.dynamic_index_in_dim(wts_mb, mb_idx, 0, False)
            _, vjp_fn, aux = jax.vjp(
                lambda st, io_, xr: full_fn(
                    st, io_, xr, ids_b, lab_b, wts_b, mb_idx
                ),
                stack_local, io, x_saved, has_aux=True,
            )
            metrics_b, nll_s, w_s, z_s, n_tok = aux
            live = bwd_valid.astype(jnp.float32)
            # varying(): cotangent VMA types must match the primals', which
            # vary over every manual axis; these masks only derive from the
            # pipe index.
            g_h = varying(
                (jnp.where(is_last, 0.0, recv_g) * live).astype(dtype)
            )
            g_ce = varying(jnp.where(is_last, live / w_total, jnp.float32(0.0)))
            g_aux = varying(live / jnp.float32(n_micro))
            g_stack_c, g_io_c, g_x = vjp_fn((g_h, g_ce, g_aux))
            acc = lambda a, g: jax.tree.map(
                lambda x, y: x + y.astype(jnp.float32) * live, a, g
            )
            g_stack = acc(carry["g_stack"], g_stack_c)
            g_io = acc(carry["g_io"], g_io_c)
            last_live = live * is_last.astype(jnp.float32)
            ce = carry["ce"]
            ce = dict(
                nll=ce["nll"] + nll_s * last_live,
                w=ce["w"] + w_s * last_live,
                z=ce["z"] + z_s * last_live,
                n_tok=ce["n_tok"] + n_tok * last_live,
            )
            macc = jax.tree.map(
                lambda a, m: a + m.astype(jnp.float32) * live,
                carry["macc"], metrics_b,
            )

            # ---- forward work
            m_f = t - p
            fwd_valid = (m_f >= 0) & (m_f < n_micro)
            mf_idx = jnp.clip(m_f, 0, n_micro - 1)
            ids_f = jax.lax.dynamic_index_in_dim(ids_mb, mf_idx, 0, False)
            out_f = fwd_only(stack_local, io, recv_act, ids_f, mf_idx)
            saved = jnp.where(
                fwd_valid,
                jax.lax.dynamic_update_index_in_dim(
                    carry["saved"], recv_act.astype(dtype), mf_idx % R, 0
                ),
                carry["saved"],
            )
            return dict(
                act_send=out_f,
                g_send=g_x.astype(jnp.float32),
                saved=saved,
                g_stack=g_stack,
                g_io=g_io,
                ce=ce,
                macc=macc,
            ), None

        carry, _ = jax.lax.scan(one_tick, carry0, jnp.arange(T))
        # Cross-stage reductions: CE sums live on the last stage, io grads
        # and layer metrics are per-stage partials, stack grads stay
        # stage-local (they ARE the pipe-sharded grad). Under manual ep,
        # token-sharded paths make io/ce/non-expert-stack grads partial
        # over 'expert' too (psum), while wi/wo grads are already total
        # (post-all-to-all experts see every shard's tokens) and stay
        # local; MoE metrics were pmean'd inside the layer, so macc takes
        # a pmean over 'expert' rather than double-counting.
        g_io = jax.tree.map(
            lambda g: jax.lax.psum(g, manual_axes), carry["g_io"]
        )
        ce = jax.tree.map(
            lambda v: jax.lax.psum(v, manual_axes), carry["ce"]
        )
        macc = jax.tree.map(lambda v: jax.lax.psum(v, "pipe"), carry["macc"])
        g_stack = carry["g_stack"]
        if token_axes:
            macc = jax.tree.map(
                lambda v: jax.lax.pmean(v, token_axes), macc
            )
            # wi/wo grads are already total over the expert axis (post
            # all-to-all, experts see every expert-shard's tokens) but
            # still partial over sequence chunks; everything else is
            # partial over every token axis.
            expert_grad_axes = tuple(a for a in token_axes if a != "expert")
            g_stack = jax.tree_util.tree_map_with_path(
                lambda pth, g: (
                    (
                        jax.lax.psum(g, expert_grad_axes)
                        if expert_grad_axes
                        else g
                    )
                    if _is_expert_leaf(pth)
                    else jax.lax.psum(g, token_axes)
                ),
                g_stack,
            )
        return g_stack, g_io, ce, macc

    def loss_fn(params, batch: Batch, rng: jax.Array):
        ids = batch["input_ids"]
        B, S = ids.shape
        mb = B // n_micro
        labels, valid = shift_labels(batch)
        mask, weights = _shifted_mask_weights(batch, valid)
        wts = mask if weights is None else mask * weights
        wts = wts.astype(jnp.float32)
        w_total = jnp.maximum(wts.sum(), 1.0)
        split = lambda x: x.reshape(n_micro, mb, S)
        ids_mb, lab_mb, wts_mb = split(ids), split(labels), split(wts)

        stack = params["scan_0"]["block_0"]
        io = {
            "embedder": params["embedder"],
            "final_norm": params["final_norm"],
        }
        # Replicate the io params over every auto mesh axis before entering
        # the manual region: embed-encode and the fused CE run INSIDE the
        # 1F1B schedule, and XLA's SPMD partitioner check-fails when it has
        # to group the tensor/fsdp collectives those ops would need inside
        # a partial-manual shard_map (spmd_partitioner_util.cc:495). The
        # all-gather happens once per step out here; CE compute is
        # replicated across tensor shards (same trade GPipe makes across
        # pipe shards by running CE outside).
        io = jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(
                x, jax.NamedSharding(mesh, P())
            ),
            io,
        )

        stack_specs = jax.tree_util.tree_map_with_path(
            lambda pth, x: (
                P("pipe", "expert")
                if ep > 1 and _is_expert_leaf(pth)
                else P("pipe")
            ),
            stack,
        )
        # Tokens shard over 'expert' on the microbatch dim (ep > 1) and
        # over 'sequence' on the length dim (sp > 1).
        mb_spec = P(
            None,
            "expert" if ep > 1 else None,
            "sequence" if sp > 1 else None,
        )
        sharded = shard_map(
            schedule_body,
            mesh=mesh,
            axis_names=frozenset(manual_axes),
            in_specs=(
                stack_specs, P(), mb_spec, mb_spec, mb_spec, P(), P(),
            ),
            out_specs=(stack_specs, P(), P(), P()),
        )

        def run(stack_, io_):
            g_stack, g_io, ce, macc = sharded(
                stack_, io_, ids_mb, lab_mb, wts_mb, rng, w_total
            )
            denom = jnp.maximum(ce["w"], 1.0)
            ce_loss = ce["nll"] / denom
            metrics = {
                "ce_loss": ce_loss,
                "perplexity": jnp.exp(jnp.clip(ce_loss, max=20.0)),
                "tokens_in_loss": ce["n_tok"],
            }
            total = ce_loss
            if zw > 0.0:
                z = ce["z"] / denom * zw
                total = total + z
                metrics["z_loss"] = z
            metrics["total_loss"] = total
            aux_total = jnp.float32(0.0)
            for key, v in macc.items():
                if key.endswith("_loss"):
                    per_mb = v / n_micro
                    metrics[key] = per_mb
                    aux_total = aux_total + per_mb
                else:
                    metrics[key] = v / (L * n_micro)
            total = total + aux_total
            metrics["loss"] = total
            metrics["aux_loss"] = aux_total
            return total, metrics, g_stack, g_io

        @jax.custom_vjp
        def f(stack_, io_):
            loss, metrics, _, _ = run(stack_, io_)
            return loss, metrics

        def f_fwd(stack_, io_):
            loss, metrics, g_stack, g_io = run(stack_, io_)
            return (loss, metrics), (g_stack, g_io)

        def f_bwd(res, cts):
            g_stack, g_io = res
            g_loss = cts[0]
            scale = lambda t: jax.tree.map(lambda g: g * g_loss, t)
            return scale(g_stack), scale(g_io)

        f.defvjp(f_fwd, f_bwd)
        return f(stack, io)

    return loss_fn


def make_pipeline_fwd_metrics_fn(config: Config, model, mesh: Mesh) -> Callable:
    """Forward-only pipeline eval: deterministic routing, CE computed at
    the last stage inside the region (same manual machinery as the 1F1B
    train loss, minus the backward) — so it supports every mesh the train
    path does, including manual expert parallelism."""
    ok, why = pipeline_compatible(config)
    if not ok:
        raise ValueError(f"config not pipeline-compatible: {why}")
    assert config.fused_lm_head_ce, (
        "pipeline eval requires fused_lm_head_ce"
    )
    from luminaai_tpu.ops.fused import fused_lm_head_ce_sums

    Pn = config.pipeline_parallel_size
    L = config.num_layers
    n_local = L // Pn
    n_micro = config.pipeline_microbatches or Pn
    T = n_micro + Pn - 1
    zw = config.z_loss_weight
    dtype = model.dtype
    ep = config.expert_parallel_size
    sp = config.sequence_parallel_size
    manual_axes, token_axes = _pipe_manual_axes(config)
    block = TransformerBlock(
        _pipe_block_config(config),
        layer_idx=0, dtype=dtype, deterministic=True,
    )

    from luminaai_tpu.models.layers import Embedder, RMSNorm

    embedder = Embedder(config, dtype=dtype, name=None)
    final_norm = RMSNorm(config.rms_norm_eps, dtype=dtype)
    head_name = "embedding" if config.tie_word_embeddings else "lm_head"

    def schedule_body(stack_local, io, ids_mb, lab_mb, wts_mb, rng):
        p = jax.lax.axis_index("pipe")
        is_last = p == Pn - 1
        first_layer = p * n_local
        mb, S = ids_mb.shape[1], ids_mb.shape[2]
        H = config.hidden_size
        fwd_perm = [(i, (i + 1) % Pn) for i in range(Pn)]
        positions = None
        if sp > 1:
            positions = (
                jax.lax.axis_index("sequence") * S + jnp.arange(S)
            )[None, :]

        def fwd_ce(x_recv, ids, lab, wts, m_idx):
            emb_x = embedder.apply(
                {"params": io["embedder"]}, ids, method="encode"
            )
            x_in = jnp.where(p == 0, emb_x, x_recv)
            h, metrics = _stage_apply(
                config, block, stack_local, x_in,
                jax.random.fold_in(rng, m_idx), n_local, first_layer,
                positions=positions,
            )
            nh = final_norm.apply({"params": io["final_norm"]}, h)
            emb_head = io["embedder"][head_name]
            if isinstance(emb_head, nn.meta.AxisMetadata):
                emb_head = emb_head.unbox()
            sums = fused_lm_head_ce_sums(
                nh, emb_head, lab, wts,
                label_smoothing=config.label_smoothing,
                chunk_size=config.loss_chunk_size,
            )
            return h, sums, metrics

        def varying(a):
            need = tuple(
                ax for ax in manual_axes if ax not in jax.typeof(a).vma
            )
            return jax.lax.pcast(a, need, to="varying") if need else a

        act0 = varying(jnp.zeros((mb, S, H), dtype))
        m_shape = jax.eval_shape(
            lambda: fwd_ce(act0, ids_mb[0], lab_mb[0], wts_mb[0], 0)[2]
        )
        carry0 = dict(
            act_send=act0,
            ce={
                k: varying(jnp.float32(0.0))
                for k in ("nll", "w", "z", "n_tok")
            },
            macc=jax.tree.map(
                lambda s: varying(jnp.zeros(s.shape, jnp.float32)), m_shape
            ),
        )

        def one_tick(carry, t):
            recv_act = jax.lax.ppermute(carry["act_send"], "pipe", fwd_perm)
            m_f = t - p
            valid = (m_f >= 0) & (m_f < n_micro)
            mf_idx = jnp.clip(m_f, 0, n_micro - 1)
            ids_f = jax.lax.dynamic_index_in_dim(ids_mb, mf_idx, 0, False)
            lab_f = jax.lax.dynamic_index_in_dim(lab_mb, mf_idx, 0, False)
            wts_f = jax.lax.dynamic_index_in_dim(wts_mb, mf_idx, 0, False)
            out_f, sums, metrics = fwd_ce(recv_act, ids_f, lab_f, wts_f, mf_idx)
            live = valid.astype(jnp.float32)
            last_live = live * is_last.astype(jnp.float32)
            nll_s, w_s, z_s, n_tok = sums
            ce = carry["ce"]
            ce = dict(
                nll=ce["nll"] + nll_s * last_live,
                w=ce["w"] + w_s * last_live,
                z=ce["z"] + z_s * last_live,
                n_tok=ce["n_tok"] + n_tok * last_live,
            )
            macc = jax.tree.map(
                lambda a, m: a + m.astype(jnp.float32) * live,
                carry["macc"], metrics,
            )
            return dict(act_send=out_f, ce=ce, macc=macc), None

        carry, _ = jax.lax.scan(one_tick, carry0, jnp.arange(T))
        ce = jax.tree.map(
            lambda v: jax.lax.psum(v, manual_axes), carry["ce"]
        )
        macc = jax.tree.map(lambda v: jax.lax.psum(v, "pipe"), carry["macc"])
        if token_axes:
            macc = jax.tree.map(
                lambda v: jax.lax.pmean(v, token_axes), macc
            )
        return ce, macc

    def eval_loss(params, batch: Batch):
        ids = batch["input_ids"]
        B, S = ids.shape
        mb = B // n_micro
        labels, valid = shift_labels(batch)
        mask, weights = _shifted_mask_weights(batch, valid)
        wts = mask if weights is None else mask * weights
        wts = wts.astype(jnp.float32)
        split = lambda x: x.reshape(n_micro, mb, S)

        stack = params["scan_0"]["block_0"]
        io = {
            "embedder": params["embedder"],
            "final_norm": params["final_norm"],
        }
        io = jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(
                x, jax.NamedSharding(mesh, P())
            ),
            io,
        )
        stack_specs = jax.tree_util.tree_map_with_path(
            lambda pth, x: (
                P("pipe", "expert")
                if ep > 1 and _is_expert_leaf(pth)
                else P("pipe")
            ),
            stack,
        )
        mb_spec = P(
            None,
            "expert" if ep > 1 else None,
            "sequence" if sp > 1 else None,
        )
        sharded = shard_map(
            schedule_body,
            mesh=mesh,
            axis_names=frozenset(manual_axes),
            in_specs=(stack_specs, P(), mb_spec, mb_spec, mb_spec, P()),
            out_specs=(P(), P()),
        )
        ce, macc = sharded(
            stack, io, split(ids), split(labels), split(wts),
            jax.random.key(0),
        )
        denom = jnp.maximum(ce["w"], 1.0)
        ce_loss = ce["nll"] / denom
        metrics = {
            "ce_loss": ce_loss,
            "perplexity": jnp.exp(jnp.clip(ce_loss, max=20.0)),
            "tokens_in_loss": ce["n_tok"],
        }
        total = ce_loss
        if zw > 0.0:
            z = ce["z"] / denom * zw
            total = total + z
            metrics["z_loss"] = z
        metrics["total_loss"] = total
        aux_total = jnp.float32(0.0)
        for key, v in macc.items():
            if key.endswith("_loss"):
                per_mb = v / n_micro
                metrics[key] = per_mb
                aux_total = aux_total + per_mb
            else:
                metrics[key] = v / (L * n_micro)
        metrics["loss"] = total + aux_total
        metrics["aux_loss"] = aux_total
        return metrics

    return eval_loss


def make_pipeline_train_step(
    config: Config,
    model,
    state_shardings: TrainState,
    mesh: Mesh,
    schedule: Optional[optax.Schedule],
    tx: optax.GradientTransformation,
):
    """Donated, sharded, jitted pipeline train step (1F1B or GPipe per
    config.pipeline_schedule).

    Same contract as parallel.train_step.make_train_step — in fact it IS
    that step builder with the pipeline loss injected (grad accumulation
    is validated to 1 under pp, so the shared body's accumulation path
    degenerates to a single value_and_grad; clipping, donation, and metric
    reporting stay single-sourced).
    """
    from luminaai_tpu.parallel.train_step import make_train_step

    if config.pipeline_schedule == "1f1b":
        loss_fn = make_1f1b_loss_fn(config, model, mesh)
    else:
        loss_fn = make_pipeline_loss_fn(config, model, mesh)
    return make_train_step(
        config, model, state_shardings, mesh, schedule, tx,
        loss_fn=loss_fn,
    )


def make_pipeline_eval_step(
    config: Config,
    model,
    state_shardings: TrainState,
    mesh: Mesh,
):
    """Forward-only eval over the pipeline schedule (deterministic
    routing) — the non-pipelined eval step would all-gather every stage's
    layers onto every device per scan iteration. Under the 1F1B schedule
    the eval loss shares its in-region CE machinery (and so composes with
    manual expert parallelism); the GPipe schedule keeps its
    autodiff-free forward loss."""
    from luminaai_tpu.parallel.train_step import make_eval_step

    if config.pipeline_schedule == "1f1b":
        eval_loss = make_pipeline_fwd_metrics_fn(config, model, mesh)
    else:
        pipe_loss = make_pipeline_loss_fn(
            config, model, mesh, deterministic=True
        )
        fixed_rng = jax.random.key(0)  # deterministic path ignores it

        def eval_loss(params, batch):
            _, metrics = pipe_loss(params, batch, fixed_rng)
            return metrics

    return make_eval_step(
        config, model, state_shardings, mesh, loss_fn=eval_loss
    )
