"""Logical-axis sharding rules and sharded state initialization.

Replaces the reference's per-backend sharding logic (ref: Src/Main_Scripts/
core/backend/backend_fsdp.py:44 auto-wrap policy, backend_deepspeed.py ZeRO
stage config). Model code annotates params/activations with *logical* axis
names (`flax.linen.with_logical_partitioning`); this module maps those names
onto mesh axes. One rule table expresses what the reference needed three
backends for:

  - 'embed' → fsdp        : parameters sharded over the fsdp axis = ZeRO-3.
  - 'heads'/'mlp' → tensor: Megatron-style tensor parallelism. Attention is
    column-parallel on wq/wk/wv (heads axis) and row-parallel on wo, so the
    only collective per block is the psum XLA inserts after the row-parallel
    matmuls.
  - 'expert' → expert     : expert parallelism; dispatch einsums trigger
    all-to-alls over ICI.
  - 'activation_length' → sequence: context parallelism (ring attention).

Optimizer state inherits parameter shardings (ZeRO-1/2 comes for free:
Adam moments carry the same fsdp sharding as their parameter).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from luminaai_tpu.config import Config

# (logical axis, mesh axis/axes). First matching rule wins; a logical axis
# mapped to None stays replicated along that dimension.
LOGICAL_AXIS_RULES: Tuple[Tuple[str, Any], ...] = (
    # Leading scan axis on stacked per-layer params (scan_layers=True):
    # the pipeline axis — stage p holds its layer slice (replicated when
    # pipe=1).
    ("layers", "pipe"),
    ("embed", "fsdp"),
    ("vocab", "tensor"),
    ("heads", "tensor"),
    ("kv_heads", "tensor"),
    ("mlp", "tensor"),
    ("mlp_fused", "tensor"),
    ("expert", "expert"),
    ("head_dim", None),
    # Activations: batch over data+fsdp (fsdp reuses its devices as extra
    # data parallelism for activations), sequence over the sp axis.
    ("activation_batch", ("data", "fsdp")),
    ("activation_length", "sequence"),
    ("activation_embed", None),
    ("activation_heads", "tensor"),
    ("activation_kv_heads", "tensor"),
    ("activation_vocab", "tensor"),
    ("activation_exp_batch", ("data", "fsdp")),
)


def logical_axis_rules(config: Optional[Config] = None):
    """Rule table, adjusted for configs where a mapping would not divide.

    kv_heads often < tensor size under GQA; dropping that one rule (the kv
    projections replicate over tensor) beats failing to compile — same
    fallback the ref fsdp backend used for undivisible wrap units.
    """
    rules = list(LOGICAL_AXIS_RULES)
    if config is not None and config.tensor_parallel_size > 1:
        if config.num_kv_heads % config.tensor_parallel_size != 0:
            rules = [
                (l, None if l in ("kv_heads", "activation_kv_heads") else m)
                for l, m in rules
            ]
    if (
        config is not None
        and config.pipeline_parallel_size > 1
        and config.sequence_parallel_size > 1
    ):
        # Inside the 1F1B manual region the 'sequence' axis is manual:
        # activations arrive pre-chunked and the ring body does its own
        # ppermutes, so an auto activation_length constraint would ask the
        # SPMD partitioner to reshard over a manual axis (the group-check
        # crash class). Every block constraint traces inside the region
        # under pp, so dropping the rule for the whole pipeline step is
        # sound.
        rules = [
            (l, None if l == "activation_length" else m) for l, m in rules
        ]
    return tuple(rules)


def manual_axis_rules(config: Optional[Config], manual_axes) -> Tuple:
    """logical_axis_rules with every rule touching a MANUAL mesh axis
    dropped (mapped to None).

    Inside a partial-auto shard_map region (the hierarchical gradient
    sync's (data, fsdp) region, parallel/grad_reduce.py) the manual axes
    are invisible to the SPMD partitioner: a with_sharding_constraint
    naming one would ask it to reshard over an axis it no longer owns —
    the same group-check crash class the 1F1B pipeline dodges by
    dropping 'activation_length' (see logical_axis_rules above). Rules
    over the remaining AUTO axes (tensor, expert, ...) pass through
    untouched."""
    manual = frozenset(manual_axes)

    def touches_manual(mesh_axes) -> bool:
        if mesh_axes is None:
            return False
        axes = (
            mesh_axes if isinstance(mesh_axes, (tuple, list))
            else (mesh_axes,)
        )
        return any(a in manual for a in axes)

    return tuple(
        (logical, None if touches_manual(mesh) else mesh)
        for logical, mesh in logical_axis_rules(config)
    )


class TrainState(struct.PyTreeNode):
    """Minimal train state: params + optimizer state + step + rng.

    (ref training/trainer.py keeps these scattered across the Trainer object
    and the DeepSpeed engine; here it is one pytree so the whole update is a
    single donated jit.) The optax transform itself is NOT stored — it is
    closed over by the train step, so the orchestrator can swap optimizers
    (LR override) without changing the pytree structure the jit was traced
    with.
    """

    step: jax.Array
    params: Any
    opt_state: Any
    rng: jax.Array

    def apply_gradients(
        self,
        grads,
        tx: optax.GradientTransformation,
        host_offload: bool = False,
    ):
        opt_state = self.opt_state
        if host_offload:
            # Optimizer state lives in pinned host RAM: stream it to
            # device memory for the update and back after (ref DeepSpeed
            # cpu_offload_optimizer role). Scalars (Adam count) never
            # left device memory (state_shardings).
            opt_state = jax.tree.map(
                lambda x: (
                    jax.device_put(x, jax.memory.Space.Device)
                    if x.ndim > 0
                    else x
                ),
                opt_state,
            )
        updates, new_opt_state = tx.update(grads, opt_state, self.params)
        new_params = optax.apply_updates(self.params, updates)
        if host_offload:
            new_opt_state = jax.tree.map(
                lambda x: (
                    jax.device_put(x, jax.memory.Space.Host)
                    if x.ndim > 0
                    else x
                ),
                new_opt_state,
            )
        return self.replace(
            step=self.step + 1,
            params=new_params,
            opt_state=new_opt_state,
        )


def unbox(tree):
    """Strip flax Partitioned metadata boxes, leaving raw arrays."""
    return jax.tree.map(
        lambda x: x.unbox() if isinstance(x, nn.meta.AxisMetadata) else x,
        tree,
        is_leaf=lambda x: isinstance(x, nn.meta.AxisMetadata),
    )


def batch_spec() -> PartitionSpec:
    """Input batches: [B, S] batch over (data, fsdp), sequence over sp."""
    return PartitionSpec(("data", "fsdp"), "sequence")


def make_init_fn(config: Config, model, tx):
    def init(rng: jax.Array) -> TrainState:
        params_rng, state_rng = jax.random.split(rng)
        dummy = jnp.zeros((1, config.seq_length), dtype=jnp.int32)
        params = unbox(model.init(params_rng, dummy)["params"])
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
            rng=state_rng,
        )

    return init


def _abstract_boxed_params(config: Config, model):
    dummy = jnp.zeros((1, config.seq_length), dtype=jnp.int32)
    return jax.eval_shape(
        lambda r: model.init(r, dummy)["params"], jax.random.key(0)
    )


def _shardings_from_boxed(config: Config, boxed, mesh: Mesh):
    rules = logical_axis_rules(config)
    replicated = NamedSharding(mesh, PartitionSpec())

    def spec_of(leaf):
        if isinstance(leaf, nn.LogicallyPartitioned):
            logical = PartitionSpec(*leaf.names)
            return nn.logical_to_mesh_sharding(logical, mesh, rules)
        return replicated

    return jax.tree.map(
        spec_of, boxed, is_leaf=lambda x: isinstance(x, nn.meta.AxisMetadata)
    )


def param_shardings(config: Config, model, mesh: Mesh):
    """NamedSharding tree for params from their logical annotations."""
    return _shardings_from_boxed(
        config, _abstract_boxed_params(config, model), mesh
    )


def state_shardings(config: Config, model, tx, mesh: Mesh) -> TrainState:
    """Shardings for the full TrainState without materializing it.

    Optimizer-state leaves inherit their parameter's sharding (matched by
    dict-key path suffix — Adam mu/nu mirror the param tree); counters and
    scalars replicate. This is the ZeRO-1/2 analogue: sharded Adam moments.
    """
    boxed = _abstract_boxed_params(config, model)  # one model.init trace
    p_shardings = _shardings_from_boxed(config, boxed, mesh)
    replicated = NamedSharding(mesh, PartitionSpec())

    flat_param = {
        tuple(k.key for k in path): s
        for path, s in jax.tree_util.tree_flatten_with_path(p_shardings)[0]
    }

    abstract_opt = jax.eval_shape(tx.init, unbox(boxed))

    # Optimizer-state offload to host RAM (memory_kind='pinned_host'):
    # XLA streams the moments to HBM around the update — the TPU analogue
    # of the reference's DeepSpeed cpu_offload_optimizer (config field
    # cpu_offload=True; Src/Main_Scripts/config/config_manager.py). Gate
    # on the memory spaces the backend actually exposes (the CPU backend
    # also has pinned_host, which is what lets the full offloaded step run
    # under CPU test). Scalars (Adam's count) stay in device memory — the
    # SPMD partitioner rejects placement annotations on replicated scalars.
    offload = False
    if config.host_offload_optimizer:
        # TPU-only at execution time: XLA:CPU has no runtime for the
        # annotate_device_placement custom call (and its SPMD partitioner
        # rejects placement on replicated arrays), so enabling it off-TPU
        # would crash at step compile. The CPU test instead validates
        # placement + the in-jit streaming trace directly
        # (tests/test_sharding.py test_host_offload_optimizer_*).
        platform = mesh.devices.flat[0].platform
        offload = platform == "tpu"
        if not offload:
            import logging

            logging.getLogger(__name__).warning(
                "host_offload_optimizer ignored: backend %s does not "
                "support pinned_host placement in compiled programs",
                platform,
            )

    def opt_spec(path, leaf):
        keys = tuple(
            p.key for p in path if isinstance(p, jax.tree_util.DictKey)
        )
        sharding = replicated
        for plen in range(len(keys), 0, -1):
            sh = flat_param.get(keys[-plen:])
            if sh is not None and len(sh.spec) <= len(leaf.shape):
                sharding = sh
                break
        if offload and leaf.ndim > 0:
            sharding = sharding.with_memory_kind("pinned_host")
        return sharding

    opt_shardings = jax.tree_util.tree_map_with_path(opt_spec, abstract_opt)

    return TrainState(
        step=replicated,
        params=p_shardings,
        opt_state=opt_shardings,
        rng=replicated,
    )


def init_sharded_state(
    config: Config, model, tx, mesh: Mesh, rng: jax.Array
) -> Tuple[TrainState, TrainState]:
    """Jit-init the TrainState directly into its target shardings.

    Parameters are *born sharded* — no host-side full materialization, which
    is what lets B100/B300-class configs init on a pod at all (the ref relied
    on DeepSpeed ZeRO-3 deferred init for the same reason).

    Returns (state, shardings).
    """
    shardings = state_shardings(config, model, tx, mesh)
    init = make_init_fn(config, model, tx)
    init_shardings = shardings
    if is_host_offloaded(shardings.opt_state):
        init_shardings = jax.tree.map(
            lambda s: (
                s.with_memory_kind("device")
                if getattr(s, "memory_kind", None) == "pinned_host"
                else s
            ),
            shardings,
            is_leaf=lambda s: isinstance(s, NamedSharding),
        )
        with mesh, nn.logical_axis_rules(logical_axis_rules(config)):
            state = jax.jit(init, out_shardings=init_shardings)(rng)
        state = state.replace(
            opt_state=jax.device_put(state.opt_state, shardings.opt_state)
        )
        return state, shardings
    with mesh, nn.logical_axis_rules(logical_axis_rules(config)):
        state = jax.jit(init, out_shardings=init_shardings)(rng)
    return state, shardings


def is_host_offloaded(shardings_tree) -> bool:
    """True when any leaf sharding places its buffer in pinned host RAM.

    Single source of truth for the offload marker — the train step uses
    it to enable in-jit streaming, and init/reinit paths use it to route
    around the SPMD partitioner's rejection of mixed-memory-kind jit
    outputs (init into device memory, then device_put to pinned_host)."""
    return any(
        getattr(s, "memory_kind", None) == "pinned_host"
        for s in jax.tree.leaves(shardings_tree)
    )


def init_opt_to_shardings(tx, params, opt_shardings):
    """Initialize fresh optimizer state into (possibly host-offloaded)
    target shardings. Mixed memory kinds can't be jit out_shardings
    (SPMD partitioner limitation), so offloaded trees init on device and
    stream over afterwards — the reinit twin of init_sharded_state, for
    mid-run rebuilds like expert evolution (training/trainer.py)."""
    if not is_host_offloaded(opt_shardings):
        return jax.jit(tx.init, out_shardings=opt_shardings)(params)
    device_shardings = jax.tree.map(
        lambda s: (
            s.with_memory_kind("device")
            if getattr(s, "memory_kind", None) == "pinned_host"
            else s
        ),
        opt_shardings,
        is_leaf=lambda s: isinstance(s, NamedSharding),
    )
    opt_state = jax.jit(tx.init, out_shardings=device_shardings)(params)
    return jax.device_put(opt_state, opt_shardings)
