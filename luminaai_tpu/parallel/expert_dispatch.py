"""Cross-host expert parallelism: hierarchical all-to-all token dispatch.

ROADMAP item 3 / X-MoE (PAPERS.md, arxiv 2508.13337): the gmm and
einsum/gather dispatch modes reach remote experts through a REPLICATED
layout — tokens are replicated over the 'expert' mesh axis, every expert
shard runs its experts over the whole local batch, and a full-activation
psum over 'expert' assembles the outputs. That works inside one host's
ICI, but the psum payload is the entire [G, S, H] token tensor: expert
capacity cannot scale past one host because every added expert shard
re-crosses the whole batch. This module replaces that with true token
routing:

  - **padding-free token buffers**: tokens are sorted by destination
    expert shard and packed into per-destination buckets; per-destination
    counts are exchanged FIRST (a [ep, E/ep] int32 all-to-all), so the
    payload all-to-all carries only routed tokens plus a pow2-bucketed
    static bound (`DispatchPlan.bucket_rows`) instead of the
    capacity-padded [E, G, C, H] slabs of the einsum path. Dropped pairs
    never travel.

  - **two-stage hierarchical all-to-all**: the expert axis is factored as
    dcn × ici (hosts × chips-per-host, `config.expert_dcn_size`); stage 1
    exchanges buckets between ICI peers within each host so that every
    token sits on the local rail matching its destination's local index,
    stage 2 crosses hosts along fixed rails. Fewer, larger DCN messages
    (the DeepSpeed/X-MoE hierarchy), and the jaxpr keeps the two stages
    as separate collectives so the comms auditor
    (analysis/jaxpr_audit.enumerate_collectives) can price DCN-crossing
    bytes separately. Single-stage fallback when there is no dcn tier.

  - **dispatch/compute overlap**: the bucket rows are split into chunks
    (`config.moe_a2a_overlap_chunks`); each chunk's stage-2 exchange is
    data-independent of the other chunks' expert FFN compute, so XLA's
    latency-hiding scheduler can run chunk 1's DCN transfer under chunk
    0's grouped matmul.

The expert FFN itself reuses the megablox grouped-matmul contract from
models/moe.py (`gmm_fn`, row-sorted buffers, group_sizes exclusion,
operand masking) so the kernel boundary stays clean per the
portable-dispatch framing of the Triton fused-MoE paper (arxiv
2605.23911): swap the gmm and the whole dispatch pipeline is unchanged.

This module is also the sanctioned home for raw collective calls:
astlint rule LX010 fails `lumina analyze` on direct `lax.all_to_all` /
`lax.ppermute` use outside `parallel/` — route through
`parallel.mesh.all_to_all` / `parallel.mesh.ppermute` (thin wrappers
kept next to the shard_map compat wrapper) so every collective call
site in model code stays enumerable.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from luminaai_tpu.parallel.mesh import all_to_all, shard_map

logger = logging.getLogger(__name__)

__all__ = [
    "DispatchPlan",
    "make_dispatch_plan",
    "hierarchical_groups",
    "hierarchical_all_to_all",
    "a2a_expert_ffn",
    "expert_a2a_probe",
    "next_pow2",
]


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (n >= 1)."""
    n = max(1, int(n))
    p = 1
    while p < n:
        p *= 2
    return p


# --------------------------------------------------------------------------
# static plan: bucket bound + byte accounting
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class DispatchPlan:
    """Static shape/byte plan for one a2a dispatch (per expert shard).

    Everything here is derived from config shapes at trace time — the
    numbers describe the traced program, not a run — which is what lets
    bench extras and the comms auditor price the dispatch without
    hardware. Byte formulas (one direction; dispatch+combine doubles
    them):

      payload_bytes   = ep * bucket_rows * hidden * itemsize
                        (the bucketed token buffer one shard sends)
      stage ici bytes = payload * (ici-1)/ici   (leaves the chip, stays
                        on-host)
      stage dcn bytes = payload * (dcn-1)/dcn   (crosses hosts)

    The replicated-gather baseline these replace (gmm/einsum dispatch
    with tokens replicated over 'expert' + a full-activation psum over
    the expert axis) moves, per shard per direction,
    ring-allreduce-style ~2*(ax-1)/ax of the full [G_dp, S, H] token
    tensor across the expert axis — `baseline_*_bytes` below. The a2a
    advantage is structural: its payload shards the batch over the
    expert axis (G_local = G_dp/ep) and carries only routed tokens, so
    dcn bytes scale like cf*k/ep of the baseline's.
    """

    ep: int               # expert-axis size (dcn * ici)
    dcn: int              # host tier size (1 = single stage)
    ici: int              # per-host tier size
    local_groups: int     # G_l: batch groups per expert shard
    seq: int
    top_k: int
    capacity: int         # per-(group, expert) token capacity
    experts_local: int    # E / ep
    hidden: int
    itemsize: int         # payload dtype bytes
    bucket_rows: int      # B: pow2-bucketed per-destination row bound
    n_chunks: int         # overlap chunks (stage-2/compute pipelining)
    dp_groups: int        # G_dp: groups per (data,fsdp) shard (baseline)

    @property
    def pair_rows(self) -> int:
        return self.local_groups * self.seq * self.top_k

    @property
    def payload_bytes(self) -> int:
        return self.ep * self.bucket_rows * self.hidden * self.itemsize

    @property
    def counts_bytes(self) -> int:
        return self.ep * self.experts_local * 4

    def stage_bytes(self, stage: str) -> int:
        """One-direction off-device payload bytes for a stage ('ici' or
        'dcn'); 0 when the stage has one participant."""
        ax = self.ici if stage == "ici" else self.dcn
        return int(self.payload_bytes * (ax - 1) / ax) if ax > 1 else 0

    @property
    def a2a_dcn_bytes(self) -> int:
        """DCN-crossing bytes per shard per step (dispatch + combine)."""
        return 2 * self.stage_bytes("dcn")

    @property
    def baseline_psum_bytes(self) -> int:
        """The replicated path's expert-axis psum payload: the full
        per-(data,fsdp)-shard token activation, ring-reduced over the
        expert axis (~2x(ep-1)/ep of it leaves each shard)."""
        act = self.dp_groups * self.seq * self.hidden * self.itemsize
        return int(2 * act * (self.ep - 1) / self.ep) if self.ep > 1 else 0

    @property
    def baseline_dcn_bytes(self) -> int:
        """DCN-crossing share of the replicated path's expert psum."""
        act = self.dp_groups * self.seq * self.hidden * self.itemsize
        return int(2 * act * (self.dcn - 1) / self.dcn) if self.dcn > 1 else 0

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d.update(
            payload_bytes=self.payload_bytes,
            counts_bytes=self.counts_bytes,
            ici_stage_bytes=self.stage_bytes("ici"),
            dcn_stage_bytes=self.stage_bytes("dcn"),
            a2a_dcn_bytes=self.a2a_dcn_bytes,
            baseline_psum_bytes=self.baseline_psum_bytes,
            baseline_dcn_bytes=self.baseline_dcn_bytes,
        )
        return d


def make_dispatch_plan(
    *,
    ep: int,
    dcn_size: int,
    local_groups: int,
    seq: int,
    top_k: int,
    capacity: int,
    num_experts: int,
    hidden: int,
    itemsize: int,
    overlap_chunks: int = 1,
    dp_groups: Optional[int] = None,
) -> DispatchPlan:
    """Resolve the static dispatch plan for one expert shard.

    bucket_rows is the pow2-bucketed bound on tokens any one destination
    shard can receive from this shard: kept pairs are capped both by the
    local pair count (G_l*S*k) and by the destination's capacity budget
    (G_l * E_local * C), so the bucket never overflows — routing-drop
    semantics stay exactly _sort_routing's, which is what pins a2a
    bit-comparable to the gather path."""
    if dcn_size < 1 or ep % dcn_size:
        raise ValueError(
            f"expert_dcn_size {dcn_size} must divide the expert axis {ep}"
        )
    e_l = num_experts // ep
    n_pairs = local_groups * seq * top_k
    bound = min(n_pairs, local_groups * e_l * capacity)
    bucket = next_pow2(bound)
    chunks = max(1, int(overlap_chunks))
    while bucket % chunks:
        chunks -= 1
    return DispatchPlan(
        ep=ep,
        dcn=dcn_size,
        ici=ep // dcn_size,
        local_groups=local_groups,
        seq=seq,
        top_k=top_k,
        capacity=capacity,
        experts_local=e_l,
        hidden=hidden,
        itemsize=itemsize,
        bucket_rows=bucket,
        n_chunks=chunks,
        dp_groups=dp_groups if dp_groups is not None else local_groups * ep,
    )


def export_plan_gauges(plan: DispatchPlan, registry=None) -> None:
    """ep_a2a_bytes{stage} gauges from the static plan. Best-effort: the
    plan is built at trace time inside the model forward, so this must
    never break a trace over a telemetry hiccup."""
    try:
        from luminaai_tpu.monitoring.telemetry import get_registry

        registry = registry or get_registry()
        g = registry.gauge(
            "ep_a2a_bytes",
            "Static per-shard one-direction payload bytes of the expert "
            "a2a dispatch per stage (from the DispatchPlan, trace time)",
            labelnames=("stage",),
        )
        g.labels(stage="ici").set(float(plan.stage_bytes("ici")))
        g.labels(stage="dcn").set(float(plan.stage_bytes("dcn")))
    except Exception:  # pragma: no cover - telemetry must not break traces
        logger.debug("ep_a2a_bytes gauge export failed", exc_info=True)


# --------------------------------------------------------------------------
# hierarchical all-to-all
# --------------------------------------------------------------------------


def hierarchical_groups(
    ep: int, dcn: int
) -> Tuple[List[List[int]], List[List[int]]]:
    """Factor a single expert axis of size ep = dcn*ici into the two
    collective tiers. Shard s = h*ici + i (hosts outermost — matching
    how contiguous device blocks land on hosts for the trailing mesh
    axes). Stage 1 groups are the contiguous per-host blocks (ICI);
    stage 2 groups are the strided cross-host rails (DCN) — the comms
    auditor uses exactly this contiguous-vs-strided signature to
    classify a collective's tier."""
    ici = ep // dcn
    stage1 = [[h * ici + i for i in range(ici)] for h in range(dcn)]
    stage2 = [[h * ici + i for h in range(dcn)] for i in range(ici)]
    return stage1, stage2


def _stage1(x, axis_name, dcn, ici, groups):
    """Intra-host exchange: destination-local-index buckets move to the
    matching ICI peer. [dcn, ici_dest, ...] -> [dcn, ici_src, ...]."""
    return all_to_all(
        x, axis_name, split_axis=1, concat_axis=1, tiled=True,
        axis_index_groups=groups,
    )


def _stage2(x, axis_name, dcn, ici, groups):
    """Cross-host exchange along fixed rails. [dcn_dest, ici, ...] ->
    [dcn_src, ici, ...]. Block-level all-to-all with split == concat is
    an involution, so the combine path reuses the same call."""
    return all_to_all(
        x, axis_name, split_axis=0, concat_axis=0, tiled=True,
        axis_index_groups=groups,
    )


def hierarchical_all_to_all(
    x: jax.Array,
    ici_axis: str,
    *,
    dcn_axis: Optional[str] = None,
    dcn_size: int = 1,
) -> jax.Array:
    """Destination-major bucket exchange, hierarchical when a DCN tier
    exists. `x` is [ep, ...payload...] with leading dim indexing the
    destination shard (d = h*ici + i); returns [ep, ...] with leading
    dim indexing the source shard — i.e. exactly what a single flat
    `all_to_all(tiled=True)` over the whole axis produces, but staged
    ici-then-dcn so the DCN tier sees few large rail-aligned messages.

    Two spellings of the hierarchy:
      - `dcn_axis` names a REAL second mesh axis (the 2D dcn×ici probe
        mesh `cli diagnose` builds); `dcn_size` must then carry that
        axis's size (shapes are static — the body can't ask the mesh);
      - `dcn_size` alone factors a single named axis (the in-model
        path: the standard mesh has one 'expert' axis;
        `config.expert_dcn_size` declares how much of it spans hosts)
        via axis_index_groups.
    With neither, this is the single-stage fallback."""
    if dcn_axis is None and dcn_size <= 1:
        return all_to_all(x, ici_axis, split_axis=0, concat_axis=0,
                          tiled=True)
    if dcn_axis is not None:
        # Real 2D mesh: x's leading dim is still the flat destination
        # id; reshape to (dcn, ici) blocks, stage over each named axis.
        dcn = int(dcn_size)
        ici = x.shape[0] // dcn
        r = x.reshape((dcn, ici) + x.shape[1:])
        r = all_to_all(r, ici_axis, split_axis=1, concat_axis=1, tiled=True)
        r = all_to_all(r, dcn_axis, split_axis=0, concat_axis=0, tiled=True)
        return r.reshape(x.shape)
    ep = x.shape[0]
    dcn = int(dcn_size)
    ici = ep // dcn
    g1, g2 = hierarchical_groups(ep, dcn)
    r = x.reshape((dcn, ici) + x.shape[1:])
    r = _stage1(r, ici_axis, dcn, ici, g1)
    r = _stage2(r, ici_axis, dcn, ici, g2)
    return r.reshape(x.shape)


# --------------------------------------------------------------------------
# the expert FFN over routed buckets (runs inside a shard_map body)
# --------------------------------------------------------------------------


def a2a_expert_ffn(
    x: jax.Array,
    router_probs: jax.Array,
    wi: jax.Array,
    wo: jax.Array,
    *,
    top_k: int,
    capacity: int,
    num_experts: int,
    dtype,
    gmm_fn,
    ep_axis: str,
    plan: DispatchPlan,
    tp_axis: Optional[str] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array, Dict[str, jax.Array]]:
    """One expert shard's routed-token expert FFN (shard_map body).

    x [G_l, S, H] and router_probs [G_l, S, E] are this shard's OWN
    token groups — unlike the gmm path, tokens are sharded over the
    expert axis too (EP borrows the data dimension), so adding expert
    shards adds token shards: the layout that scales expert capacity
    across hosts. wi [E_l, H, 2F] / wo [E_l, F, H] are the local
    experts (F possibly tensor-sharded; partial row outputs are psum'd
    over `tp_axis` before the combine exchange, Megatron row-parallel).

    Pipeline: route (_sort_routing, the SAME global capacity semantics
    as every other dispatch mode — parity is pinned against gather) ->
    pack destination buckets via an inverted index + one row gather (no
    H-wide scatter anywhere, the r3 lesson) -> exchange per-destination
    counts -> hierarchical bucket exchange, stage-2 chunked for
    dispatch/compute overlap -> grouped matmul over exactly the
    received rows -> mirrored combine -> unpack + gate-weight on the
    home shard. No full-activation psum exists on this path.

    Returns (out [G_l,S,H], tokens_per_expert [E] local counts,
    dropped [G_l,S], stats {ep_tokens_routed, ep_tokens_dcn} — local
    scalars, psum'd by the caller)."""
    from luminaai_tpu.models.moe import _GMM_ROW_TILE, _sort_routing
    from flax import linen as nn

    G, S, H = x.shape
    E, k, C = num_experts, top_k, capacity
    E_l = wi.shape[0]
    ep, dcn, ici = plan.ep, plan.dcn, plan.ici
    B = plan.bucket_rows
    N = G * S * k

    slot, gate, dropped, counts = _sort_routing(router_probs, k, C)
    gate = gate.astype(dtype)

    # --- pack: destination-major buckets -------------------------------
    # Pair -> global expert (sentinel E for dropped); experts are
    # contiguous per destination shard, so expert-major order IS
    # destination-major order — one stable sort serves both.
    e_pair = jnp.where(slot < E * C, slot // C, E).reshape(-1)  # [N]
    d_pair = jnp.where(e_pair < E, e_pair // E_l, ep)           # [N]
    perm = jnp.argsort(e_pair, stable=True)                     # [N]
    cnt_de = counts.sum(axis=0).astype(jnp.int32).reshape(ep, E_l)
    cnt_d = cnt_de.sum(axis=1)                                  # [ep]
    dstart = jnp.cumsum(cnt_d) - cnt_d
    dest_sorted = d_pair[perm]
    pos = jnp.arange(N) - dstart[jnp.minimum(dest_sorted, ep - 1)]
    valid = dest_sorted < ep
    # Flat bucket slot per sorted rank; dropped pairs -> spill slot.
    flat = jnp.where(valid, dest_sorted * B + pos, ep * B).astype(jnp.int32)
    # Invert slot -> sorted rank (KB-scale int scatter), then fill the
    # send buffer with ONE H-wide row gather through it.
    inv = jnp.full((ep * B + 1,), N, jnp.int32).at[flat].set(
        jnp.arange(N, dtype=jnp.int32)
    )[: ep * B]
    tok_sorted = (perm // k).astype(jnp.int32)
    x_flat = x.astype(dtype).reshape(G * S, H)
    filled = (inv < N)[:, None].astype(dtype)
    sb = (
        x_flat[tok_sorted[jnp.minimum(inv, N - 1)]] * filled
    ).reshape(ep, B, H)

    # --- counts exchange first (padding-free contract) -----------------
    rcnt = all_to_all(
        cnt_de, ep_axis, split_axis=0, concat_axis=0, tiled=True
    )  # [ep_src, E_l]
    rtot = rcnt.sum(axis=1)                    # [ep] rows per source
    rcum = jnp.cumsum(rcnt, axis=1)            # [ep, E_l]

    # --- dispatch exchange: stage 1 once, stage 2 per chunk ------------
    groups = hierarchical_groups(ep, dcn) if dcn > 1 else None
    if groups is not None:
        sb = _stage1(
            sb.reshape(dcn, ici, B, H), ep_axis, dcn, ici, groups[0]
        )

    n_chunks = plan.n_chunks
    Bc = B // n_chunks

    def _exchange(piece):
        if groups is not None:
            return _stage2(piece, ep_axis, dcn, ici, groups[1])
        return all_to_all(
            piece, ep_axis, split_axis=0, concat_axis=0, tiled=True
        )

    def _ffn_chunk(rb_c, row0):
        """Grouped matmul over one received chunk [ep, Bc, H]: rows
        sorted expert-major across sources, group_sizes from the
        exchanged counts, the megablox operand-masking contract from
        _gmm_local (uninitialized tails annihilated via jnp.where on
        the operands, fwd AND both VJPs)."""
        r_ids = row0 + jnp.arange(Bc)
        # expert of bucket row r from source s: how many of source s's
        # per-expert runs end at or before r.
        e_loc = jax.vmap(
            lambda cum: jnp.searchsorted(cum, r_ids, side="right")
        )(rcum)                                   # [ep, Bc]
        live = r_ids[None, :] < rtot[:, None]
        key = jnp.where(live, e_loc, E_l).reshape(-1)  # [M]
        M = ep * Bc
        p2 = jnp.argsort(key, stable=True)
        gs = jnp.sum(
            jax.nn.one_hot(key, E_l + 1, dtype=jnp.int32), axis=0
        )[:E_l]
        Mp = -(-M // _GMM_ROW_TILE) * _GMM_ROW_TILE
        rows = rb_c.reshape(M, H)[p2]
        if Mp != M:
            rows = jnp.pad(rows, ((0, Mp - M), (0, 0)))
        total_kept = gs.sum()
        row_kept = jnp.arange(Mp)[:, None] < total_kept
        lhs = jnp.where(row_kept, rows, 0)
        fused = gmm_fn(
            lhs, wi.astype(dtype), gs, preferred_element_type=dtype
        )
        gate_act, up = jnp.split(fused, 2, axis=-1)
        act = jnp.where(row_kept, nn.silu(gate_act) * up, 0)
        yrow = gmm_fn(
            act, wo.astype(dtype), gs, preferred_element_type=dtype
        )
        yrow = jnp.where(row_kept, yrow, 0.0)[:M]
        if tp_axis is not None:
            # Row-parallel epilogue: partial token outputs join here so
            # only ONE copy rides the combine exchange.
            yrow = jax.lax.psum(yrow, tp_axis)
        inv2 = jnp.argsort(p2)
        return yrow[inv2].reshape(ep, Bc, H)

    back = []
    for c in range(n_chunks):
        if groups is not None:
            piece = sb[:, :, c * Bc:(c + 1) * Bc, :]
        else:
            piece = sb[:, c * Bc:(c + 1) * Bc, :]
        rb_c = _exchange(piece)
        if groups is not None:
            rb_c = rb_c.reshape(ep, Bc, H)
        yb_c = _ffn_chunk(rb_c, c * Bc)
        if groups is not None:
            yb_c = yb_c.reshape(dcn, ici, Bc, H)
        # Stage 2 is a block-permutation involution: the same call
        # routes outputs back toward their source hosts.
        back.append(_exchange(yb_c))
    cb = jnp.concatenate(back, axis=2 if groups is not None else 1)
    if groups is not None:
        cb = _stage1(cb, ep_axis, dcn, ici, groups[0]).reshape(ep, B, H)

    # --- unpack + gate-weight on the home shard ------------------------
    cbf = cb.reshape(ep * B, H)
    y_sorted = cbf[jnp.minimum(flat, ep * B - 1)] * (
        valid[:, None].astype(dtype)
    )
    inv_perm = jnp.argsort(perm)
    y_pairs = y_sorted[inv_perm].reshape(G, S, k, H)
    out = jnp.einsum("gskh,gsk->gsh", y_pairs, gate)

    # Per-stage routed-token stats (local; caller psums): every kept
    # pair rides stage 1, only host-crossing pairs ride stage 2.
    my_host = jax.lax.axis_index(ep_axis) // ici
    dest_host = jnp.arange(ep) // ici
    routed = cnt_d.sum().astype(jnp.float32)
    routed_dcn = jnp.where(
        dest_host != my_host, cnt_d, 0
    ).sum().astype(jnp.float32)
    stats = {"ep_tokens_routed": routed, "ep_tokens_dcn": routed_dcn}
    return out, counts.sum(axis=0).astype(jnp.float32), dropped, stats


# --------------------------------------------------------------------------
# diagnose probe: a real timed two-stage all-to-all over the probe mesh
# --------------------------------------------------------------------------


def expert_a2a_probe(
    payload_mb: float = 4.0, iters: int = 5, registry=None
) -> Dict[str, Any]:
    """Time a REAL two-stage hierarchical all-to-all over the dcn×ici
    probe factorization — the `cli diagnose` rung that tells the
    MULTICHIP_r* harness what an expert-dispatch exchange actually
    costs on this fleet, next to the connectivity probe's all-reduce.

    Multi-host jobs use the (process, local-device) grid as the real
    dcn×ici split; a single host with >= 4 local devices SIMULATES a
    2-host tier (dcn=2) so the two-stage code path is exercised and
    timed even on the CPU harness — the numbers then validate the
    dispatch machinery, not an interconnect. Degrades to the
    single-stage fallback below 4 devices.

    Exports diagnose_expert_a2a_seconds{stage} gauges mirroring the
    connectivity probe's contract."""
    import time as _time

    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    from luminaai_tpu.monitoring.telemetry import get_registry

    registry = registry or get_registry()
    n_proc = jax.process_count()
    n_global = jax.device_count()
    if n_proc > 1 and n_global % n_proc == 0:
        dcn, ici = n_proc, n_global // n_proc
        simulated = False
    elif n_global >= 4 and n_global % 2 == 0:
        dcn, ici = 2, n_global // 2
        simulated = True
    else:
        dcn, ici = 1, n_global
        simulated = n_proc == 1
    ep = dcn * ici
    devices = np.array(jax.devices()[: ep]).reshape(ep)
    mesh = Mesh(devices, ("expert",))
    out: Dict[str, Any] = {
        "ep": ep, "dcn": dcn, "ici": ici, "simulated_dcn": simulated,
        "stages": {},
    }
    # Per-destination buckets sized so the whole exchange carries
    # ~payload_mb per shard.
    H = 128
    rows = max(1, int(payload_mb * 1e6 / 4 / H / ep))
    g1, g2 = hierarchical_groups(ep, dcn) if dcn > 1 else (None, None)

    def _run_stage(stage_fn, name):
        @jax.jit  # lumina: disable=LX006 -- probe re-times the same buffer; donation would free it between iters
        def stepped(xs):
            return shard_map(
                stage_fn, mesh=mesh,
                in_specs=PartitionSpec("expert"),
                out_specs=PartitionSpec("expert"),
                check_vma=False,
            )(xs)

        x = jax.device_put(
            jnp.ones((ep * ep, rows, H), jnp.float32),
            NamedSharding(mesh, PartitionSpec("expert")),
        )
        try:
            stepped(x).block_until_ready()
            t0 = _time.perf_counter()
            for _ in range(iters):
                y = stepped(x)
            y.block_until_ready()
            dt = (_time.perf_counter() - t0) / iters
        except Exception as e:  # probe must never wedge diagnose
            out["stages"][name] = {"error": f"{type(e).__name__}: {e}"}
            return
        payload = ep * rows * H * 4
        out["stages"][name] = {
            "payload_mb": round(payload / 1e6, 2),
            "mean_seconds": round(dt, 6),
            "algo_gbps": round(payload / max(dt, 1e-9) / 1e9, 3),
        }

    if dcn > 1:
        _run_stage(
            lambda v: _stage1(
                v.reshape((dcn, ici) + v.shape[1:]), "expert", dcn, ici, g1
            ).reshape(v.shape),
            "ici",
        )
        _run_stage(
            lambda v: _stage2(
                v.reshape((dcn, ici) + v.shape[1:]), "expert", dcn, ici, g2
            ).reshape(v.shape),
            "dcn",
        )
        _run_stage(
            lambda v: hierarchical_all_to_all(v, "expert", dcn_size=dcn),
            "two_stage",
        )
    else:
        _run_stage(
            lambda v: hierarchical_all_to_all(v, "expert"), "single_stage"
        )
    g = registry.gauge(
        "diagnose_expert_a2a_seconds",
        "Mean timed expert-dispatch all-to-all per stage at last diagnose",
        labelnames=("stage",),
    )
    for name, rec in out["stages"].items():
        if isinstance(rec, dict) and "mean_seconds" in rec:
            g.labels(stage=name).set(rec["mean_seconds"])
    return out
