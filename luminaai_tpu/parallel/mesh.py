"""Device-mesh construction and multi-host initialization.

Replaces the reference's process-group plumbing (ref: Src/Main_Scripts/core/
backend/backend_deepspeed.py, backend_fsdp.py, backend_colossalai.py — NCCL
process groups, DeepSpeed ZeRO stages, FSDP wrapping). On TPU the single
abstraction is a `jax.sharding.Mesh` with named axes; every parallelism the
reference implements as a separate backend (ZeRO-3 == 'fsdp' axis, Megatron
TP == 'tensor' axis, expert parallel == 'expert' axis, sequence/context
parallel == 'sequence' axis, plain DDP == 'data' axis) is just a different
mesh shape + sharding rule set over the same train step. XLA inserts the
collectives (psum / all-gather / reduce-scatter / all-to-all) on ICI.
"""

from __future__ import annotations

import logging
import math
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from luminaai_tpu.config import Config

logger = logging.getLogger(__name__)

# Default axis order; overridden by Config.mesh_axes. Trailing axes get
# devices that are closest on the physical torus (mesh_utils places the last
# axis on the innermost ring), so the chattiest collectives (tensor) go last.
MESH_AXES = ("data", "pipe", "fsdp", "expert", "sequence", "tensor")


def mesh_shape_from_config(
    config: Config, n_devices: Optional[int] = None
) -> Dict[str, int]:
    """Resolve per-axis sizes; data axis (-1) absorbs remaining devices.

    Mirrors ref backend auto-sizing (world_size // model_parallel), but over
    six named axes instead of DeepSpeed's dp/mp split.
    """
    if n_devices is None:
        n_devices = jax.device_count()
    fixed = {
        "pipe": config.pipeline_parallel_size,
        "fsdp": config.fsdp_parallel_size,
        "expert": config.expert_parallel_size,
        "sequence": config.sequence_parallel_size,
        "tensor": config.tensor_parallel_size,
    }
    model_parallel = math.prod(fixed.values())
    if n_devices % model_parallel != 0:
        raise ValueError(
            f"device count {n_devices} not divisible by model-parallel "
            f"product {model_parallel} (pipe×fsdp×expert×sequence×tensor)"
        )
    dp = config.data_parallel_size
    if dp == -1:
        dp = n_devices // model_parallel
    if dp * model_parallel != n_devices:
        raise ValueError(
            f"mesh {dp}×{model_parallel} != {n_devices} devices; set "
            "data_parallel_size=-1 to auto-size"
        )
    return {"data": dp, **fixed}


def build_mesh(
    config: Config, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """Create the named device mesh for a config.

    Uses `mesh_utils.create_device_mesh` on real TPU slices so axis
    neighbours are ICI neighbours; falls back to a plain reshape for CPU
    meshes (virtual devices have no topology).
    """
    if devices is None:
        devices = jax.devices()
    axes = tuple(config.mesh_axes)
    if sorted(axes) != sorted(MESH_AXES):
        raise ValueError(
            f"mesh_axes must be a permutation of {MESH_AXES}, got {axes}"
        )
    shape = mesh_shape_from_config(config, len(devices))
    dims = tuple(shape[a] for a in axes)
    if devices[0].platform == "tpu":
        from jax.experimental import mesh_utils

        device_array = mesh_utils.create_device_mesh(
            dims,
            devices=devices,
            allow_split_physical_axes=config.allow_split_physical_axes,
        )
    else:
        device_array = np.asarray(devices).reshape(dims)
    return Mesh(device_array, axes)


def shard_map(f, mesh, in_specs, out_specs, axis_names=None, check_vma=None):
    """`jax.shard_map` across jax versions — the ONE entry point repo code
    calls (models/moe.py, ops/ring_attention.py, parallel/pipeline.py).

    Newer jax exposes `jax.shard_map` (manual axes named via `axis_names`,
    replication check via `check_vma`); 0.4.x ships it as
    `jax.experimental.shard_map.shard_map` (COMPLEMENT semantics: `auto` =
    the axes left automatic, replication check `check_rep`, and partial-
    auto requires the check off). Passing neither flag keeps each
    implementation's default.
    """
    if hasattr(jax, "shard_map"):
        kw = {}
        if check_vma is not None:
            kw["check_vma"] = check_vma
        if axis_names is not None:
            kw["axis_names"] = axis_names
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    kw = {}
    if check_vma is not None:
        kw["check_rep"] = check_vma
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
        if auto:
            kw["auto"] = auto
            # Partial-auto shard_map predates the rep checker's support
            # for it in 0.4.x; the checker must be off there.
            kw["check_rep"] = False
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
    )


def all_to_all(x, axis_name, *, split_axis, concat_axis, tiled=False,
               axis_index_groups=None):
    """`lax.all_to_all`, the ONE entry point repo code outside parallel/
    calls (astlint LX010). Keeping every explicit collective call site
    routed through parallel/ keeps them enumerable — the comms auditor
    (analysis/jaxpr_audit.enumerate_collectives) and the hierarchical
    dispatch groups (parallel/expert_dispatch.py) both rely on knowing
    where collectives enter model code."""
    return jax.lax.all_to_all(
        x, axis_name, split_axis=split_axis, concat_axis=concat_axis,
        tiled=tiled, axis_index_groups=axis_index_groups,
    )


def ppermute(x, axis_name, perm):
    """`lax.ppermute` through the same sanctioned entry point (LX010) —
    ring attention's KV rotation and the pipeline's stage hops."""
    return jax.lax.ppermute(x, axis_name, perm)


def psum_scatter(x, axis_name, *, scatter_dimension=0, tiled=True,
                 axis_index_groups=None):
    """`lax.psum_scatter` through the sanctioned parallel/ entry point —
    the ici-tier reduce-scatter of the hierarchical gradient sync
    (parallel/grad_reduce.py). Grouped calls classify as a hierarchy
    stage in the comms auditor exactly like the a2a exchanges."""
    return jax.lax.psum_scatter(
        x, axis_name, scatter_dimension=scatter_dimension, tiled=tiled,
        axis_index_groups=axis_index_groups,
    )


def all_gather(x, axis_name, *, axis=0, tiled=True,
               axis_index_groups=None):
    """`lax.all_gather` through the sanctioned parallel/ entry point —
    the gather leg of the hierarchical gradient sync."""
    return jax.lax.all_gather(
        x, axis_name, axis=axis, tiled=tiled,
        axis_index_groups=axis_index_groups,
    )


def psum(x, axis_name, *, axis_index_groups=None):
    """`lax.psum` with optional groups through the sanctioned parallel/
    entry point — the DCN rail crossing of the hierarchical gradient
    sync (strided groups = the cross-host tier)."""
    return jax.lax.psum(x, axis_name, axis_index_groups=axis_index_groups)


# Explicit registry for the mesh the current trace runs under. The train
# step factories push here (use_mesh below); thread_resources is only a
# legacy fallback for code that entered `with mesh:` directly.
import contextlib
import threading

_ACTIVE_MESH = threading.local()


@contextlib.contextmanager
def use_mesh(mesh: Mesh):
    """`with mesh:` plus registration for active_mesh()."""
    prev = getattr(_ACTIVE_MESH, "mesh", None)
    _ACTIVE_MESH.mesh = mesh
    try:
        with mesh:
            yield mesh
    finally:
        _ACTIVE_MESH.mesh = prev


def active_mesh() -> Optional[Mesh]:
    """The Mesh whose use_mesh()/`with mesh:` context encloses the caller.

    Model code that needs explicit collectives (ring attention's shard_map)
    runs under the train step's trace context; this recovers that mesh
    without threading it through every flax module attribute. Checks the
    explicit registry first; falls back to the (deprecated) global mesh
    context for callers that used `with mesh:` directly.
    """
    mesh = getattr(_ACTIVE_MESH, "mesh", None)
    if mesh is not None:
        return mesh
    try:
        import warnings

        from jax.interpreters import pxla

        with warnings.catch_warnings():
            warnings.simplefilter("ignore", DeprecationWarning)
            m = pxla.thread_resources.env.physical_mesh
        return None if m.empty else m
    except Exception:  # API moved/removed; no implicit context available
        return None


def initialize_multihost(config: Config) -> None:
    """Bring up the JAX distributed runtime for multi-host training.

    Replaces ref NCCL/MPI env bootstrap (backend communication_backend=nccl;
    MASTER_ADDR/RANK env handling). Over TPU pods the coordination service
    only handles control-plane setup — data-plane collectives ride ICI/DCN
    via XLA, so there is no NCCL analogue to configure.
    """
    if not config.multihost:
        return
    kwargs = {}
    if config.coordinator_address is not None:
        kwargs["coordinator_address"] = config.coordinator_address
    if config.num_processes is not None:
        kwargs["num_processes"] = config.num_processes
    if config.process_id is not None:
        kwargs["process_id"] = config.process_id
    jax.distributed.initialize(**kwargs)
    logger.info(
        "multihost initialized: process %d/%d, %d local / %d global devices",
        jax.process_index(),
        jax.process_count(),
        jax.local_device_count(),
        jax.device_count(),
    )


def describe_mesh(mesh: Mesh) -> str:
    """Human-readable mesh summary for logs/reports."""
    parts = [f"{a}={s}" for a, s in zip(mesh.axis_names, mesh.devices.shape)]
    plat = mesh.devices.flat[0].platform
    return f"Mesh[{' × '.join(parts)}] on {mesh.devices.size} {plat} device(s)"
