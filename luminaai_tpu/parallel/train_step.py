"""pjit train/eval step factory with in-jit gradient accumulation.

Replaces the reference's backend train loops (ref: Src/Main_Scripts/core/
backend/backend_deepspeed.py engine.step(), backend_fsdp.py:44,
training/training_loop.py microbatch loop). Differences, by design:

  - One jit covers forward, backward, accumulation, clip, and optimizer
    update. The reference crosses the Python boundary per microbatch; here
    grad accumulation is a `lax.scan` inside the step, so XLA pipelines
    microbatches without host round-trips.
  - Parallelism is data-driven: the same traced function runs dp / fsdp /
    tp / ep / sp depending on the shardings attached to state and batch.
    XLA inserts the gradient psum over the data axis (the reference's
    all-reduce), reduce-scatter/all-gather for fsdp (ZeRO-3), and
    all-to-alls for expert parallelism.
  - The TrainState buffer is donated: params/opt-state update in place in
    HBM, halving peak optimizer memory vs a copy.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn
from jax.sharding import Mesh, NamedSharding

from luminaai_tpu.config import Config
from luminaai_tpu.ops.fused import (
    clip_by_global_norm,
    cross_entropy_loss,
    fused_lm_head_cross_entropy,
    global_norm,
)
from luminaai_tpu.parallel.mesh import use_mesh
from luminaai_tpu.parallel.sharding import (
    TrainState,
    batch_spec,
    is_host_offloaded,
    logical_axis_rules,
)

Batch = Dict[str, jax.Array]


def shift_labels(batch: Batch) -> Tuple[jax.Array, jax.Array]:
    """Next-token labels + validity mask from input_ids.

    (ref core/dataset.py builds shifted labels host-side; doing it in-jit
    keeps the host pipeline dtype-only.) Last position has no target.
    """
    ids = batch["input_ids"]
    labels = jnp.concatenate(
        [ids[:, 1:], jnp.zeros_like(ids[:, :1])], axis=1
    )
    valid = jnp.concatenate(
        [
            jnp.ones_like(ids[:, 1:], dtype=jnp.float32),
            jnp.zeros_like(ids[:, :1], dtype=jnp.float32),
        ],
        axis=1,
    )
    return labels, valid


def shift_with_labels(x: jax.Array) -> jax.Array:
    """Left-shift a per-position tensor so index i refers to the PREDICTED
    token (ids[i+1]), matching shift_labels. loss_mask/loss_weights arrive
    aligned to input positions; the loss at position i is for predicting
    token i+1, so its gate/weight must come from position i+1 (ref
    core/dataset.py:505-507 shifts labels[1:] and loss_weights[1:] together).
    """
    return jnp.concatenate([x[:, 1:], jnp.zeros_like(x[:, :1])], axis=1)


def _shifted_mask_weights(
    batch: Batch, valid: jax.Array
) -> Tuple[jax.Array, Optional[jax.Array]]:
    loss_mask = batch.get("loss_mask")
    mask = valid if loss_mask is None else valid * shift_with_labels(loss_mask)
    weights = batch.get("loss_weights")
    if weights is not None:
        weights = shift_with_labels(weights)
    return mask, weights


def _ce(
    config: Config,
    params,
    model_out,
    labels,
    mask,
    weights,
    z_loss_weight: float = 0.0,
    label_smoothing: float = 0.0,
):
    """Route to the fused LM-head CE (chunked, no [B,S,V] logits) or the
    plain logits path, depending on config.fused_lm_head_ce."""
    if config.fused_lm_head_ce:
        hidden = model_out
        head_name = (
            "embedding" if config.tie_word_embeddings else "lm_head"
        )
        embedding = params["embedder"][head_name]
        if isinstance(embedding, nn.meta.AxisMetadata):
            embedding = embedding.unbox()  # raw model.init trees are boxed
        return fused_lm_head_cross_entropy(
            hidden,
            embedding,
            labels,
            loss_mask=mask,
            loss_weights=weights,
            z_loss_weight=z_loss_weight,
            label_smoothing=label_smoothing,
            chunk_size=config.loss_chunk_size,
        )
    return cross_entropy_loss(
        model_out,
        labels,
        loss_mask=mask,
        loss_weights=weights,
        z_loss_weight=z_loss_weight,
        label_smoothing=label_smoothing,
    )


def make_loss_fn(config: Config, model) -> Callable:
    def loss_fn(params, batch: Batch, rng: jax.Array):
        rngs = {"routing": rng, "dropout": jax.random.fold_in(rng, 1)}
        model_out, aux = model.apply(
            {"params": params},
            batch["input_ids"],
            deterministic=False,
            rngs=rngs,
            return_hidden=config.fused_lm_head_ce,
        )
        labels, valid = shift_labels(batch)
        mask, weights = _shifted_mask_weights(batch, valid)
        loss, metrics = _ce(
            config, params, model_out, labels, mask, weights,
            z_loss_weight=config.z_loss_weight,
            label_smoothing=config.label_smoothing,
        )
        total = loss + aux.get("aux_loss", 0.0)
        for k, v in aux.items():
            metrics[k] = v
        metrics["loss"] = total
        return total, metrics

    return loss_fn


def _accumulate_grads(
    loss_fn, params, batch: Batch, rng: jax.Array, accum_steps: int
):
    """Gradient accumulation via lax.scan over microbatch slices.

    (ref training_loop.py loops microbatches in Python with engine
    .backward(); here the loop is compiled, grads accumulate in fp32.)
    """
    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    if accum_steps <= 1:
        (loss, metrics), grads = grad_fn(params, batch, rng)
        return grads, metrics

    def to_micro(x):
        return x.reshape(accum_steps, x.shape[0] // accum_steps, *x.shape[1:])

    micro = jax.tree.map(to_micro, batch)
    acc_grads = jax.tree.map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params
    )

    def body(acc, xs):
        mb, step_rng = xs
        (_, metrics), grads = grad_fn(params, mb, step_rng)
        acc = jax.tree.map(
            lambda a, g: a + g.astype(jnp.float32) / accum_steps, acc, grads
        )
        return acc, metrics

    rngs = jax.random.split(rng, accum_steps)
    grads, metrics_stack = jax.lax.scan(body, acc_grads, (micro, rngs))
    # Count-like metrics sum over microbatches; the rest average.
    metrics = {
        k: m.sum(axis=0) if k == "tokens_in_loss" else m.mean(axis=0)
        for k, m in metrics_stack.items()
    }
    return grads, metrics


def make_train_step(
    config: Config,
    model,
    state_shardings: TrainState,
    mesh: Mesh,
    schedule: Optional[optax.Schedule],
    tx: optax.GradientTransformation,
    loss_fn: Optional[Callable] = None,
):
    """Build the donated, sharded, jitted train step.

    Returns `step(state, batch) -> (state, metrics)`. Call under no special
    context — mesh and logical rules are bound at trace time here. `tx` is
    closed over (not stored in state), so a rebuilt step with a new
    transform reuses the same TrainState as long as the opt-state structure
    matches (e.g. LR overrides).

    With pipeline_parallel_size > 1 this dispatches to the GPipe step
    (parallel/pipeline.py) — same contract, layer stack pipelined over the
    'pipe' mesh axis.
    """
    if config.pipeline_parallel_size > 1 and loss_fn is None:
        from luminaai_tpu.parallel.pipeline import make_pipeline_train_step

        return make_pipeline_train_step(
            config, model, state_shardings, mesh, schedule, tx
        )
    loss_fn = loss_fn or make_loss_fn(config, model)
    accum = config.gradient_accumulation_steps
    bspec = NamedSharding(mesh, batch_spec())
    # Host-offloaded optimizer state (pinned_host memory kinds in the
    # shardings): the update streams it through device memory in-jit.
    offloaded = is_host_offloaded(state_shardings.opt_state)
    # Explicit hierarchical gradient reduction (parallel/grad_reduce.py):
    # forward/backward/accumulation run shard-locally inside a manual
    # (data, fsdp) region and ONE post-scan bucketed sync replaces the
    # implicit GSPMD all-reduce — reduce-scatter on ICI, one grouped
    # DCN psum per bucket, all-gather back.
    hier_grad_fn = None
    if config.grad_reduce == "hierarchical":
        from luminaai_tpu.parallel.grad_reduce import (
            make_hierarchical_grad_fn,
        )

        hier_grad_fn = make_hierarchical_grad_fn(
            config, loss_fn, mesh, accum
        )

    def train_step(state: TrainState, batch: Batch):
        step_rng, new_rng = jax.random.split(state.rng)
        if hier_grad_fn is not None:
            grads, metrics = hier_grad_fn(state.params, batch, step_rng)
        else:
            grads, metrics = _accumulate_grads(
                loss_fn, state.params, batch, step_rng, accum
            )
        if config.grad_clip_norm > 0:
            grads, grad_norm = clip_by_global_norm(grads, config.grad_clip_norm)
        else:  # clipping off; still report the norm for monitoring
            grad_norm = global_norm(grads)
        new_state = state.apply_gradients(
            grads, tx, host_offload=offloaded
        ).replace(rng=new_rng)
        metrics["grad_norm"] = grad_norm
        if schedule is not None:
            metrics["learning_rate"] = schedule(state.step)
        return new_state, metrics

    def traced(state, batch):
        with use_mesh(mesh), nn.logical_axis_rules(logical_axis_rules(config)):
            return train_step(state, batch)

    jitted = jax.jit(
        traced,
        in_shardings=(state_shardings, bspec),
        out_shardings=(state_shardings, None),
        donate_argnums=(0,) if config.donate_state else (),
    )

    def call(state, batch):
        with mesh:
            return jitted(state, batch)

    # AOT handle for compiled-cost accounting (monitoring/attribution.py):
    # `call.jitted.lower(state, batch).compile().cost_analysis()` queries
    # XLA's cost model for THIS executable without executing it.
    call.jitted = jitted
    # Static sync plan (grad_reduce='hierarchical' only): filled at first
    # trace; trainer telemetry reads it after compile (no host syncs).
    call.grad_reduce_plan = (
        hier_grad_fn.plan_box if hier_grad_fn is not None else None
    )
    return call


def make_eval_step(
    config: Config, model, state_shardings: TrainState, mesh: Mesh,
    loss_fn: Optional[Callable] = None,
):
    """Forward-only eval step: loss + metrics, deterministic routing.

    Dispatches to the pipelined eval (the GPipe loss injected through the
    same wrapper) under pipeline_parallel_size > 1; `loss_fn(params,
    batch) -> metrics` overrides the standard eval loss when given."""
    if config.pipeline_parallel_size > 1 and loss_fn is None:
        from luminaai_tpu.parallel.pipeline import make_pipeline_eval_step

        return make_pipeline_eval_step(config, model, state_shardings, mesh)

    def eval_loss(params, batch: Batch):
        model_out, aux = model.apply(
            {"params": params},
            batch["input_ids"],
            deterministic=True,
            return_hidden=config.fused_lm_head_ce,
        )
        labels, valid = shift_labels(batch)
        mask, weights = _shifted_mask_weights(batch, valid)
        loss, metrics = _ce(config, params, model_out, labels, mask, weights)
        for k, v in aux.items():
            metrics[k] = v
        metrics["loss"] = loss + aux.get("aux_loss", 0.0)
        return metrics

    run_loss = loss_fn or eval_loss
    bspec = NamedSharding(mesh, batch_spec())

    def traced(state, batch):
        with use_mesh(mesh), nn.logical_axis_rules(logical_axis_rules(config)):
            return run_loss(state.params, batch)

    jitted = jax.jit(traced, in_shardings=(state_shardings, bspec))

    def call(state, batch):
        with mesh:
            return jitted(state, batch)

    return call
