"""Hierarchical cross-host gradient reduction with backward/comms overlap.

ROADMAP item 3's other cross-host hot path (the first was MoE token
dispatch, parallel/expert_dispatch.py): fsdp/dp gradient reduction.
Under `grad_reduce="flat"` that sync is whatever GSPMD emits — implicit
all-reduces at full fp32 width, invisible to the comms auditor, and
under gradient accumulation free to re-issue per microbatch inside the
accumulation scan. Scalable pjit/TPUv4 training (arxiv 2204.06514) and
X-MoE's hierarchical exchange (arxiv 2508.13337) both prescribe the
same cure, implemented here as `grad_reduce="hierarchical"`:

  - **shard-local accumulation, one deferred sync**: the whole
    forward/backward/accumulation scan runs inside a partial-auto
    shard_map manual over (data, fsdp). Gradients accumulate
    shard-locally in fp32 across every microbatch; the ONLY collectives
    inside the scan are scalar loss-normalization psums. The H-wide
    payload crosses the wire exactly once, post-scan — the before/after
    collective census is pinned by analysis/jaxpr_audit.audit_grad_reduce.

  - **size-bucketed hierarchical sync**: the gradient pytree flattens
    into fp32 buckets (`grad_reduce_bucket_mb`); each bucket
    reduce-scatters over the ici tier (the fsdp axis plus the in-host
    factor of the data axis), crosses DCN once via a grouped psum over
    the strided cross-host rails (`gradient_dcn_size` factors the data
    axis, reusing the a2a dispatch's `hierarchical_groups`), and
    all-gathers back. DCN sees 1/ici-tier of the payload — few large
    rail-aligned messages instead of a full-width flat ring.

  - **overlap**: buckets are data-independent of each other
    (`grad_reduce_overlap_chunks` floors the bucket count), so bucket
    k's DCN hop overlaps bucket k-1's all-gather under XLA's
    latency-hiding scheduler.

  - **optional DCN compression**: `grad_reduce_dcn_dtype='bf16'` casts
    only the DCN hop down — each shard's scattered chunk is already the
    full fp32 in-host sum before the cast, so in-host accumulation
    precision is untouched. Parity-gated in tests/test_grad_reduce.py.

Loss semantics: the implicit path computes each microbatch's loss as a
weighted mean over the GLOBAL microbatch. Inside the manual region each
shard sees only its slice, so the local loss is rescaled by
local_denom / max(psum(weight_sum), 1) — the gradient of the sum of
those rescaled local losses is exactly the gradient of the global
weighted mean (empty shard slices included), at the cost of one scalar
psum per microbatch. Model AUX losses (MoE load balance, router z) are
computed per shard and averaged (rescale 1/world) — the standard
data-parallel-local balance formulation. For the balance loss, which
is NONLINEAR in the batch routing statistics (Σ_e f_e·p_e of per-shard
fractions ≠ the global-batch product), that is a deliberately
different regularizer from the flat path's global-batch aux: the CE
gradient stays exact, the aux gradient constrains balance per shard
instead of in aggregate. Loss-trajectory parity vs the implicit path
is therefore pinned at 1e-6 for dense models (dp and dp×fsdp CPU
meshes, grad accumulation on and off); MoE configs are pinned at
loose tolerance only (tests/test_grad_reduce.py).

Accumulation-partition caveat: with accum > 1 the manual region slices
microbatches SHARD-LOCALLY (each shard splits its contiguous rows),
while GSPMD's reshape redistributes rows so global microbatch i is a
different row set. With uniform per-row loss weights — the normal LM
case — every partition yields the identical gradient (equal
per-microbatch denominators) and 1e-6 parity holds; with NONUNIFORM
per-row weights the two paths weight microbatches differently (both
are valid equal-weight-per-microbatch accumulation semantics, matching
would cost an extra exchange per microbatch).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from luminaai_tpu.parallel.expert_dispatch import hierarchical_groups
from luminaai_tpu.parallel.mesh import (
    all_gather,
    psum,
    psum_scatter,
    shard_map,
)

logger = logging.getLogger(__name__)

__all__ = [
    "GradReducePlan",
    "make_grad_reduce_plan",
    "export_grad_reduce_gauges",
    "hierarchical_grad_sync",
    "make_hierarchical_grad_fn",
    "grad_reduce_probe",
]


# --------------------------------------------------------------------------
# static plan: bucket layout + byte accounting
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class GradReducePlan:
    """Static shape/byte plan for one hierarchical gradient sync.

    Derived purely from gradient avals and config at trace time — the
    numbers describe the traced program, not a run — so bench extras and
    the comms auditor can price the sync without hardware. Per shard,
    per optimizer step:

      ici-tier bytes = the reduce-scatter + all-gather legs: the full
        fp32 bucket payload enters/leaves each shard once each way,
        ring-style (~2*(t-1)/t of it off-chip for a tier of t shards).
      dcn bytes      = the grouped psum over the cross-host rails: each
        shard's SCATTERED chunk (1/ici_tier of the payload) rides a
        ring over the dcn hosts, at `dcn_itemsize` width.

    The flat GSPMD baseline moves the whole fp32 gradient through one
    logical all-reduce whose DCN-crossing share is ~2*(dcn-1)/dcn of
    the full payload — `flat_dcn_bytes`. The hierarchical advantage is
    structural: DCN traffic scales like 1/ici_tier (× 1/2 again under
    bf16 compression) of the flat baseline's.
    """

    world: int            # data * fsdp shards participating in the sync
    dcn: int              # host tier size (1 = single-stage fallback)
    data_size: int
    fsdp_size: int
    grad_bytes: int       # fp32 bytes of the flattened gradient
    padded_bytes: int     # after bucket/scatter padding
    n_buckets: int
    bucket_bytes: int     # per-bucket fp32 bytes (padded/n_buckets)
    overlap_chunks: int
    dcn_itemsize: int     # 4 (fp32) or 2 (bf16-over-DCN)

    @property
    def ici_tier(self) -> int:
        """Shards reduced per host before anything crosses DCN."""
        return self.world // self.dcn

    def stage_bytes(self, stage: str) -> int:
        """One-direction off-device payload bytes per shard for a tier;
        0 when the tier has one participant."""
        if stage == "ici":
            t = self.ici_tier
            return (
                int(self.padded_bytes * (t - 1) / t) if t > 1 else 0
            )
        scattered = self.padded_bytes // max(1, self.ici_tier)
        scattered = scattered * self.dcn_itemsize // 4
        d = self.dcn
        return int(scattered * (d - 1) / d) if d > 1 else 0

    @property
    def hier_dcn_bytes(self) -> int:
        """DCN-crossing bytes per shard per step (reduce + broadcast
        halves of the rail psum)."""
        return 2 * self.stage_bytes("dcn")

    @property
    def flat_dcn_bytes(self) -> int:
        """The implicit GSPMD baseline: one full-width fp32 all-reduce,
        ~2*(dcn-1)/dcn of the whole gradient crossing hosts."""
        d = self.dcn
        return (
            int(2 * self.grad_bytes * (d - 1) / d) if d > 1 else 0
        )

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d.update(
            ici_tier=self.ici_tier,
            ici_stage_bytes=self.stage_bytes("ici"),
            dcn_stage_bytes=self.stage_bytes("dcn"),
            hier_dcn_bytes=self.hier_dcn_bytes,
            flat_dcn_bytes=self.flat_dcn_bytes,
        )
        return d


def make_grad_reduce_plan(
    *,
    grad_elems: int,
    data_size: int,
    fsdp_size: int,
    dcn_size: int = 1,
    bucket_mb: float = 32.0,
    overlap_chunks: int = 1,
    dcn_dtype: Optional[str] = None,
) -> GradReducePlan:
    """Resolve the static bucket layout for a gradient of `grad_elems`
    fp32 elements on a (data, fsdp) grid.

    Bucket count = max(size-derived count, overlap_chunks); the flat
    vector pads to a multiple of n_buckets * scatter_factor so every
    bucket reduce-scatters evenly over the ici tier."""
    data_size = max(1, int(data_size))
    fsdp_size = max(1, int(fsdp_size))
    dcn = max(1, int(dcn_size))
    if data_size % dcn:
        raise ValueError(
            f"gradient_dcn_size {dcn} must divide the data axis "
            f"{data_size}"
        )
    world = data_size * fsdp_size
    grad_bytes = int(grad_elems) * 4
    bucket_bytes = max(1, int(bucket_mb * 2**20))
    n_buckets = max(
        -(-grad_bytes // bucket_bytes), max(1, int(overlap_chunks))
    )
    n_buckets = min(n_buckets, max(1, int(grad_elems)))
    scatter = fsdp_size * (data_size // dcn)
    quantum = n_buckets * scatter
    padded = -(-max(1, int(grad_elems)) // quantum) * quantum
    return GradReducePlan(
        world=world,
        dcn=dcn,
        data_size=data_size,
        fsdp_size=fsdp_size,
        grad_bytes=grad_bytes,
        padded_bytes=padded * 4,
        n_buckets=n_buckets,
        bucket_bytes=padded * 4 // n_buckets,
        overlap_chunks=max(1, int(overlap_chunks)),
        dcn_itemsize=2 if dcn_dtype == "bf16" else 4,
    )


def export_grad_reduce_gauges(plan: GradReducePlan, registry=None) -> None:
    """grad_reduce_bytes{stage} gauges from the static plan. Best-effort
    — the plan is built at trace time inside the train step, so this
    must never break a trace over a telemetry hiccup (same contract as
    expert_dispatch.export_plan_gauges)."""
    try:
        from luminaai_tpu.monitoring.telemetry import get_registry

        registry = registry or get_registry()
        g = registry.gauge(
            "grad_reduce_bytes",
            "Static per-shard one-direction payload bytes of the "
            "hierarchical gradient sync per tier (from the "
            "GradReducePlan, trace time)",
            labelnames=("stage",),
        )
        g.labels(stage="ici").set(float(plan.stage_bytes("ici")))
        g.labels(stage="dcn").set(float(plan.stage_bytes("dcn")))
        registry.gauge(
            "grad_reduce_buckets",
            "Size-bucketed chunk count of the hierarchical gradient "
            "sync at last trace",
        ).set(float(plan.n_buckets))
    except Exception:  # pragma: no cover - telemetry must not break traces
        logger.debug("grad_reduce_bytes gauge export failed", exc_info=True)


# --------------------------------------------------------------------------
# the sync itself (runs inside a shard_map body, manual over data+fsdp)
# --------------------------------------------------------------------------


def hierarchical_grad_sync(
    grads,
    *,
    data_axis: str = "data",
    fsdp_axis: str = "fsdp",
    data_size: int,
    fsdp_size: int,
    dcn_size: int = 1,
    bucket_mb: float = 32.0,
    overlap_chunks: int = 1,
    dcn_dtype: Optional[str] = None,
    plan_out: Optional[Dict[str, Any]] = None,
    registry=None,
):
    """Reduce a pytree of SHARD-LOCAL partial gradients to the global
    sum, staged ici-then-dcn. Must run inside a shard_map body manual
    over (data_axis, fsdp_axis).

    Pipeline per bucket: reduce-scatter over the fsdp axis (always
    in-host), reduce-scatter over the in-host factor of the data axis
    (contiguous groups), ONE grouped psum over the strided cross-host
    rails (optionally bf16), all-gather back in reverse order. Buckets
    are mutually data-independent so XLA overlaps bucket k's DCN hop
    with bucket k-1's gather. Leaves return in their original dtypes.
    """
    leaves, treedef = jax.tree.flatten(grads)
    if not leaves:
        return grads
    shapes = [l.shape for l in leaves]
    dtypes = [l.dtype for l in leaves]
    sizes = [int(l.size) for l in leaves]
    total = sum(sizes)
    plan = make_grad_reduce_plan(
        grad_elems=total,
        data_size=data_size,
        fsdp_size=fsdp_size,
        dcn_size=dcn_size,
        bucket_mb=bucket_mb,
        overlap_chunks=overlap_chunks,
        dcn_dtype=dcn_dtype,
    )
    if plan_out is not None:
        plan_out["plan"] = plan
    export_grad_reduce_gauges(plan, registry=registry)

    dcn = plan.dcn
    ici_d = data_size // dcn  # in-host factor of the data axis
    padded = plan.padded_bytes // 4
    flat = jnp.concatenate(
        [l.astype(jnp.float32).reshape(-1) for l in leaves]
    )
    if padded != total:
        flat = jnp.pad(flat, (0, padded - total))
    cl = padded // plan.n_buckets
    g1 = g2 = None
    if dcn > 1:
        g1, g2 = hierarchical_groups(data_size, dcn)

    pieces = []
    for k in range(plan.n_buckets):
        c = flat[k * cl:(k + 1) * cl]
        if fsdp_size > 1:
            c = psum_scatter(c, fsdp_axis, scatter_dimension=0, tiled=True)
        if data_size > 1:
            if ici_d > 1:
                c = psum_scatter(
                    c, data_axis, scatter_dimension=0, tiled=True,
                    axis_index_groups=g1,
                )
            if dcn > 1:
                # The one DCN crossing per bucket. Under bf16
                # compression only this hop narrows: each shard's
                # scattered chunk already holds the full fp32 in-host
                # sum before the cast.
                if dcn_dtype == "bf16":
                    c = psum(
                        c.astype(jnp.bfloat16), data_axis,
                        axis_index_groups=g2,
                    ).astype(jnp.float32)
                else:
                    c = psum(c, data_axis, axis_index_groups=g2)
            if ici_d > 1:
                c = all_gather(
                    c, data_axis, axis=0, tiled=True,
                    axis_index_groups=g1,
                )
        if fsdp_size > 1:
            c = all_gather(c, fsdp_axis, axis=0, tiled=True)
        pieces.append(c)
    out = pieces[0] if len(pieces) == 1 else jnp.concatenate(pieces)
    out = out[:total]

    synced = []
    offset = 0
    for shape, dtype, size in zip(shapes, dtypes, sizes):
        synced.append(
            out[offset:offset + size].reshape(shape).astype(dtype)
        )
        offset += size
    return jax.tree.unflatten(treedef, synced)


# --------------------------------------------------------------------------
# the shard_map wrapper: local accumulation + deferred sync
# --------------------------------------------------------------------------

_WSCALE_KEYS = ("ce_loss", "total_loss", "z_loss")


def _make_local_loss_fn(
    loss_fn: Callable, axes: Tuple[str, ...], world: int
) -> Callable:
    """Wrap a (params, batch, rng) -> (loss, metrics) loss so its
    per-shard gradient SUMS to the implicit path's global gradient.

    The CE loss is a weighted mean over the global microbatch; each
    shard rescales its local mean by local_denom / psum-denom (one
    scalar psum — the weight sums carry no parameter gradient, so
    autodiff sees a data-dependent constant). Model aux losses rescale
    by 1/world: per-shard aux averaged over shards — exact for
    aux terms linear in per-token stats, a per-shard (rather than
    global-batch) regularizer for the nonlinear MoE balance product
    (see module docstring). Metrics are combined to the implicit
    path's global values: weight-scaled for the CE family, summed for
    token counts, pmean'd otherwise."""
    from luminaai_tpu.parallel.train_step import (
        _shifted_mask_weights,
        shift_labels,
    )

    def local_loss(params, batch, rng):
        total, metrics = loss_fn(params, batch, rng)
        _, valid = shift_labels(batch)
        mask, weights = _shifted_mask_weights(batch, valid)
        w = mask if weights is None else mask * weights
        # local_denom mirrors the CE's own max(w_sum, 1) clamp; the
        # GLOBAL denominator clamps the RAW psum (not a sum of clamped
        # locals) so a shard whose slice is all padding contributes 0
        # without inflating the divisor — exactly the implicit path's
        # max(global_w_sum, 1).
        raw_w = w.sum()
        local_denom = jnp.maximum(raw_w, 1.0)
        global_denom = jnp.maximum(jax.lax.psum(raw_w, axes), 1.0)
        wscale = local_denom / global_denom
        ce_part = metrics.get("total_loss", total)
        aux_part = total - ce_part
        scaled = ce_part * wscale + aux_part * (1.0 / world)
        out: Dict[str, jax.Array] = {}
        for key, v in metrics.items():
            if key == "perplexity":
                continue  # recomputed from the global ce below
            if key == "tokens_in_loss":
                out[key] = jax.lax.psum(v, axes)
            elif key in _WSCALE_KEYS:
                out[key] = jax.lax.psum(v * wscale, axes)
            elif key == "loss":
                out[key] = jax.lax.psum(scaled, axes)
            else:
                out[key] = jax.lax.pmean(v, axes)
        if "ce_loss" in out:
            out["perplexity"] = jnp.exp(jnp.clip(out["ce_loss"], max=20.0))
        return scaled, out

    return local_loss


def make_hierarchical_grad_fn(
    config, loss_fn: Callable, mesh, accum: int
) -> Callable:
    """Build the explicit gradient stage for make_train_step:
    `(params, batch, rng) -> (grads, metrics)` with grads fully reduced
    over (data, fsdp) by the hierarchical sync.

    Everything — microbatch scan included — runs inside ONE partial-auto
    shard_map manual over (data, fsdp); tensor/expert/sequence stay
    automatic (all but data/fsdp must be trivial or auto-partitionable,
    enforced by config.validate). Params enter replicated over the
    manual axes (fsdp-sharded params are gathered at region entry — the
    ZeRO-2 trade the explicit sync currently makes; grads and optimizer
    state stay sharded outside). The returned fn also carries a
    `plan_box` dict that holds the GradReducePlan after first trace."""
    from flax import linen as nn
    from jax.sharding import PartitionSpec as P

    from luminaai_tpu.parallel.sharding import manual_axis_rules
    from luminaai_tpu.parallel.train_step import _accumulate_grads

    data_axis, fsdp_axis = "data", "fsdp"
    data_size = int(mesh.shape[data_axis])
    fsdp_size = int(mesh.shape[fsdp_axis])
    world = data_size * fsdp_size
    dcn = int(config.gradient_dcn_size)
    if data_size % dcn:
        raise ValueError(
            f"gradient_dcn_size {dcn} must divide the mesh data axis "
            f"({data_size})"
        )
    axes = (data_axis, fsdp_axis)
    local_loss = _make_local_loss_fn(loss_fn, axes, world)
    rules = manual_axis_rules(config, axes)
    plan_box: Dict[str, Any] = {}

    def body(params, batch, rng):
        # Distinct per-shard rng stream: with routing noise / dropout
        # ON, each shard draws iid noise for its own rows (the implicit
        # path draws one global tensor; both are valid schemes — parity
        # tests run deterministic configs).
        idx = (
            jax.lax.axis_index(data_axis) * fsdp_size
            + jax.lax.axis_index(fsdp_axis)
        )
        rng = jax.random.fold_in(rng, idx)
        with nn.logical_axis_rules(rules):
            grads, metrics = _accumulate_grads(
                local_loss, params, batch, rng, accum
            )
        grads = hierarchical_grad_sync(
            grads,
            data_axis=data_axis,
            fsdp_axis=fsdp_axis,
            data_size=data_size,
            fsdp_size=fsdp_size,
            dcn_size=dcn,
            bucket_mb=config.grad_reduce_bucket_mb,
            overlap_chunks=config.grad_reduce_overlap_chunks,
            dcn_dtype=config.grad_reduce_dcn_dtype,
            plan_out=plan_box,
        )
        return grads, metrics

    fn = shard_map(
        body,
        mesh,
        in_specs=(P(), P((data_axis, fsdp_axis)), P()),
        out_specs=(P(), P()),
        axis_names=axes,
        check_vma=False,
    )
    fn.plan_box = plan_box
    return fn


# --------------------------------------------------------------------------
# diagnose probe: a real timed two-stage reduction over the probe mesh
# --------------------------------------------------------------------------


def grad_reduce_probe(
    payload_mb: float = 4.0, iters: int = 5, registry=None
) -> Dict[str, Any]:
    """Time a REAL two-stage hierarchical gradient reduction over the
    dcn×ici probe factorization — the `cli diagnose` rung that tells
    the MULTICHIP_r* harness what a bucketed gradient sync actually
    costs on this fleet, next to the expert-a2a probe.

    Multi-host jobs use the (process, local-device) grid as the real
    dcn×ici split; a single host with >= 4 devices SIMULATES a 2-host
    tier so the two-stage code path is exercised and timed even on the
    CPU harness. Degrades to the single-stage fallback below 4 devices.
    Exports diagnose_grad_reduce_seconds{stage} gauges mirroring the
    expert-a2a probe's contract."""
    import time as _time

    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    from luminaai_tpu.monitoring.telemetry import get_registry

    registry = registry or get_registry()
    n_proc = jax.process_count()
    n_global = jax.device_count()
    if n_proc > 1 and n_global % n_proc == 0:
        dcn, ici = n_proc, n_global // n_proc
        simulated = False
    elif n_global >= 4 and n_global % 2 == 0:
        dcn, ici = 2, n_global // 2
        simulated = True
    else:
        dcn, ici = 1, n_global
        simulated = n_proc == 1
    world = dcn * ici
    devices = np.array(jax.devices()[:world]).reshape(world)
    mesh = Mesh(devices, ("data",))
    out: Dict[str, Any] = {
        "world": world, "dcn": dcn, "ici": ici,
        "simulated_dcn": simulated, "stages": {},
    }
    # Per-shard payload sized so the synced gradient is ~payload_mb;
    # rounded to world² so every shard's slice reduce-scatters evenly
    # over any tier factoring.
    elems = max(world * world, int(payload_mb * 1e6 / 4))
    elems = -(-elems // (world * world)) * world * world
    g1, g2 = hierarchical_groups(world, dcn) if dcn > 1 else (None, None)

    def _run_stage(stage_fn, name):
        @jax.jit  # lumina: disable=LX006 -- probe re-times the same buffer; donation would free it between iters
        def stepped(xs):
            return shard_map(
                stage_fn, mesh=mesh,
                in_specs=PartitionSpec("data"),
                out_specs=PartitionSpec("data"),
                check_vma=False,
            )(xs)

        x = jax.device_put(
            jnp.ones((elems,), jnp.float32),
            NamedSharding(mesh, PartitionSpec("data")),
        )
        try:
            stepped(x).block_until_ready()
            t0 = _time.perf_counter()
            for _ in range(iters):
                y = stepped(x)
            y.block_until_ready()
            dt = (_time.perf_counter() - t0) / iters
        except Exception as e:  # probe must never wedge diagnose
            out["stages"][name] = {"error": f"{type(e).__name__}: {e}"}
            return
        payload = elems // world * 4
        out["stages"][name] = {
            "payload_mb": round(elems * 4 / 1e6, 2),
            "mean_seconds": round(dt, 6),
            "algo_gbps": round(payload / max(dt, 1e-9) / 1e9, 3),
        }

    from luminaai_tpu.monitoring.telemetry import MetricsRegistry

    # The sync exports its plan gauges at trace time; the probe's toy
    # payload must not clobber a training process's real
    # grad_reduce_bytes{stage} plan — sink them into a throwaway.
    _plan_sink = MetricsRegistry()

    def _full(v):
        # One full sync over a single-leaf "gradient": the production
        # bucket pipeline end to end.
        return hierarchical_grad_sync(
            v, data_axis="data", fsdp_axis="data",
            data_size=world, fsdp_size=1, dcn_size=dcn,
            bucket_mb=1.0, overlap_chunks=2, registry=_plan_sink,
        )

    if dcn > 1:
        _run_stage(
            lambda v: all_gather(
                psum_scatter(
                    v, "data", scatter_dimension=0, tiled=True,
                    axis_index_groups=g1,
                ),
                "data", axis=0, tiled=True, axis_index_groups=g1,
            ),
            "ici",
        )
        _run_stage(
            lambda v: psum(v, "data", axis_index_groups=g2), "dcn"
        )
        _run_stage(_full, "two_stage")
    else:
        _run_stage(_full, "single_stage")
    g = registry.gauge(
        "diagnose_grad_reduce_seconds",
        "Mean timed hierarchical gradient-sync per stage at last "
        "diagnose",
        labelnames=("stage",),
    )
    for name, rec in out["stages"].items():
        if isinstance(rec, dict) and "mean_seconds" in rec:
            g.labels(stage=name).set(rec["mean_seconds"])
    return out
