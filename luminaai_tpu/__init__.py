"""luminaai_tpu — TPU-native adaptive training framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of MatN23/LuminaAI
(dense + MoE + MoD transformers, adaptive orchestration, distributed training)
targeting TPU meshes via jax.sharding/pjit instead of CUDA/DeepSpeed.
"""

__version__ = "0.1.0"

from luminaai_tpu.config import Config, ConfigManager, ConfigPresets

__all__ = ["Config", "ConfigManager", "ConfigPresets", "__version__"]
