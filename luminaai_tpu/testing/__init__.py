"""Test-support toolkit: fault injectors for the resilience contract
(docs/resilience.md; driven by tests/test_resilience.py)."""

from luminaai_tpu.testing.faults import (  # noqa: F401
    corrupt_checkpoint,
    fail_step_at,
    preempt_at_step,
    sigterm_at_step,
    slow_decode,
    truncated_checkpoint_writes,
)
