"""Fault injectors: make failure modes reproducible on a laptop.

The recovery paths (OOM backoff ladder, instability rollback, emergency
save, restore fallback-walk, serving drain/deadline eviction) are only a
contract if they can be exercised deliberately; these monkeypatch-style
injectors do that without touching production code paths. Every injector
is a context manager that restores what it wrapped — and restores
NOTHING if the wrapped attribute was legitimately replaced mid-test
(e.g. the OOM ladder rebuilding `trainer.train_step` is the behavior
under test, not collateral to undo).

Used by tests/test_resilience.py (pytest marker: `faults`); documented
in docs/resilience.md.
"""

from __future__ import annotations

import contextlib
import logging
import os
import signal as _signal
import threading
import time
from pathlib import Path
from typing import Callable, Iterator, Optional

logger = logging.getLogger(__name__)


def _restore(obj, name, wrapper, original) -> None:
    """Put `original` back only if our wrapper is still installed — a
    recovery path that legitimately rebuilt the attribute (the thing
    under test) must keep its rebuilt version."""
    if getattr(obj, name, None) is wrapper:
        setattr(obj, name, original)


@contextlib.contextmanager
def fail_step_at(
    trainer,
    step_no: int,
    exc_factory: Optional[Callable[[], BaseException]] = None,
    times: int = 1,
) -> Iterator[dict]:
    """Make the trainer's `step_no`-th train_step CALL (1-based, counted
    from entry) raise — default a JaxRuntimeError that reads as a device
    OOM, so `train_with_oom_protection`'s backoff ladder engages. Raises
    `times` consecutive calls, then passes through. Yields a stats dict
    ({'calls', 'raised'})."""
    if exc_factory is None:
        import jax

        def exc_factory():
            return jax.errors.JaxRuntimeError(
                "RESOURCE_EXHAUSTED: injected fault: Ran out of memory"
            )

    stats = {"calls": 0, "raised": 0}
    original = trainer.train_step

    def wrapper(state, batch):
        stats["calls"] += 1
        if stats["calls"] >= step_no and stats["raised"] < times:
            stats["raised"] += 1
            raise exc_factory()
        return original(state, batch)

    trainer.train_step = wrapper
    try:
        yield stats
    finally:
        _restore(trainer, "train_step", wrapper, original)


@contextlib.contextmanager
def preempt_at_step(trainer, step_no: int) -> Iterator[dict]:
    """Call `trainer.request_stop()` right after the `step_no`-th train
    step completes — the in-process equivalent of a SIGTERM landing
    mid-step: the loop must finish the step, run a BLOCKING emergency
    save at the boundary, and return with summary['preempted']=True."""
    stats = {"calls": 0}
    original = trainer.train_step

    def wrapper(state, batch):
        stats["calls"] += 1
        out = original(state, batch)
        if stats["calls"] == step_no:
            trainer.request_stop("injected preemption")
        return out

    trainer.train_step = wrapper
    try:
        yield stats
    finally:
        _restore(trainer, "train_step", wrapper, original)


@contextlib.contextmanager
def sigterm_at_step(trainer, step_no: int) -> Iterator[dict]:
    """Deliver a REAL SIGTERM to this process right after the
    `step_no`-th train step — exercises the installed signal handler end
    to end (cli._install_signal_handlers → request_stop → emergency
    save → RESUMABLE_EXIT). Only for subprocess-based tests: the default
    SIGTERM disposition kills the process."""
    stats = {"calls": 0}
    original = trainer.train_step

    def wrapper(state, batch):
        stats["calls"] += 1
        out = original(state, batch)
        if stats["calls"] == step_no:
            os.kill(os.getpid(), _signal.SIGTERM)
        return out

    trainer.train_step = wrapper
    try:
        yield stats
    finally:
        _restore(trainer, "train_step", wrapper, original)


def corrupt_checkpoint(
    checkpoint_dir, step: int, mode: str = "truncate"
) -> int:
    """Corrupt an on-disk orbax checkpoint the way a kill-mid-commit or
    disk-full does: `truncate` halves every state file (partial write),
    `delete` removes them. Returns the number of files damaged; raises if
    the step directory does not exist (a typo must not silently 'pass')."""
    step_dir = Path(checkpoint_dir) / str(step)
    if not step_dir.is_dir():
        raise FileNotFoundError(f"no checkpoint step dir {step_dir}")
    state_dir = step_dir / "state"
    root = state_dir if state_dir.is_dir() else step_dir
    damaged = 0
    for f in sorted(root.rglob("*")):
        if not f.is_file():
            continue
        if mode == "delete":
            f.unlink()
            damaged += 1
        else:
            size = f.stat().st_size
            if size > 1:
                with f.open("r+b") as fh:
                    fh.truncate(max(1, size // 2))
                damaged += 1
    if damaged == 0:
        raise RuntimeError(f"nothing to corrupt under {root}")
    logger.warning("corrupted %d file(s) in %s (%s)", damaged, root, mode)
    return damaged


@contextlib.contextmanager
def truncated_checkpoint_writes(manager) -> Iterator[dict]:
    """Make every save through this CheckpointManager land truncated on
    disk (the commit 'succeeds' but the bytes are partial) — the failure
    a restore-side integrity walk must survive. Yields {'saves': n}."""
    stats = {"saves": 0}
    original = manager.save

    def wrapper(state, step, *args, **kwargs):
        ok = original(state, step, *args, **kwargs)
        manager.wait()  # let the async commit land before damaging it
        try:
            corrupt_checkpoint(manager.dir, step)
            stats["saves"] += 1
        except (FileNotFoundError, RuntimeError):
            pass  # save was skipped (duplicate step): nothing written
        return ok

    manager.save = wrapper
    try:
        yield stats
    finally:
        _restore(manager, "save", wrapper, original)


@contextlib.contextmanager
def hang_step_at(
    trainer, step_no: int, seconds: float = 2.0, times: int = 1
) -> Iterator[dict]:
    """Stall the `step_no`-th train_step CALL (1-based) for `seconds` of
    wall clock before executing it — what a stuck DCN collective or a
    wedged compile helper looks like from the host loop's seat. The step
    eventually completes, so the watchdog's detect→dump→continue path
    and (with an injected exit fn) detect→abort are both drivable from
    one injector. Stalls `times` consecutive calls. Yields
    {'calls', 'hangs'}."""
    stats = {"calls": 0, "hangs": 0}
    original = trainer.train_step

    def wrapper(state, batch):
        stats["calls"] += 1
        if stats["calls"] >= step_no and stats["hangs"] < times:
            stats["hangs"] += 1
            time.sleep(seconds)
        return original(state, batch)

    trainer.train_step = wrapper
    try:
        yield stats
    finally:
        _restore(trainer, "train_step", wrapper, original)


@contextlib.contextmanager
def slow_tick(decoder, delay_s: float = 0.5, after: int = 3) -> Iterator[dict]:
    """Serving hang injector: every decode_step AFTER the `after`-th
    stalls `delay_s`. The fast warmup ticks build the serving watchdog's
    rolling stats, then the tick time jumps — so what trips is the
    ROBUST threshold crossing, not absolute slowness (contrast
    slow_decode, which slows every step uniformly for deadline-eviction
    tests). Yields {'steps'}."""
    stats = {"steps": 0}
    original = decoder.decode_step

    def wrapper(*args, **kwargs):
        stats["steps"] += 1
        if stats["steps"] > after:
            time.sleep(delay_s)
        return original(*args, **kwargs)

    decoder.decode_step = wrapper
    try:
        yield stats
    finally:
        _restore(decoder, "decode_step", wrapper, original)


@contextlib.contextmanager
def flaky_storage(
    times: int = 3,
    ops: Optional[tuple] = None,
    error_factory: Optional[Callable[[str], BaseException]] = None,
) -> Iterator[dict]:
    """Make the first `times` durable-I/O operations raise a TRANSIENT
    error before the real call runs, then succeed — a flaky GCS/NFS
    mount as seen from the retry seam (utils/retry.set_fault_hook), so
    the whole backoff ladder is exercised through the REAL call sites
    (checkpoint save/restore, jsonl opens, token-cache reads) without
    monkeypatching `builtins.open`. `ops` filters to op-name prefixes
    (e.g. ("checkpoint",) or ("data",)). Yields {'calls', 'raised'}."""
    from luminaai_tpu.utils import retry as _retry

    if error_factory is None:
        def error_factory(op):
            return _retry.TransientIOError(
                f"injected transient storage fault ({op})"
            )

    stats = {"calls": 0, "raised": 0}

    def hook(op: str) -> None:
        stats["calls"] += 1
        if ops is not None and not any(op.startswith(p) for p in ops):
            return
        if stats["raised"] < times:
            stats["raised"] += 1
            raise error_factory(op)

    prev = _retry.set_fault_hook(hook)
    try:
        yield stats
    finally:
        _retry.set_fault_hook(prev)


def bitflip_checkpoint(checkpoint_dir, step: int) -> str:
    """Flip ONE byte mid-file in the step's largest state file WITHOUT
    changing its size — silent bit corruption: orbax restores it
    without complaint, every size check passes, and only the sha256
    integrity manifest can tell. Returns the damaged file's path;
    raises if the step (or something to flip) does not exist."""
    from luminaai_tpu.training.checkpoint import MANIFEST_NAME

    step_dir = Path(checkpoint_dir) / str(step)
    if not step_dir.is_dir():
        raise FileNotFoundError(f"no checkpoint step dir {step_dir}")
    candidates = [
        f for f in sorted(step_dir.rglob("*"))
        if f.is_file() and f.name != MANIFEST_NAME
        and not f.name.endswith(".tmp") and f.stat().st_size > 0
    ]
    # Prefer the tensor bytes: a flipped metadata byte often breaks the
    # parse (loud), a flipped shard byte changes a weight (silent).
    state_files = [
        f for f in candidates if "state" in f.relative_to(step_dir).parts
    ]
    pool = state_files or candidates
    if not pool:
        raise RuntimeError(f"nothing to bitflip under {step_dir}")
    target = max(pool, key=lambda f: f.stat().st_size)
    mid = target.stat().st_size // 2
    with target.open("r+b") as fh:
        fh.seek(mid)
        byte = fh.read(1)
        fh.seek(mid)
        fh.write(bytes([byte[0] ^ 0xFF]))
    logger.warning("bitflipped %s at offset %d", target, mid)
    return str(target)


def torn_manifest(checkpoint_dir, step: int) -> str:
    """Truncate the step's integrity manifest halfway — the torn-write
    artifact of a writer killed mid-rename-less flush. Verification
    must classify it as corruption (walk back), never as 'no manifest,
    proceed unverified'. Returns the manifest path."""
    from luminaai_tpu.training.checkpoint import MANIFEST_NAME

    m = Path(checkpoint_dir) / str(step) / MANIFEST_NAME
    if not m.is_file():
        raise FileNotFoundError(f"no manifest at {m}")
    data = m.read_bytes()
    m.write_bytes(data[: max(1, len(data) // 2)])
    logger.warning("tore manifest %s to %d bytes", m, max(1, len(data) // 2))
    return str(m)


def kill_replica(replica) -> None:
    """Kill one serving replica the unclean way. A subprocess replica
    (anything with a .pid) gets a real SIGKILL — mid-stream sockets are
    severed with no FIN-and-drain courtesy. An in-process replica (a
    ThreadingHTTPServer, or anything with an .httpd) has its listening
    socket closed immediately, so every NEW connection is refused like a
    dead host's would be; in-flight handler threads keep their already-
    accepted sockets (in-process tests drive mid-stream death through
    the router's transport seam instead, and the multi-process smoke
    exercises the real-SIGKILL shape end to end)."""
    pid = getattr(replica, "pid", None)
    if pid is not None:
        os.kill(int(pid), _signal.SIGKILL)
        return
    httpd = getattr(replica, "httpd", replica)
    try:
        httpd.socket.close()  # refuse new connections NOW
    except OSError:
        pass
    # Unblock the accept loop without waiting on in-flight handlers
    # (shutdown() joins the poll loop; a fault injector must not).
    threading.Thread(target=httpd.shutdown, daemon=True).start()
    logger.warning("killed in-process replica on %s",
                   getattr(httpd, "server_address", "?"))


@contextlib.contextmanager
def replica_5xx_burst(server, times: int = 5,
                      status: int = 500) -> Iterator[dict]:
    """Make one ChatServer's next `times` generation requests (JSON and
    SSE alike) answer `status` before any model work — the flapping-
    dependency shape a fronting router's circuit breaker must absorb:
    the burst opens the breaker, the half-open probe after the cooldown
    finds the burst exhausted and closes it. Yields
    {'calls', 'failed'}."""
    stats = {"calls": 0, "failed": 0}
    orig_handle = server.handle
    orig_stream = server.start_stream

    def handle(method, path, body, token, request_id=None):
        if method == "POST" and path in ("/v1/generate", "/v1/chat"):
            stats["calls"] += 1
            if stats["failed"] < times:
                stats["failed"] += 1
                return status, {"error": "injected replica fault"}
        return orig_handle(method, path, body, token,
                           request_id=request_id)

    def start_stream(path, body, token, request_id=None):
        stats["calls"] += 1
        if stats["failed"] < times:
            stats["failed"] += 1
            return (status, {"error": "injected replica fault"}), None
        return orig_stream(path, body, token, request_id=request_id)

    server.handle = handle
    server.start_stream = start_stream
    try:
        yield stats
    finally:
        _restore(server, "handle", handle, orig_handle)
        _restore(server, "start_stream", start_stream, orig_stream)


@contextlib.contextmanager
def slow_replica(server_or_engine, delay_s: float = 0.2) -> Iterator[dict]:
    """Inflate every decode tick on ONE replica's engine — the slow-
    replica fleet shape hedged dispatch exists for: the affine target
    still answers, just late, so only a hedge (not a failover) recovers
    the tail. Wraps the engine's generate / generate_batch /
    generate_stream; pass a ChatServer or the engine itself. Yields
    {'calls'}."""
    engine = getattr(server_or_engine, "engine", server_or_engine)
    stats = {"calls": 0}
    wrapped = []

    def _wrap(name):
        original = getattr(engine, name, None)
        if original is None:
            return
        if name == "generate_stream":
            def wrapper(*args, **kwargs):
                stats["calls"] += 1
                for ev in original(*args, **kwargs):
                    time.sleep(delay_s)
                    yield ev
        else:
            def wrapper(*args, **kwargs):
                stats["calls"] += 1
                time.sleep(delay_s)
                return original(*args, **kwargs)
        setattr(engine, name, wrapper)
        wrapped.append((name, wrapper, original))

    for name in ("generate", "generate_batch", "generate_stream"):
        _wrap(name)
    try:
        yield stats
    finally:
        for name, wrapper, original in wrapped:
            _restore(engine, name, wrapper, original)


@contextlib.contextmanager
def drop_page_pulls(client, times: int = 0) -> Iterator[dict]:
    """Make a PageShareClient's page fetches fail with a connection
    error — the dead/unreachable owner shape the remote-hit admission
    must degrade from: the pull books a failure, the admission falls
    back to local prefill, and the client sees nothing. `times=0`
    drops every fetch; `times=N` drops the first N then passes
    through. Yields {'calls', 'dropped'}."""
    stats = {"calls": 0, "dropped": 0}
    original = client.fetch_page

    def wrapper(owner_url, key, timeout_s=None):
        stats["calls"] += 1
        if times == 0 or stats["dropped"] < times:
            stats["dropped"] += 1
            # Book the failure through the client's own accounting so
            # serve_prefix_remote_pull_failures_total still increments.
            client._observe_pull(key, owner_url, client._clock(),
                                 ok=False, nbytes=0)
            raise OSError("injected page pull drop")
        return original(owner_url, key, timeout_s=timeout_s)

    client.fetch_page = wrapper
    try:
        yield stats
    finally:
        _restore(client, "fetch_page", wrapper, original)


@contextlib.contextmanager
def slow_page_pulls(client, delay_s: float = 0.5) -> Iterator[dict]:
    """Stall every page fetch `delay_s` before it runs — the congested/
    half-dead owner shape the transfer deadline exists for: with
    delay_s above the client's timeout budget, the pull chain runs out
    of deadline partway and the admission degrades to local prefill
    for the rest. Yields {'calls'}."""
    stats = {"calls": 0}
    original = client.fetch_page

    def wrapper(owner_url, key, timeout_s=None):
        stats["calls"] += 1
        time.sleep(delay_s)
        return original(owner_url, key, timeout_s=timeout_s)

    client.fetch_page = wrapper
    try:
        yield stats
    finally:
        _restore(client, "fetch_page", wrapper, original)


@contextlib.contextmanager
def slow_decode(decoder, delay_s: float = 0.2) -> Iterator[dict]:
    """Slow/stuck-lane injector: every decode_step stalls `delay_s`, so a
    serving request with a deadline goes overdue mid-decode and the
    scheduler's eviction path fires. Yields {'steps': n}."""
    stats = {"steps": 0}
    original = decoder.decode_step

    def wrapper(*args, **kwargs):
        stats["steps"] += 1
        time.sleep(delay_s)
        return original(*args, **kwargs)

    decoder.decode_step = wrapper
    try:
        yield stats
    finally:
        _restore(decoder, "decode_step", wrapper, original)
