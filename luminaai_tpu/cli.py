"""Command-line entry point: train / resume / chat / benchmark / data /
diagnose / presets.

Covers the reference CLI surface (ref: Src/Main_Scripts/Main.py:1506 main()
with config selection + adaptive-vs-standard training, :619 system
diagnostics, :1404 chinchilla auto-epochs, :1126 signal handlers, plus
Chat.py's interactive entry) as a proper argparse program:

    python -m luminaai_tpu train --preset debug --synthetic --steps 30
    python -m luminaai_tpu resume --output-dir runs/exp1
    python -m luminaai_tpu chat --checkpoint runs/exp1/checkpoints
    python -m luminaai_tpu benchmark
    python -m luminaai_tpu data sample --out data/sample.jsonl
    python -m luminaai_tpu diagnose
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import signal
import sys
from pathlib import Path
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

logger = logging.getLogger(__name__)

# Exit code for "stopped on a preemption signal with a resumable
# checkpoint banked" — EX_TEMPFAIL by convention, distinct from both
# success (0) and failure (1/2) so orchestrators can reschedule with
# `resume` instead of alerting (docs/resilience.md).
RESUMABLE_EXIT = 75


# ---------------------------------------------------------------------------
# config assembly
# ---------------------------------------------------------------------------
def _apply_overrides(cfg, args) -> None:
    """Map CLI flags onto Config fields (only when explicitly given)."""
    for flag, field in [
        ("lr", "learning_rate"),
        ("batch_size", "batch_size"),
        ("seq_length", "seq_length"),
        ("steps", "max_steps"),
        ("epochs", "num_epochs"),
        ("precision", "precision"),
        ("output_dir", "output_dir"),
        ("experiment", "experiment_name"),
        ("grad_accum", "gradient_accumulation_steps"),
        ("tokenizer", "tokenizer_name"),
        ("dp", "data_parallel_size"),
        ("pp", "pipeline_parallel_size"),
        ("fsdp", "fsdp_parallel_size"),
        ("tp", "tensor_parallel_size"),
        ("ep", "expert_parallel_size"),
        ("sp", "sequence_parallel_size"),
        ("moe_dispatch", "moe_dispatch"),
        ("attention_window", "attention_window"),
        ("profile_dir", "profile_dir"),
        ("watchdog", "watchdog"),
        ("watchdog_k", "watchdog_k"),
        ("watchdog_floor", "watchdog_floor_s"),
        ("slo", "slo"),
        ("slo_config", "slo_config"),
    ]:
        val = getattr(args, flag, None)
        if val is not None:
            setattr(cfg, field, val)
    if getattr(args, "no_moe", False):
        cfg.use_moe = False
    if getattr(args, "no_flash", False):
        cfg.use_flash_attention = False
    # Windowed in-run profiling (docs/observability.md "Attribution"):
    # --profile-steps N captures a device trace for N steps (starting at
    # --profile-start, default step 3 so the compile step never pollutes
    # the window) and exports the per-subsystem breakdown. Either flag
    # alone enables the window — --profile-start without --profile-steps
    # uses the config's profile_num_steps (default 3), never a silent
    # no-op.
    if getattr(args, "profile_start", None):
        cfg.profile_start_step = args.profile_start
    if getattr(args, "profile_steps", None):
        cfg.profile_num_steps = args.profile_steps
        if not cfg.profile_start_step:
            cfg.profile_start_step = 3
    if getattr(args, "cost_analysis", False):
        cfg.compiled_cost_analysis = True
    if getattr(args, "watchdog_abort", False):
        cfg.watchdog_abort = True
    # Axis-implied settings (ring attention under sp, scan_layers and the
    # grad-accum fold under pp) — one shared code path on Config.
    cfg.normalize_parallelism()


def build_config(args):
    from luminaai_tpu.config import ConfigManager, ConfigPresets

    if getattr(args, "config", None):
        from luminaai_tpu.config import Config

        cfg = Config.load(args.config)
    else:
        cfg = ConfigPresets.get(args.preset)
    _apply_overrides(cfg, args)
    if getattr(args, "auto_hardware", False):
        cfg = ConfigManager.optimize_for_hardware(cfg)
    cfg.validate()
    return cfg


# ---------------------------------------------------------------------------
# data wiring
# ---------------------------------------------------------------------------
def _synthetic_batches(cfg, n_batches: int = 200, seed: int = 0):
    """Learnable repeating-pattern batches (smoke training, ref debug
    runs on synthetic data). Deterministic per (seed, epoch) and wrapped
    in a PrefetchLoader, so even synthetic runs get the exact-resume
    contract (docs/resilience.md)."""
    from luminaai_tpu.data.dataset import PrefetchLoader

    def gen(epoch: int = 0) -> Iterator[Dict[str, np.ndarray]]:
        rng = np.random.RandomState(seed + epoch)
        period = min(64, cfg.vocab_size - 2)
        for _ in range(n_batches):
            starts = rng.randint(0, 32, size=(cfg.batch_size, 1))
            seq = (starts + np.arange(cfg.seq_length)) % period + 1
            yield {"input_ids": seq.astype(np.int32)}

    return PrefetchLoader(gen, prefetch=2)


def make_data(cfg, args):
    """Returns (train_fn, eval_fn, dataset_tokens|None)."""
    from luminaai_tpu.data.dataset import (
        ConversationDataset,
        PackedDataset,
        PrefetchLoader,
        build_text_cache,
        conversation_batches,
    )
    from luminaai_tpu.data.tokenizer import ConversationTokenizer

    # --data wins; config train_data_path is a fallback only when the file
    # actually exists (its default 'data/train.jsonl' must not shadow the
    # synthetic-data default on fresh checkouts).
    cfg_path = cfg.train_data_path
    data_path = getattr(args, "data", None) or (
        cfg_path if cfg_path and Path(cfg_path).exists() else None
    )
    if getattr(args, "synthetic", False) or not data_path:
        if not getattr(args, "synthetic", False):
            logger.warning("no --data given; training on synthetic data")
        return _synthetic_batches(cfg), None, None

    path = data_path
    tokenizer = ConversationTokenizer(
        model_name=cfg.tokenizer_name,
        assistant_loss_weight=cfg.assistant_loss_weight,
    )
    if tokenizer.vocab_size > cfg.vocab_size:
        # A trained vocab larger than the model's embedding table would
        # index out of range; grow the model to fit (tokenizer.vocab_size
        # is already 128-aligned).
        logger.warning(
            "tokenizer vocab %d > model vocab_size %d; raising model "
            "vocab_size to match", tokenizer.vocab_size, cfg.vocab_size,
        )
        cfg.vocab_size = tokenizer.vocab_size
    # Per-host shard identity comes from config, not live jax state (the
    # distributed runtime comes up later, in Trainer.__init__). On pods
    # where jax auto-detects the process id, process_id is legitimately
    # None — sharding on it would put EVERY host on shard 0, so fall back
    # to the process-oblivious full-batch loader (Trainer._put slices
    # each host's rows at runtime).
    pi, pc = 0, 1
    if cfg.multihost and (cfg.num_processes or 1) > 1:
        if cfg.process_id is not None:
            pi, pc = cfg.process_id, cfg.num_processes
        else:
            logger.warning(
                "multihost without explicit process_id: data sharding "
                "disabled; every host will read the full corpus (set "
                "config.process_id to enable per-host shards)"
            )
    if getattr(args, "packed", False):
        cache = build_text_cache(
            path, str(Path(cfg.output_dir) / "cache" / Path(path).stem),
            tokenizer,
        )
        ds = PackedDataset(
            cache, cfg.batch_size, cfg.seq_length,
            pad_id=tokenizer.pad_token_id, eos_id=tokenizer.eos_token_id,
            shuffle_seed=cfg.seed,
            use_native=cfg.use_native_dataloader,
            split_docs=cfg.pack_sequences,
            process_index=pi,
            process_count=pc,
        )
        return (
            PrefetchLoader(
                lambda: iter(ds), prefetch=max(1, cfg.num_workers),
                source=ds,  # curriculum set_difficulty forwards to the ds
            ),
            None, cache.n_tokens,
        )

    ds = ConversationDataset(path, tokenizer, cfg)
    tokens = None
    if not ds.streaming:
        tokens = sum(int(s["loss_mask"].size) for s in ds.samples)

    def train_fn(epoch: int):
        # Fresh permutation per epoch, derived from the epoch NUMBER (not
        # a process-local counter): the PrefetchLoader passes the epoch
        # through, so a resumed run replays the same per-epoch shuffles
        # and the batch stream continues exactly (docs/resilience.md).
        return conversation_batches(
            ds, cfg.batch_size, seed=cfg.seed + epoch,
            process_index=pi, process_count=pc,
        )

    eval_fn = None
    eval_path = getattr(args, "eval_data", None) or (
        cfg.eval_data_path
        if cfg.eval_data_path and Path(cfg.eval_data_path).exists()
        else None
    )
    if eval_path:
        eval_ds = ConversationDataset(eval_path, tokenizer, cfg, split="eval")

        def eval_fn():
            return conversation_batches(eval_ds, cfg.batch_size, seed=0)

    return (
        PrefetchLoader(train_fn, prefetch=max(1, cfg.num_workers)),
        eval_fn, tokens,
    )


# ---------------------------------------------------------------------------
# subcommands
# ---------------------------------------------------------------------------
def cmd_train(args) -> int:
    from luminaai_tpu.training.orchestrator import AdaptiveTrainingOrchestrator
    from luminaai_tpu.training.scaler import ChinchillaScaler
    from luminaai_tpu.training.trainer import Trainer
    from luminaai_tpu.utils.environment import format_diagnostics

    if not args.quiet:
        print(format_diagnostics())

    cfg = build_config(args)
    logging.getLogger().setLevel(cfg.log_level)
    if args.resume:
        cfg.auto_resume = True
    train_fn, eval_fn, dataset_tokens = make_data(cfg, args)

    auto_epochs = args.auto_epochs or cfg.use_chinchilla_scaling
    if auto_epochs and dataset_tokens:
        # Chinchilla budget → step count (ref Main.py:1404
        # auto_adjust_epochs_chinchilla). An explicit --steps wins: the
        # budget is advice, not an override of the operator.
        plan = ChinchillaScaler(cfg).plan(dataset_tokens)
        if args.steps is None:
            cfg.max_steps = plan.recommended_steps
        print(
            f"chinchilla auto-budget: recommended_steps="
            f"{plan.recommended_steps} (dataset {dataset_tokens:,} tokens, "
            f"applied={'yes' if args.steps is None else 'no, --steps set'})"
        )

    # Rough wall-clock estimate before committing compute (ref Main.py:1008
    # estimate_and_display_training_time).
    steps = cfg.max_steps or 0
    if steps and not args.quiet:
        tok_per_step = cfg.batch_size * cfg.seq_length
        # ~40% MFU planning number on detected hardware; CPU ≈ debug only.
        from luminaai_tpu.utils.environment import (
            device_peak_flops,
            get_device_info,
        )

        dev = get_device_info()
        if dev["platform"] == "tpu":
            peak = device_peak_flops()
        else:
            peak = {"gpu": 312e12}.get(dev["platform"], 5e11)
        est_tps = max(
            1.0,
            0.4 * peak * dev["device_count"]
            / (6 * max(cfg.estimate_active_parameters(), 1)),
        )
        hours = steps * tok_per_step / est_tps / 3600
        print(
            f"estimated training time: ~{hours:.2f}h for {steps} steps "
            f"({tok_per_step * steps / 1e6:.0f}M tokens at ~{est_tps:,.0f} "
            "tok/s planning rate)"
        )

    # Start-of-run experiment metadata (ref Main.py:1192
    # save_experiment_metadata) — written before the trainer is even built
    # so any crash still leaves provenance on disk. A resume never
    # overwrites the original run's record.
    meta_path = Path(cfg.output_dir) / "experiment_metadata.json"
    if not (args.resume and meta_path.exists()):
        meta_path.parent.mkdir(parents=True, exist_ok=True)
        meta_path.write_text(json.dumps(_jsonable({
            "experiment_name": cfg.experiment_name,
            "config": cfg.to_dict(),
            "total_params": cfg.estimate_parameters(),
            "active_params": cfg.estimate_active_parameters(),
            "dataset_tokens": dataset_tokens,
            "planned_steps": cfg.max_steps,
            "argv": sys.argv[1:],
        }), indent=2))

    trainer = Trainer(cfg, train_data=train_fn, eval_data=eval_fn)
    _install_signal_handlers(trainer)

    oom_protect = getattr(args, "oom_protect", True)
    if args.adaptive:
        orchestrator = AdaptiveTrainingOrchestrator(trainer)
        summary = orchestrator.run(oom_protect=oom_protect)
    elif oom_protect:
        summary = trainer.train_with_oom_protection()
    else:
        summary = trainer.train()
    trainer.close()

    out = Path(cfg.output_dir) / "training_summary.json"
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(_jsonable(summary), indent=2))
    final = summary.get("final_metrics", {})
    if summary.get("preempted"):
        print(
            f"training PREEMPTED at step {summary.get('final_step')}: "
            f"emergency checkpoint committed; rerun `resume` to continue "
            f"(exit {RESUMABLE_EXIT} = resumable)"
        )
        return RESUMABLE_EXIT
    print(
        f"training done: steps={summary.get('final_step')} "
        f"final_loss={final.get('loss', float('nan')):.4f} "
        f"summary={out}"
    )
    return 0


def cmd_chat(args) -> int:
    from luminaai_tpu.inference.chat import ChatInterface

    chat = ChatInterface(
        checkpoint_dir=args.checkpoint,
        quantize=getattr(args, "quantize", None),
        adapter=getattr(args, "adapter", None),
        kv_cache_dtype=getattr(args, "kv_cache_dtype", None),
    )
    if chat.engine.quantization_info:
        q = chat.engine.quantization_info
        if q.get("mode") == "int8_compute":
            print(
                f"serving with int8 COMPUTE quantization: "
                f"{q['quantized_leaves']} tensors run int8 MXU dots "
                f"(W8A8), {q['compression']:.2f}x smaller resident",
                file=sys.stderr,
            )
        else:
            print(
                f"serving with int{q['bits']} weight round-trip: "
                f"{q['quantized_leaves']} tensors, {q['compression']:.2f}x "
                "smaller at rest (resident serving copy stays bf16 for MXU "
                "compute)", file=sys.stderr,
            )
    # Generation defaults live on the engine's config (ref Chat.py mode
    # presets); CLI flags override them for the session.
    chat.engine.config.temperature = args.temperature
    chat.engine.config.top_p = args.top_p
    chat.engine.config.max_new_tokens = args.max_new_tokens

    if args.secure:
        # Authenticated, rate-limited, input-validated path (ref
        # security/rate_limiter.py:107 SecureConversationalChat).
        from luminaai_tpu.security import SecureChatSession

        secure = SecureChatSession(chat.respond)
        user = args.user or "operator"
        password = args.password
        if password is None:
            import getpass

            password = getpass.getpass(f"password for {user}: ")
        if user not in secure.security.users:
            if not secure.create_user(user, password):
                print("could not create user (weak password?)", file=sys.stderr)
                return 2
        token = secure.authenticate(user, password)
        if token is None:
            print("authentication failed", file=sys.stderr)
            return 2
        if args.prompt:
            out = secure.secure_respond(args.prompt, token)
            if not out["ok"]:
                print(f"rejected: {out['error']}", file=sys.stderr)
                return 1
            print(out["reply"])
            return 0
        print("secure chat — 'quit' to exit")
        while True:  # pragma: no cover - interactive
            try:
                line = input("> ")
            except (EOFError, KeyboardInterrupt):
                break
            if line.strip().lower() in ("quit", "exit"):
                break
            out = secure.secure_respond(line, token)
            print(out["reply"] if out["ok"] else f"[{out['error']}]")
        return 0

    if args.prompt:
        reply, stats = chat.respond(args.prompt)
        print(reply)
        if args.verbose:
            print(json.dumps(stats, indent=2), file=sys.stderr)
        return 0
    chat.run()
    return 0


def cmd_benchmark(args) -> int:
    """Run the repo bench harness (one JSON line, same as the driver)."""
    import subprocess

    bench = Path(__file__).resolve().parent.parent / "bench.py"
    if args.ops:
        bench = Path(__file__).resolve().parent.parent / "bench_ops.py"
    if not bench.exists():
        print(f"benchmark harness not found: {bench}", file=sys.stderr)
        return 2
    return subprocess.call([sys.executable, str(bench)])


def cmd_data(args) -> int:
    from luminaai_tpu.data.processing import (
        create_sample_data,
        process_oasst_data,
        validate_data_comprehensive,
    )

    if args.action == "sample":
        n = create_sample_data(args.out, num_conversations=args.count)
        print(f"wrote {n} sample conversations to {args.out}")
    elif args.action == "acquire":
        from luminaai_tpu.config import Config
        from luminaai_tpu.data.acquisition import DatasetDownloader

        max_per_file = args.max_per_file
        if max_per_file is None:  # flag overrides the config default
            max_per_file = Config().max_conversations_per_file
        dl = DatasetDownloader(
            args.out or "data/oasst",
            max_records_per_file=max_per_file,
        )
        if args.inp:  # offline path: local raw OASST dump
            stats = dl.process_local_dump(args.inp)
            print(json.dumps(_jsonable(stats), indent=2))
        else:
            ok = dl.download_and_process()
            if not ok:
                print(
                    "download unavailable (offline?); pass --in DUMP.jsonl "
                    "to process a local raw dump", file=sys.stderr,
                )
                return 1
    elif args.action == "train-tokenizer":
        # Offline BPE vocab training (data/bpe.py; the reference can only
        # consume pretrained tiktoken vocabs). --in accepts conversation
        # or plain-text jsonl; --vocab-size is the target vocab.
        from luminaai_tpu.data.bpe import train_bpe

        def texts():
            with open(args.inp) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        row = json.loads(line)
                    except json.JSONDecodeError:
                        yield line
                        continue
                    if isinstance(row, dict) and "messages" in row:
                        for m in row["messages"]:
                            yield str(m.get("content", ""))
                    elif isinstance(row, dict) and "text" in row:
                        yield str(row["text"])
                    else:
                        yield line

        tok = train_bpe(texts(), vocab_size=args.vocab_size)
        tok.save(args.out)
        sample = "The quick brown fox jumps over the lazy dog."
        n_bpe = len(tok.encode(sample))
        print(
            f"trained {tok.n_vocab}-token BPE -> {args.out} "
            f"(sample compression {len(sample.encode()) / max(n_bpe, 1):.2f} "
            "bytes/token; use with --tokenizer "
            f"bpe:{args.out})"
        )
    elif args.action == "oasst":
        n = process_oasst_data(args.inp, args.out)
        print(f"converted {n} conversations -> {args.out}")
    elif args.action == "validate":
        from luminaai_tpu.data.tokenizer import ConversationTokenizer

        report = validate_data_comprehensive(
            args.inp, ConversationTokenizer()
        )
        print(json.dumps(_jsonable(report), indent=2))
    elif args.action == "blend":
        # Weighted multi-source blend → one jsonl (ref Main.py:1350
        # setup_multi_dataset_training + multi_source main()). --sources
        # takes name=weight=glob triples.
        import glob as globlib

        from luminaai_tpu.data.multi_source import MultiSourcePipeline
        from luminaai_tpu.data.tokenizer import ConversationTokenizer

        if not args.sources:
            print(
                "blend requires --sources name=weight=glob [...]",
                file=sys.stderr,
            )
            return 2
        weights: Dict[str, float] = {}
        shards: Dict[str, List[str]] = {}
        for spec in args.sources:
            try:
                name, weight, pattern = spec.split("=", 2)
                weights[name] = float(weight)
            except ValueError:
                print(f"bad --sources entry {spec!r}", file=sys.stderr)
                return 2
            shards[name] = sorted(globlib.glob(pattern))
            if not shards[name]:
                print(f"no files match {pattern!r}", file=sys.stderr)
                return 2
        if sum(weights.values()) <= 0:
            print("--sources weights must sum to > 0", file=sys.stderr)
            return 2
        pipeline = MultiSourcePipeline(ConversationTokenizer(), weights)
        out_path = args.out or "blended.jsonl"
        n = 0
        with open(out_path, "w", encoding="utf-8") as f:
            for rec in pipeline.iter_blended(shards):
                f.write(json.dumps(rec, ensure_ascii=False) + "\n")
                n += 1
        print(f"blended {n} documents from {len(shards)} sources -> {out_path}")
    return 0


def cmd_evaluate(args) -> int:
    """Standalone perplexity/loss evaluation of a checkpoint on a jsonl
    dataset (ref trainer.py:2667 evaluate, exposed without a Trainer)."""
    import jax
    import jax.numpy as jnp

    from luminaai_tpu.data.dataset import ConversationDataset, conversation_batches
    from luminaai_tpu.data.tokenizer import ConversationTokenizer
    from luminaai_tpu.inference.chat import load_model_for_inference
    from luminaai_tpu.parallel.train_step import (
        _shifted_mask_weights,
        shift_labels,
    )
    from luminaai_tpu.ops.fused import fused_lm_head_cross_entropy

    model, params, cfg = load_model_for_inference(args.checkpoint)
    if args.batch_size:
        cfg.batch_size = args.batch_size
    tokenizer = ConversationTokenizer(
        assistant_loss_weight=cfg.assistant_loss_weight
    )
    ds = ConversationDataset(args.data, tokenizer, cfg, split="eval")

    @jax.jit
    def eval_batch(params, batch):
        hidden, _ = model.apply(
            {"params": params}, batch["input_ids"],
            deterministic=True, return_hidden=True,
        )
        labels, valid = shift_labels(batch)
        mask, weights = _shifted_mask_weights(batch, valid)
        head = params["embedder"][
            "embedding" if cfg.tie_word_embeddings else "lm_head"
        ]
        loss, metrics = fused_lm_head_cross_entropy(
            hidden, head, labels, loss_mask=mask, loss_weights=weights,
        )
        return metrics

    total_nll = total_tokens = 0.0
    n_batches = 0
    for batch in conversation_batches(
        ds, cfg.batch_size, seed=0, drop_last=False
    ):
        if args.max_batches and n_batches >= args.max_batches:
            break
        m = eval_batch(params, {k: jnp.asarray(v) for k, v in batch.items()})
        ntok = float(m["tokens_in_loss"])
        total_nll += float(m["ce_loss"]) * ntok
        total_tokens += ntok
        n_batches += 1
    if total_tokens == 0:
        print("no evaluable tokens found", file=sys.stderr)
        return 1
    loss = total_nll / total_tokens
    result = {
        "eval_loss": round(loss, 4),
        "perplexity": round(float(np.exp(min(loss, 20.0))), 2),
        "tokens": int(total_tokens),
        "batches": n_batches,
    }
    print(json.dumps(result, indent=2))
    return 0


def _fleet_child_argv(argv: List[str], port: int) -> List[str]:
    """Rebuild a replica's serve argv from the parent's: same flags,
    its own port, no --replicas (a replica must not recurse). The
    page-share wiring flags are stripped too — the fleet parent
    re-issues them pointing at its own router."""
    drop = ("--replicas", "--port", "--page-share", "--page-share-self")
    out: List[str] = []
    skip = False
    for a in argv:
        if skip:
            skip = False
            continue
        if a in drop:
            skip = True
            continue
        if any(a.startswith(d + "=") for d in drop):
            continue
        out.append(a)
    return out + ["--port", str(port)]


def _serve_fleet(args) -> int:
    """`lumina serve --replicas N`: spawn N replica serve processes on
    port+1..port+N, wait for their /healthz, then front them with the
    router on --port. Dev-fleet ergonomics — one command, one ^C."""
    import signal
    import subprocess

    from luminaai_tpu.config import Config
    from luminaai_tpu.serving.router import Router, wait_ready

    cfg = Config()
    n = args.replicas
    ports = [args.port + 1 + i for i in range(n)]
    urls = [f"http://{args.host}:{p}" for p in ports]
    procs = []
    router_url = f"http://{args.host}:{args.port}"
    try:
        for p in ports:
            child = _fleet_child_argv(sys.argv[1:], p)
            # Auto-wire cross-replica page sharing: every replica
            # reports its harvested prefix keys to the fleet router and
            # can pull pages from siblings (docs/serving.md
            # "Cross-replica prefix sharing").
            child += [
                "--page-share", router_url,
                "--page-share-self", f"http://{args.host}:{p}",
            ]
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "luminaai_tpu"] + child
            ))
        print(f"fleet: {n} replica(s) on ports {ports}; waiting for "
              "warmup...", file=sys.stderr)
        wait_ready(urls, timeout_s=600.0)
        router = Router(
            list(zip([f"r{i}" for i in range(n)], urls)),
            probe_interval_s=cfg.router_probe_interval_s,
            breaker_failures=cfg.router_breaker_failures,
            breaker_cooldown_s=cfg.router_breaker_cooldown_s,
            max_failovers=min(cfg.router_max_failovers, n - 1),
            hedge_budget=cfg.router_hedge_budget,
            hedge_max_tokens=cfg.router_hedge_max_tokens,
            flight_dir=getattr(args, "flight_dir", None),
        )
        router.probe_all()
        router.start_probing()
        router.serve_forever(args.host, args.port)
        return 0
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        for proc in procs:
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()


def cmd_serve(args) -> int:
    """HTTP chat/completion server (ref Dockerfile.backend: Flask on :5001
    with /health; here stdlib http.server — luminaai_tpu/serving).
    --replicas N spawns a local fleet fronted by the replica router."""
    if getattr(args, "replicas", 1) > 1:
        return _serve_fleet(args)
    from luminaai_tpu.serving import serve

    bootstrap = None
    if args.secure:
        if bool(args.user) != bool(args.password):
            print("--secure bootstrap needs BOTH --user and --password",
                  file=sys.stderr)
            return 2
        if args.user:
            bootstrap = (args.user, args.password)
        elif not Path("users.json").exists():
            print("--secure with no --user/--password and no existing "
                  "users.json: nobody could authenticate", file=sys.stderr)
            return 2
    stale_after = getattr(args, "healthz_stale_after", None)
    if stale_after is not None and stale_after <= 0:
        # Mirrors the --latency-buckets pattern: die with exit 2 NOW,
        # not a ValueError after minutes of checkpoint load.
        print(
            f"--healthz-stale-after needs a positive number of seconds, "
            f"got {stale_after!r}",
            file=sys.stderr,
        )
        return 2
    buckets = None
    raw_buckets = getattr(args, "latency_buckets", None)
    if raw_buckets:
        import math

        try:
            buckets = sorted(
                float(b) for b in raw_buckets.split(",") if b.strip()
            )
            # Mirror Histogram.__init__'s contract (unique finite
            # positive) HERE, so a bad flag dies with exit 2 now instead
            # of a ValueError traceback after minutes of checkpoint load.
            if (
                not buckets
                or any(not math.isfinite(b) or b <= 0 for b in buckets)
                or len(set(buckets)) != len(buckets)
            ):
                raise ValueError(raw_buckets)
        except ValueError:
            print(f"--latency-buckets needs unique positive "
                  f"comma-separated seconds, got {raw_buckets!r}",
                  file=sys.stderr)
            return 2
    serve(
        checkpoint=args.checkpoint,
        host=args.host,
        port=args.port,
        secure=args.secure,
        bootstrap_user=bootstrap,
        quantize=getattr(args, "quantize", None),
        adapter=getattr(args, "adapter", None),
        kv_cache_dtype=getattr(args, "kv_cache_dtype", None),
        num_slots=getattr(args, "num_slots", 8),
        page_size=getattr(args, "page_size", 128),
        admission_window_ms=getattr(args, "admission_window_ms", 0.0),
        continuous=(
            False if getattr(args, "no_continuous", False) else "auto"
        ),
        telemetry=not getattr(args, "no_telemetry", False),
        trace_jsonl=getattr(args, "trace_jsonl", None),
        trace_jax=getattr(args, "trace_jax", False),
        latency_buckets=buckets,
        request_timeout_s=getattr(args, "request_timeout_s", None),
        max_queue_depth=getattr(args, "max_queue_depth", 128),
        drain_grace_s=getattr(args, "drain_grace_s", 30.0),
        flight_dir=getattr(args, "flight_dir", None),
        prefill_chunk_tokens=getattr(args, "prefill_chunk_tokens", None),
        prefix_cache_pages=getattr(args, "prefix_cache_pages", None),
        prefix_cache_tenant_quota=getattr(
            args, "prefix_cache_tenant_quota", None
        ),
        tenant_rate_per_s=getattr(args, "tenant_rate_per_s", None),
        tenant_burst=getattr(args, "tenant_burst", None),
        watchdog=not getattr(args, "no_watchdog", False),
        watchdog_abort=getattr(args, "watchdog_abort", False),
        watchdog_k=getattr(args, "watchdog_k", None),
        watchdog_floor_s=getattr(args, "watchdog_floor", None),
        slo=not getattr(args, "no_slo", False),
        slo_config=getattr(args, "slo_config", None),
        healthz_stale_after_s=getattr(args, "healthz_stale_after", None),
        page_share=getattr(args, "page_share", None),
        page_share_self_url=getattr(args, "page_share_self", None),
        page_pull_timeout_s=getattr(args, "page_pull_timeout", None) or 2.0,
        page_share_max_inflight=(
            getattr(args, "page_share_max_inflight", None) or 2
        ),
    )
    return 0


def cmd_route(args) -> int:
    """Health-aware data-plane router fronting N ChatServer replicas
    (docs/serving.md "Replica router"): active /healthz + /slo probing,
    per-replica circuit breakers, prefix-hash-affine dispatch with
    bounded failover, Retry-After-aware shedding, optional hedged
    dispatch. Flag defaults come from Config's router_* knobs."""
    from luminaai_tpu.config import Config
    from luminaai_tpu.serving.router import run_router

    cfg = Config()

    def knob(name, default):
        v = getattr(args, name, None)
        return default if v is None else v

    urls = []
    for u in args.replicas:
        if "://" not in u:
            u = "http://" + u
        urls.append(u.rstrip("/"))
    if len(urls) != len(set(urls)):
        print("duplicate --replica urls", file=sys.stderr)
        return 2
    run_router(
        urls,
        host=args.host,
        port=args.port,
        probe_interval_s=knob(
            "probe_interval_s", cfg.router_probe_interval_s
        ),
        breaker_failures=knob(
            "breaker_failures", cfg.router_breaker_failures
        ),
        breaker_cooldown_s=knob(
            "breaker_cooldown_s", cfg.router_breaker_cooldown_s
        ),
        max_failovers=min(
            knob("max_failovers", cfg.router_max_failovers),
            len(urls) - 1,
        ),
        request_timeout_s=getattr(args, "request_timeout_s", None),
        hedge=getattr(args, "hedge", False),
        hedge_delay_s=getattr(args, "hedge_delay_s", None),
        hedge_budget=knob("hedge_budget", cfg.router_hedge_budget),
        hedge_max_tokens=knob(
            "hedge_max_tokens", cfg.router_hedge_max_tokens
        ),
        flight_dir=getattr(args, "flight_dir", None),
    )
    return 0


def cmd_finetune(args) -> int:
    """LoRA fine-tuning against a frozen base checkpoint (docs/adapters.md;
    ref adapter programme). Optimizer state exists only for the adapter."""
    import jax
    import jax.numpy as jnp
    import optax

    from luminaai_tpu.data.dataset import (
        ConversationDataset,
        conversation_batches,
    )
    from luminaai_tpu.data.tokenizer import ConversationTokenizer
    from luminaai_tpu.inference.chat import load_model_for_inference
    from luminaai_tpu.training.adapters import (
        LoRASpec,
        init_lora_params,
        lora_param_count,
        make_lora_train_step,
        merge_lora,
        save_lora,
    )

    # keep_master_dtype: we train against (and may re-export) these
    # weights; the serving bf16 downcast would permanently round away the
    # fp32 masters and swallow small LoRA deltas at merge time.
    model, params, cfg = load_model_for_inference(
        args.checkpoint, keep_master_dtype=True
    )
    if args.batch_size:
        cfg.batch_size = args.batch_size
    patterns = [r"attention/", r"ffn/"]
    if args.adapt_experts:
        patterns.append(r"moe/")
    spec = LoRASpec(
        rank=args.rank, alpha=args.alpha, target_patterns=tuple(patterns)
    )
    rng = jax.random.key(cfg.seed)
    lora = init_lora_params(params, spec, rng)
    base_n = cfg.estimate_parameters()
    print(
        f"adapter: rank {spec.rank}, {lora_param_count(lora) / 1e6:.2f}M "
        f"params ({lora_param_count(lora) / max(base_n, 1):.3%} of base, "
        f"{len(lora)} kernels)"
    )

    tx = optax.adam(args.lr)
    step = make_lora_train_step(cfg, model, params, spec, tx)
    carry = (lora, tx.init(lora))

    tokenizer = ConversationTokenizer(
        assistant_loss_weight=cfg.assistant_loss_weight
    )
    ds = ConversationDataset(args.data, tokenizer, cfg, split="train")
    done = 0
    last = float("nan")
    while done < args.steps:
        made_progress = False
        for batch in conversation_batches(ds, cfg.batch_size, seed=done):
            if done >= args.steps:
                break
            made_progress = True
            carry, metrics = step(
                carry,
                {k: jnp.asarray(v) for k, v in batch.items()},
                jax.random.fold_in(rng, done),
            )
            done += 1
            if done % max(1, args.steps // 10) == 0 or done == 1:
                last = float(metrics["loss"])
                print(f"step {done}/{args.steps} loss {last:.4f}")
        if not made_progress:
            print(
                f"no batches: dataset has fewer than batch_size="
                f"{cfg.batch_size} usable samples (pass --batch-size)",
                file=sys.stderr,
            )
            return 1

    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)
    save_lora(str(out / "adapter"), carry[0], spec)
    print(f"adapter saved: {out / 'adapter'}.npz (final loss {last:.4f})")

    if args.merge_out:
        import orbax.checkpoint as ocp

        merged = merge_lora(params, carry[0], spec)
        mout = Path(args.merge_out).absolute()
        mout.mkdir(parents=True, exist_ok=True)
        with ocp.CheckpointManager(mout) as mngr:
            mngr.save(
                0,
                args=ocp.args.Composite(
                    state=ocp.args.StandardSave({"params": merged}),
                    metadata=ocp.args.JsonSave(
                        {"step": 0, "config": cfg.to_dict(),
                         "adapter": str(out / "adapter")}
                    ),
                ),
            )
            mngr.wait_until_finished()
        print(f"merged checkpoint: {mout}")
    return 0


def cmd_convert(args) -> int:
    """Convert a checkpoint between per-layer and scanned param layouts
    (the same weights, bit-identical outputs — models/transformer.py
    stack/unstack_params_for_scan), so scan_layers can change between
    runs without retraining."""
    import dataclasses as dc

    import jax
    import orbax.checkpoint as ocp

    from luminaai_tpu.config import Config
    from luminaai_tpu.inference.chat import load_model_for_inference
    from luminaai_tpu.models.transformer import (
        stack_params_for_scan,
        unstack_params_from_scan,
    )

    try:
        _, params, cfg = load_model_for_inference(args.checkpoint)
    except ValueError as e:
        # e.g. an int8 serving export fed back into convert: quantizing
        # quantized codes would write a silently-corrupt checkpoint.
        print(str(e), file=sys.stderr)
        return 1
    is_scanned = any(k.startswith("scan_") for k in params)
    if args.to == "int8":
        # Quantized serving export (ref trainer.py:681,712 GPTQ/quanto
        # model saves): weights stored as int8 codes + scales in the
        # serving compute layout — half the disk/load bytes; chat/serve
        # load it directly with no re-quantization pass.
        from luminaai_tpu.training.quantization import (
            export_quantized_tree,
            quantize_for_serving,
        )

        if is_scanned:
            print("convert --to plain first (int8 export needs the "
                  "per-layer layout)", file=sys.stderr)
            return 1
        qtree, info = quantize_for_serving(params)
        plain, manifest = export_quantized_tree(qtree)
        new_cfg = dc.replace(cfg, quantization_method=None)
        out = Path(args.out).absolute()
        out.mkdir(parents=True, exist_ok=True)
        with ocp.CheckpointManager(out) as mngr:
            mngr.save(
                0,
                args=ocp.args.Composite(
                    state=ocp.args.StandardSave({"params": plain}),
                    metadata=ocp.args.JsonSave(
                        {"step": 0, "config": new_cfg.to_dict(),
                         "converted_from": str(args.checkpoint),
                         "quantization": {"manifest": manifest,
                                          "info": info}}
                    ),
                ),
            )
            mngr.wait_until_finished()
        print(
            f"int8 serving export: {info['quantized_leaves']}/"
            f"{info['total_leaves']} tensors quantized, "
            f"{info['compression']:.2f}x smaller -> {out}"
        )
        return 0
    if args.to == "scan" and is_scanned:
        print("checkpoint is already in scanned layout", file=sys.stderr)
        return 1
    if args.to == "plain" and not is_scanned:
        print("checkpoint is already in per-layer layout", file=sys.stderr)
        return 1

    if args.to == "scan":
        new_cfg = dc.replace(cfg, scan_layers=True)
        new_params = stack_params_for_scan(new_cfg, params)
    else:
        new_params = unstack_params_from_scan(cfg, params)
        new_cfg = dc.replace(cfg, scan_layers=False)

    out = Path(args.out).absolute()
    out.mkdir(parents=True, exist_ok=True)
    with ocp.CheckpointManager(out) as mngr:
        mngr.save(
            0,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave({"params": new_params}),
                metadata=ocp.args.JsonSave(
                    {"step": 0, "config": new_cfg.to_dict(),
                     "converted_from": str(args.checkpoint)}
                ),
            ),
        )
        mngr.wait_until_finished()
    n = sum(x.size for x in jax.tree.leaves(new_params))
    print(f"converted to {args.to} layout: {n / 1e6:.1f}M params -> {out}")
    return 0


def cmd_report(args) -> int:
    """HTML reports (ref utils/reporting.py)."""
    if args.kind == "training":
        from luminaai_tpu.utils.reporting import create_training_report

        if not args.dir:
            print("report training requires --dir EXPERIMENT_DIR",
                  file=sys.stderr)
            return 2
        out = create_training_report(args.dir, args.out)
        if out is None:
            print(
                f"no training_summary.json under {args.dir}", file=sys.stderr
            )
            return 1
        print(f"training report: {out}")
    else:
        from luminaai_tpu.data.tokenizer import ConversationTokenizer
        from luminaai_tpu.utils.reporting import create_data_summary_report

        out = create_data_summary_report(
            args.inputs, ConversationTokenizer(),
            output_path=args.out or "data_summary_report.html",
        )
        print(f"data report: {out}")
    return 0


def cmd_diagnose(args) -> int:
    from luminaai_tpu.utils.environment import (
        check_config_fits,
        connectivity_probe,
        format_diagnostics,
        recommend_preset,
        tpu_runtime_diagnostics,
    )

    # Runtime probes FIRST (ref cuda_debug_script.py's role): reachability
    # via a subprocess matmul with a hard timeout — initializing a dead
    # tunnel in-process would hang this very tool, so jax is only touched
    # here after the probe answers ok.
    rt = tpu_runtime_diagnostics(
        probe_timeout=getattr(args, "probe_timeout", 90)
    )
    print(format_diagnostics(
        include_accelerator=rt["backend"]["status"] == "ok"
    ))
    print("[runtime]")
    for section, vals in rt.items():
        print(f"  {section}:")
        for k, v in vals.items():
            print(f"    {k}: {v}")
    if rt["backend"]["status"] != "ok":
        return 1
    # ICI/DCN connectivity: per-host device visibility + a timed
    # all-reduce per mesh axis, exported as diagnose_* registry gauges
    # (VERDICT "What's missing" #3; the reference's scripts/net.sh role).
    # Only after the backend probe answered ok — see above.
    try:
        conn = connectivity_probe()
        print("[connectivity]")
        for section, vals in conn.items():
            print(f"  {section}:")
            for k, v in vals.items():
                print(f"    {k}: {v}")
        if not conn["visibility"]["visibility_ok"]:
            print(
                "    WARNING: global devices != process_count * local "
                "devices — a host is missing part of the slice"
            )
    except Exception as e:
        print(f"connectivity probe unavailable: {e}")
    # Expert-dispatch rung: a REAL timed two-stage (ici-then-dcn)
    # all-to-all over the probe mesh — the hierarchical exchange the
    # a2a MoE dispatch runs (parallel/expert_dispatch.py), priced per
    # stage for the MULTICHIP_r* harness. Single-host fleets simulate
    # the dcn tier so the two-stage path is still exercised; exported
    # as diagnose_expert_a2a_seconds{stage} gauges.
    try:
        from luminaai_tpu.parallel.expert_dispatch import expert_a2a_probe

        a2a = expert_a2a_probe()
        print("[expert-a2a]")
        print(
            f"  mesh: ep={a2a['ep']} (dcn={a2a['dcn']} x ici={a2a['ici']}"
            f"{', simulated dcn' if a2a.get('simulated_dcn') else ''})"
        )
        for stage, rec in a2a["stages"].items():
            print(f"  {stage}:")
            for k, v in rec.items():
                print(f"    {k}: {v}")
    except Exception as e:
        print(f"expert-a2a probe unavailable: {e}")
    # Gradient-reduction rung: a REAL timed two-stage (reduce-scatter →
    # rail psum → all-gather) bucketed gradient sync over the same
    # dcn×ici probe factorization (parallel/grad_reduce.py) — what a
    # grad_reduce='hierarchical' optimizer step's sync costs on this
    # fleet, exported as diagnose_grad_reduce_seconds{stage} gauges.
    try:
        from luminaai_tpu.parallel.grad_reduce import grad_reduce_probe

        gr = grad_reduce_probe()
        print("[grad-reduce]")
        print(
            f"  mesh: world={gr['world']} (dcn={gr['dcn']} x "
            f"ici={gr['ici']}"
            f"{', simulated dcn' if gr.get('simulated_dcn') else ''})"
        )
        for stage, rec in gr["stages"].items():
            print(f"  {stage}:")
            for k, v in rec.items():
                print(f"    {k}: {v}")
    except Exception as e:
        print(f"grad-reduce probe unavailable: {e}")
    try:
        print(f"recommended preset for this fleet: {recommend_preset()}")
        if args.preset:
            from luminaai_tpu.config import ConfigPresets

            fit = check_config_fits(ConfigPresets.get(args.preset))
            print(f"{args.preset}: {json.dumps(fit, indent=2)}")
    except Exception as e:
        print(f"recommendation unavailable: {e}")
    return 0


def cmd_analyze(args) -> int:
    """JAX-aware static analysis gate (docs/static_analysis.md).

    Source layer: analysis/astlint.py rules LX001..LX008 with inline
    `# lumina: disable=LXnnn -- reason` waivers. Abstract layer
    (skippable with --no-audit): the recompile-surface enumerator,
    sharding-coverage auditor and host-transfer detector from
    analysis/jaxpr_audit.py. Exit 1 on any unwaived, unbaselined
    finding or failed audit — this is the CI contract."""
    import luminaai_tpu
    from luminaai_tpu.analysis import astlint

    pkg_dir = os.path.dirname(os.path.abspath(luminaai_tpu.__file__))
    repo_root = os.path.dirname(pkg_dir)
    paths = args.paths or [pkg_dir]
    findings = astlint.lint_paths(paths, rel_to=repo_root)

    # Baseline: accepted legacy findings, keyed rule:path with a count —
    # line numbers shift too easily to key on. A baselined (rule, path)
    # pair only absorbs as many findings as were accepted.
    accepted: Dict[str, int] = {}
    if args.baseline and os.path.exists(args.baseline):
        with open(args.baseline) as fh:
            accepted = dict(json.load(fh).get("accepted", {}))
    budget = dict(accepted)
    unwaived = []
    baselined = 0
    for f in findings:
        if f.waived:
            continue
        key = f"{f.rule}:{f.path}"
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            f.baselined = True
            baselined += 1
            continue
        unwaived.append(f)

    if args.write_baseline:
        counts: Dict[str, int] = {}
        for f in findings:
            if not f.waived:
                key = f"{f.rule}:{f.path}"
                counts[key] = counts.get(key, 0) + 1
        with open(args.write_baseline, "w") as fh:
            json.dump({"accepted": counts}, fh, indent=2, sort_keys=True)
            fh.write("\n")
        print(
            f"baseline written: {args.write_baseline} "
            f"({sum(counts.values())} accepted finding(s))",
            file=sys.stderr,
        )

    verdicts, audit_report = [], {}
    if not args.no_audit:
        from luminaai_tpu.analysis.jaxpr_audit import run_audits

        verdicts, audit_report = run_audits()

    failed_audits = [v.name for v in verdicts if not v.ok]
    exit_code = 1 if (unwaived or failed_audits) else 0

    if args.json:
        doc = astlint.findings_to_json(findings)
        doc["summary"]["baselined"] = baselined
        doc["summary"]["unwaived"] = len(unwaived)
        doc["audits"] = audit_report
        doc["audit_verdicts"] = [
            {"name": v.name, "ok": v.ok, "detail": v.detail}
            for v in verdicts
        ]
        doc["exit_code"] = exit_code
        print(json.dumps(_jsonable(doc), indent=2))
    else:
        print(astlint.format_findings(findings))
        if baselined:
            print(f"baseline: {baselined} finding(s) accepted as legacy")
        for v in verdicts:
            status = "ok" if v.ok else "FAIL"
            print(f"audit {v.name}: {status}")
        surface = audit_report.get("recompile_surface", {})
        for prog, rec in surface.get("programs", {}).items():
            print(
                f"recompile surface [{prog}]: "
                f"{rec['distinct_signatures']} distinct executable(s) "
                f"across {len(rec['variants'])} variant(s)"
            )
        if exit_code:
            print(
                f"analyze: FAIL ({len(unwaived)} unwaived finding(s), "
                f"{len(failed_audits)} failed audit(s))",
                file=sys.stderr,
            )
        else:
            print("analyze: clean")
    return exit_code


def cmd_events(args) -> int:
    """Query the wide-event flight recorder (docs/observability.md).

    Sources, in order of preference: explicit dump files, directories
    (the newest flightrec-*.jsonl inside each — checkpoint dirs are the
    usual argument), or — with no paths — this process's live ring
    buffer (mostly useful in-process / in tests). Filters: --type,
    --grep (regex over the serialized record), --since (epoch ts or
    s/m/h/d duration ago), --tail N. --stats summarizes the filtered
    set (count/rate per type, first/last ts) instead of listing.
    --json prints one JSON record per line for piping into jq."""
    from luminaai_tpu.monitoring.events import (
        events_stats,
        filter_events,
        format_event,
        get_recorder,
        latest_dump,
        parse_since,
        read_events,
    )

    if args.grep:
        import re

        try:
            re.compile(args.grep)
        except re.error as e:
            print(f"bad --grep regex {args.grep!r}: {e}", file=sys.stderr)
            return 2
    since = None
    if getattr(args, "since", None):
        try:
            since = parse_since(args.since)
        except ValueError as e:
            print(f"bad --since value {args.since!r}: {e}", file=sys.stderr)
            return 2

    events: List[Dict[str, Any]] = []
    sources: List[str] = []
    for p in args.paths or []:
        path = p
        if os.path.isdir(p):
            path = latest_dump(p)
            if path is None:
                print(f"no flightrec-*.jsonl dumps under {p}",
                      file=sys.stderr)
                return 2
        if not os.path.exists(path):
            print(f"no such dump: {path}", file=sys.stderr)
            return 2
        events.extend(read_events(path))
        sources.append(path)
    if not args.paths:
        events = get_recorder().snapshot()
        sources.append("<live buffer>")

    total = len(events)
    events = filter_events(
        events, type=args.etype, grep=args.grep,
        request=getattr(args, "request_id", None),
        since=since,
        tail=args.tail if args.tail else None,
    )
    if getattr(args, "stats", False) or getattr(args, "stats_by", None):
        # --by implies --stats (a grouping axis only means something for
        # the summary form).
        stats = events_stats(events, by=getattr(args, "stats_by", None))
        if args.json:
            print(json.dumps(stats, default=str))
        elif stats.get("by"):
            _print_grouped_stats(stats)
        else:
            import time as _time

            def _fmt_ts(ts):
                if not isinstance(ts, (int, float)):
                    return "?"
                return _time.strftime(
                    "%Y-%m-%d %H:%M:%S", _time.localtime(ts)
                )

            print(
                f"{stats['total']} event(s) spanning "
                f"{stats['span_s']}s ({_fmt_ts(stats['first_ts'])} .. "
                f"{_fmt_ts(stats['last_ts'])})"
            )
            header = f"{'type':<24}{'count':>8}{'rate/s':>10}  first .. last"
            print(header)
            print("-" * len(header))
            for t, rec in stats["by_type"].items():
                rate = (
                    f"{rec['rate_per_s']:.3f}"
                    if rec["rate_per_s"] is not None
                    else "-"
                )
                print(
                    f"{t:<24}{rec['count']:>8}{rate:>10}  "
                    f"{_fmt_ts(rec['first_ts'])} .. "
                    f"{_fmt_ts(rec['last_ts'])}"
                )
    elif args.json:
        for ev in events:
            print(json.dumps(ev, default=str))
    else:
        for ev in events:
            print(format_event(ev))
    print(
        f"{len(events)} event(s) shown of {total} from "
        f"{', '.join(sources)}",
        file=sys.stderr,
    )
    return 0


def _print_grouped_stats(stats: Dict[str, Any]) -> None:
    """`lumina events --stats --by tenant|request` table: biggest
    burners first, each with its rate and top event types."""
    import time as _time

    def _fmt_ts(ts):
        if not isinstance(ts, (int, float)):
            return "?"
        return _time.strftime("%H:%M:%S", _time.localtime(ts))

    print(
        f"{stats['total']} event(s) spanning {stats['span_s']}s, "
        f"grouped by {stats['by']}"
    )
    header = (
        f"{stats['by']:<26}{'count':>8}{'rate/s':>10}  "
        f"first .. last  top types"
    )
    print(header)
    print("-" * len(header))
    for key, rec in stats["groups"].items():
        rate = (
            f"{rec['rate_per_s']:.3f}"
            if rec["rate_per_s"] is not None
            else "-"
        )
        top = ", ".join(
            f"{t}={n}"
            for t, n in sorted(
                rec["by_type"].items(), key=lambda kv: (-kv[1], kv[0])
            )[:3]
        )
        print(
            f"{key:<26}{rec['count']:>8}{rate:>10}  "
            f"{_fmt_ts(rec['first_ts'])} .. {_fmt_ts(rec['last_ts'])}  "
            f"{top}"
        )


def _top_sources(args):
    """Resolve `lumina top`'s data source into (fetch_fn, source_label).

    fetch_fn() -> (history_dict, slo_dict_or_None, fleet_dict_or_None).
    Exit-2 errors raise SystemExit here so the caller stays flat."""
    import urllib.error
    import urllib.request

    from luminaai_tpu.monitoring.timeseries import (
        get_history,
        latest_history_dump,
        load_history,
    )

    url = getattr(args, "url", None)
    path = getattr(args, "source", None)
    if url:
        base = url.rstrip("/")

        def fetch_url():
            # --url points at either a replica (history + slo) or a
            # router (fleet table). Probe both shapes; a missing route
            # 404s, which just means the other kind of process.
            def _get(route):
                try:
                    with urllib.request.urlopen(
                        f"{base}{route}", timeout=10
                    ) as r:
                        return json.loads(r.read())
                except urllib.error.HTTPError:
                    return None

            history = _get("/metrics/history")
            slo = _get("/slo")
            fleet = _get("/fleet")
            if history is None and fleet is None:
                print(
                    f"{base} answers neither /metrics/history (replica) "
                    "nor /fleet (router)", file=sys.stderr,
                )
                raise SystemExit(2)
            return history or {"series": {}}, slo, fleet

        return fetch_url, base
    if path:
        resolved = path
        if os.path.isdir(path):
            resolved = latest_history_dump(path)
            if resolved is None:
                print(f"no tshist-*.json dumps under {path}",
                      file=sys.stderr)
                raise SystemExit(2)
        if not os.path.exists(resolved):
            print(f"no such history dump: {resolved}", file=sys.stderr)
            raise SystemExit(2)

        def fetch_file(resolved=resolved):
            try:
                doc = load_history(resolved)
            except (ValueError, json.JSONDecodeError) as e:
                print(f"bad history dump {resolved}: {e}", file=sys.stderr)
                raise SystemExit(2)
            # Dumps written by a live SLO engine embed the verdict table
            # so the post-mortem view matches the live one.
            return doc, doc.get("slo"), None

        return fetch_file, resolved

    def fetch_live():
        ring = get_history()
        if ring is None:
            print(
                "no live history ring in this process (start a trainer/"
                "server with SLO on, or pass a dump path / --url)",
                file=sys.stderr,
            )
            raise SystemExit(2)
        # Read-only attach: sampling here would split counter deltas
        # into refresh-sized intervals AND fire any attached SLO
        # engine's evaluation — viewing the dashboard must never skew
        # the data or advance the alert state machine. Between-tick
        # staleness (≤ one sample interval) is the honest trade. The
        # engine advertised on the ring supplies the verdict table from
        # its CACHED last evaluation (no state advance).
        engine = getattr(ring, "slo", None)
        return ring.snapshot(), (
            engine.verdicts() if engine is not None else None
        ), None

    return fetch_live, "<live ring>"


def cmd_top(args) -> int:
    """Live operator dashboard over the time-series ring
    (docs/observability.md "SLOs & burn rate"): sparklines for
    throughput/latency/occupancy, per-tenant top-K, and the SLO
    burn-rate verdict table. Sources: --url against a serving process
    (GET /metrics/history + /slo), a tshist-*.json dump (or a directory
    holding them), or — with neither — this process's live ring.
    --once renders a single frame; --json emits the machine form."""
    from luminaai_tpu.monitoring.top import render_top, top_payload

    try:
        fetch, source = _top_sources(args)
    except SystemExit as e:
        return int(e.code or 2)

    def frame():
        try:
            history, slo, fleet = fetch()
        except SystemExit as e:  # bad dump discovered on read
            raise
        except Exception as e:
            print(f"fetch failed: {e}", file=sys.stderr)
            raise SystemExit(2)
        if args.json:
            return json.dumps(
                top_payload(
                    history, slo,
                    window_s=args.window, top_k=args.top_k,
                    fleet=fleet,
                ),
                default=str,
            )
        return render_top(
            history, slo, source=source,
            window_s=args.window, top_k=args.top_k,
            fleet=fleet,
        )

    try:
        if args.once or args.json:
            print(frame())
            return 0
        import time as _time

        while True:  # refresh loop; ^C exits
            out = frame()
            # ANSI clear + home keeps the frame in place like top(1).
            sys.stdout.write("\x1b[2J\x1b[H" + out)
            sys.stdout.flush()
            _time.sleep(max(0.2, float(args.interval)))
    except KeyboardInterrupt:
        return 0
    except SystemExit as e:
        return int(e.code or 2)


def cmd_verify_checkpoint(args) -> int:
    """Walk a checkpoint directory's integrity manifests
    (docs/resilience.md "Durable I/O"): per-step ok / corrupt /
    unmanifested. Exit 0 when every verified step is intact
    (unmanifested legacy steps are reported, not failed), 1 on any
    corruption, 2 when the directory/step does not exist — the same
    exit-code contract shape as `lumina events`."""
    from luminaai_tpu.training.checkpoint import verify_checkpoint_dir

    try:
        report = verify_checkpoint_dir(
            args.dir, step=args.step, mode=args.mode
        )
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(report, default=str))
    else:
        header = f"{'step':>8}  {'status':<14}{'files':>7}{'hashed':>8}  detail"
        print(f"checkpoint manifests under {report['root']} "
              f"(mode={report['mode']})")
        print(header)
        print("-" * len(header))
        for s, rep in sorted(report["steps"].items()):
            detail = ""
            if rep["mismatches"]:
                m = rep["mismatches"][0]
                detail = f"{m['file']}: {m['reason']}"
                if len(rep["mismatches"]) > 1:
                    detail += f" (+{len(rep['mismatches']) - 1} more)"
            print(
                f"{s:>8}  {rep['status']:<14}{rep['files']:>7}"
                f"{rep['hashed']:>8}  {detail}"
            )
        print(
            f"{len(report['ok'])} ok, {len(report['corrupt'])} corrupt, "
            f"{len(report['unmanifested'])} unmanifested"
        )
    if not report["steps"]:
        print(f"no checkpoint steps under {args.dir}", file=sys.stderr)
        return 2
    return 1 if report["corrupt"] else 0


def cmd_presets(args) -> int:
    from luminaai_tpu.config import ConfigPresets

    info = ConfigPresets.get_preset_info()
    if args.json:
        print(json.dumps(info, indent=2))
        return 0
    header = (
        f"{'preset':<16}{'hidden':>8}{'layers':>8}{'params':>12}"
        f"{'active':>12}{'experts':>8}{'seq':>8}"
    )
    print(header)
    print("-" * len(header))
    for name, d in info.items():
        print(
            f"{name:<16}{d['hidden_size']:>8}{d['num_layers']:>8}"
            f"{d['total_params'] / 1e6:>10.0f}M{d['active_params'] / 1e6:>10.0f}M"
            f"{d['num_experts']:>8}{d['seq_length']:>8}"
        )
    return 0


# ---------------------------------------------------------------------------
# plumbing
# ---------------------------------------------------------------------------
def _jsonable(obj: Any) -> Any:
    if isinstance(obj, dict):
        return {k: _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, (np.floating, np.integer)):
        return obj.item()
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, float) and not np.isfinite(obj):
        return str(obj)
    return obj


def _install_signal_handlers(trainer) -> None:
    """SIGINT/SIGTERM → graceful preemption (ref Main.py:1126
    setup_signal_handlers, rebuilt for correctness): the FIRST signal only
    arms `trainer.request_stop()` — the train loop finishes the step in
    flight, runs a BLOCKING emergency save at the boundary, and cmd_train
    exits RESUMABLE_EXIT. Saving from inside the handler (the old
    behavior) raced the dispatched train step and could checkpoint a
    half-updated state. A SECOND signal escalates: save whatever state
    exists right now and exit immediately."""
    seen = {"n": 0}

    def handler(sig, frame):  # pragma: no cover - signal-driven
        seen["n"] += 1
        if seen["n"] == 1:
            print(
                f"\nsignal {sig}: stopping at the next step boundary "
                "(emergency checkpoint + exact data cursor); signal again "
                "to force an immediate save and exit"
            )
            trainer.request_stop(f"signal {sig}")
            return
        print(f"\nsignal {sig} (again): immediate emergency save...")
        try:
            trainer.checkpoints.emergency_save(
                trainer.state, trainer.global_step, f"signal {sig} forced",
                data_state=trainer._data_state(),
            )
            # Forensics for the forced exit: the last N step/alert
            # events ride next to the save (lumina events replays them).
            trainer._dump_flight_record(f"signal_{sig}_forced")
            print("state saved; exiting")
        except Exception as e:
            print(f"emergency save failed: {e}")
        sys.exit(RESUMABLE_EXIT)

    try:
        signal.signal(signal.SIGINT, handler)
        signal.signal(signal.SIGTERM, handler)
    except ValueError:  # pragma: no cover - non-main thread (tests)
        pass


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="luminaai_tpu",
        description="TPU-native adaptive training framework",
    )
    sub = p.add_subparsers(dest="command", required=True)

    def add_config_flags(sp):
        sp.add_argument("--preset", default="debug")
        sp.add_argument("--config", help="yaml/json config file")
        sp.add_argument("--lr", type=float)
        sp.add_argument("--batch-size", dest="batch_size", type=int)
        sp.add_argument("--seq-length", dest="seq_length", type=int)
        sp.add_argument("--steps", type=int, help="max optimizer steps")
        sp.add_argument("--epochs", type=int)
        sp.add_argument("--grad-accum", dest="grad_accum", type=int)
        sp.add_argument("--precision", choices=["fp32", "bf16", "mixed_bf16", "auto"])
        sp.add_argument("--output-dir", dest="output_dir")
        sp.add_argument("--experiment")
        sp.add_argument("--no-moe", action="store_true")
        sp.add_argument("--no-flash", action="store_true")
        sp.add_argument(
            "--moe-dispatch", dest="moe_dispatch",
            choices=["sort", "gather", "einsum", "gmm"],
            help="expert dispatch engine (docs/sparse_architectures.md; "
                 "gmm = ragged grouped matmul, single-chip)",
        )
        sp.add_argument(
            "--attention-window", dest="attention_window", type=int,
            help="sliding-window attention: attend to the last N "
                 "positions only (O(S*W) long-context attention)",
        )
        sp.add_argument(
            "--auto-hardware", action="store_true",
            help="optimize parallelism for detected devices",
        )
        prof = sp.add_argument_group(
            "performance attribution (docs/observability.md)"
        )
        prof.add_argument(
            "--profile-steps", dest="profile_steps", type=int,
            help="capture a jax.profiler trace for N steps and export the "
                 "per-subsystem step breakdown (gauges + attribution.jsonl)",
        )
        prof.add_argument(
            "--profile-start", dest="profile_start", type=int,
            help="first profiled step (default 3: skip the compile step)",
        )
        prof.add_argument(
            "--profile-dir", dest="profile_dir",
            help="trace output dir (default OUTPUT_DIR/profile)",
        )
        prof.add_argument(
            "--cost-analysis", dest="cost_analysis", action="store_true",
            help="export XLA compiled-cost gauges (flops/bytes/HBM) and "
                 "the analytic-vs-compiled MFU cross-check at first compile",
        )
        wd = sp.add_argument_group(
            "hang watchdog (docs/observability.md 'Goodput & sentinels')"
        )
        wd.add_argument(
            "--watchdog", dest="watchdog",
            action=argparse.BooleanOptionalAction, default=None,
            help="heartbeat hang detection over the train loop "
                 "(default: on; fires hang_suspected + stack/ring dumps "
                 "when a step window exceeds k x rolling median)",
        )
        wd.add_argument(
            "--watchdog-abort", dest="watchdog_abort", action="store_true",
            help="exit 75 (resumable) after a confirmed hang is dumped, "
                 "so the orchestrator restarts instead of burning the "
                 "reservation",
        )
        wd.add_argument(
            "--watchdog-k", dest="watchdog_k", type=float,
            help="robust threshold multiplier over the rolling median "
                 "step window (default 10)",
        )
        wd.add_argument(
            "--watchdog-floor", dest="watchdog_floor", type=float,
            help="minimum stall seconds before the watchdog can fire "
                 "(default 30)",
        )
        so = sp.add_argument_group(
            "SLO engine (docs/observability.md 'SLOs & burn rate')"
        )
        so.add_argument(
            "--slo", dest="slo",
            action=argparse.BooleanOptionalAction, default=None,
            help="windowed history ring + burn-rate alerts over the "
                 "default train objectives (default: on)",
        )
        so.add_argument(
            "--slo-config", dest="slo_config",
            help="JSON file REPLACING the default objectives "
                 "(docs/observability.md lists the schema)",
        )
        par = sp.add_argument_group("parallelism (docs/parallelism.md)")
        par.add_argument("--dp", type=int, help="data axis (-1 = auto)")
        par.add_argument(
            "--pp", type=int,
            help="pipeline stages (1F1B schedule; pipeline_schedule=gpipe "
                 "via --config for A/B)",
        )
        par.add_argument("--fsdp", type=int, help="ZeRO-3-style shard ways")
        par.add_argument("--tp", type=int, help="tensor-parallel ways")
        par.add_argument("--ep", type=int, help="expert-parallel ways")
        par.add_argument("--sp", type=int,
                         help="sequence/ring-attention ways")

    t = sub.add_parser("train", help="train a model")
    add_config_flags(t)
    t.add_argument("--data", help="jsonl conversations (or text with --packed)")
    t.add_argument("--tokenizer",
                   help="tokenizer backend: byte | bpe:PATH | tiktoken:NAME "
                        "| hf:NAME")
    t.add_argument("--eval-data", dest="eval_data")
    t.add_argument("--packed", action="store_true",
                   help="treat --data as base-training text jsonl")
    t.add_argument("--synthetic", action="store_true",
                   help="train on synthetic pattern data (smoke test)")
    t.add_argument("--adaptive", action=argparse.BooleanOptionalAction,
                   default=True, help="run under the adaptive orchestrator")
    t.add_argument("--auto-epochs", action="store_true",
                   help="chinchilla-style step budget from dataset size")
    t.add_argument("--resume", action="store_true")
    t.add_argument("--quiet", action="store_true")
    t.add_argument("--oom-protect", dest="oom_protect",
                   action=argparse.BooleanOptionalAction, default=True,
                   help="backoff ladder on device OOM (microbatch split, "
                        "then batch halving)")
    t.set_defaults(fn=cmd_train)

    r = sub.add_parser("resume", help="resume training from output dir")
    add_config_flags(r)
    r.add_argument("--data")
    r.add_argument("--tokenizer")
    r.add_argument("--eval-data", dest="eval_data")
    r.add_argument("--packed", action="store_true")
    r.add_argument("--synthetic", action="store_true")
    r.add_argument("--adaptive", action=argparse.BooleanOptionalAction,
                   default=True)
    r.add_argument("--auto-epochs", action="store_true")
    r.add_argument("--quiet", action="store_true")
    r.add_argument("--oom-protect", dest="oom_protect",
                   action=argparse.BooleanOptionalAction, default=True)
    r.set_defaults(fn=cmd_train, resume=True)
    t.set_defaults(resume=False)

    c = sub.add_parser("chat", help="interactive chat with a checkpoint")
    c.add_argument("--checkpoint", help="checkpoint dir (auto-discovers latest)")
    c.add_argument("--temperature", type=float, default=0.8)
    c.add_argument("--top-p", dest="top_p", type=float, default=0.9)
    c.add_argument("--max-new-tokens", dest="max_new_tokens", type=int,
                   default=256)
    c.add_argument("--prompt", help="one-shot prompt (non-interactive)")
    c.add_argument("--verbose", action="store_true")
    c.add_argument("--secure", action="store_true",
                   help="require auth; rate-limit and validate inputs")
    c.add_argument("--user")
    c.add_argument("--password")
    c.add_argument("--quantize", choices=["int8", "int4"],
                   help="weight-only quantization for serving")
    c.add_argument("--kv-cache-dtype", choices=["bf16", "int8"],
                   help="decode KV cache storage (int8 halves cache HBM)")
    c.add_argument("--adapter",
                   help="LoRA adapter (.npz from finetune) merged at load")
    c.set_defaults(fn=cmd_chat)

    ft = sub.add_parser(
        "finetune", help="LoRA fine-tune against a frozen base checkpoint"
    )
    ft.add_argument("--checkpoint", required=True, help="base checkpoint dir")
    ft.add_argument("--data", required=True, help="jsonl conversations")
    ft.add_argument("--out", required=True, help="adapter output dir")
    ft.add_argument("--rank", type=int, default=8)
    ft.add_argument("--alpha", type=float, default=16.0)
    ft.add_argument("--lr", type=float, default=1e-4)
    ft.add_argument("--steps", type=int, default=100)
    ft.add_argument("--batch-size", dest="batch_size", type=int)
    ft.add_argument("--adapt-experts", action="store_true",
                    help="also adapt MoE expert kernels (per-expert factors)")
    ft.add_argument("--merge-out", dest="merge_out",
                    help="also export base+adapter as a merged checkpoint")
    ft.set_defaults(fn=cmd_finetune)

    sv = sub.add_parser("serve", help="HTTP chat/completion server")
    sv.add_argument("--checkpoint", help="checkpoint dir (auto-discovers)")
    sv.add_argument("--host", default="127.0.0.1")
    sv.add_argument("--port", type=int, default=5001)
    sv.add_argument("--secure", action="store_true",
                    help="token auth + rate limit + input validation")
    sv.add_argument("--user", help="bootstrap user (secure mode)")
    sv.add_argument("--password", help="bootstrap password (secure mode)")
    sv.add_argument("--quantize", choices=["int8", "int4"])
    sv.add_argument("--kv-cache-dtype", choices=["bf16", "int8"],
                    help="decode KV cache storage (int8 halves cache HBM)")
    sv.add_argument("--adapter", help="LoRA adapter merged at load")
    sv.add_argument("--num-slots", dest="num_slots", type=int, default=8,
                    help="continuous-batching KV pool slots "
                         "(concurrent decode lanes)")
    sv.add_argument("--page-size", dest="page_size", type=int, default=128,
                    help="KV pool page granularity in tokens")
    sv.add_argument("--prefill-chunk", dest="prefill_chunk_tokens",
                    type=int, default=None,
                    help="chunked-prefill chunk size in tokens: long "
                         "admissions prefill one chunk per decode tick "
                         "instead of stalling the batch (default: the "
                         "config's prefill_chunk_size; 0 disables)")
    sv.add_argument("--admission-window-ms", dest="admission_window_ms",
                    type=float, default=0.0,
                    help="wait this long for same-key peers before a "
                         "generation's first decode step")
    sv.add_argument("--no-continuous", dest="no_continuous",
                    action="store_true",
                    help="legacy run-to-completion micro-batching")
    sv.add_argument("--no-telemetry", dest="no_telemetry",
                    action="store_true",
                    help="skip hot-path metric recording (/metrics stays "
                         "up but latency histograms stay empty)")
    sv.add_argument("--trace-jsonl", dest="trace_jsonl",
                    help="write request/prefill/stream spans to this "
                         "JSONL file (tracing is off without it)")
    sv.add_argument("--trace-jax", dest="trace_jax", action="store_true",
                    help="mirror spans as jax.profiler TraceAnnotations "
                         "(visible when a device trace is captured)")
    sv.add_argument("--latency-buckets", dest="latency_buckets",
                    help="comma-separated histogram bucket bounds in "
                         "seconds (default spans 0.5ms..30s)")
    sv.add_argument("--request-timeout", dest="request_timeout_s",
                    type=float, default=None,
                    help="per-request deadline in seconds: overdue lanes "
                         "are evicted (504 / SSE error). A request's own "
                         "timeout_s can only shorten it. Default: none")
    sv.add_argument("--max-queue-depth", dest="max_queue_depth",
                    type=int, default=128,
                    help="admission queue cap: beyond it, generation "
                         "requests get 503 + Retry-After instead of "
                         "queuing unboundedly (0 disables shedding)")
    sv.add_argument("--drain-grace", dest="drain_grace_s", type=float,
                    default=30.0,
                    help="seconds SIGTERM waits for in-flight generations "
                         "to finish before shutdown")
    sv.add_argument("--flight-dir", dest="flight_dir",
                    help="where drain dumps the wide-event flight record "
                         "(flightrec-*.jsonl; default: the checkpoint "
                         "dir, else the working dir)")
    sv.add_argument("--prefix-cache-pages", dest="prefix_cache_pages",
                    type=int, default=None,
                    help="radix prefix cache budget in KV pool pages: "
                         "admissions splice cached shared-prefix pages "
                         "(system prompts, few-shot templates) instead "
                         "of re-prefilling them; LRU-evicted beyond the "
                         "budget (default: the config's "
                         "prefix_cache_pages; 0 disables)")
    sv.add_argument("--prefix-cache-tenant-quota",
                    dest="prefix_cache_tenant_quota", type=int,
                    default=None,
                    help="max cached pages one tenant may own — at "
                         "quota a tenant evicts its OWN pages, never "
                         "other tenants' (0 = unbounded)")
    sv.add_argument("--tenant-rate", dest="tenant_rate_per_s",
                    type=float, default=None,
                    help="per-tenant token-bucket admission rate "
                         "(requests/sec refill; unset disables the "
                         "bucket gate)")
    sv.add_argument("--tenant-burst", dest="tenant_burst", type=int,
                    default=None,
                    help="per-tenant token-bucket burst capacity "
                         "(default: ~1s of --tenant-rate)")
    sv.add_argument("--no-watchdog", dest="no_watchdog",
                    action="store_true",
                    help="disable the decode-loop hang watchdog "
                         "(hang_suspected events + stack/ring dumps on a "
                         "stuck decode step)")
    sv.add_argument("--watchdog-abort", dest="watchdog_abort",
                    action="store_true",
                    help="exit 75 (resumable) after a confirmed decode "
                         "hang is dumped, so the orchestrator restarts "
                         "the replica")
    sv.add_argument("--watchdog-k", dest="watchdog_k", type=float,
                    default=None,
                    help="robust threshold multiplier over the rolling "
                         "median decode step (default 10)")
    sv.add_argument("--watchdog-floor", dest="watchdog_floor", type=float,
                    default=None,
                    help="minimum stall seconds before the serving "
                         "watchdog can fire (default 30; raise above "
                         "your worst-case decode compile before "
                         "enabling --watchdog-abort)")
    sv.add_argument("--no-slo", dest="no_slo", action="store_true",
                    help="disable the history ring + SLO burn-rate "
                         "engine (GET /slo and /metrics/history then "
                         "answer 404)")
    sv.add_argument("--slo-config", dest="slo_config",
                    help="JSON file REPLACING the default serve "
                         "objectives (docs/observability.md 'SLOs & "
                         "burn rate')")
    sv.add_argument("--healthz-stale-after", dest="healthz_stale_after",
                    type=float, default=None,
                    help="seconds since the last decode tick (while "
                         "busy) or train step after which /healthz "
                         "reports status=degraded (still 200) so "
                         "probes catch wedged-but-alive processes "
                         "before the watchdog aborts")
    sv.add_argument("--replicas", type=int, default=1,
                    help="spawn N replica serve processes (ports "
                         "port+1..port+N) fronted by the replica "
                         "router on --port — the one-command dev "
                         "fleet (docs/serving.md 'Replica router')")
    sv.add_argument("--page-share", dest="page_share", default=None,
                    help="router URL for cross-replica KV page sharing: "
                         "report harvested prefix-chain keys there and "
                         "pull indexed pages from sibling replicas on "
                         "cold admissions (--replicas wires this "
                         "automatically; docs/serving.md 'Cross-replica "
                         "prefix sharing')")
    sv.add_argument("--page-share-self", dest="page_share_self",
                    default=None,
                    help="this replica's own base URL, as siblings "
                         "should reach it for GET /pages/<key> "
                         "(required for reporting; --replicas sets it)")
    sv.add_argument("--page-pull-timeout", dest="page_pull_timeout",
                    type=float, default=None,
                    help="seconds one whole remote page pull may take "
                         "(lookup + transfers) before the admission "
                         "degrades to local prefill (default 2)")
    sv.add_argument("--page-share-max-inflight",
                    dest="page_share_max_inflight", type=int,
                    default=None,
                    help="max concurrent remote page pulls per replica "
                         "(default 2); further cold admissions just "
                         "prefill locally")
    sv.set_defaults(fn=cmd_serve)

    rt = sub.add_parser(
        "route",
        help="data-plane router fronting N serve replicas: health "
             "probing, circuit breakers, affine dispatch + failover, "
             "hedged retries",
    )
    rt.add_argument("--replica", dest="replicas", action="append",
                    required=True,
                    help="replica base URL (repeat per replica)")
    rt.add_argument("--host", default="127.0.0.1")
    rt.add_argument("--port", type=int, default=8000)
    rt.add_argument("--probe-interval", dest="probe_interval_s",
                    type=float, default=None,
                    help="seconds between /healthz+/slo probe rounds "
                         "(default: config router_probe_interval_s)")
    rt.add_argument("--breaker-failures", dest="breaker_failures",
                    type=int, default=None,
                    help="consecutive failures opening a replica's "
                         "circuit breaker (default: config)")
    rt.add_argument("--breaker-cooldown", dest="breaker_cooldown_s",
                    type=float, default=None,
                    help="seconds an open breaker waits before its "
                         "half-open probe (default: config)")
    rt.add_argument("--max-failovers", dest="max_failovers", type=int,
                    default=None,
                    help="extra candidates a failed dispatch may try "
                         "(capped at replicas-1; default: config)")
    rt.add_argument("--request-timeout", dest="request_timeout_s",
                    type=float, default=None,
                    help="per-attempt replica timeout in seconds")
    rt.add_argument("--hedge", action="store_true",
                    help="hedged dispatch: fire a second replica for "
                         "short non-stream requests after a p95-based "
                         "delay; first answer wins, loser cancelled")
    rt.add_argument("--hedge-delay", dest="hedge_delay_s", type=float,
                    default=None,
                    help="fixed hedge delay in seconds (default: the "
                         "fleet's observed p95)")
    rt.add_argument("--hedge-budget", dest="hedge_budget", type=float,
                    default=None,
                    help="max hedged fraction of non-stream traffic "
                         "(default: config router_hedge_budget)")
    rt.add_argument("--hedge-max-tokens", dest="hedge_max_tokens",
                    type=int, default=None,
                    help="only hedge requests asking for at most this "
                         "many new tokens (default: config)")
    rt.add_argument("--flight-dir", dest="flight_dir",
                    help="dump the router's wide-event flight record "
                         "here on exit (flightrec-*.jsonl)")
    rt.set_defaults(fn=cmd_route)

    b = sub.add_parser("benchmark", help="run the bench harness")
    b.add_argument("--ops", action="store_true",
                   help="op-level microbenchmarks instead of train throughput")
    b.set_defaults(fn=cmd_benchmark)

    d = sub.add_parser("data", help="dataset utilities")
    d.add_argument(
        "action",
        choices=["sample", "oasst", "validate", "acquire", "blend",
                 "train-tokenizer"],
    )
    d.add_argument("--sources", nargs="*",
                   help="blend: name=weight=glob triples")
    d.add_argument("--in", dest="inp")
    d.add_argument("--out")
    d.add_argument("--count", type=int, default=100)
    d.add_argument("--vocab-size", dest="vocab_size", type=int, default=4096,
                   help="train-tokenizer: target vocab (incl. 256 bytes)")
    d.add_argument("--max-per-file", dest="max_per_file", type=int,
                   default=None,
                   help="acquire: rotate output shards after N conversations "
                        "(config.max_conversations_per_file equivalent)")
    d.set_defaults(fn=cmd_data)

    cv = sub.add_parser(
        "convert",
        help="convert checkpoint layout (scan <-> plain) or export an "
             "int8-quantized serving checkpoint",
    )
    cv.add_argument("--checkpoint", required=True)
    cv.add_argument("--to", choices=["scan", "plain", "int8"], required=True)
    cv.add_argument("--out", required=True)
    cv.set_defaults(fn=cmd_convert)

    e = sub.add_parser("evaluate", help="perplexity/loss on a dataset")
    e.add_argument("--checkpoint", required=True)
    e.add_argument("--data", required=True, help="jsonl conversations")
    e.add_argument("--batch-size", dest="batch_size", type=int)
    e.add_argument("--max-batches", dest="max_batches", type=int, default=0)
    e.set_defaults(fn=cmd_evaluate)

    rp = sub.add_parser("report", help="HTML reports")
    rp.add_argument("kind", choices=["training", "data"])
    rp.add_argument("--dir", help="experiment dir (training report)")
    rp.add_argument("--out")
    rp.add_argument("inputs", nargs="*", help="jsonl files (data report)")
    rp.set_defaults(fn=cmd_report)

    g = sub.add_parser("diagnose", help="system diagnostics")
    g.add_argument("--preset", help="also check whether PRESET fits")
    g.add_argument("--probe-timeout", type=int, default=90,
                   help="seconds before the backend probe is declared hung")
    g.set_defaults(fn=cmd_diagnose)

    an = sub.add_parser(
        "analyze",
        help="static analysis gate: AST lint rules + abstract-eval audits",
    )
    an.add_argument(
        "paths", nargs="*",
        help="files/dirs to lint (default: the luminaai_tpu package)",
    )
    an.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    an.add_argument("--baseline",
                    help="JSON file of accepted legacy findings")
    an.add_argument("--write-baseline", metavar="FILE",
                    help="write current unwaived findings as a baseline")
    an.add_argument("--no-audit", action="store_true",
                    help="skip the abstract-eval auditors (lint only)")
    an.set_defaults(fn=cmd_analyze)

    ev = sub.add_parser(
        "events",
        help="query the wide-event flight recorder (flightrec-*.jsonl "
             "dumps or the live buffer)",
    )
    ev.add_argument(
        "paths", nargs="*",
        help="dump files or directories holding flightrec-*.jsonl "
             "(e.g. a checkpoint dir); default: the in-process buffer",
    )
    ev.add_argument("--tail", type=int, default=0,
                    help="show only the last N matching events")
    ev.add_argument("--grep", help="regex over the serialized record")
    ev.add_argument("--type", dest="etype",
                    help="only events of this type (e.g. request_admitted)")
    ev.add_argument("--request", dest="request_id",
                    help="only events of one request id: its full "
                         "lifecycle (admission -> prefix_hit -> chunks "
                         "-> completion) — the cache-splice debugging "
                         "loop")
    ev.add_argument("--since", dest="since",
                    help="only events at/after this floor: an epoch "
                         "timestamp, or a duration ago with an s/m/h/d "
                         "suffix (e.g. 90s, 5m, 2h)")
    ev.add_argument("--stats", action="store_true",
                    help="summarize instead of listing: count + rate per "
                         "event type, first/last timestamps (applies "
                         "after the other filters)")
    ev.add_argument("--by", dest="stats_by", choices=("tenant", "request"),
                    help="with --stats: group the summary by identity — "
                         "per-tenant (or per-request) counts, rates and "
                         "type breakdowns, biggest burners first")
    ev.add_argument("--json", action="store_true",
                    help="one JSON record per line (pipe into jq); with "
                         "--stats, the summary as one JSON object")
    ev.set_defaults(fn=cmd_events)

    tp = sub.add_parser(
        "top",
        help="live operator dashboard over the time-series ring "
             "(sparklines + SLO burn-rate table)",
    )
    tp.add_argument(
        "source", nargs="?",
        help="tshist-*.json history dump, or a directory holding them "
             "(e.g. a checkpoint dir); default: this process's live ring",
    )
    tp.add_argument("--url",
                    help="attach to a serving process instead: polls "
                         "GET /metrics/history + /slo (e.g. "
                         "http://127.0.0.1:5001)")
    tp.add_argument("--once", action="store_true",
                    help="render one frame and exit (scripts, tests)")
    tp.add_argument("--json", action="store_true",
                    help="machine form of the frame (implies --once)")
    tp.add_argument("--interval", type=float, default=2.0,
                    help="refresh seconds for the live view (default 2)")
    tp.add_argument("--window", type=float, default=None,
                    help="restrict rows/tenant sums to the last N "
                         "seconds (default: everything retained)")
    tp.add_argument("--top-k", dest="top_k", type=int, default=4,
                    help="tenants shown in the top-K table (default 4)")
    tp.set_defaults(fn=cmd_top)

    vc = sub.add_parser(
        "verify-checkpoint",
        help="verify checkpoint integrity manifests (exit 1 on corruption)",
    )
    vc.add_argument("dir", help="checkpoint directory (holds <step>/ dirs)")
    vc.add_argument("--step", type=int, default=None,
                    help="verify one step only (default: every step)")
    vc.add_argument("--mode", choices=("full", "sample"), default="full",
                    help="full = hash every manifested file; sample = "
                         "sizes for all, hashes for a deterministic "
                         "subset (fast mode for huge checkpoints)")
    vc.add_argument("--json", action="store_true")
    vc.set_defaults(fn=cmd_verify_checkpoint)

    s = sub.add_parser("presets", help="list model presets")
    s.add_argument("--json", action="store_true")
    s.set_defaults(fn=cmd_presets)
    return p


def main(argv: Optional[List[str]] = None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
