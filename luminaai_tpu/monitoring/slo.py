"""SLO engine: declarative objectives + multi-window burn-rate alerts.

The time-series ring (monitoring/timeseries.py) retains windowed history;
this module judges it. Each `Objective` declares what "good" means for
one service-level indicator — a latency quantile under a target, an
error ratio under a budget, a goodput fraction above a floor, a step
time within a factor of its own rolling median — and the engine
evaluates every objective over TWO windows at sampling cadence, Google
SRE-workbook style:

  - FAST window (default 60s): burn rate >= `fast_burn` pages. A full-on
    incident (every sample violating) burns the budget `1/budget`x as
    fast as allowed; with the default budget of 0.1 that is 10x, so the
    default fast_burn of 10 pages only on a totally-bad fast window —
    high precision, minutes of detection latency.
  - SLOW window (default 600s): burn rate >= `slow_burn` (default 2)
    warns. Catches the slow bleed the fast window forgives.

Burn rate is `violating fraction / budget` for threshold objectives and
`error ratio / target` for ratio objectives — 1.0 means "spending the
error budget exactly as fast as allowed".

State machine per objective: ok -> warn|page fires IMMEDIATELY (one
`slo_burn` flight event + `slo_burn_alerts_total{objective,severity}`);
downward transitions require `clear_evals` consecutive evaluations below
`clear_ratio` of the firing threshold — the hysteresis that keeps a
flapping indicator from re-paging every sample. Every transition (fire
AND clear) books a `slo_burn` event into the flight recorder, so a
forensic dump replays the alert history (`lumina events --type
slo_burn`).

Nothing here touches jax or the hot path: evaluation is pure host
arithmetic over ring windows, riding the sampler's cadence via
`ring.on_sample`.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from luminaai_tpu.monitoring.timeseries import TimeSeriesRing

logger = logging.getLogger(__name__)

__all__ = [
    "Objective",
    "SLOEngine",
    "default_train_objectives",
    "default_serve_objectives",
    "load_slo_config",
    "objectives_for",
    "STATES",
]

# Severity ladder; transitions compare by index.
STATES = ("ok", "warn", "page")


@dataclasses.dataclass
class Objective:
    """One declarative service-level objective.

    Threshold form (`series` set): good means `latest op target` — the
    violating fraction of window samples is judged against `budget`.
    With `baseline` set, the target is RELATIVE: good means
    `value op target * baseline_value` (step-time vs rolling median).

    Ratio form (`bad` set): good means bad/(bad+good) <= target, where
    bad/good are counter DELTA series summed over the window and
    `target` doubles as the error budget (an allowed error RATE).
    """

    name: str
    description: str = ""
    series: Optional[str] = None
    op: str = "<="                 # "<=" or ">="
    target: float = 0.0
    budget: float = 0.1            # allowed violating fraction
    baseline: Optional[str] = None
    bad: Optional[Tuple[str, ...]] = None
    good: Optional[Tuple[str, ...]] = None
    min_samples: int = 2
    # Grace period from ring start before this objective is judged at
    # all. For LIFETIME-ratio indicators (goodput fraction) the early
    # value is structurally low — the first compile dominates elapsed —
    # and paging every cold start is noise, not signal. Per-window
    # indicators (latency quantiles) default to 0: they only exist once
    # traffic flows.
    warmup_s: float = 0.0

    def __post_init__(self):
        if self.op not in ("<=", ">="):
            raise ValueError(f"objective {self.name}: op must be <= or >=")
        if self.warmup_s < 0:
            raise ValueError(
                f"objective {self.name}: warmup_s must be >= 0"
            )
        if (self.series is None) == (self.bad is None):
            raise ValueError(
                f"objective {self.name}: exactly one of series/bad required"
            )
        if self.bad is not None:
            self.bad = tuple(self.bad)
            if not self.good:
                raise ValueError(
                    f"objective {self.name}: ratio form needs good series"
                )
            self.good = tuple(self.good)
            if not 0.0 < self.target <= 1.0:
                raise ValueError(
                    f"objective {self.name}: ratio target must be in (0, 1]"
                )
        elif not 0.0 < self.budget <= 1.0:
            raise ValueError(
                f"objective {self.name}: budget must be in (0, 1]"
            )

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Objective":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"objective {d.get('name', '?')}: unknown keys "
                f"{sorted(unknown)} (one of {sorted(known)})"
            )
        return cls(**d)

    def to_dict(self) -> Dict[str, Any]:
        out = dataclasses.asdict(self)
        return {
            k: (list(v) if isinstance(v, tuple) else v)
            for k, v in out.items()
            if v is not None and v != ""
        }


class _ObjState:
    __slots__ = ("state", "clear_streak", "fires")

    def __init__(self):
        self.state = "ok"
        self.clear_streak = 0
        self.fires = 0


class SLOEngine:
    """Evaluates objectives over the ring's fast/slow windows and owns
    the per-objective alert state machine."""

    def __init__(
        self,
        ring: TimeSeriesRing,
        objectives: Sequence[Objective],
        registry=None,
        recorder=None,
        program: str = "serve",
        fast_window_s: float = 60.0,
        slow_window_s: float = 600.0,
        fast_burn: float = 10.0,
        slow_burn: float = 2.0,
        clear_ratio: float = 0.5,
        clear_evals: int = 2,
        clock=time.time,
    ):
        names = [o.name for o in objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate objective names: {names}")
        self.ring = ring
        self.objectives: List[Objective] = list(objectives)
        self.program = str(program)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        if not self.fast_window_s < self.slow_window_s:
            raise ValueError("fast window must be shorter than slow window")
        self.fast_burn = float(fast_burn)
        self.slow_burn = float(slow_burn)
        self.clear_ratio = float(clear_ratio)
        self.clear_evals = max(1, int(clear_evals))
        self._clock = clock
        self._lock = threading.Lock()
        self._states: Dict[str, _ObjState] = {
            o.name: _ObjState() for o in self.objectives
        }
        self._evaluations = 0
        self._last: Optional[Dict[str, Any]] = None
        self.recorder = recorder
        self._m_alerts = self._g_burn = self._g_state = None
        if registry is not None:
            # Objective names are config-declared (not attacker-supplied)
            # but the label budget is declared anyway — the LX009 rule's
            # spirit: no labeled family without a cardinality bound.
            self._m_alerts = registry.counter(
                "slo_burn_alerts_total",
                "Burn-rate alerts fired, by objective and severity "
                "(docs/observability.md \"SLOs & burn rate\")",
                labelnames=("objective", "severity"),
                max_label_values=64,
            )
            self._g_burn = registry.gauge(
                "slo_burn_rate",
                "Latest burn rate per objective and window (1.0 = "
                "spending error budget exactly as fast as allowed)",
                labelnames=("objective", "window"),
                max_label_values=64,
            )
            self._g_state = registry.gauge(
                "slo_state",
                "Alert state per objective (0 ok, 1 warn, 2 page)",
                labelnames=("objective",),
                max_label_values=64,
            )

    def attach(self) -> "SLOEngine":
        """Evaluate after every ring sample (the normal wiring), and
        advertise this engine on the ring so a live `lumina top` attach
        can read the verdict table without a side channel."""
        self.ring.on_sample(lambda _ring, now: self.evaluate(now=now))
        self.ring.slo = self
        return self

    # -- indicator math ----------------------------------------------------
    def _burn(
        self, obj: Objective, window_s: float, now: float
    ) -> Dict[str, Any]:
        """One objective over one window -> burn rate + evidence."""
        if obj.bad is not None:
            bad = self.ring.window_sum(obj.bad, window_s, now=now)
            good = self.ring.window_sum(obj.good, window_s, now=now)
            total = bad + good
            if total < obj.min_samples:
                # min_samples applies to ratio objectives too: one shed
                # request against zero admissions (startup, lull) is a
                # ratio of 1.0 but not evidence worth paging on.
                return {
                    "burn": 0.0,
                    "value": None,
                    "bad": bad,
                    "total": total,
                    "samples": int(total),
                }
            ratio = (bad / total) if total > 0 else 0.0
            return {
                "burn": ratio / obj.target,
                "value": round(ratio, 6),
                "bad": bad,
                "total": total,
                "samples": int(total),
            }
        pts = self.ring.window(obj.series, window_s, now=now)
        if len(pts) < obj.min_samples:
            return {"burn": 0.0, "value": None, "samples": len(pts)}
        base_pts = (
            self.ring.window(obj.baseline, window_s, now=now)
            if obj.baseline
            else None
        )
        violations = 0
        judged = 0
        last_value = None
        for ts, v in pts:
            target = obj.target
            if base_pts is not None:
                # Most recent baseline at/before this sample: a spike
                # must be judged against the regime it interrupted.
                base = None
                for bts, bv in base_pts:
                    if bts <= ts:
                        base = bv
                if base is None or base <= 0:
                    continue
                target = obj.target * base
            judged += 1
            last_value = v
            ok = v <= target if obj.op == "<=" else v >= target
            if not ok:
                violations += 1
        if judged < obj.min_samples:
            return {"burn": 0.0, "value": last_value, "samples": judged}
        frac = violations / judged
        return {
            "burn": frac / obj.budget,
            "value": last_value,
            "violating": violations,
            "samples": judged,
        }

    # -- evaluation + state machine ---------------------------------------
    def evaluate(self, now: Optional[float] = None) -> Dict[str, Any]:
        now = self._clock() if now is None else float(now)
        verdicts: Dict[str, Any] = {}
        with self._lock:
            self._evaluations += 1
            for obj in self.objectives:
                warming = (
                    obj.warmup_s > 0
                    and now - self.ring.created_ts < obj.warmup_s
                )
                if warming:
                    # Objective grace: judged as healthy-with-no-burn
                    # until the run is old enough for its indicator to
                    # mean anything (goodput during first compile).
                    fast = {"burn": 0.0, "value": None, "samples": 0}
                    slow = fast
                else:
                    fast = self._burn(obj, self.fast_window_s, now)
                    slow = self._burn(obj, self.slow_window_s, now)
                desired = "ok"
                if fast["burn"] >= self.fast_burn:
                    desired = "page"
                elif slow["burn"] >= self.slow_burn:
                    desired = "warn"
                st = self._states[obj.name]
                prev = st.state
                transition = None
                if STATES.index(desired) > STATES.index(st.state):
                    # Upward: fire immediately.
                    st.state = desired
                    st.clear_streak = 0
                    st.fires += 1
                    transition = "fire"
                elif STATES.index(desired) < STATES.index(st.state):
                    # Downward: hysteresis — only after clear_evals
                    # consecutive evaluations comfortably below the
                    # firing threshold (clear_ratio), so a flapping
                    # indicator cannot re-page every sample.
                    below = (
                        fast["burn"] < self.clear_ratio * self.fast_burn
                        and (
                            desired == "warn"
                            or slow["burn"]
                            < self.clear_ratio * self.slow_burn
                        )
                    )
                    st.clear_streak = st.clear_streak + 1 if below else 0
                    if st.clear_streak >= self.clear_evals:
                        st.state = desired
                        st.clear_streak = 0
                        transition = "clear"
                else:
                    st.clear_streak = 0
                verdicts[obj.name] = {
                    "state": st.state,
                    "burn_fast": round(fast["burn"], 4),
                    "burn_slow": round(slow["burn"], 4),
                    "value": fast.get("value"),
                    "target": obj.target,
                    "op": obj.op,
                    "baseline": obj.baseline,
                    "samples_fast": fast.get("samples", 0),
                    "samples_slow": slow.get("samples", 0),
                    "fires": st.fires,
                    "ok": st.state == "ok",
                    **({"warming": True} if warming else {}),
                }
                if self._g_burn is not None:
                    self._g_burn.labels(
                        objective=obj.name, window="fast"
                    ).set(fast["burn"])
                    self._g_burn.labels(
                        objective=obj.name, window="slow"
                    ).set(slow["burn"])
                    self._g_state.labels(objective=obj.name).set(
                        STATES.index(st.state)
                    )
                if transition is not None:
                    severity = st.state if transition == "fire" else prev
                    if transition == "fire" and self._m_alerts is not None:
                        self._m_alerts.labels(
                            objective=obj.name, severity=st.state
                        ).inc()
                    if self.recorder is not None:
                        self.recorder.emit(
                            "slo_burn",
                            program=self.program,
                            objective=obj.name,
                            transition=transition,
                            severity=severity,
                            state=st.state,
                            prev_state=prev,
                            burn_fast=round(fast["burn"], 4),
                            burn_slow=round(slow["burn"], 4),
                            value=fast.get("value"),
                            target=obj.target,
                        )
                    logger.log(
                        logging.WARNING
                        if transition == "fire"
                        else logging.INFO,
                        "slo %s: %s %s -> %s (burn fast %.2f / slow "
                        "%.2f, value %s vs target %s)",
                        obj.name, transition, prev, st.state,
                        fast["burn"], slow["burn"],
                        fast.get("value"), obj.target,
                    )
            out = {
                "v": 1,
                "ts": round(now, 3),
                "program": self.program,
                "windows": {
                    "fast_s": self.fast_window_s,
                    "slow_s": self.slow_window_s,
                    "fast_burn": self.fast_burn,
                    "slow_burn": self.slow_burn,
                },
                "evaluations": self._evaluations,
                "alerting": sorted(
                    n for n, s in self._states.items() if s.state != "ok"
                ),
                "objectives": verdicts,
            }
            self._last = out
            return out

    def verdicts(self) -> Dict[str, Any]:
        """Last evaluation (evaluating fresh when none ran yet) — the
        payload `/slo`, bench extras and `lumina top` share."""
        with self._lock:
            last = self._last
        return last if last is not None else self.evaluate()

    def state(self, name: str) -> str:
        with self._lock:
            return self._states[name].state


# -- default objectives (Config slo_* knobs) -------------------------------
def default_serve_objectives(cfg) -> List[Objective]:
    """Serving SLOs over the scheduler/server series PR 2 and PR 7
    already export (docs/observability.md lists them)."""
    return [
        Objective(
            name="serve_ttft_p95",
            description="p95 time-to-first-token within target",
            series="serve_ttft_seconds:p95",
            op="<=", target=cfg.slo_ttft_p95_s, budget=cfg.slo_budget,
        ),
        Objective(
            name="serve_decode_p50",
            description="median per-token decode latency within target",
            series="serve_token_latency_seconds:p50",
            op="<=", target=cfg.slo_decode_p50_s, budget=cfg.slo_budget,
        ),
        Objective(
            name="serve_error_rate",
            description="shed + timed-out requests within error budget",
            bad=(
                "serving_overload_rejections_total",
                "serving_requests_timed_out_total",
            ),
            good=("serve_admissions_total",),
            target=cfg.slo_error_rate,
        ),
    ]


def default_train_objectives(cfg) -> List[Objective]:
    return [
        Objective(
            name="train_goodput",
            description="goodput fraction above floor",
            series="training_goodput_fraction",
            op=">=", target=cfg.slo_goodput_fraction,
            budget=cfg.slo_budget,
            # Goodput is a LIFETIME ratio: during the first compile it
            # is structurally ~0, so judging it before one slow window
            # has elapsed would page every cold start (found driving a
            # real preempted run — not a hypothetical).
            warmup_s=cfg.slo_slow_window_s,
        ),
        Objective(
            name="train_step_time",
            description="windowed step-time p95 within a factor of the "
                        "rolling median (regression, not absolute speed)",
            series="train_step_seconds:p95",
            baseline="train_step_seconds_median",
            op="<=", target=cfg.slo_step_time_factor,
            budget=cfg.slo_budget,
        ),
    ]


def load_slo_config(path: str) -> List[Objective]:
    """Parse a --slo-config JSON file: either a bare list of objective
    dicts or {"objectives": [...]}. Replaces (not extends) the
    defaults, so an override file states the whole contract."""
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    if isinstance(doc, dict):
        doc = doc.get("objectives")
    if not isinstance(doc, list) or not doc:
        raise ValueError(
            f"{path}: expected a non-empty objective list (or "
            "{'objectives': [...]})"
        )
    return [Objective.from_dict(d) for d in doc]


def objectives_for(
    program: str, cfg, slo_config: Optional[str] = None
) -> List[Objective]:
    """Resolve the objective set: --slo-config JSON override when given,
    else the Config-knob defaults for `program`."""
    if slo_config:
        return load_slo_config(slo_config)
    if program == "train":
        return default_train_objectives(cfg)
    return default_serve_objectives(cfg)


def build_slo_stack(
    cfg,
    registry=None,
    recorder=None,
    program: str = "serve",
    slo_config: Optional[str] = None,
    clock=time.time,
) -> Tuple[TimeSeriesRing, SLOEngine]:
    """ONE constructor for the ring + attached engine pair: the trainer,
    the serving server, and bench all build through here, so every
    slo_* Config knob is read in exactly one place and a new knob cannot
    silently diverge across the three call sites. `slo_config` (the
    CLI override path) wins over cfg.slo_config when given."""
    ring = TimeSeriesRing(
        registry,
        interval_s=getattr(cfg, "slo_sample_interval_s", 5.0),
        capacity=getattr(cfg, "slo_ring_points", 720),
        max_series=getattr(cfg, "slo_max_series", 256),
        clock=clock,
    )
    engine = SLOEngine(
        ring,
        objectives_for(
            program, cfg,
            slo_config or getattr(cfg, "slo_config", None),
        ),
        registry=registry,
        recorder=recorder,
        program=program,
        fast_window_s=getattr(cfg, "slo_fast_window_s", 60.0),
        slow_window_s=getattr(cfg, "slo_slow_window_s", 600.0),
        fast_burn=getattr(cfg, "slo_fast_burn", 10.0),
        slow_burn=getattr(cfg, "slo_slow_burn", 2.0),
        clock=clock,
    ).attach()
    return ring, engine
