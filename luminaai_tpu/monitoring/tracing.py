"""Request/step span tracing: context-manager spans with JSONL export.

The metrics registry (telemetry.py) answers "how fast is the system" in
aggregate; this module answers "where did THIS request's time go". A
`SpanTracer` hands out context-manager spans (queue wait, prefill,
time-to-first-token, SSE stream, train step...) that record wall-clock
start/duration, parent/child nesting per thread, and free-form
attributes, and appends each finished span as one JSON line — the same
sink shape the training health monitor already writes, greppable and
pandas-loadable without a collector deployment.

Optionally each span also opens a `jax.profiler.TraceAnnotation`, so
when a device trace is being captured (trainer `--profile-start-step`,
or `jax.profiler.trace()` around a serving window) the host-side spans
show up as named regions on the TensorBoard timeline, correlating HTTP
requests with the device steps they caused. The jax import is lazy and
every failure path degrades to plain host spans: tracing must never be
able to take down serving.

Disabled tracers (the default for serving: `--trace-jsonl` opts in) cost
one attribute check per span — no objects, no lock, no I/O.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import threading
import time
from typing import Any, Dict, IO, Optional

logger = logging.getLogger(__name__)

__all__ = ["Span", "SpanTracer", "NULL_TRACER"]

_ids = itertools.count(1)


class Span:
    """One timed region. Mutable while open (`set(key=value)` adds
    attributes, e.g. tokens generated — known only at the end); frozen
    into a dict when the context exits."""

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "t0", "duration_s",
        "attrs", "error",
    )

    def __init__(self, name: str, trace_id: int, parent_id: Optional[int],
                 attrs: Dict[str, Any]):
        self.name = name
        self.trace_id = trace_id
        self.span_id = next(_ids)
        self.parent_id = parent_id
        self.t0 = time.time()
        self.duration_s: Optional[float] = None
        self.attrs = attrs
        self.error: Optional[str] = None

    def set(self, **attrs: Any) -> "Span":
        self.attrs.update(attrs)
        return self

    def to_dict(self) -> Dict[str, Any]:
        out = {
            "name": self.name,
            "trace": self.trace_id,
            "span": self.span_id,
            "parent": self.parent_id,
            "ts": round(self.t0, 6),
            "duration_s": (
                round(self.duration_s, 6)
                if self.duration_s is not None
                else None
            ),
        }
        if self.error:
            out["error"] = self.error
        if self.attrs:
            out["attrs"] = self.attrs
        return out


class _NullSpan:
    """Shared no-op span for disabled tracers: set() swallows attrs so
    call sites never branch on whether tracing is on."""

    __slots__ = ()

    def set(self, **attrs: Any) -> "_NullSpan":
        return self

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _OpenSpan:
    """Context manager binding one Span to the tracer's per-thread stack
    (parenting) and, optionally, a jax.profiler.TraceAnnotation."""

    __slots__ = ("_tracer", "_span", "_t0", "_annotation")

    def __init__(self, tracer: "SpanTracer", span: Span):
        self._tracer = tracer
        self._span = span
        self._t0 = 0.0
        self._annotation = None

    def __enter__(self) -> Span:
        tracer = self._tracer
        stack = tracer._stack()
        stack.append(self._span)
        if tracer.use_jax_profiler:
            try:
                import jax

                self._annotation = jax.profiler.TraceAnnotation(
                    self._span.name
                )
                self._annotation.__enter__()
            except Exception:  # no jax / no profiler backend: host-only
                self._annotation = None
        self._t0 = time.perf_counter()
        return self._span

    def __exit__(self, exc_type, exc, tb):
        span = self._span
        span.duration_s = time.perf_counter() - self._t0
        if exc is not None:
            span.error = f"{type(exc).__name__}: {exc}"
        if self._annotation is not None:
            try:
                self._annotation.__exit__(exc_type, exc, tb)
            except Exception:
                pass
        tracer = self._tracer
        stack = tracer._stack()
        if stack and stack[-1] is span:
            stack.pop()
        elif span in stack:  # mis-nested exit (generator close order)
            stack.remove(span)
        tracer._record(span)
        return False


class SpanTracer:
    """Span factory + JSONL writer.

    `tracer.span("prefill", slot=3)` returns a context manager yielding
    a Span; on exit the span (duration, attrs, error) is appended to the
    JSONL file under a lock. Nesting is per-thread: a span opened inside
    another on the same thread records it as parent, and the outermost
    span starts a new trace id — in serving, the per-request span, so
    every child carries the request's trace id.
    """

    def __init__(
        self,
        jsonl_path: Optional[str] = None,
        enabled: bool = True,
        use_jax_profiler: bool = False,
        max_spans_in_memory: int = 1000,
    ):
        self.enabled = bool(enabled)
        self.use_jax_profiler = bool(use_jax_profiler)
        self.jsonl_path = jsonl_path
        self._write_lock = threading.Lock()
        self._file: Optional[IO[str]] = None
        self._tls = threading.local()
        self._trace_ids = itertools.count(1)
        # Ring of recent finished spans for in-process inspection
        # (/healthz debugging, tests) without re-reading the file.
        self._recent: list = []
        self._max_recent = int(max_spans_in_memory)
        self.spans_recorded = 0
        self.dropped_writes = 0
        if jsonl_path:
            try:
                d = os.path.dirname(os.path.abspath(jsonl_path))
                os.makedirs(d, exist_ok=True)
                self._file = open(jsonl_path, "a")
            except OSError as e:
                logger.warning(
                    "span jsonl %s unwritable (%s); spans kept in memory "
                    "only", jsonl_path, e,
                )
                self._file = None

    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def span(self, name: str, **attrs: Any):
        """Open a span. Returns a context manager yielding the Span (or
        a shared no-op when disabled)."""
        if not self.enabled:
            return _NULL_SPAN
        stack = self._stack()
        if stack:
            parent = stack[-1]
            s = Span(name, parent.trace_id, parent.span_id, attrs)
        else:
            s = Span(name, next(self._trace_ids), None, attrs)
        return _OpenSpan(self, s)

    def _record(self, span: Span) -> None:
        with self._write_lock:
            self.spans_recorded += 1
            self._recent.append(span)
            if len(self._recent) > self._max_recent:
                del self._recent[: len(self._recent) - self._max_recent]
            if self._file is not None:
                try:
                    self._file.write(json.dumps(span.to_dict()) + "\n")
                    self._file.flush()
                except (OSError, ValueError):
                    self.dropped_writes += 1

    def recent(self, name: Optional[str] = None) -> list:
        with self._write_lock:
            spans = list(self._recent)
        if name is not None:
            spans = [s for s in spans if s.name == name]
        return spans

    def close(self) -> None:
        with self._write_lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None


# Shared disabled tracer: the zero-cost default every instrumented
# component falls back to when tracing is off.
NULL_TRACER = SpanTracer(enabled=False)
