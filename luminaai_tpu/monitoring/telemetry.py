"""Unified metrics registry: the single telemetry sink for serving AND
training.

The reference monitoring stack (monitoring/logger.py) only watches
training, and only into a jsonl file — the serving path (continuous
batching over the slot-paged KV pool) ran dark, and the training health
numbers had no pull-based export. This module gives both the same
Prometheus-shaped sink: a thread-safe registry of counters, gauges
(including pull-time callback gauges for things like KV-pool occupancy)
and fixed-bucket histograms with interpolated p50/p95/p99, rendered as
Prometheus text exposition (`GET /metrics` in serving/server.py) and
snapshot-able as plain JSON (bench.py embeds it so perf claims carry
their own telemetry provenance).

Design constraints, in order:

  1. Never on the device path. Everything here is host-side pure Python
     consuming scalars the hot loops already have; an `observe()` is one
     lock acquire + a bisect + three float adds. No jax import.
  2. Never a hard dependency. `prometheus_client` is not in the image
     and must not be: exposition is ~40 lines of text formatting, and
     owning it keeps the serving component stdlib-only.
  3. One process-wide default registry (`get_registry()`), so serving
     histograms, KV-pool gauges and training counters flow out the same
     `/metrics` endpoint — but every constructor takes an explicit
     registry for test isolation.

Histogram quantiles use Prometheus' own bucket-interpolation rule
(linear within the bucket that crosses the target rank), which makes
them monotone in q by construction and exact at bucket boundaries.
"""

from __future__ import annotations

import bisect
import math
import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "get_registry",
    "set_registry",
    "register_build_info",
    "BUILD_INFO_SCHEMA_VERSION",
    "DEFAULT_LATENCY_BUCKETS",
    "DEFAULT_MAX_LABEL_VALUES",
    "MAX_LABEL_VALUE_LEN",
    "OVERFLOW_LABEL",
]

# Latency buckets in SECONDS, spanning sub-ms token steps on TPU up to
# multi-second prefills/compiles on CPU fallbacks. Overridable per
# histogram and via the serve CLI (--latency-buckets).
DEFAULT_LATENCY_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)

_RESERVED_SUFFIXES = ("_bucket", "_sum", "_count")

# Label hardening: exposition size is label-cardinality × families, and
# label VALUES often come from the outside world (tenant hashes, routes).
# Every labeled family therefore clamps: values longer than
# MAX_LABEL_VALUE_LEN truncate, and once a label has minted
# max_label_values distinct values, new ones collapse into the
# OVERFLOW_LABEL bucket — a hostile client can cost one extra series,
# never an unbounded /metrics.
MAX_LABEL_VALUE_LEN = 64
DEFAULT_MAX_LABEL_VALUES = 100
OVERFLOW_LABEL = "_overflow"


def _fmt(v: float) -> str:
    """Prometheus sample value formatting: integers without the trailing
    .0 noise, +Inf spelled the way its parsers expect."""
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    if isinstance(v, float) and v.is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape_label(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _label_str(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label(str(v))}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


class _Child:
    """One (metric family, label set) sample holder. Families without
    label names ARE their own single child."""

    __slots__ = ("_lock", "_labels")

    def __init__(self, lock: threading.Lock, labels: Dict[str, str]):
        self._lock = lock
        self._labels = labels


class Counter(_Child):
    """Monotone counter. inc() only; negative increments are a bug in
    the caller and raise rather than silently corrupting rates."""

    __slots__ = ("_value",)

    def __init__(self, lock, labels):
        super().__init__(lock, labels)
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter increment must be >= 0, got {amount}")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge(_Child):
    """Settable gauge, or a pull-time callback gauge (`set_function`) for
    state that already lives somewhere authoritative — e.g. KV-pool
    occupancy, where a push-model gauge would just be a stale copy."""

    __slots__ = ("_value", "_fn")

    def __init__(self, lock, labels):
        super().__init__(lock, labels)
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None

    def set(self, value: float) -> None:
        with self._lock:
            self._fn = None
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    def set_function(self, fn: Callable[[], float]) -> None:
        with self._lock:
            self._fn = fn

    @property
    def value(self) -> float:
        with self._lock:
            fn = self._fn
            if fn is None:
                return self._value
        try:  # called outside the lock: the callback may take its own
            return float(fn())
        except Exception:  # telemetry must never take down the server
            return float("nan")


class Histogram(_Child):
    """Fixed-bucket histogram with Prometheus bucket semantics
    (cumulative `le` counts + sum + count) and interpolated quantiles.

    quantile(q) follows Prometheus' histogram_quantile: find the first
    bucket whose cumulative count reaches rank q*N, then interpolate
    linearly between the bucket's bounds. The +Inf bucket clamps to the
    highest finite bound (there is nothing to interpolate against), and
    because ranks are monotone in q over one frozen cumulative
    distribution, quantiles are monotone in q by construction.
    """

    __slots__ = ("_bounds", "_counts", "_sum", "_count")

    def __init__(self, lock, labels, bounds: Sequence[float]):
        super().__init__(lock, labels)
        b = sorted(float(x) for x in bounds)
        if not b or any(
            not math.isfinite(x) for x in b
        ) or len(set(b)) != len(b):
            raise ValueError(f"histogram buckets must be unique finite: {bounds}")
        self._bounds = b  # finite upper bounds; +Inf is implicit
        self._counts = [0] * (len(b) + 1)
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float, count: int = 1) -> None:
        """Record `value`, optionally `count` times in one lock acquire —
        the per-token decode latency path observes one step duration once
        per lane that produced a token."""
        if count < 1:
            return
        v = float(value)
        idx = bisect.bisect_left(self._bounds, v)
        with self._lock:
            self._counts[idx] += count
            self._sum += v * count
            self._count += count

    def time(self) -> "_HistogramTimer":
        return _HistogramTimer(self)

    # -- reads -----------------------------------------------------------
    def _frozen(self) -> Tuple[List[int], float, int]:
        with self._lock:
            return list(self._counts), self._sum, self._count

    @property
    def count(self) -> int:
        return self._frozen()[2]

    @property
    def sum(self) -> float:
        return self._frozen()[1]

    def quantile(self, q: float) -> Optional[float]:
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        counts, _, total = self._frozen()
        if total == 0:
            return None
        rank = q * total
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            if cum >= rank and c > 0:
                if i >= len(self._bounds):
                    # +Inf bucket: clamp to the largest finite bound.
                    return self._bounds[-1]
                lo = self._bounds[i - 1] if i > 0 else 0.0
                hi = self._bounds[i]
                return lo + (hi - lo) * ((rank - (cum - c)) / c)
        return self._bounds[-1]  # pragma: no cover - rank <= total always

    def quantiles(self) -> Dict[str, Optional[float]]:
        return {
            "p50": self.quantile(0.50),
            "p95": self.quantile(0.95),
            "p99": self.quantile(0.99),
        }


class _HistogramTimer:
    """`with hist.time():` convenience; also usable non-contextually via
    observe_duration() for paths that start/stop across callbacks."""

    def __init__(self, hist: Histogram):
        self._hist = hist
        self._t0 = time.perf_counter()

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._hist.observe(time.perf_counter() - self._t0)
        return False


_CHILD_TYPES = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class _Family:
    """One named metric family: holds children keyed by label values.
    Unlabeled families proxy child methods directly, so the common case
    stays `registry.counter("x", "help").inc()`."""

    def __init__(self, name, help_text, typ, labelnames, lock,
                 max_label_values: Optional[int] = None, **kw):
        self.name = name
        self.help = help_text
        self.type = typ
        self.labelnames = tuple(labelnames or ())
        self.max_label_values = int(
            max_label_values or DEFAULT_MAX_LABEL_VALUES
        )
        self._lock = lock
        self._kw = kw
        self._children: Dict[Tuple[str, ...], _Child] = {}
        # Distinct values minted per label name (the cardinality budget).
        self._label_values: Dict[str, set] = {
            k: set() for k in self.labelnames
        }
        if not self.labelnames:
            self._children[()] = self._make({})

    def _make(self, labels: Dict[str, str]) -> _Child:
        cls = _CHILD_TYPES[self.type]
        if self.type == "histogram":
            return cls(self._lock, labels, self._kw["buckets"])
        return cls(self._lock, labels)

    def labels(self, **labels: str):
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name} takes labels {self.labelnames}, got "
                f"{tuple(labels)}"
            )
        with self._lock:
            key = tuple(
                self._clamp_value(k, str(labels[k]))
                for k in self.labelnames
            )
            child = self._children.get(key)
            if child is None:
                child = self._make(dict(zip(self.labelnames, key)))
                self._children[key] = child
        return child

    def _clamp_value(self, labelname: str, value: str) -> str:
        """Bounded-cardinality guard (call under self._lock): length-cap
        the value, then charge it against the label's distinct-value
        budget — an exhausted budget routes NEW values into the
        `_overflow` series instead of minting one. Already-seen values
        (and `_overflow` itself) always resolve to their live child, so
        established series keep accumulating."""
        if len(value) > MAX_LABEL_VALUE_LEN:
            value = value[:MAX_LABEL_VALUE_LEN]
        seen = self._label_values[labelname]
        if value not in seen and value != OVERFLOW_LABEL:
            if len(seen) >= self.max_label_values:
                return OVERFLOW_LABEL
            seen.add(value)
        return value

    def children(self) -> List[_Child]:
        with self._lock:
            return list(self._children.values())

    # Unlabeled families act as their own child.
    def _sole(self) -> _Child:
        if self.labelnames:
            raise ValueError(
                f"{self.name} is labeled {self.labelnames}; call .labels()"
            )
        return self._children[()]

    def inc(self, amount: float = 1.0):
        return self._sole().inc(amount)

    def dec(self, amount: float = 1.0):
        return self._sole().dec(amount)

    def set(self, value: float):
        return self._sole().set(value)

    def set_function(self, fn: Callable[[], float]):
        return self._sole().set_function(fn)

    def observe(self, value: float, count: int = 1):
        return self._sole().observe(value, count)

    def time(self):
        return self._sole().time()

    def quantile(self, q: float):
        return self._sole().quantile(q)

    def quantiles(self):
        return self._sole().quantiles()

    @property
    def value(self):
        return self._sole().value

    @property
    def count(self):
        return self._sole().count

    @property
    def sum(self):
        return self._sole().sum


class MetricsRegistry:
    """Thread-safe named-metric store with Prometheus text exposition.

    Creation is get-or-create: asking for an existing name with the same
    type/labels returns the live family (serving and training both run
    `__init__`-time registration against the shared process registry, and
    tests spin several servers per process), while a type or label-name
    conflict raises — two meanings for one exposition name is how
    dashboards silently lie.
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._families: Dict[str, _Family] = {}

    def _get_or_create(self, name, help_text, typ, labelnames,
                       max_label_values=None, **kw) -> _Family:
        if not name or not name.replace("_", "a").replace(":", "a").isalnum():
            raise ValueError(f"bad metric name {name!r}")
        if typ != "histogram" and name.endswith(_RESERVED_SUFFIXES):
            raise ValueError(
                f"{name!r} collides with histogram exposition suffixes"
            )
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.type != typ or fam.labelnames != tuple(labelnames or ()):
                    raise ValueError(
                        f"metric {name!r} already registered as {fam.type} "
                        f"with labels {fam.labelnames}"
                    )
                if typ == "histogram" and tuple(
                    sorted(kw["buckets"])
                ) != tuple(sorted(fam._kw["buckets"])):
                    # Silently returning the old layout would drop the
                    # caller's requested resolution into +Inf unnoticed.
                    raise ValueError(
                        f"histogram {name!r} already registered with "
                        f"buckets {fam._kw['buckets']}"
                    )
                return fam  # first registration's cardinality cap stands
            fam = _Family(
                name, help_text, typ, labelnames, self._lock,
                max_label_values=max_label_values, **kw,
            )
            self._families[name] = fam
            return fam

    def counter(self, name, help_text="", labelnames=(),
                max_label_values=None) -> _Family:
        return self._get_or_create(
            name, help_text, "counter", labelnames,
            max_label_values=max_label_values,
        )

    def gauge(self, name, help_text="", labelnames=(),
              max_label_values=None) -> _Family:
        return self._get_or_create(
            name, help_text, "gauge", labelnames,
            max_label_values=max_label_values,
        )

    def histogram(
        self,
        name,
        help_text="",
        buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS,
        labelnames=(),
        max_label_values=None,
    ) -> _Family:
        return self._get_or_create(
            name, help_text, "histogram", labelnames,
            max_label_values=max_label_values, buckets=tuple(buckets)
        )

    def get(self, name: str) -> Optional[_Family]:
        with self._lock:
            return self._families.get(name)

    def families(self) -> List[_Family]:
        with self._lock:
            return sorted(self._families.values(), key=lambda f: f.name)

    # -- exposition ------------------------------------------------------
    def render_prometheus(self) -> str:
        """Prometheus text format 0.0.4. Stable ordering (sorted family
        names, sorted label sets) so diffs between scrapes are
        meaningful in tests and incident logs."""
        out: List[str] = []
        for fam in self.families():
            if fam.help:
                out.append(f"# HELP {fam.name} {fam.help}")
            out.append(f"# TYPE {fam.name} {fam.type}")
            children = sorted(
                fam.children(), key=lambda c: sorted(c._labels.items())
            )
            for child in children:
                labels = child._labels
                if fam.type == "histogram":
                    counts, total_sum, total = child._frozen()
                    cum = 0
                    for bound, c in zip(
                        child._bounds + [float("inf")], counts
                    ):
                        cum += c
                        ls = _label_str({**labels, "le": _fmt(bound)})
                        out.append(f"{fam.name}_bucket{ls} {cum}")
                    ls = _label_str(labels)
                    out.append(f"{fam.name}_sum{ls} {_fmt(total_sum)}")
                    out.append(f"{fam.name}_count{ls} {total}")
                else:
                    out.append(
                        f"{fam.name}{_label_str(labels)} "
                        f"{_fmt(child.value)}"
                    )
        return "\n".join(out) + "\n"

    # -- JSON snapshot (bench provenance) --------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """Plain-JSON view of every metric: counters/gauges as values,
        histograms as {count, sum, p50, p95, p99}. bench.py embeds this
        in its artifact so a throughput claim ships with the latency
        distribution and occupancy counters behind it."""
        snap: Dict[str, Any] = {}
        for fam in self.families():
            per_child: Dict[str, Any] = {}
            for child in fam.children():
                key = (
                    ",".join(
                        f"{k}={v}" for k, v in sorted(child._labels.items())
                    )
                    or ""
                )
                if fam.type == "histogram":
                    counts, total_sum, total = child._frozen()
                    q = child.quantiles()
                    val = {
                        "count": total,
                        "sum": round(total_sum, 6),
                        "p50": q["p50"],
                        "p95": q["p95"],
                        "p99": q["p99"],
                    }
                else:
                    v = child.value
                    val = None if (isinstance(v, float) and math.isnan(v)) else v
                per_child[key] = val
            if tuple(fam.labelnames):
                snap[fam.name] = per_child
            else:
                snap[fam.name] = per_child.get("", None)
        return snap


def weak_callback(
    obj: Any, read: Callable[[Any], float]
) -> Callable[[], float]:
    """Pull-time gauge callback holding only a WEAK reference to `obj`.

    Components register callback gauges against the process-wide
    registry, which outlives any one server/scheduler; a strong closure
    would pin a replaced object (and everything it owns — e.g. a KV
    pool's device arrays) for process lifetime, and keep exporting its
    stale state as current. With a weak ref, a collected object reads
    as NaN — rendered as absent data, not a lie. `read` must not itself
    capture obj (pass it the resolved object instead)."""
    ref = weakref.ref(obj)

    def call() -> float:
        o = ref()
        if o is None:
            return float("nan")
        return read(o)

    return call


# -- build info (fleet debugging) ----------------------------------------
# Bump when the exposition/event envelope contracts change together; the
# build_info gauge carries it so a fleet scrape can spot version skew.
BUILD_INFO_SCHEMA_VERSION = 1

_git_commit_cache: Optional[str] = None


def _git_commit() -> str:
    """Best-effort short commit id: CI env vars first, then one cached
    `git rev-parse` (never raises — 'unknown' beats a crashed startup)."""
    global _git_commit_cache
    if _git_commit_cache is not None:
        return _git_commit_cache
    import os

    commit = os.environ.get("GIT_COMMIT") or os.environ.get("GITHUB_SHA")
    if not commit:
        import subprocess

        try:
            commit = subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"],
                capture_output=True, text=True, timeout=5,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            ).stdout.strip()
        except Exception:
            commit = ""
    _git_commit_cache = (commit or "unknown")[:12]
    return _git_commit_cache


def config_digest(config: Any) -> str:
    """Short stable hash of a Config (or any to_dict-able / dict /
    string) so two processes can be compared for config skew without
    shipping the whole config through labels."""
    import hashlib
    import json as _json

    if config is None:
        return "none"
    if hasattr(config, "to_dict"):
        config = config.to_dict()
    try:
        blob = _json.dumps(config, sort_keys=True, default=str)
    except (TypeError, ValueError):
        blob = str(config)
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


def register_build_info(registry=None, config=None) -> Dict[str, str]:
    """Register the `build_info` gauge (value 1, identity in labels):
    git commit, jax/jaxlib versions, config hash, schema version — the
    standard fleet-debugging series ("which replicas run which build").
    Called at process start by the trainer, the serving server and the
    bench children; idempotent per label set. Returns the label dict."""
    if registry is None:
        registry = get_registry()
    try:  # telemetry itself must stay importable without jax
        import jax

        jax_v = getattr(jax, "__version__", "unknown")
    except Exception:
        jax_v = "unavailable"
    try:
        import jaxlib

        jaxlib_v = getattr(jaxlib, "__version__", "unknown")
    except Exception:
        jaxlib_v = "unavailable"
    labels = {
        "git_commit": _git_commit(),
        "jax": str(jax_v),
        "jaxlib": str(jaxlib_v),
        "config_hash": config_digest(config),
        "schema": str(BUILD_INFO_SCHEMA_VERSION),
    }
    registry.gauge(
        "build_info",
        "Process build identity (value is always 1; the labels are the "
        "payload): git commit, jax/jaxlib versions, config hash, "
        "schema version",
        labelnames=tuple(sorted(labels)),
        # A process registers a handful of identities (trainer + server
        # colocated, a few configs in tests) — small bounded budget.
        max_label_values=16,
    ).labels(**labels).set(1)
    return labels


# -- process-wide default sink ------------------------------------------
_default_registry = MetricsRegistry()
_default_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    """The process-wide registry: serving endpoints, the KV pool, the
    trainer and the health monitor all default to this one sink, so a
    colocated process exports everything from one /metrics scrape."""
    return _default_registry


def set_registry(registry: MetricsRegistry) -> MetricsRegistry:
    """Swap the process default (tests). Returns the previous registry."""
    global _default_registry
    with _default_lock:
        prev = _default_registry
        _default_registry = registry
        return prev
