"""Monitoring: training health (logger), unified metrics (telemetry),
request/step tracing (tracing), and performance attribution
(attribution: compiled-cost accounting + per-subsystem trace
breakdown). telemetry.get_registry() is the process-wide sink serving
and training both export through."""

from luminaai_tpu.monitoring.attribution import (
    OpRow,
    TraceAttribution,
    attribute_trace,
    classify_op,
    compiled_cost_metrics,
    export_attribution,
)
from luminaai_tpu.monitoring.events import (
    FlightRecorder,
    get_recorder,
    set_recorder,
)
from luminaai_tpu.monitoring.goodput import CAUSES, GoodputLedger
from luminaai_tpu.monitoring.watchdog import (
    HangWatchdog,
    RobustStats,
    StepTimeSentinel,
    host_step_skew,
)
from luminaai_tpu.monitoring.logger import (
    MetricsCollector,
    TrainingAlert,
    TrainingHealthMonitor,
)
from luminaai_tpu.monitoring.slo import (
    Objective,
    SLOEngine,
    build_slo_stack,
    default_serve_objectives,
    default_train_objectives,
    load_slo_config,
    objectives_for,
)
from luminaai_tpu.monitoring.telemetry import (
    DEFAULT_LATENCY_BUCKETS,
    MetricsRegistry,
    get_registry,
    register_build_info,
    set_registry,
)
from luminaai_tpu.monitoring.timeseries import (
    TimeSeriesRing,
    get_history,
    load_history,
    set_history,
)
from luminaai_tpu.monitoring.tracing import NULL_TRACER, Span, SpanTracer

__all__ = [
    "FlightRecorder",
    "get_recorder",
    "set_recorder",
    "CAUSES",
    "GoodputLedger",
    "HangWatchdog",
    "RobustStats",
    "StepTimeSentinel",
    "host_step_skew",
    "MetricsCollector",
    "TrainingAlert",
    "TrainingHealthMonitor",
    "MetricsRegistry",
    "DEFAULT_LATENCY_BUCKETS",
    "get_registry",
    "set_registry",
    "register_build_info",
    "Objective",
    "SLOEngine",
    "build_slo_stack",
    "default_serve_objectives",
    "default_train_objectives",
    "load_slo_config",
    "objectives_for",
    "TimeSeriesRing",
    "get_history",
    "set_history",
    "load_history",
    "SpanTracer",
    "Span",
    "NULL_TRACER",
    "OpRow",
    "TraceAttribution",
    "attribute_trace",
    "classify_op",
    "compiled_cost_metrics",
    "export_attribution",
]
