"""Goodput accounting: a wall-clock ledger for the whole run.

Large-scale TPU reports organize around one headline number — what
fraction of reserved wall-clock was PRODUCTIVE training ("goodput",
PAPERS.md "Scalable Training of Language Models using JAX pjit and
TPUv4"). The metrics stack answers "how fast is a step" (PR 2) and
"what does a step cost" (PR 3); nothing answered "where did the other
six hours go". This module is that ledger.

Mechanics: at any instant exactly ONE cause is accruing. `switch()`
closes the open segment (attributing its elapsed wall time to the old
cause) and opens a new one, so the per-cause totals partition elapsed
time BY CONSTRUCTION — `sum(seconds.values()) == elapsed` is an
identity, not a hope, and the contract test pins it. `region()` is the
context-manager form that restores the enclosing cause on exit (eval
inside productive, checkpoint inside productive, ...).

Two special flows cannot be expressed as regions:

  - resume replay: the PrefetchLoader burns time skipping batches the
    interrupted run already consumed; from the trainer's seat that time
    accrues inside a `data_wait` pull. The loader counts its own skip
    seconds and the trainer calls `reattribute("resume_replay", s)`
    while the data_wait segment is still OPEN — the open segment
    shrinks, resume_replay grows, the partition holds.
  - hang: the watchdog (monitoring/watchdog.py) detects a stall while
    some segment is open and reattributes the stalled seconds to
    `hang` the same way, from its own thread (the ledger is locked).

Cost: a couple of float ops + a lock per transition, transitions happen
at loop boundaries (not per device op), and nothing here ever touches a
jax value — zero new host syncs on the step path by construction.

Exports (docs/observability.md "Goodput & sentinels"):
  - `training_time_seconds_total{cause}` counter — incremented as
    segments close / reattribute (monotone: attribution only adds).
  - `training_goodput_fraction` gauge — pull-time callback, weak ref.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Dict, Optional, Tuple

__all__ = ["CAUSES", "GoodputLedger"]

# The canonical partition of a run's wall clock. Every snapshot carries
# every key (zeros included) so dashboards and the CI check never probe
# for optional fields.
CAUSES = (
    "productive",     # executing train steps
    "compile",        # first-compile window (step dispatch + sync)
    "checkpoint",     # save/restore, incl. blocking emergency saves
    "data_wait",      # host loop blocked on the (prefetch) loader
    "resume_replay",  # loader fast-forwarding past already-trained batches
    "eval",           # eval windows (not train throughput, not idle)
    "hang",           # stalled time the watchdog attributed to a hang
    "idle",           # everything else (init, between train() calls)
)


class GoodputLedger:
    """Wall-clock attribution ledger with a partition-by-construction
    invariant. Thread-safe: the owning loop switches causes, the
    watchdog thread may `reattribute` concurrently."""

    def __init__(
        self,
        registry=None,
        clock=time.monotonic,
        kind: str = "training",
        enabled: bool = True,
    ):
        self.enabled = bool(enabled)
        self._clock = clock
        self._lock = threading.Lock()
        self._totals: Dict[str, float] = {c: 0.0 for c in CAUSES}
        self._cause: Optional[str] = None  # open segment's cause
        self._seg_t0: float = 0.0          # open segment's start
        self._t_start: Optional[float] = None
        self._t_stop: Optional[float] = None
        self._m_seconds = None
        self._m_fraction = None
        if registry is not None and self.enabled:
            from luminaai_tpu.monitoring.telemetry import weak_callback

            self._m_seconds = registry.counter(
                f"{kind}_time_seconds_total",
                "Run wall-clock attributed per cause (partition of "
                "elapsed time; docs/observability.md)",
                labelnames=("cause",),
            )
            registry.gauge(
                f"{kind}_goodput_fraction",
                "Fraction of elapsed wall-clock spent executing train "
                "steps (productive / elapsed)",
            ).set_function(weak_callback(self, lambda l: l.fraction()))

    # -- attribution ------------------------------------------------------
    def start(self, cause: str = "idle") -> None:
        """Open the ledger (idempotent). Elapsed counts from here."""
        if not self.enabled:
            return
        with self._lock:
            if self._t_start is not None and self._t_stop is None:
                return  # already running
            now = self._clock()
            if self._t_start is None:
                self._t_start = now
            elif self._t_stop is not None:
                # Restart after stop(): the stopped gap is still part of
                # elapsed, so book it as idle or the partition breaks.
                self._totals["idle"] += max(0.0, now - self._t_stop)
            self._t_stop = None
            self._cause = self._check(cause)
            self._seg_t0 = now

    def switch(self, cause: str) -> str:
        """Close the open segment and open one for `cause`. Returns the
        previous cause (so callers can restore it)."""
        if not self.enabled:
            return "idle"
        cause = self._check(cause)
        with self._lock:
            prev = self._close_open_segment()
            self._cause = cause
            return prev

    @contextlib.contextmanager
    def region(self, cause: str):
        """Attribute the enclosed wall time to `cause`, then restore the
        enclosing cause (regions nest)."""
        if not self.enabled:
            yield self
            return
        prev = self.switch(cause)
        try:
            yield self
        finally:
            self.switch(prev)

    def reattribute(self, cause: str, seconds: float) -> float:
        """Move up to `seconds` of the OPEN segment's accrual to `cause`
        (resume replay discovered inside a data_wait pull; hang detected
        by the watchdog mid-stall). Clamped to what the open segment has
        actually accrued so the partition can never go negative.
        Returns the seconds actually moved."""
        if not self.enabled or seconds <= 0:
            return 0.0
        cause = self._check(cause)
        with self._lock:
            if self._cause is None:
                return 0.0
            accrued = max(0.0, self._clock() - self._seg_t0)
            take = min(float(seconds), accrued)
            if take <= 0:
                return 0.0
            self._totals[cause] += take
            self._seg_t0 += take  # the open segment accrues that much less
            if self._m_seconds is not None:
                self._m_seconds.labels(cause=cause).inc(take)
            return take

    def stop(self) -> None:
        """Close the open segment; `start()` reopens (elapsed excludes
        the stopped gap only if never restarted — the trainer keeps one
        ledger running for its whole life)."""
        if not self.enabled:
            return
        with self._lock:
            if self._cause is not None:
                self._close_open_segment()
                self._cause = None
            self._t_stop = self._clock()

    # -- reads ------------------------------------------------------------
    def _totals_elapsed_locked(self) -> Tuple[Dict[str, float], float]:
        """One lock section, ONE clock reading for both the per-cause
        totals (open segment included) and elapsed — a read descheduled
        between two clock calls must not fake a partition error."""
        with self._lock:
            now = self._clock()
            out = dict(self._totals)
            if self._cause is not None:
                out[self._cause] += max(0.0, now - self._seg_t0)
            if self._t_start is None:
                el = 0.0
            else:
                end = self._t_stop if self._t_stop is not None else now
                el = max(0.0, end - self._t_start)
            return out, el

    def elapsed(self) -> float:
        return self._totals_elapsed_locked()[1]

    def current_cause(self) -> Optional[str]:
        """The cause accruing right now (None when disabled/stopped) —
        lets liveness surfaces distinguish 'not advancing because
        wedged' from 'not advancing because legitimately inside an
        eval/checkpoint window'."""
        if not self.enabled:
            return None
        with self._lock:
            return self._cause

    def seconds(self) -> Dict[str, float]:
        """Per-cause totals INCLUDING the open segment's live accrual,
        so the partition identity holds at any instant."""
        return self._totals_elapsed_locked()[0]

    def fraction(self) -> float:
        """productive / elapsed — the headline goodput number."""
        secs, el = self._totals_elapsed_locked()
        if el <= 0:
            return 0.0
        return min(1.0, secs["productive"] / el)

    def snapshot(self) -> Dict[str, Any]:
        """JSON-friendly record for bench artifacts and summaries."""
        if not self.enabled:
            return {"available": False, "reason": "goodput ledger disabled"}
        secs, el = self._totals_elapsed_locked()
        frac = min(1.0, secs["productive"] / el) if el > 0 else 0.0
        return {
            "available": True,
            "elapsed_s": round(el, 4),
            "goodput_fraction": round(frac, 4),
            "seconds": {c: round(secs[c], 4) for c in CAUSES},
            # |sum - elapsed|: ~0 by construction (same instant for both
            # sides); the contract test and the CI check read this
            # instead of re-deriving it.
            "partition_error_s": round(abs(sum(secs.values()) - el), 6),
        }

    # -- internals (lock held) -------------------------------------------
    def _close_open_segment(self) -> str:
        prev = self._cause or "idle"
        now = self._clock()
        if self._cause is not None:
            dt = max(0.0, now - self._seg_t0)
            self._totals[self._cause] += dt
            if self._m_seconds is not None and dt > 0:
                self._m_seconds.labels(cause=self._cause).inc(dt)
        self._seg_t0 = now
        return prev

    @staticmethod
    def _check(cause: str) -> str:
        if cause not in CAUSES:
            raise ValueError(
                f"unknown goodput cause {cause!r} (one of {CAUSES})"
            )
        return cause
