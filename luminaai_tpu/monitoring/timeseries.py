"""In-process time-series retention over the metrics registry.

Every telemetry surface so far answers "what is the value NOW": a
/metrics scrape between two incidents looks healthy, and "are we meeting
TTFT over the last 5 minutes vs the last hour" is unanswerable without
an external Prometheus that dev boxes and TPU-pod smoke runs don't have.
This module is the missing retention tier: a fixed-memory ring of
samples taken FROM the existing registry (monitoring/telemetry.py) on a
background thread, held in-process so the SLO engine (monitoring/slo.py),
`GET /metrics/history`, and `lumina top` can all ask windowed questions
without any external infrastructure.

Sampling semantics per family type:

  - counters are stored as DELTAS per sample interval (the registry's
    raw value is monotone-from-zero in-process, so the first sample's
    delta against an implicit 0 baseline is exact). Rates fall out as
    delta / interval; window sums as sums of deltas.
  - gauges are stored as-is (NaN — e.g. a collected weak callback — is
    skipped, not stored as a lie).
  - histograms are stored as WINDOWED quantiles: the delta of the
    cumulative bucket counts between consecutive samples is itself a
    histogram of just that interval's observations, and the Prometheus
    interpolation rule over those delta counts yields p50/p95/p99 of
    the interval — not the process-lifetime quantiles the live
    histogram reports. A `:count` series carries the interval's
    observation count so consumers can weight or ignore thin windows.

Design constraints, in order:

  1. Fixed memory by construction: `capacity` points per series
     (deque maxlen) and a hard `max_series` budget. When the budget is
     exhausted, NEW series collapse into the shared `_overflow` series
     (which counts suppressed points per tick) — mirroring the
     registry's own label-budget `_overflow` contract, so a hostile
     label can cost one series, never unbounded host memory.
  2. Never on the device path: the sampler reads host-side registry
     state on its own daemon thread. Gauge callbacks run exactly as
     they do for a /metrics scrape. Zero jax imports.
  3. Lock discipline: registry/child locks are taken while GATHERING
     raw values, the ring's own lock only while storing — the sampler
     can never deadlock against a producer emitting mid-sample, and
     `snapshot()` (scrape) stays safe against concurrent `sample_once()`
     (contract-tested in tests/test_slo.py's race test).

Durability rides the flight-recorder pattern: `dump_to_dir()` writes a
`tshist-*.json` snapshot next to the flightrec dumps so `lumina top`
can attach to a dead process's history.
"""

from __future__ import annotations

import json
import logging
import math
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

__all__ = [
    "HISTORY_SCHEMA_VERSION",
    "DUMP_PREFIX",
    "OVERFLOW_SERIES",
    "TimeSeriesRing",
    "windowed_quantile",
    "load_history",
    "latest_history_dump",
    "get_history",
    "set_history",
]

# Bump when the snapshot envelope changes shape; new series appearing is
# not a schema change (readers must tolerate unknown names).
HISTORY_SCHEMA_VERSION = 1

DUMP_PREFIX = "tshist-"

# Series-budget overflow sink (mirrors telemetry.OVERFLOW_LABEL): once
# max_series distinct series exist, points for NEW series land here as a
# suppressed-point count — bounded memory, visible loss.
OVERFLOW_SERIES = "_overflow"


def windowed_quantile(
    bounds: List[float], counts: List[int], q: float
) -> Optional[float]:
    """Prometheus-rule interpolated quantile over DELTA bucket counts.

    `counts` has len(bounds) + 1 entries (the +Inf bucket last), exactly
    the shape of Histogram._counts — but holding one interval's
    observations rather than the process lifetime's. Same interpolation
    as Histogram.quantile, so windowed and lifetime quantiles agree when
    the window IS the lifetime, and monotonicity in q holds for the same
    reason (one frozen cumulative distribution)."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile must be in [0, 1], got {q}")
    total = sum(counts)
    if total <= 0:
        return None
    rank = q * total
    cum = 0
    for i, c in enumerate(counts):
        cum += c
        if cum >= rank and c > 0:
            if i >= len(bounds):
                return bounds[-1]  # +Inf bucket clamps to last finite
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i]
            return lo + (hi - lo) * ((rank - (cum - c)) / c)
    return bounds[-1]  # pragma: no cover - rank <= total always


class TimeSeriesRing:
    """Bounded in-process history of the registry's metric families.

    Series are keyed `name` (unlabeled) or `name{k=v,...}` (sorted
    labels), with histogram families fanning out into `:p50`, `:p95`,
    `:p99` and `:count` suffixed series. Each series is a deque of
    (ts, value) capped at `capacity` points.
    """

    def __init__(
        self,
        registry=None,
        interval_s: float = 5.0,
        capacity: int = 720,
        max_series: int = 256,
        clock: Callable[[], float] = time.time,
    ):
        if registry is None:
            from luminaai_tpu.monitoring.telemetry import get_registry

            registry = get_registry()
        self.registry = registry
        self.interval_s = max(0.05, float(interval_s))
        self.capacity = max(2, int(capacity))
        self.max_series = max(1, int(max_series))
        self._clock = clock
        self._lock = threading.Lock()
        self._series: Dict[str, "deque[Tuple[float, float]]"] = {}
        # Counter baselines (raw value at last sample; implicit 0 start)
        # and histogram cumulative-count baselines.
        self._last_counter: Dict[str, float] = {}
        self._last_hist: Dict[str, List[int]] = {}
        self._samples = 0
        self._overflow_points = 0  # lifetime suppressed points
        self._created_ts = clock()
        self._listeners: List[Callable[["TimeSeriesRing", float], None]] = []
        # The SLO engine judging this ring, advertised by
        # SLOEngine.attach() — lets a live `lumina top` attach render
        # the verdict table (reference cycle is fine; gc handles it).
        self.slo = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- sampling ----------------------------------------------------------
    @staticmethod
    def _key(fam, child) -> str:
        labels = ",".join(
            f"{k}={v}" for k, v in sorted(child._labels.items())
        )
        return f"{fam.name}{{{labels}}}" if labels else fam.name

    def sample_once(self, now: Optional[float] = None) -> int:
        """Take one sample of every family in the registry. Returns the
        number of points stored. Safe to call concurrently with
        producers, scrapes, and the background thread (idempotence is
        NOT implied — each call is its own interval for delta series)."""
        now = self._clock() if now is None else float(now)
        # Phase 1: gather raw values holding only registry/child locks
        # (gauge callbacks may take arbitrary locks of their own — the
        # ring's lock must not be held around them).
        gathered: List[Tuple[str, str, Any]] = []
        for fam in self.registry.families():
            for child in fam.children():
                key = self._key(fam, child)
                if fam.type == "histogram":
                    counts, _, _ = child._frozen()
                    gathered.append(
                        (key, "histogram", (list(child._bounds), counts))
                    )
                elif fam.type == "counter":
                    gathered.append((key, "counter", child.value))
                else:
                    gathered.append((key, "gauge", child.value))
        # Phase 2: store under the ring's own lock.
        stored = 0
        with self._lock:
            for key, typ, raw in gathered:
                if typ == "gauge":
                    stored += self._push(key, now, raw)
                elif typ == "counter":
                    last = self._last_counter.get(key, 0.0)
                    delta = float(raw) - last
                    self._last_counter[key] = float(raw)
                    if delta < 0:
                        continue  # registry swapped/reset: new baseline
                    stored += self._push(key, now, delta)
                else:
                    bounds, counts = raw
                    last = self._last_hist.get(key)
                    self._last_hist[key] = counts
                    if last is None or len(last) != len(counts):
                        deltas = counts
                    else:
                        deltas = [c - p for c, p in zip(counts, last)]
                        if any(d < 0 for d in deltas):
                            continue  # reset: re-baseline, skip interval
                    n = sum(deltas)
                    stored += self._push(key + ":count", now, float(n))
                    if n > 0:
                        for q, suffix in (
                            (0.50, ":p50"), (0.95, ":p95"), (0.99, ":p99"),
                        ):
                            stored += self._push(
                                key + suffix, now,
                                windowed_quantile(bounds, deltas, q),
                            )
            self._samples += 1
        for fn in list(self._listeners):
            try:
                fn(self, now)
            except Exception:  # a broken listener must not stop sampling
                logger.exception("time-series sample listener failed")
        return stored

    def _push(self, name: str, ts: float, value) -> int:
        """Store one point (lock held). Returns 1 if stored."""
        if value is None:
            return 0
        value = float(value)
        if math.isnan(value):
            return 0
        dq = self._series.get(name)
        if dq is None:
            if (
                len(self._series) >= self.max_series
                and name != OVERFLOW_SERIES
            ):
                # Budget exhausted: mirror the label-budget contract —
                # the point collapses into the shared overflow series
                # (counting suppressed points, not summing their values,
                # which would be meaningless across series).
                self._overflow_points += 1
                odq = self._series.get(OVERFLOW_SERIES)
                if odq is None:
                    odq = self._series[OVERFLOW_SERIES] = deque(
                        maxlen=self.capacity
                    )
                if odq and odq[-1][0] == ts:
                    odq[-1] = (ts, odq[-1][1] + 1.0)
                else:
                    odq.append((ts, 1.0))
                return 0
            dq = self._series[name] = deque(maxlen=self.capacity)
        dq.append((ts, value))
        return 1

    def on_sample(
        self, fn: Callable[["TimeSeriesRing", float], None]
    ) -> None:
        """Register a post-sample callback (the SLO engine evaluates
        here, so alerts ride the sampling cadence with no extra thread)."""
        self._listeners.append(fn)

    # -- background sampler ------------------------------------------------
    def start(self) -> None:
        """Start the daemon sampler thread (idempotent)."""
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="timeseries-sampler", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:  # pragma: no cover - must never die silently
                logger.exception("time-series sampling failed")

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
            self._thread = None

    # -- reads -------------------------------------------------------------
    @property
    def created_ts(self) -> float:
        """When this ring started observing (objective warmup grace)."""
        return self._created_ts

    def series_names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def window(
        self, name: str, seconds: float, now: Optional[float] = None
    ) -> List[Tuple[float, float]]:
        """Points of `name` with ts >= now - seconds, in time order."""
        now = self._clock() if now is None else float(now)
        floor = now - float(seconds)
        with self._lock:
            dq = self._series.get(name)
            if dq is None:
                return []
            return [(ts, v) for ts, v in dq if ts >= floor]

    def latest(self, name: str) -> Optional[float]:
        with self._lock:
            dq = self._series.get(name)
            return dq[-1][1] if dq else None

    def window_sum(
        self, names, seconds: float, now: Optional[float] = None
    ) -> float:
        """Sum of points across delta (counter) series over the window —
        the 'events in the last W seconds' primitive ratio SLOs need."""
        total = 0.0
        for n in names:
            total += sum(v for _, v in self.window(n, seconds, now=now))
        return total

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "samples": self._samples,
                "series": len(self._series),
                "capacity": self.capacity,
                "max_series": self.max_series,
                "interval_s": self.interval_s,
                "overflow_points": self._overflow_points,
            }

    def snapshot(
        self,
        window_s: Optional[float] = None,
        max_points: Optional[int] = None,
        now: Optional[float] = None,
    ) -> Dict[str, Any]:
        """JSON-friendly history dump. Budget-guarded by construction:
        at most `capacity` points per series and `max_series` series,
        tightened further by window_s / max_points for HTTP consumers
        (`GET /metrics/history?seconds=...`)."""
        now = self._clock() if now is None else float(now)
        floor = now - float(window_s) if window_s else None
        with self._lock:
            series: Dict[str, List[List[float]]] = {}
            for name, dq in self._series.items():
                pts = [
                    [round(ts, 3), round(v, 6)]
                    for ts, v in dq
                    if floor is None or ts >= floor
                ]
                if max_points is not None and len(pts) > max_points:
                    pts = pts[-max_points:]
                if pts:
                    series[name] = pts
            return {
                "v": HISTORY_SCHEMA_VERSION,
                "ts": round(now, 3),
                "created_ts": round(self._created_ts, 3),
                "interval_s": self.interval_s,
                "samples": self._samples,
                "series_count": len(self._series),
                "overflow_points": self._overflow_points,
                "series": series,
            }

    # -- durability --------------------------------------------------------
    def dump(self, path: str, slo: Optional[Dict[str, Any]] = None) -> int:
        """Write the full history snapshot as JSON (optionally embedding
        the SLO engine's last verdicts, so `lumina top <dump>` can draw
        the alert table post-mortem). Returns the series count written.
        Atomic (tmp + rename) like the flight recorder."""
        snap = self.snapshot()
        if slo is not None:
            snap["slo"] = slo
        tmp = f"{path}.tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(snap, fh, default=str)
        os.replace(tmp, path)
        return len(snap["series"])

    def dump_to_dir(
        self, directory: str, reason: str = "",
        slo: Optional[Dict[str, Any]] = None,
    ) -> Optional[str]:
        """Dump into `directory` as tshist-<utc>-<reason>.json. Rides
        shutdown/forensic paths: never raises (mirrors
        FlightRecorder.dump_to_dir)."""
        from luminaai_tpu.monitoring.events import _safe_reason

        try:
            os.makedirs(directory, exist_ok=True)
            stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
            base = f"{DUMP_PREFIX}{stamp}-{_safe_reason(reason)}"
            path = os.path.join(directory, f"{base}.json")
            i = 0
            while os.path.exists(path):  # never overwrite a forensic dump
                i += 1
                path = os.path.join(
                    directory, f"{base}-{os.getpid()}.{i}.json"
                )
            n = self.dump(path, slo=slo)
            logger.info("time-series history: %d series -> %s", n, path)
            return path
        except Exception as e:
            logger.warning("time-series history dump failed: %s", e)
            return None


# -- dump readers (lumina top, tests) --------------------------------------
def load_history(path: str) -> Dict[str, Any]:
    """Load a tshist-*.json dump (or any TimeSeriesRing.snapshot JSON).
    Raises ValueError when the file is not a history snapshot."""
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict) or not isinstance(
        doc.get("series"), dict
    ):
        raise ValueError(f"{path} is not a time-series history snapshot")
    return doc


def latest_history_dump(directory: str) -> Optional[str]:
    """Newest tshist-*.json under `directory`, or None."""
    try:
        names = [
            n for n in os.listdir(directory)
            if n.startswith(DUMP_PREFIX) and n.endswith(".json")
        ]
    except OSError:
        return None
    if not names:
        return None
    paths = [os.path.join(directory, n) for n in names]
    return max(paths, key=lambda p: (os.path.getmtime(p), p))


# -- process-wide default ring ---------------------------------------------
# Unlike the registry/recorder defaults there is no always-on instance:
# sampling costs a thread, so the first program that WANTS history
# (trainer, serving) installs its ring here and `lumina top` (no args,
# in-process) reads it.
_default_history: Optional[TimeSeriesRing] = None
_default_lock = threading.Lock()


def get_history() -> Optional[TimeSeriesRing]:
    return _default_history


def set_history(
    ring: Optional[TimeSeriesRing],
) -> Optional[TimeSeriesRing]:
    """Install the process-default ring (trainer/server at start; tests
    swap and restore). Returns the previous ring."""
    global _default_history
    with _default_lock:
        prev = _default_history
        _default_history = ring
        return prev
