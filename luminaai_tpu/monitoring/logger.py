"""Metrics collection, alerting and training-health monitoring.

Covers the reference monitoring stack (ref: Src/Main_Scripts/monitoring/
logger.py:29 MetricsCollector, :276 TrainingHealthMonitor) — windowed metric
stats, threshold/trend alerts, loss-spike and NaN detection, gradient-norm
watch, health score, phase tracking, jsonl export and health reports. Host-
side pure Python: it consumes scalars the train step already computed, so it
adds no device work and never blocks dispatch (values arrive as jax.Arrays
and are only coerced to float here, off the critical path).
"""

from __future__ import annotations

import json
import logging
import math
import time
from collections import deque
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)


@dataclass
class TrainingAlert:
    """One raised alert (ref logger.py:18)."""

    severity: str  # 'info' | 'warning' | 'critical'
    message: str
    metric: str
    value: float
    step: int
    timestamp: float = field(default_factory=time.time)

    def to_dict(self) -> Dict[str, Any]:
        return dict(self.__dict__)


class MetricsCollector:
    """Windowed metric store with threshold/trend alerting (ref logger.py:29)."""

    def __init__(
        self,
        window_size: int = 100,
        loss_spike_threshold: float = 2.0,
        grad_norm_threshold: float = 100.0,
        registry=None,
        recorder=None,
    ):
        self.window_size = window_size
        self.loss_spike_threshold = loss_spike_threshold
        self.grad_norm_threshold = grad_norm_threshold
        self.metrics: Dict[str, deque] = {}
        self.alerts: List[TrainingAlert] = []
        # Event-spine unification (monitoring/events.py): alerts land on
        # the SAME flight recorder the serving/trainer events use, so a
        # crash dump carries the alert trail, not a parallel half-trail.
        from luminaai_tpu.monitoring.events import get_recorder

        self._recorder = recorder if recorder is not None else get_recorder()
        # Optional bridge into the unified telemetry registry
        # (monitoring/telemetry.py): alerts become a labeled counter on
        # the same /metrics surface the serving stack exports.
        self._alerts_total = None
        if registry is not None:
            self._alerts_total = registry.counter(
                "training_alerts_total",
                "Training alerts raised, by severity",
                labelnames=("severity",),
            )

    def add_metric(self, name: str, value: float, step: int) -> None:
        value = float(value)
        window = self.metrics.setdefault(name, deque(maxlen=self.window_size))
        self._check_alerts(name, value, step, window)
        window.append((step, value))

    def add_metrics(self, metrics: Dict[str, Any], step: int) -> None:
        for name, value in metrics.items():
            try:
                v = float(value)
            except (TypeError, ValueError):
                continue
            self.add_metric(name, v, step)

    # -- alert rules (ref logger.py:66-170) ------------------------------
    def _check_alerts(self, name, value, step, window) -> None:
        if math.isnan(value) or math.isinf(value):
            self._alert("critical", f"{name} is {value}", name, value, step)
            return
        if "loss" in name and window:
            recent = [v for _, v in list(window)[-10:]]
            mean = sum(recent) / len(recent)
            if mean > 0 and value > mean * self.loss_spike_threshold:
                self._alert(
                    "warning",
                    f"loss spike: {value:.4f} vs recent mean {mean:.4f}",
                    name, value, step,
                )
        if name == "grad_norm" and value > self.grad_norm_threshold:
            self._alert(
                "warning",
                f"grad norm {value:.1f} exceeds {self.grad_norm_threshold}",
                name, value, step,
            )
        if name == "learning_rate" and value < 0:
            self._alert("warning", f"negative LR {value}", name, value, step)
        if name == "moe_drop_rate" and value > 0.5:
            self._alert(
                "warning", f"MoE dropping {value:.0%} of tokens", name, value, step
            )

    def _alert(self, severity, message, metric, value, step) -> None:
        alert = TrainingAlert(severity, message, metric, value, step)
        self.alerts.append(alert)
        if self._alerts_total is not None:
            self._alerts_total.labels(severity=severity).inc()
        self._recorder.emit(
            "alert", severity=severity, metric=metric,
            value=(float(value) if math.isfinite(value) else str(value)),
            step=step, message=message,
        )
        log = logger.critical if severity == "critical" else logger.warning
        log("[%s] step %d: %s", severity.upper(), step, message)

    def get_recent_alerts(self, minutes: float = 5.0) -> List[TrainingAlert]:
        cutoff = time.time() - minutes * 60
        return [a for a in self.alerts if a.timestamp >= cutoff]

    # -- summaries (ref logger.py:205,223,246) ---------------------------
    def get_metric_summary(self, name: str) -> Dict[str, Any]:
        window = self.metrics.get(name)
        if not window:
            return {}
        values = [v for _, v in window]
        return {
            "current": values[-1],
            "mean": sum(values) / len(values),
            "min": min(values),
            "max": max(values),
            "count": len(values),
            "trend": self._trend(values),
        }

    @staticmethod
    def _trend(values: List[float]) -> str:
        if len(values) < 10:
            return "insufficient_data"
        half = len(values) // 2
        first = sum(values[:half]) / half
        second = sum(values[half:]) / (len(values) - half)
        if abs(first) < 1e-12:
            return "stable"
        change = (second - first) / abs(first)
        if change < -0.02:
            return "decreasing"
        if change > 0.02:
            return "increasing"
        return "stable"

    def get_health_score(self) -> float:
        """0-100 composite (ref logger.py:246): penalize alerts, reward a
        decreasing loss trend."""
        score = 100.0
        recent = self.get_recent_alerts(10.0)
        score -= 25.0 * sum(a.severity == "critical" for a in recent)
        score -= 5.0 * sum(a.severity == "warning" for a in recent)
        loss = self.get_metric_summary("loss")
        if loss:
            if loss.get("trend") == "increasing":
                score -= 15.0
            elif loss.get("trend") == "decreasing":
                score += 5.0
        return max(0.0, min(100.0, score))


class TrainingHealthMonitor:
    """Step logging + periodic health checks + reports (ref logger.py:276).

    Writes one jsonl line per logged step under `log_dir` and keeps a
    rolling health assessment the orchestrator polls for interventions.
    """

    PHASES = ("warmup", "early", "steady", "converging")

    def __init__(
        self,
        log_dir: Optional[str] = None,
        loss_spike_threshold: float = 2.0,
        grad_norm_threshold: float = 100.0,
        health_check_interval: int = 100,
        wandb_config: Optional[Dict[str, Any]] = None,
        registry: Optional[Any] = None,
        recorder: Optional[Any] = None,
    ):
        # Optional Weights & Biases mirror (ref enable_wandb). Degrades to
        # a warning when the package is absent (this image has no wandb);
        # the jsonl log below is always the source of truth.
        self._wandb = None
        if wandb_config and wandb_config.get("enable"):
            try:
                import wandb

                self._wandb = wandb.init(
                    project=wandb_config.get("project") or "luminaai_tpu",
                    entity=wandb_config.get("entity"),
                    name=wandb_config.get("run_name"),
                    config=wandb_config.get("run_config"),
                )
            except Exception as e:
                logger.warning("wandb disabled (%s); jsonl logging only", e)
        # Unified-telemetry bridge: every scalar logged here is mirrored
        # as a `training_<name>` gauge in the shared registry, so the
        # serving /metrics endpoint (or any colocated exporter) exposes
        # training health through the exact same pipe. None disables.
        self._registry = registry
        if registry is not None:
            from luminaai_tpu.monitoring.telemetry import weak_callback

            self._health_gauge = registry.gauge(
                "training_health_score",
                "Composite 0-100 training health (alerts + loss trend)",
            )
            # Weak ref: the process registry outlives any one monitor.
            self._health_gauge.set_function(
                weak_callback(self, lambda m: m.collector.get_health_score())
            )
        # One structured trail, not two half-trails: every scalar logged
        # here ALSO lands as a train_step event on the process flight
        # recorder (monitoring/events.py), so the jsonl file (durable,
        # full history) and the ring buffer (last-N, crash-dumpable,
        # `lumina events`-queryable) tell the same story.
        from luminaai_tpu.monitoring.events import get_recorder

        self._recorder = recorder if recorder is not None else get_recorder()
        self.collector = MetricsCollector(
            loss_spike_threshold=loss_spike_threshold,
            grad_norm_threshold=grad_norm_threshold,
            registry=registry,
            recorder=self._recorder,
        )
        self.health_check_interval = health_check_interval
        self.phase = "warmup"
        self.start_time = time.time()
        # (seconds, steps) pairs between log calls — log cadence may be
        # sparser than 1 (the trainer logs every log_every steps).
        self.step_times: deque = deque(maxlen=100)
        self._last_log: Optional[tuple] = None  # (time, step)
        self.log_path: Optional[Path] = None
        if log_dir:
            try:
                import jax

                is_primary = jax.process_index() == 0
            except Exception:  # pragma: no cover
                is_primary = True
            if is_primary:
                d = Path(log_dir)
                d.mkdir(parents=True, exist_ok=True)
                self.log_path = d / "metrics.jsonl"

    def log_step(self, step: int, metrics: Dict[str, Any],
                 event: str = "train_step") -> None:
        now = time.time()
        if self._last_log is not None and step > self._last_log[1]:
            self.step_times.append((now - self._last_log[0], step - self._last_log[1]))
        if self._last_log is None or step > self._last_log[1]:
            self._last_log = (now, step)

        scalars = {}
        for k, v in metrics.items():
            try:
                f = float(v)
            except (TypeError, ValueError):
                continue
            scalars[k] = f
        self.collector.add_metrics(scalars, step)
        self._recorder.emit(
            event, step=step,
            # Envelope keys (and `step`, bound above) can't ride as
            # kwargs — a metric named like one would TypeError.
            **{k: v for k, v in scalars.items()
               if k not in ("v", "ts", "type", "seq", "step")},
        )
        self._update_phase(step, scalars)
        if self._registry is not None:
            self._mirror_to_registry(step, scalars)

        if self.log_path is not None:
            with self.log_path.open("a") as f:
                f.write(json.dumps({"step": step, "ts": now, **scalars}) + "\n")
        if self._wandb is not None:
            try:
                self._wandb.log(scalars, step=step)
            except Exception:  # never let telemetry kill training
                pass

    @staticmethod
    def _metric_name(key: str) -> str:
        """Logged scalar key -> valid exposition metric name."""
        safe = "".join(
            c if (c.isalnum() or c == "_") else "_" for c in key
        ).strip("_") or "unnamed"
        return f"training_{safe}"

    def _mirror_to_registry(self, step: int, scalars: Dict[str, float]) -> None:
        import math as _math

        r = self._registry
        for k, v in scalars.items():
            if not _math.isfinite(v):
                continue  # NaN/Inf are alert material, not gauge values
            try:
                r.gauge(
                    self._metric_name(k), f"Training scalar '{k}' (latest)"
                ).set(v)
            except ValueError:
                # A scalar key colliding with an existing non-gauge metric
                # must not kill training; the jsonl log still has it.
                continue
        r.gauge(
            "training_step", "Latest logged global step"
        ).set(step)

    def _update_phase(self, step: int, metrics: Dict[str, float]) -> None:
        """Rough phase model (ref logger.py:340 _update_training_phase)."""
        loss = self.collector.get_metric_summary("loss")
        if step < 100:
            self.phase = "warmup"
        elif loss.get("trend") == "decreasing":
            self.phase = "early" if step < 1000 else "steady"
        elif loss.get("trend") == "stable" and step > 1000:
            self.phase = "converging"

    def steps_per_second(self) -> float:
        total_s = sum(s for s, _ in self.step_times)
        total_steps = sum(n for _, n in self.step_times)
        if total_s <= 0:
            return 0.0
        return total_steps / total_s

    def get_health_summary(self) -> Dict[str, Any]:
        score = self.collector.get_health_score()
        return {
            "health_score": score,
            "status": self._status(score),
            "phase": self.phase,
            "steps_per_second": round(self.steps_per_second(), 3),
            "uptime_minutes": round((time.time() - self.start_time) / 60, 1),
            "recent_alerts": [a.to_dict() for a in self.collector.get_recent_alerts()],
            "loss": self.collector.get_metric_summary("loss"),
            "grad_norm": self.collector.get_metric_summary("grad_norm"),
        }

    @staticmethod
    def _status(score: float) -> str:
        if score >= 80:
            return "healthy"
        if score >= 60:
            return "degraded"
        if score >= 40:
            return "unstable"
        return "critical"

    def save_health_report(self, path: str) -> None:
        report = {
            "generated": time.time(),
            "summary": self.get_health_summary(),
            "metrics": {
                name: self.collector.get_metric_summary(name)
                for name in self.collector.metrics
            },
            "alerts": [a.to_dict() for a in self.collector.alerts[-100:]],
        }
        Path(path).parent.mkdir(parents=True, exist_ok=True)
        Path(path).write_text(json.dumps(report, indent=1))
