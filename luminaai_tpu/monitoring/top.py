"""`lumina top`: a live terminal view over the time-series ring.

Renders the operator's five questions — how fast, how slow, how busy,
how healthy, who's burning budget — as sparkline rows over the ring's
retained history (monitoring/timeseries.py) plus the SLO engine's
verdict table (monitoring/slo.py). Three sources, one renderer:

  - a running server: `lumina top --url http://host:5001` polls
    `GET /metrics/history` + `GET /slo`;
  - a dumped history file (tshist-*.json, written next to the flightrec
    dumps on drain/forensics): `lumina top <path>` — post-mortem view;
  - the in-process default ring (no argument; tests and embedders).

Rendering is a PURE function of (history snapshot, slo verdicts) — no
clocks, no terminal queries — so `--once` output is deterministic and
golden-testable, and `--json` is the same data without the drawing.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "sparkline",
    "top_payload",
    "render_top",
    "history_rate",
    "DEFAULT_ROWS",
]

_SPARK = "▁▂▃▄▅▆▇█"

# (label, series, kind) rows probed in order; rows whose series are
# absent from the history are skipped, so one renderer serves train,
# serve, and colocated processes.
DEFAULT_ROWS: Tuple[Tuple[str, str, str], ...] = (
    ("serve tok/s", "serve_tokens_out_total", "rate"),
    ("ttft p95 s", "serve_ttft_seconds:p95", "value"),
    ("decode p50 s", "serve_token_latency_seconds:p50", "value"),
    ("active lanes", "serve_active_lanes", "value"),
    ("queue depth", "serve_queue_depth", "value"),
    ("train tok/s", "train_tokens_per_sec", "value"),
    ("goodput", "training_goodput_fraction", "value"),
    ("step p95 s", "train_step_seconds:p95", "value"),
)

_TENANT_RX = re.compile(r"^tenant_tokens_out_total\{tenant=(.+)\}$")


def sparkline(values: List[float], width: int = 24) -> str:
    """Unicode sparkline of the LAST `width` values, min-max scaled.
    Constant (or single-point) series render mid-height so "flat" and
    "empty" stay visually distinct."""
    vals = [float(v) for v in values][-width:]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return _SPARK[3] * len(vals)
    span = hi - lo
    return "".join(
        _SPARK[min(len(_SPARK) - 1, int((v - lo) / span * len(_SPARK)))]
        for v in vals
    )


def _fmt(v: Optional[float]) -> str:
    if v is None:
        return "-"
    v = float(v)
    a = abs(v)
    if a >= 1e6:
        return f"{v / 1e6:.1f}M"
    if a >= 1e4:
        return f"{v / 1e3:.1f}k"
    if a >= 100 or v == int(v):
        return f"{int(round(v))}"
    if a >= 1:
        return f"{v:.2f}"
    return f"{v:.4f}"


def _points(
    history: Dict[str, Any], name: str, window_s: Optional[float]
) -> List[List[float]]:
    pts = history.get("series", {}).get(name) or []
    if window_s:
        floor = float(history.get("ts", 0.0)) - float(window_s)
        pts = [p for p in pts if p[0] >= floor]
    return pts


def history_rate(
    history: Dict[str, Any], name: str, window_s: Optional[float] = None
) -> List[float]:
    """Per-second rates from a counter-delta series (delta / interval)."""
    interval = max(1e-9, float(history.get("interval_s", 1.0)))
    return [p[1] / interval for p in _points(history, name, window_s)]


def top_payload(
    history: Dict[str, Any],
    slo: Optional[Dict[str, Any]] = None,
    window_s: Optional[float] = None,
    top_k: int = 4,
    fleet: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """The machine form behind both `--json` and the drawn frame."""
    rows: Dict[str, Dict[str, Any]] = {}
    for label, series, kind in DEFAULT_ROWS:
        if kind == "rate":
            vals = history_rate(history, series, window_s)
        else:
            vals = [p[1] for p in _points(history, series, window_s)]
        if not vals:
            continue
        rows[label] = {
            "series": series,
            "last": round(vals[-1], 6),
            "min": round(min(vals), 6),
            "max": round(max(vals), 6),
            "points": len(vals),
            "values": [round(v, 6) for v in vals],
        }
    tenants: List[Dict[str, Any]] = []
    for name in history.get("series", {}):
        m = _TENANT_RX.match(name)
        if not m:
            continue
        total = sum(p[1] for p in _points(history, name, window_s))
        tenants.append({"tenant": m.group(1), "tokens_out": int(total)})
    tenants.sort(key=lambda t: (-t["tokens_out"], t["tenant"]))
    return {
        "ts": history.get("ts"),
        "interval_s": history.get("interval_s"),
        "samples": history.get("samples"),
        "series_count": history.get("series_count"),
        "overflow_points": history.get("overflow_points", 0),
        "window_s": window_s,
        "rows": rows,
        "tenants": tenants[: max(0, int(top_k))],
        "slo": slo,
        "fleet": fleet,
    }


def render_top(
    history: Dict[str, Any],
    slo: Optional[Dict[str, Any]] = None,
    source: str = "live",
    window_s: Optional[float] = None,
    top_k: int = 4,
    spark_width: int = 32,
    fleet: Optional[Dict[str, Any]] = None,
) -> str:
    """One drawn frame. Pure: everything comes from the payloads
    (history + slo from a replica, or a router's /fleet table)."""
    pay = top_payload(history, slo, window_s=window_s, top_k=top_k,
                      fleet=fleet)
    out: List[str] = []
    out.append(
        f"lumina top — {source} — samples={pay['samples']} "
        f"series={pay['series_count']} interval={pay['interval_s']}s"
        + (
            f" overflow={pay['overflow_points']}"
            if pay.get("overflow_points")
            else ""
        )
    )
    out.append("")
    if pay["rows"]:
        label_w = max(len(lbl) for lbl in pay["rows"]) + 2
        for label, row in pay["rows"].items():
            spark = sparkline(row["values"], width=spark_width)
            out.append(
                f"{label:<{label_w}}{spark:<{spark_width + 2}}"
                f"{_fmt(row['last']):>8}  "
                f"[{_fmt(row['min'])} .. {_fmt(row['max'])}]"
            )
    elif not fleet:
        out.append("(no series in window — is telemetry/history on?)")
    if pay["tenants"]:
        out.append("")
        out.append(f"top tenants (tokens out{', windowed' if window_s else ''}):")
        for t in pay["tenants"]:
            out.append(f"  {t['tenant']:<20}{t['tokens_out']:>10}")
    if slo and slo.get("objectives"):
        out.append("")
        out.append(
            f"slo ({slo.get('program', '?')}; fast "
            f"{slo['windows']['fast_s']}s/slow {slo['windows']['slow_s']}s):"
        )
        hdr = (
            f"  {'objective':<22}{'state':<7}{'burn f/s':>12}"
            f"{'value':>10}{'target':>10}"
        )
        out.append(hdr)
        for name, v in sorted(slo["objectives"].items()):
            mark = {"ok": " ", "warn": "!", "page": "!!"}.get(
                v["state"], "?"
            )
            out.append(
                f"{mark:<2}{name:<22}{v['state']:<7}"
                f"{v['burn_fast']:>6.2f}/{v['burn_slow']:<5.2f}"
                f"{_fmt(v.get('value')):>10}"
                f"{v['op']:>4}{_fmt(v['target']):>6}"
                + (" ×median" if v.get("baseline") else "")
            )
        alerting = slo.get("alerting") or []
        if alerting:
            out.append(f"  ALERTING: {', '.join(alerting)}")
    if fleet and fleet.get("replicas"):
        reps = fleet["replicas"]
        out.append("")
        out.append(
            f"fleet — {fleet.get('status', '?')} "
            f"({fleet.get('available', '?')}/{len(reps)} available, "
            f"{fleet.get('breakers_open', 0)} breaker(s) open):"
        )
        out.append(
            f"  {'replica':<10}{'status':<10}{'breaker':<11}"
            f"{'infl':>5}{'reqs':>7}{'fails':>7}{'p95 s':>8}  slo"
        )
        for r in reps:
            slo_cell = "-"
            if r.get("slo"):
                alerting = r["slo"].get("alerting") or []
                slo_cell = (
                    "ALERT:" + ",".join(alerting) if alerting else "ok"
                )
            shed = r.get("shed_for_s") or 0
            status = r.get("status", "?") + (
                f"+shed{shed:g}s" if shed else ""
            )
            mark = " " if r.get("breaker") == "closed" else "!"
            out.append(
                f"{mark:<2}{r.get('replica', '?'):<10}{status:<10}"
                f"{r.get('breaker', '?'):<11}"
                f"{_fmt(r.get('inflight')):>5}"
                f"{_fmt(r.get('requests')):>7}"
                f"{_fmt(r.get('failures')):>7}"
                f"{_fmt(r.get('p95_s')):>8}  {slo_cell}"
            )
    return "\n".join(out) + "\n"
