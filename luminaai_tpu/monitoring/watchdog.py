"""Hang watchdog and step-time anomaly sentinel.

A stuck DCN collective, a wedged compile helper, or a straggling host
hangs a run SILENTLY: the loop blocks inside a jax sync, no exception is
raised, and the reservation burns until a human notices. This module is
the runtime tripwire:

  - `HangWatchdog`: a heartbeat armed by the training loop and the
    serving scheduler. Producers `beat()` at their synced boundaries
    (the trainer at log cadence, right after the float() window sync;
    the scheduler after each decode step). A daemon thread watches the
    gap since the last beat against a ROBUST threshold — k x rolling
    median (+MAD guard) of recent beat intervals, floored — and when it
    trips: emits a `hang_suspected` flight event, writes ALL-thread
    stacks plus the flight ring next to the checkpoints, bumps
    `{training,serving}_hangs_total`, reattributes the stalled seconds
    to the goodput ledger's `hang` cause, and (opt-in `abort=True`,
    `--watchdog-abort`) exits RESUMABLE_EXIT=75 so the orchestrator
    restarts the job instead of burning the reservation. Warmup-aware
    by construction: the trainer arms AFTER the first-compile sync and
    nothing fires until `warmup` intervals exist, so a first compile
    (minutes on flagship shapes) can never trip it.

  - `StepTimeSentinel`: online robust stats over step durations. Each
    observation is checked against the rolling median/MAD BEFORE it
    joins the window (a spike must not defend itself), emitting
    `step_anomaly` events and `<prefix>_{median,mad}` gauges. Reset on
    recompile — a new executable is a new timing regime.

  - `host_step_skew()`: per-host step-completion skew, gathered at the
    caller's EXISTING multihost sync point (the trainer's log-window
    float() conversion) — max-min of per-host wall clocks, the
    straggler signal. Single-host returns 0.0 with no device work.

Everything here is host-side wall clock: zero new syncs enter the step
path (LX002 stays clean), and the monitor thread holds no jax state.
"""

from __future__ import annotations

import contextlib
import logging
import os
import sys
import threading
import time
import traceback
from collections import deque
from typing import Any, Dict, Optional

logger = logging.getLogger(__name__)

__all__ = [
    "RESUMABLE_EXIT",
    "RobustStats",
    "HangWatchdog",
    "StepTimeSentinel",
    "host_step_skew",
    "dump_all_stacks",
]

# Mirrors cli.RESUMABLE_EXIT: orchestrators treat 75 (EX_TEMPFAIL) as
# "restart me", distinct from a real failure.
RESUMABLE_EXIT = 75

# MAD -> sigma for a normal distribution; used to turn the MAD guard
# into comparable units with the median.
_MAD_SIGMA = 1.4826


class RobustStats:
    """Rolling median/MAD over the last `window` observations. Sorting a
    <=128-element window at beat/log cadence is microseconds — robust
    beats clever here."""

    def __init__(self, window: int = 64):
        self._buf: "deque[float]" = deque(maxlen=max(2, int(window)))

    def add(self, x: float) -> None:
        self._buf.append(float(x))

    def __len__(self) -> int:
        return len(self._buf)

    def median(self) -> float:
        if not self._buf:
            return 0.0
        s = sorted(self._buf)
        n = len(s)
        mid = n // 2
        return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])

    def mad(self) -> float:
        """Median absolute deviation (raw, not sigma-scaled)."""
        if not self._buf:
            return 0.0
        med = self.median()
        s = sorted(abs(x - med) for x in self._buf)
        n = len(s)
        mid = n // 2
        return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])

    def clear(self) -> None:
        self._buf.clear()


def dump_all_stacks(path: str) -> Optional[str]:
    """Write every live thread's Python stack to `path` (the hang
    forensics a restart would otherwise destroy). Never raises — it
    rides the watchdog's firing path."""
    try:
        names = {t.ident: t.name for t in threading.enumerate()}
        with open(path, "w", encoding="utf-8") as fh:
            for tid, frame in sys._current_frames().items():
                fh.write(
                    f"--- thread {names.get(tid, '?')} (ident={tid}) ---\n"
                )
                fh.write("".join(traceback.format_stack(frame)))
                fh.write("\n")
        return path
    except Exception as e:  # pragma: no cover - filesystem failures
        logger.warning("all-thread stack dump failed: %s", e)
        return None


class HangWatchdog:
    """Heartbeat monitor: detect -> dump -> (abort | keep watching).

    Producers call `beat()` at synced boundaries; `arm()`/`disarm()`
    bracket the active region (an idle scheduler or a finished trainer
    must never trip); `pause()` brackets legitimately-slow host work
    (eval, blocking checkpoint saves) — the interval spanning a pause is
    excluded from the stats and cannot fire.

    Threshold: k * (median + MAD_sigma) of the rolling beat intervals,
    floored at `floor_s` — k x rolling median with the MAD term guarding
    noisy windows, armed only once `warmup` intervals exist.
    """

    def __init__(
        self,
        kind: str = "training",
        registry=None,
        recorder=None,
        dump_dir: Optional[str] = None,
        k: float = 10.0,
        floor_s: float = 30.0,
        warmup: int = 3,
        window: int = 64,
        poll_s: float = 1.0,
        abort: bool = False,
        ledger=None,
        clock=time.monotonic,
        exit_fn=os._exit,
    ):
        self.kind = str(kind)
        self.dump_dir = dump_dir
        self.k = float(k)
        self.floor_s = float(floor_s)
        self.warmup = max(1, int(warmup))
        self.poll_s = max(0.01, float(poll_s))
        self.abort = bool(abort)
        self.ledger = ledger
        self._clock = clock
        self._exit_fn = exit_fn
        self._lock = threading.Lock()
        self._stats = RobustStats(window)
        self._armed = False
        self._paused = 0
        self._skip_next = False
        self._fired = False
        self._last_beat: Optional[float] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.fires = 0  # lifetime hang_suspected count (tests, /stats)
        if recorder is None:
            from luminaai_tpu.monitoring.events import get_recorder

            recorder = get_recorder()
        self.recorder = recorder
        self._m_hangs = None
        if registry is not None:
            self._m_hangs = registry.counter(
                f"{self.kind}_hangs_total",
                "Suspected hangs: a step/tick exceeded the robust "
                "k x rolling-median threshold (docs/observability.md)",
            )

    # -- producer API -----------------------------------------------------
    def arm(self) -> None:
        """Start watching from NOW (the first interval begins here).
        Lazily spawns the monitor thread — an unarmed watchdog costs
        nothing."""
        with self._lock:
            self._armed = True
            self._last_beat = self._clock()
            self._fired = False
            self._skip_next = False
            if self._thread is None:
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._monitor,
                    name=f"{self.kind}-watchdog",
                    daemon=True,
                )
                self._thread.start()

    def disarm(self) -> None:
        with self._lock:
            self._armed = False
            self._last_beat = None

    def beat(self) -> None:
        """One synced boundary passed. Records the interval into the
        rolling stats (unless flagged skip: pause exits, recompiles) and
        re-enables firing for the next stall."""
        now = self._clock()
        with self._lock:
            if not self._armed:
                return
            if self._last_beat is not None and not self._skip_next:
                self._stats.add(now - self._last_beat)
            self._last_beat = now
            self._skip_next = False
            self._fired = False

    def skip_next(self) -> None:
        """Exclude the in-flight interval from the stats and from firing
        (recompile boundaries: a rebuild is a new timing regime, and its
        one long step is expected). Also clears the rolling window."""
        with self._lock:
            self._skip_next = True
            self._stats.clear()
            self._last_beat = self._clock()

    @contextlib.contextmanager
    def pause(self):
        """Suspend firing across legitimately-slow host work (eval,
        blocking checkpoint saves). The spanning interval is excluded
        from the stats on exit."""
        with self._lock:
            self._paused += 1
        try:
            yield
        finally:
            with self._lock:
                self._paused -= 1
                self._skip_next = True
                self._last_beat = self._clock()

    def close(self) -> None:
        self.disarm()
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
            self._thread = None

    # -- reads ------------------------------------------------------------
    def threshold_s(self) -> Optional[float]:
        """Current firing threshold, or None while warming up."""
        with self._lock:
            return self._threshold_locked()

    def _threshold_locked(self) -> Optional[float]:
        if len(self._stats) < self.warmup:
            return None
        med = self._stats.median()
        mad = self._stats.mad() * _MAD_SIGMA
        return max(self.floor_s, self.k * (med + mad))

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "kind": self.kind,
                "armed": self._armed,
                "intervals": len(self._stats),
                "median_s": round(self._stats.median(), 6),
                "mad_s": round(self._stats.mad(), 6),
                "threshold_s": self._threshold_locked(),
                "fires": self.fires,
                "abort": self.abort,
            }

    # -- monitor thread ---------------------------------------------------
    def _monitor(self) -> None:
        while not self._stop.wait(self.poll_s):
            with self._lock:
                if (
                    not self._armed
                    or self._paused
                    or self._fired
                    or self._last_beat is None
                ):
                    continue
                thr = self._threshold_locked()
                if thr is None:
                    continue  # warmup: first compile can never trip
                stalled = self._clock() - self._last_beat
                if stalled <= thr:
                    continue
                self._fired = True
                self.fires += 1
                med = self._stats.median()
                mad = self._stats.mad()
            self._fire(stalled, thr, med, mad)

    def _fire(self, stalled: float, thr: float, med: float, mad: float):
        """Detect -> record -> dump -> (abort | continue). Never raises:
        a broken dump path must not kill the monitor."""
        logger.critical(
            "%s hang suspected: %.1fs since last heartbeat "
            "(threshold %.1fs = k=%.1f x rolling median %.3fs, MAD %.3fs)",
            self.kind, stalled, thr, self.k, med, mad,
        )
        if self._m_hangs is not None:
            self._m_hangs.inc()
        if self.ledger is not None:
            try:
                # The stall was accruing to whatever cause is open
                # (usually productive); move it where it belongs.
                self.ledger.reattribute("hang", stalled)
            except Exception:  # pragma: no cover - ledger must not kill us
                pass
        stacks_path = None
        dump_path = None
        if self.dump_dir:
            try:
                os.makedirs(self.dump_dir, exist_ok=True)
                stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
                stacks_path = dump_all_stacks(
                    os.path.join(
                        self.dump_dir,
                        f"stacks-{stamp}-{os.getpid()}-hang.txt",
                    )
                )
            except Exception as e:  # pragma: no cover
                logger.warning("stack dump failed: %s", e)
        self.recorder.emit(
            "hang_suspected",
            kind=self.kind,
            stalled_s=round(stalled, 3),
            threshold_s=round(thr, 3),
            median_s=round(med, 6),
            mad_s=round(mad, 6),
            k=self.k,
            stacks=stacks_path,
            abort=self.abort,
        )
        if self.dump_dir:
            dump_path = self.recorder.dump_to_dir(
                self.dump_dir, reason=f"{self.kind}_hang_suspected"
            )
        if self.abort:
            logger.critical(
                "--watchdog-abort: exiting %d (resumable) so the "
                "orchestrator restarts instead of burning the "
                "reservation; forensics: %s / %s",
                RESUMABLE_EXIT, stacks_path, dump_path,
            )
            # The run is WEDGED inside a sync — a graceful save cannot
            # land. os._exit skips atexit/finally by design: the last
            # periodic checkpoint plus the dumps above are the record.
            self._exit_fn(RESUMABLE_EXIT)


class StepTimeSentinel:
    """Online step-time anomaly detection over robust rolling stats.

    `observe(seconds)` checks the value against the PRIOR window
    (median/MAD) before adding it: anomalous when it exceeds BOTH
    `k x median` (ratio: it is many steps' worth of time) and
    `median + guard_sigmas x MAD_sigma` (significance: the window is not
    just noisy). Emits one `step_anomaly` event per anomaly, keeps
    `<prefix>_median` / `<prefix>_mad` gauges fresh, and counts into
    `step_time_anomalies_total{program}`.
    """

    def __init__(
        self,
        registry=None,
        recorder=None,
        prefix: str = "train_step_seconds",
        program: str = "train",
        k: float = 4.0,
        guard_sigmas: float = 6.0,
        window: int = 64,
        warmup: int = 5,
        enabled: bool = True,
    ):
        self.enabled = bool(enabled)
        if not self.enabled:
            registry = recorder = None  # no gauges, no events, no cost
        self.program = str(program)
        self.k = float(k)
        self.guard_sigmas = float(guard_sigmas)
        self.warmup = max(2, int(warmup))
        self._stats = RobustStats(window)
        self._lock = threading.Lock()
        self.anomalies = 0
        self.recorder = recorder
        self._g_median = self._g_mad = self._m_anomalies = None
        if registry is not None:
            self._g_median = registry.gauge(
                f"{prefix}_median",
                f"Rolling median of observed {self.program} step seconds",
            )
            self._g_mad = registry.gauge(
                f"{prefix}_mad",
                f"Rolling MAD of observed {self.program} step seconds",
            )
            self._m_anomalies = registry.counter(
                "step_time_anomalies_total",
                "Step durations flagged anomalous vs the rolling "
                "median/MAD, by program",
                labelnames=("program",),
            )

    def observe(self, seconds: float, step: Optional[int] = None) -> bool:
        """Feed one step duration; returns True when flagged anomalous."""
        if not self.enabled:
            return False
        seconds = float(seconds)
        with self._lock:
            n = len(self._stats)
            med = self._stats.median()
            mad_sigma = self._stats.mad() * _MAD_SIGMA
            anomalous = (
                n >= self.warmup
                and med > 0
                and seconds > self.k * med
                and seconds > med + self.guard_sigmas * mad_sigma
            )
            self._stats.add(seconds)
            new_med = self._stats.median()
            new_mad = self._stats.mad()
            if anomalous:
                self.anomalies += 1
        if self._g_median is not None:
            self._g_median.set(new_med)
            self._g_mad.set(new_mad)
        if anomalous:
            if self._m_anomalies is not None:
                self._m_anomalies.labels(program=self.program).inc()
            if self.recorder is not None:
                self.recorder.emit(
                    "step_anomaly",
                    program=self.program,
                    seconds=round(seconds, 6),
                    median_s=round(med, 6),
                    mad_s=round(mad_sigma / _MAD_SIGMA, 6),
                    k=self.k,
                    **({"step": step} if step is not None else {}),
                )
        return anomalous

    def reset(self) -> None:
        """New timing regime (recompile): forget the old distribution."""
        with self._lock:
            self._stats.clear()


def host_step_skew(registry=None) -> float:
    """Per-host step-completion skew at the caller's sync point.

    Each host contributes its wall clock the moment it reaches the
    log-window sync; the spread (max - min) is the straggler signal —
    a host consistently seconds behind is dragging every collective.
    Gathers via one tiny all-gather ONLY when multiple processes exist
    (the caller is already at a lockstep boundary); single-host — the
    whole CPU/test harness — returns 0.0 with no device work at all.

    Exported as the `host_step_skew_seconds` gauge when a registry is
    passed."""
    import jax

    skew = 0.0
    if jax.process_count() > 1:
        import jax.numpy as jnp
        import numpy as np
        from jax.experimental import multihost_utils

        # Epoch seconds (~1.75e9) do NOT fit float32 (ulp ~128s), and
        # without jax_enable_x64 a float64 array silently downcasts —
        # so ship (hi, lo) split at 4096s: hi stays integer-exact in
        # float32 (< 2^24) and lo carries sub-millisecond resolution;
        # reconstruct in float64 on the host before taking max - min.
        now = time.time()
        hi = float(int(now) // 4096)
        lo = now - hi * 4096.0
        gathered = multihost_utils.process_allgather(
            jnp.asarray([hi, lo], dtype=jnp.float32)
        )
        g = np.asarray(gathered, dtype=np.float64).reshape(-1, 2)
        full = g[:, 0] * 4096.0 + g[:, 1]
        skew = float(full.max() - full.min())
    if registry is not None:
        registry.gauge(
            "host_step_skew_seconds",
            "Spread (max - min) of per-host wall clocks at the last "
            "log-window sync — the straggler signal (0.0 single-host)",
        ).set(skew)
    return skew
