"""Performance attribution: turns the PR-2 registry's raw rates into
*why* numbers — where a step's FLOPs, bytes and milliseconds actually go.

Three layers, each usable alone:

1. **Compiled-cost accounting** (`compiled_cost_metrics`): run XLA's own
   cost model (`lowered.compile().cost_analysis()` / `memory_analysis()`)
   on an already-jitted step function and export what the COMPILER says
   the program costs — `compiled_flops_per_step`, `compiled_bytes_accessed`,
   peak/argument/output/temp HBM footprints — next to the analytic
   6·N·T estimate the MFU headline rests on. When the two diverge by more
   than `MFU_DIVERGENCE_THRESHOLD` the cross-check flags it: either the
   analytic model is under-counting (MoE capacity padding, remat
   recompute) or the program compiled something unexpected. Works under
   `JAX_PLATFORMS=cpu`; degrades to `{"available": False, ...}` when a
   backend returns no cost model rather than raising.

2. **Trace attribution** (`classify_op` / `attribute_trace`): the
   per-subsystem step breakdown that produced the r3 MFU attack table
   (BENCHMARKS.md "Flagship profile"), promoted out of the throwaway
   `scripts/analyze_trace.py` into a tested API. `classify_op` maps an
   XLA op's framework name / category / source line onto the model's
   subsystems (flash-attention kernels, MoE dispatch vs expert matmul,
   CE loss, ...); `attribute_trace` folds a whole hlo_stats table into
   ms/step + fraction per subsystem with the dominant roofline bound.

3. **Export** (`export_attribution` / gauges inside
   `compiled_cost_metrics`): everything lands in the unified metrics
   registry (monitoring/telemetry.py) — so `/metrics` and bench
   artifacts carry attribution, not just totals — and optionally as one
   JSONL record per capture for offline trend tooling.

Nothing here touches the device path: cost analysis is an AOT
compile-time query, trace attribution consumes an already-written
profile. No jax import at module scope (the registry contract).
"""

from __future__ import annotations

import json
import math
import os
import re
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Mapping, Optional

from luminaai_tpu.monitoring.telemetry import MetricsRegistry, get_registry

__all__ = [
    "DONATION_COVERAGE_THRESHOLD",
    "MFU_DIVERGENCE_THRESHOLD",
    "SUBSYSTEMS",
    "OpRow",
    "TraceAttribution",
    "analytic_train_flops",
    "attribute_trace",
    "attribute_xplane_dir",
    "classify_op",
    "compiled_cost_metrics",
    "donation_audit",
    "export_attribution",
    "rows_from_hlo_stats",
    "tree_bytes",
]

# Analytic (6·N·T) vs compiled-FLOPs divergence beyond this fraction is
# flagged: the MFU headline and the compiler disagree about the program.
MFU_DIVERGENCE_THRESHOLD = 0.10

# A donated train step must alias (update in place) at least this
# fraction of its resident-state bytes; below it, param/opt-state buffers
# are being COPIED per step — double peak optimizer memory, the exact
# failure donate_argnums exists to prevent.
DONATION_COVERAGE_THRESHOLD = 0.90


# ---------------------------------------------------------------------------
# op classification (promoted from scripts/analyze_trace.py, r3)
# ---------------------------------------------------------------------------

# Canonical subsystem names, in the order reports print them. Keep in sync
# with classify_op's return values — test_attribution pins the mapping.
SUBSYSTEMS = (
    "attn_flash_kernels",
    "ce_loss",
    "moe_expert_matmul",
    "moe_route_dispatch",
    "attn_proj_rope",
    "data_formatting",
    "unattributed(optimizer+dispatch_bwd)",
    "other",
)

_EXPERT_MATMUL_RE = re.compile(r"egch,ehf|egcf,efh|gmm")


def classify_op(fw_name: str, category: str = "", source: str = "") -> str:
    """Map one XLA op onto a model subsystem.

    `fw_name` is the framework op name (jax named-scope path), `category`
    the HLO op category, `source` the source-info column. The rules are
    ordered most-specific-first; an empty framework name is the signature
    of XLA-fused optimizer/backward glue, which has no scope to attribute
    to — it reports as its own bucket rather than polluting "other".
    """
    if "attention" in fw_name and "pallas_call" in fw_name:
        return "attn_flash_kernels"
    if "bch,vh->bcv" in fw_name or "fused.py" in source:
        return "ce_loss"
    if _EXPERT_MATMUL_RE.search(fw_name):
        return "moe_expert_matmul"
    if "/moe/" in fw_name:
        return "moe_route_dispatch"
    if "attention/" in fw_name or "qkv" in fw_name:
        return "attn_proj_rope"
    if category == "data formatting":
        return "data_formatting"
    if not fw_name.strip():
        return "unattributed(optimizer+dispatch_bwd)"
    return "other"


@dataclass
class OpRow:
    """One profiled op: the subset of an xprof hlo_stats row the
    classifier needs. `self_time_us` is total self time across the whole
    trace window (all steps)."""

    self_time_us: float
    fw_name: str = ""
    category: str = ""
    source: str = ""
    bound_by: str = "?"


@dataclass
class TraceAttribution:
    """Per-subsystem step breakdown of one trace window."""

    n_steps: int
    ms_per_step: Dict[str, float]
    fraction: Dict[str, float]
    dominant_bound: Dict[str, str]
    total_ms_per_step: float
    top_ops: List[Dict[str, Any]] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "n_steps": self.n_steps,
            "total_ms_per_step": round(self.total_ms_per_step, 3),
            "subsystems": {
                name: {
                    "ms_per_step": round(self.ms_per_step[name], 3),
                    "fraction": round(self.fraction[name], 4),
                    "bound": self.dominant_bound[name],
                }
                for name in self.ms_per_step
            },
            "top_ops": self.top_ops,
        }


def attribute_trace(
    rows: Iterable[OpRow], n_steps: int = 1, top_k: int = 10
) -> TraceAttribution:
    """Fold profiled ops into the per-subsystem step breakdown.

    Subsystems are sorted by time (heaviest first) in the result dicts;
    `fraction` is of total self time, so it sums to ~1 regardless of how
    many steps the window covered."""
    if n_steps < 1:
        raise ValueError(f"n_steps must be >= 1, got {n_steps}")
    groups: Dict[str, float] = {}
    bounds: Dict[str, Dict[str, float]] = {}
    kept: List[OpRow] = []
    for r in rows:
        t = float(r.self_time_us or 0.0)
        g = classify_op(r.fw_name or "", r.category or "", r.source or "")
        groups[g] = groups.get(g, 0.0) + t
        bounds.setdefault(g, {})
        b = r.bound_by or "?"
        bounds[g][b] = bounds[g].get(b, 0.0) + t
        kept.append(r)
    total = sum(groups.values())
    order = sorted(groups, key=lambda g: -groups[g])
    kept.sort(key=lambda r: -float(r.self_time_us or 0.0))
    return TraceAttribution(
        n_steps=n_steps,
        ms_per_step={g: groups[g] / n_steps / 1e3 for g in order},
        fraction={g: (groups[g] / total if total else 0.0) for g in order},
        dominant_bound={
            g: max(bounds[g], key=bounds[g].get) if bounds[g] else "?"
            for g in order
        },
        total_ms_per_step=total / n_steps / 1e3,
        top_ops=[
            {
                "ms_per_step": round(
                    float(r.self_time_us or 0.0) / n_steps / 1e3, 3
                ),
                "category": (r.category or "")[:24],
                "bound": r.bound_by or "?",
                "fw_name": (r.fw_name or "")[-90:],
            }
            for r in kept[:top_k]
        ],
    )


def rows_from_hlo_stats(table: Mapping[str, Any]) -> List[OpRow]:
    """Adapt an xprof `hlo_stats` tool table ({"cols": [...], "rows":
    [...]} as returned by xspace_to_tool_data) into OpRows."""
    cols = [c["label"] for c in table["cols"]]
    idx = {c: i for i, c in enumerate(cols)}

    def cell(r, label):
        return r[idx[label]] if label in idx else None

    out = []
    for raw in table["rows"]:
        r = [c.get("v") for c in raw["c"]]
        out.append(
            OpRow(
                self_time_us=float(cell(r, "Total self time (us)") or 0.0),
                fw_name=cell(r, "Framework op name") or "",
                category=cell(r, "HLO op category") or "",
                source=re.sub(r"<[^>]+>", "", cell(r, "Source Info") or ""),
                bound_by=cell(r, "Bound by") or "?",
            )
        )
    return out


def attribute_xplane_dir(
    outdir: str, n_steps: int = 1, top_k: int = 10
) -> TraceAttribution:
    """Attribute a saved jax.profiler trace directory (the
    `plugins/profile/*/*.xplane.pb` layout both the trainer's windowed
    capture and scripts/profile_flagship.py write). Requires the xprof
    package; raises RuntimeError with a actionable message when it (or
    the trace) is missing — callers on the training path catch and log."""
    import glob

    paths = glob.glob(
        os.path.join(outdir, "plugins/profile/*/*.xplane.pb")
    )
    if not paths:
        raise RuntimeError(f"no xplane.pb under {outdir}/plugins/profile/*/")
    try:
        from xprof.convert import raw_to_tool_data as rtd
    except ImportError as e:  # pragma: no cover - image bakes xprof in
        raise RuntimeError(f"xprof unavailable for trace analysis: {e}")
    data, _ = rtd.xspace_to_tool_data(paths, "hlo_stats", {})
    return attribute_trace(
        rows_from_hlo_stats(json.loads(data)), n_steps, top_k
    )


def export_attribution(
    attr: TraceAttribution,
    registry: Optional[MetricsRegistry] = None,
    jsonl_path: Optional[str] = None,
) -> Dict[str, Any]:
    """Publish a breakdown: per-subsystem gauges in the registry
    (`attribution_ms_per_step{subsystem=...}` etc.) and, when
    `jsonl_path` is given, one appended JSON record. Returns the record."""
    registry = registry or get_registry()
    g_ms = registry.gauge(
        "attribution_ms_per_step",
        "Per-subsystem self time per train step from the last trace window",
        labelnames=("subsystem",),
    )
    g_frac = registry.gauge(
        "attribution_fraction",
        "Per-subsystem fraction of total step self time",
        labelnames=("subsystem",),
    )
    for name in attr.ms_per_step:
        g_ms.labels(subsystem=name).set(attr.ms_per_step[name])
        g_frac.labels(subsystem=name).set(attr.fraction[name])
    registry.gauge(
        "attribution_total_ms_per_step",
        "Total attributed self time per step from the last trace window",
    ).set(attr.total_ms_per_step)
    record = attr.to_dict()
    if jsonl_path:
        os.makedirs(os.path.dirname(jsonl_path) or ".", exist_ok=True)
        with open(jsonl_path, "a") as f:
            f.write(json.dumps(record) + "\n")
    return record


# ---------------------------------------------------------------------------
# compiled-cost accounting
# ---------------------------------------------------------------------------

def analytic_train_flops(active_params: int, tokens_per_step: int) -> float:
    """The 6·N·T transformer estimate MFU headlines use (fwd 2NT + bwd
    4NT, on ACTIVE params). Per whole step across all chips."""
    return 6.0 * float(active_params) * float(tokens_per_step)


def _cost_dict(compiled) -> Optional[Dict[str, float]]:
    """Normalize Compiled.cost_analysis() across jax versions: it has
    returned a list of one dict, a bare dict, and None (no cost model)."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict) or not ca:
        return None
    return {str(k): float(v) for k, v in ca.items()
            if isinstance(v, (int, float))}


def compiled_cost_metrics(
    fn,
    *args,
    program: str = "train",
    registry: Optional[MetricsRegistry] = None,
    analytic_flops: Optional[float] = None,
    divergence_threshold: float = MFU_DIVERGENCE_THRESHOLD,
    **kwargs,
) -> Dict[str, Any]:
    """AOT-query XLA's cost model for a jitted callable and export it.

    `fn` may be a raw `jax.jit` function or a wrapper carrying one as
    `fn.jitted` (parallel/train_step.py attaches it); `args`/`kwargs`
    are example arguments of the real shapes/shardings. The compile hits
    the persistent XLA cache where configured (bench_common), so on a
    warmed bench this costs parse time, not a recompile.

    Returns a JSON-able dict. On any backend that refuses a cost model
    (some TPU runtimes return None through the tunnel) or a wrapper
    without a lowerable handle, returns `{"available": False, "reason":
    ...}` — callers embed that verbatim so absence is visible, never
    silent. With `analytic_flops` set, includes the analytic-vs-compiled
    MFU cross-check: `divergence = compiled/analytic - 1`, flagged when
    |divergence| > `divergence_threshold` (default 10%) — the two feed
    the same MFU denominator, so a large gap means the headline MFU and
    the compiled program disagree about the work being measured.
    """
    target = getattr(fn, "jitted", fn)
    lower = getattr(target, "lower", None)
    if lower is None:
        return {
            "available": False,
            "reason": f"{type(fn).__name__} has no .lower/.jitted handle",
        }
    try:
        compiled = lower(*args, **kwargs).compile()
    except Exception as e:
        return {
            "available": False,
            "reason": f"lower/compile failed: {type(e).__name__}: {e}",
        }
    out: Dict[str, Any] = {"available": True, "program": program}

    ca = _cost_dict(compiled)
    if ca is None:
        out["cost_model"] = None
        out["reason"] = "backend returned no cost model"
    else:
        flops = ca.get("flops")
        nbytes = ca.get("bytes accessed")
        out["cost_model"] = {
            "flops_per_step": flops,
            "bytes_accessed": nbytes,
            "arithmetic_intensity": (
                round(flops / nbytes, 3) if flops and nbytes else None
            ),
            "transcendentals": ca.get("transcendentals"),
        }

    mem: Dict[str, Any] = {}
    try:
        ma = compiled.memory_analysis()
    except Exception:
        ma = None
    if ma is not None:
        for label, attr in (
            ("argument_bytes", "argument_size_in_bytes"),
            ("output_bytes", "output_size_in_bytes"),
            ("temp_bytes", "temp_size_in_bytes"),
            ("alias_bytes", "alias_size_in_bytes"),
            ("generated_code_bytes", "generated_code_size_in_bytes"),
        ):
            v = getattr(ma, attr, None)
            if v is not None:
                mem[label] = int(v)
        # Peak live footprint of one executable call: arguments stay
        # resident, outputs materialize, temps are the scratch high-water
        # mark — minus aliased bytes, so donated buffers (the train step
        # donates its whole TrainState) are counted once, not as both
        # argument and output.
        if mem:
            mem["peak_bytes"] = (
                mem.get("argument_bytes", 0)
                + mem.get("output_bytes", 0)
                + mem.get("temp_bytes", 0)
                + mem.get("generated_code_bytes", 0)
                - mem.get("alias_bytes", 0)
            )
    out["memory"] = mem or None

    flops = (out.get("cost_model") or {}).get("flops_per_step")
    if analytic_flops:
        xc: Dict[str, Any] = {
            "analytic_flops_per_step": analytic_flops,
            "compiled_flops_per_step": flops,
        }
        if flops:
            div = flops / analytic_flops - 1.0
            xc["divergence"] = round(div, 4)
            xc["flagged"] = bool(abs(div) > divergence_threshold)
            xc["threshold"] = divergence_threshold
        else:
            xc["divergence"] = None
            xc["flagged"] = False
            xc["note"] = "no compiled flops to cross-check"
        out["mfu_crosscheck"] = xc

    _export_cost_gauges(out, program, registry)
    return out


def tree_bytes(tree) -> int:
    """Total buffer bytes of a pytree of arrays or ShapeDtypeStructs —
    the resident-state denominator the donation audit divides by. Counts
    anything with (size, dtype); QuantizedTensor leaves flatten to their
    code/scale arrays, so they count at their stored width."""
    import jax
    import numpy as np

    total = 0
    for leaf in jax.tree.leaves(tree):
        size = getattr(leaf, "size", None)
        dtype = getattr(leaf, "dtype", None)
        if size is None or dtype is None:
            continue
        try:
            itemsize = int(np.dtype(dtype).itemsize)
        except TypeError:
            # Extended dtypes (typed PRNG keys) refuse np.dtype; their
            # itemsize attribute (when present) covers them, and a
            # scalar key is noise against param/opt bytes regardless.
            itemsize = int(getattr(dtype, "itemsize", 0) or 0)
        total += int(size) * itemsize
    return total


def donation_audit(
    memory: Optional[Mapping[str, Any]],
    donated_bytes: float,
    *,
    expected: bool = True,
    program: str = "train",
    registry: Optional[MetricsRegistry] = None,
    threshold: float = DONATION_COVERAGE_THRESHOLD,
) -> Dict[str, Any]:
    """Audit whether a compiled step actually donates its state buffers.

    `memory` is the dict `compiled_cost_metrics` returns under "memory"
    (XLA's buffer-assignment view of one executable); `donated_bytes` the
    resident bytes of the TrainState the caller donates (params + opt
    state + counters — `tree_bytes(state)`). XLA records every
    input→output aliasing it honored as `alias_bytes`, so

        coverage = alias_bytes / donated_bytes

    is the fraction of the state updated IN PLACE. Coverage below
    `threshold` with `expected=True` means donation silently broke —
    param/opt buffers are copied each step and peak HBM carries the
    state twice (the r3 profile's "optimizer + misc" bucket is where
    that shows up). The temp/state ratio rides along: scratch growth is
    the other way that bucket regresses without any code touching the
    optimizer. Flags, never raises; callers embed the verdict (bench
    `--smoke` extras, trainer cost export) so absence-of-donation is
    visible evidence, not a silent slowdown."""
    out: Dict[str, Any] = {
        "available": bool(memory),
        "program": program,
        "donated_bytes": int(donated_bytes) if donated_bytes else 0,
        "donation_expected": bool(expected),
    }
    if not memory:
        out["reason"] = "no memory analysis from this backend"
        return out
    alias = float(memory.get("alias_bytes") or 0.0)
    temp = float(memory.get("temp_bytes") or 0.0)
    out["alias_bytes"] = int(alias)
    out["temp_bytes"] = int(temp)
    if donated_bytes:
        cov = alias / float(donated_bytes)
        out["coverage"] = round(cov, 4)
        out["temp_to_state_ratio"] = round(temp / float(donated_bytes), 4)
        out["flagged"] = bool(expected and cov < threshold)
        out["threshold"] = threshold
    else:
        out["coverage"] = None
        out["flagged"] = False
        out["reason"] = "donated_bytes unknown"
    registry = registry or get_registry()
    if out.get("coverage") is not None:
        registry.gauge(
            "donation_alias_coverage",
            "alias_bytes / donated state bytes of the step executable "
            "(1.0 = full in-place update)",
            labelnames=("program",),
        ).labels(program=program).set(out["coverage"])
        registry.gauge(
            "donation_audit_flagged",
            "1 when donation was expected but alias coverage fell below "
            "the threshold",
            labelnames=("program",),
        ).labels(program=program).set(1.0 if out["flagged"] else 0.0)
    return out


def _export_cost_gauges(
    out: Dict[str, Any], program: str, registry: Optional[MetricsRegistry]
) -> None:
    registry = registry or get_registry()
    cm = out.get("cost_model") or {}
    mem = out.get("memory") or {}

    def gset(name, help_text, value):
        if value is None or (
            isinstance(value, float) and not math.isfinite(value)
        ):
            return
        registry.gauge(name, help_text, labelnames=("program",)).labels(
            program=program
        ).set(float(value))

    gset(
        "compiled_flops_per_step",
        "XLA cost-model FLOPs for one step executable",
        cm.get("flops_per_step"),
    )
    gset(
        "compiled_bytes_accessed",
        "XLA cost-model bytes accessed for one step executable",
        cm.get("bytes_accessed"),
    )
    gset(
        "compiled_hbm_peak_bytes",
        "Peak live bytes of one step call (args+outputs+temps+code)",
        mem.get("peak_bytes"),
    )
    gset(
        "compiled_hbm_argument_bytes",
        "Argument (resident state) bytes of the step executable",
        mem.get("argument_bytes"),
    )
    gset(
        "compiled_hbm_output_bytes",
        "Output bytes of the step executable",
        mem.get("output_bytes"),
    )
    gset(
        "compiled_hbm_temp_bytes",
        "Scratch/temp high-water bytes of the step executable",
        mem.get("temp_bytes"),
    )
    xc = out.get("mfu_crosscheck") or {}
    gset(
        "analytic_flops_per_step",
        "6·N·T analytic FLOPs the MFU headline assumes",
        xc.get("analytic_flops_per_step"),
    )
    if xc.get("divergence") is not None:
        gset(
            "compiled_mfu_divergence",
            "compiled/analytic FLOPs ratio minus 1; |x|>0.1 is flagged",
            xc.get("divergence"),
        )
        gset(
            "compiled_mfu_divergence_flagged",
            "1 when the analytic-vs-compiled FLOPs cross-check tripped",
            1.0 if xc.get("flagged") else 0.0,
        )
