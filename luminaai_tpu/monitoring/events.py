"""Wide-event flight recorder: the structured "what happened" trail.

The metrics registry (monitoring/telemetry.py) answers "how fast is the
system" as aggregates; nothing answered "what happened to THIS request /
THIS step / THIS tenant". This module is that spine: a lock-protected
ring buffer of typed, timestamped, schema-versioned event records that
every producer in the stack appends to:

  - serving request lifecycle (serving/server.py ContinuousScheduler):
    request_received / request_shed / request_admitted / request_prefill
    / request_first_token / decode_tick / request_evicted /
    request_completed, each carrying request_id + tenant hash;
  - training step records (training/trainer.py via
    monitoring/logger.py): train_step, router_health, recompile, alert,
    preemption;
  - bench provenance (bench.py --smoke): bench_window.

Design constraints, in order:

  1. Never on the device path, never blocking: `emit()` is one lock
     acquire + a deque append. Producers call it with scalars they
     already have (the trainer piggybacks on the whole-window device
     sync at log cadence; the scheduler on its step loop).
  2. Bounded by construction: the ring holds the LAST `capacity`
     events; older ones fall off (counted in `dropped`). A runaway
     producer can never grow host memory.
  3. Durable on demand, not continuously: `dump_to_dir()` writes the
     buffer as `flightrec-*.jsonl` — the preemption/emergency-save path
     and the serving drain path call it so a crash or SIGTERM leaves the
     last N events next to the checkpoints for `lumina events` to
     replay. Dumping must never take down the thing it is recording, so
     it logs-and-returns-None on any filesystem error.

One process-wide default recorder (`get_recorder()`) mirrors the
registry's `get_registry()` contract; every producer also accepts an
explicit recorder for test isolation.
"""

from __future__ import annotations

import json
import logging
import math
import os
import re
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional

logger = logging.getLogger(__name__)

__all__ = [
    "EVENT_SCHEMA_VERSION",
    "FlightRecorder",
    "get_recorder",
    "set_recorder",
    "read_events",
    "latest_dump",
    "filter_events",
    "format_event",
    "events_stats",
    "parse_since",
    "DUMP_PREFIX",
    "STATS_BY_FIELDS",
]

# Bump when the envelope (v/seq/ts/type) changes shape; producers adding
# new FIELDS is not a schema change (readers must tolerate unknown keys).
EVENT_SCHEMA_VERSION = 1

DUMP_PREFIX = "flightrec-"

_REASON_SAFE = re.compile(r"[^a-z0-9_-]+")


def _safe_reason(reason: str) -> str:
    """Reason string -> filesystem-safe filename fragment."""
    out = _REASON_SAFE.sub("_", (reason or "dump").lower()).strip("_")
    return (out or "dump")[:48]


class FlightRecorder:
    """Thread-safe bounded ring of event dicts.

    Every record carries the envelope {v, seq, ts, type} plus the
    producer's fields. `seq` is monotone for the recorder's lifetime
    (it keeps counting across ring evictions), so a dump's first seq
    tells a reader how much history fell off the ring before it.
    """

    def __init__(self, capacity: int = 4096):
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._buf: "deque[Dict[str, Any]]" = deque(maxlen=self.capacity)
        self._seq = 0
        self._dropped = 0  # events evicted from the ring, lifetime
        self._counts: Dict[str, int] = {}  # by type, lifetime

    def emit(self, type: str, **fields: Any) -> Dict[str, Any]:
        """Append one event. Returns the stored record (shared, do not
        mutate). Field values should be JSON-friendly scalars/lists;
        anything else is stringified at dump time, never here (the hot
        path does no serialization work)."""
        ev = {
            "v": EVENT_SCHEMA_VERSION,
            "ts": time.time(),
            "type": str(type),
            **fields,
        }
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            if len(self._buf) == self.capacity:
                self._dropped += 1
            self._buf.append(ev)
            self._counts[ev["type"]] = self._counts.get(ev["type"], 0) + 1
        return ev

    # -- reads -----------------------------------------------------------
    def snapshot(
        self, last: Optional[int] = None, type: Optional[str] = None
    ) -> List[Dict[str, Any]]:
        """Copy of the buffered events in emission order, optionally
        filtered to one type and/or the last N (after filtering)."""
        with self._lock:
            events = list(self._buf)
        if type is not None:
            events = [e for e in events if e.get("type") == type]
        if last is not None and last > 0:
            events = events[-last:]
        return events

    def counts_by_type(self) -> Dict[str, int]:
        """Lifetime emission counts by type (survives ring eviction)."""
        with self._lock:
            return dict(self._counts)

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    def __bool__(self) -> bool:
        """Always True: a recorder's identity is what matters, never its
        fill level. Without this, defining __len__ made an EMPTY recorder
        falsy — so the natural `recorder or get_recorder()` idiom
        silently swapped a caller's explicit (empty) recorder for the
        process default. Every producer uses `is None` checks, and this
        makes the or-idiom safe too (regression-pinned in
        tests/test_events.py)."""
        return True

    def clear(self) -> None:
        """Tests only: empty the ring (seq/counts keep counting)."""
        with self._lock:
            self._buf.clear()

    # -- durability ------------------------------------------------------
    def dump(self, path: str) -> int:
        """Write the buffered events as JSONL to `path`. Returns the
        event count written. Non-JSON field values are stringified here
        (default=str) so a weird payload can never poison the dump."""
        events = self.snapshot()
        tmp = f"{path}.tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            for ev in events:
                fh.write(json.dumps(ev, default=str) + "\n")
        os.replace(tmp, path)  # readers never see a half-written dump
        return len(events)

    def dump_to_dir(self, directory: str, reason: str = "") -> Optional[str]:
        """Dump into `directory` as flightrec-<utc>-<reason>.jsonl.

        This is the crash-forensics entry point (emergency save, drain,
        forced-signal exit): it must NEVER raise — a failed dump costs a
        warning, not the shutdown path it rides on. Returns the written
        path, or None."""
        try:
            os.makedirs(directory, exist_ok=True)
            stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
            base = f"{DUMP_PREFIX}{stamp}-{_safe_reason(reason)}"
            path = os.path.join(directory, f"{base}.jsonl")
            i = 0
            while os.path.exists(path):  # N dumps in one second: never
                i += 1                   # overwrite an earlier record
                path = os.path.join(
                    directory, f"{base}-{os.getpid()}.{i}.jsonl"
                )
            n = self.dump(path)
            logger.info("flight record: %d event(s) -> %s", n, path)
            return path
        except Exception as e:
            logger.warning("flight-record dump failed: %s", e)
            return None


# -- dump readers (lumina events CLI, tests) ------------------------------
def read_events(path: str) -> List[Dict[str, Any]]:
    """Load a flightrec JSONL dump. Unparseable lines are skipped (a
    truncated tail from a hard kill must not make the rest unreadable)."""
    events: List[Dict[str, Any]] = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(ev, dict):
                events.append(ev)
    return events


def latest_dump(directory: str) -> Optional[str]:
    """Newest flightrec-*.jsonl under `directory`, or None."""
    try:
        names = [
            n for n in os.listdir(directory)
            if n.startswith(DUMP_PREFIX) and n.endswith(".jsonl")
        ]
    except OSError:
        return None
    if not names:
        return None
    paths = [os.path.join(directory, n) for n in names]
    return max(paths, key=lambda p: (os.path.getmtime(p), p))


def filter_events(
    events: Iterable[Dict[str, Any]],
    type: Optional[str] = None,
    grep: Optional[str] = None,
    tail: Optional[int] = None,
    request: Optional[str] = None,
    since: Optional[float] = None,
) -> List[Dict[str, Any]]:
    """Shared query semantics for the CLI and tests: type match, one
    request's lifecycle (`lumina events --request <id>`: admission →
    prefix_hit → chunks → completion), regex over the serialized
    record, time floor (`--since`, epoch seconds — events without a
    numeric ts are dropped by the filter), then last-N."""
    out = list(events)
    if type:
        out = [e for e in out if e.get("type") == type]
    if request:
        out = [e for e in out if e.get("request_id") == request]
    if since is not None:
        out = [
            e for e in out
            if isinstance(e.get("ts"), (int, float)) and e["ts"] >= since
        ]
    if grep:
        rx = re.compile(grep)
        out = [
            e for e in out if rx.search(json.dumps(e, default=str))
        ]
    if tail is not None and tail > 0:
        out = out[-tail:]
    return out


_SINCE_UNITS = {"s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}


def parse_since(spec: str, now: Optional[float] = None) -> float:
    """`lumina events --since <ts|dur>` → an epoch-seconds floor.

    A trailing s/m/h/d makes it a duration ago ("90s", "5m", "2h",
    "1d"); a bare number is an absolute epoch timestamp (what the
    records themselves carry). Raises ValueError on anything else —
    the CLI maps that to exit 2 like a bad --grep regex."""
    spec = (spec or "").strip()
    if not spec:
        raise ValueError("empty --since value")
    unit = _SINCE_UNITS.get(spec[-1].lower())
    if unit is not None:
        dur = float(spec[:-1]) * unit  # ValueError propagates on junk
        if not math.isfinite(dur) or dur < 0:
            raise ValueError(f"bad --since duration {spec!r}")
        return (now if now is not None else time.time()) - dur
    ts = float(spec)
    if not math.isfinite(ts):
        # float() accepts "nan"/"inf"; a NaN floor would silently filter
        # EVERY event (exit 0, empty output) instead of rejecting the
        # input — the exit-2 contract must catch it here.
        raise ValueError(f"non-finite --since timestamp {spec!r}")
    return ts


# `--stats --by <axis>` grouping axes -> the record field they key on.
STATS_BY_FIELDS = {"tenant": "tenant", "request": "request_id"}


def events_stats(
    events: Iterable[Dict[str, Any]], by: Optional[str] = None
) -> Dict[str, Any]:
    """`lumina events --stats`: per-type counts and rates plus the
    first/last timestamps — a dump or live ring summarized without
    scrolling it. Rates use the OVERALL observed span (last - first ts)
    so per-type numbers are comparable on one denominator.

    With `by` ("tenant" | "request"), adds a `groups` breakdown keyed by
    that identity field (events without it pool under "-"), each group
    carrying its own count/rate/first/last plus per-type counts — so a
    forensic dump answers "which tenant was burning the error budget"
    without jq gymnastics."""
    if by is not None and by not in STATS_BY_FIELDS:
        raise ValueError(
            f"unknown --by axis {by!r} (one of {sorted(STATS_BY_FIELDS)})"
        )
    events = list(events)
    ts = [
        e["ts"] for e in events if isinstance(e.get("ts"), (int, float))
    ]
    first = min(ts) if ts else None
    last = max(ts) if ts else None
    span = (last - first) if ts else 0.0
    by_type: Dict[str, Dict[str, Any]] = {}
    for e in events:
        t = str(e.get("type", "?"))
        rec = by_type.setdefault(
            t, {"count": 0, "first_ts": None, "last_ts": None}
        )
        rec["count"] += 1
        ets = e.get("ts")
        if isinstance(ets, (int, float)):
            if rec["first_ts"] is None or ets < rec["first_ts"]:
                rec["first_ts"] = ets
            if rec["last_ts"] is None or ets > rec["last_ts"]:
                rec["last_ts"] = ets
    for rec in by_type.values():
        rec["rate_per_s"] = (
            round(rec["count"] / span, 4) if span > 0 else None
        )
    out = {
        "total": len(events),
        "first_ts": first,
        "last_ts": last,
        "span_s": round(span, 3) if ts else 0.0,
        "by_type": dict(sorted(by_type.items())),
    }
    if by is not None:
        field = STATS_BY_FIELDS[by]
        groups: Dict[str, Dict[str, Any]] = {}
        for e in events:
            key = str(e.get(field) or "-")
            rec = groups.setdefault(
                key,
                {
                    "count": 0, "first_ts": None, "last_ts": None,
                    "by_type": {},
                },
            )
            rec["count"] += 1
            t = str(e.get("type", "?"))
            rec["by_type"][t] = rec["by_type"].get(t, 0) + 1
            ets = e.get("ts")
            if isinstance(ets, (int, float)):
                if rec["first_ts"] is None or ets < rec["first_ts"]:
                    rec["first_ts"] = ets
                if rec["last_ts"] is None or ets > rec["last_ts"]:
                    rec["last_ts"] = ets
        for rec in groups.values():
            rec["rate_per_s"] = (
                round(rec["count"] / span, 4) if span > 0 else None
            )
            rec["by_type"] = dict(sorted(rec["by_type"].items()))
        out["by"] = by
        # Biggest burners first: the question this axis exists to answer.
        out["groups"] = dict(
            sorted(groups.items(), key=lambda kv: (-kv[1]["count"], kv[0]))
        )
    return out


def format_event(ev: Dict[str, Any]) -> str:
    """One human-readable line per event for `lumina events`."""
    ts = ev.get("ts")
    when = (
        time.strftime("%H:%M:%S", time.localtime(ts))
        + f".{int((ts % 1) * 1000):03d}"
        if isinstance(ts, (int, float))
        else "?"
    )
    skip = {"v", "ts", "type", "seq"}
    fields = " ".join(
        f"{k}={ev[k]}" for k in ev if k not in skip
    )
    return f"{when} #{ev.get('seq', '?')} {ev.get('type', '?'):<22} {fields}"


# -- process-wide default recorder ----------------------------------------
_default_recorder = FlightRecorder()
_default_lock = threading.Lock()


def get_recorder() -> FlightRecorder:
    """The process-wide flight recorder: serving, training and bench all
    default to this one ring, so one dump carries the whole story."""
    return _default_recorder


def set_recorder(recorder: FlightRecorder) -> FlightRecorder:
    """Swap the process default (tests). Returns the previous recorder."""
    global _default_recorder
    with _default_lock:
        prev = _default_recorder
        _default_recorder = recorder
        return prev
