"""`python -m luminaai_tpu` → CLI (ref Main.py entry)."""

import sys

from luminaai_tpu.cli import main

sys.exit(main())
