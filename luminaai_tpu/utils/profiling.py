"""Tracing and profiling utilities (SURVEY §5).

Covers the reference's ad-hoc timing decorators (ref: Src/Main_Scripts/core/
model.py:142 profile_function, :173 profiling_context — a gc-walking global
toggle) the TPU way: `jax.profiler` traces that capture XLA execution on the
device (viewable in TensorBoard / Perfetto), `TraceAnnotation` scopes that
label host-side regions inside those traces, and a StepTimer that measures
*device-synchronized* step wall time — under async dispatch, host-side
`perf_counter` deltas measure dispatch latency, not execution (VERDICT r1
weak #7), so every timing boundary here forces completion first.
"""

from __future__ import annotations

import contextlib
import functools
import time
from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional

import jax


@contextlib.contextmanager
def profiling_context(
    trace_dir: Optional[str] = None, enabled: bool = True
):
    """Capture a device trace for the enclosed region.

    With a trace_dir, wraps `jax.profiler.trace` (TensorBoard-compatible
    XPlane output, includes TPU op timelines). Without one, is a no-op
    scope so call sites can stay unconditional (ref profiling_context's
    enable/disable role, minus the gc walk).
    """
    if not enabled or trace_dir is None:
        yield
        return
    with jax.profiler.trace(trace_dir):
        yield


def annotate(name: str):
    """Label a host-side region inside a device trace
    (`jax.profiler.TraceAnnotation`); usable as a context manager."""
    return jax.profiler.TraceAnnotation(name)


def profile_function(func: Callable) -> Callable:
    """Timing decorator (ref core/model.py:142) that syncs device work.

    Timings accumulate on `wrapper.timings`; `wrapper.summary()` reports
    count/mean/max. The return value is block_until_ready'd so the recorded
    time includes the computation the call dispatched, not just tracing.
    """

    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        t0 = time.perf_counter()
        result = func(*args, **kwargs)
        try:
            jax.block_until_ready(result)
        except TypeError:  # non-array pytree leaves
            pass
        wrapper.timings.append(time.perf_counter() - t0)
        return result

    wrapper.timings = []
    wrapper.summary = lambda: {
        "count": len(wrapper.timings),
        "mean_s": sum(wrapper.timings) / max(len(wrapper.timings), 1),
        "max_s": max(wrapper.timings, default=0.0),
    }
    return wrapper


class StepTimer:
    """Device-synchronized step timing windows.

    Usage: `timer.start()` before a span of steps, `timer.stop(n_steps,
    n_tokens, sync=out)` after — `sync` is any device value from the last
    step; it is block_until_ready'd (and, under experimental backends whose
    ready-signal is unreliable, fetched to host) before the clock stops, so
    the window measures execution, not dispatch. Aggregates per-window
    tokens/sec and step time.
    """

    def __init__(self):
        self.windows: List[Dict[str, float]] = []
        self._t0: Optional[float] = None

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self, n_steps: int, n_tokens: int, sync: Any = None) -> Dict[str, float]:
        if sync is not None:
            sync = jax.block_until_ready(sync)
            leaves = jax.tree.leaves(sync)
            if leaves:  # force a host round-trip: dispatch can't hide here
                jax.device_get(leaves[0])
        dt = time.perf_counter() - (self._t0 or time.perf_counter())
        window = {
            "seconds": dt,
            "steps": n_steps,
            "tokens": n_tokens,
            "step_ms": dt / max(n_steps, 1) * 1e3,
            "tokens_per_sec": n_tokens / dt if dt > 0 else 0.0,
        }
        self.windows.append(window)
        self._t0 = None
        return window

    def summary(self) -> Dict[str, float]:
        if not self.windows:
            return {"windows": 0}
        tot_s = sum(w["seconds"] for w in self.windows)
        tot_tok = sum(w["tokens"] for w in self.windows)
        tot_steps = sum(w["steps"] for w in self.windows)
        return {
            "windows": len(self.windows),
            "seconds": tot_s,
            "steps": tot_steps,
            "tokens": tot_tok,
            "step_ms": tot_s / max(tot_steps, 1) * 1e3,
            "tokens_per_sec": tot_tok / tot_s if tot_s > 0 else 0.0,
        }


class SectionTimer:
    """Named wall-clock sections for host-side phases (data loading,
    checkpointing, eval) — complements StepTimer's device windows."""

    def __init__(self):
        self.totals: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)

    @contextlib.contextmanager
    def section(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.totals[name] += time.perf_counter() - t0
            self.counts[name] += 1

    def summary(self) -> Dict[str, Dict[str, float]]:
        return {
            name: {
                "total_s": self.totals[name],
                "count": self.counts[name],
                "mean_s": self.totals[name] / max(self.counts[name], 1),
            }
            for name in self.totals
        }
