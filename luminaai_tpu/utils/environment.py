"""Environment detection: devices, topology, memory, recommended config.

Covers the reference environment module (ref: Src/Main_Scripts/utils/
environment.py — get_system_info, GPU/accelerator introspection, memory
estimates, recommended-config selection), re-targeted at JAX/TPU: the
accelerator story is `jax.devices()` + device memory_stats, topology is the
process/host layout JAX exposes, and the recommendation maps model memory
needs onto a mesh (fsdp/tp/ep) instead of CUDA settings.
"""

from __future__ import annotations

import os
import platform
import sys
from typing import Any, Dict, List, Optional

# Per-chip HBM for known TPU generations (GiB). Used when memory_stats()
# is unavailable (e.g. CPU hosts, some plugin backends).
_TPU_HBM_GB = {
    "v4": 32.0,
    "v5 lite": 16.0,
    "v5e": 16.0,
    "v5p": 95.0,
    "v6 lite": 32.0,
    "v6e": 32.0,
}

# Per-chip bf16 peak (FLOP/s) by generation — the MFU denominator.
# Public figures: v4 275T, v5e 197T, v5p 459T, v6e (Trillium) 918T.
_TPU_PEAK_FLOPS = {
    "v4": 275e12,
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6 lite": 918e12,
    "v6e": 918e12,
}


def _lookup_by_device_kind(kind: str, table: Dict[str, float], default):
    """Substring match of a device_kind against a generation table —
    shared by the HBM and peak-FLOPs lookups so they can't drift."""
    kind = kind.lower()
    for key, val in table.items():
        if key in kind:
            return val
    return default


def device_peak_flops(device=None, default: float = 197e12) -> float:
    """bf16 peak FLOP/s for `device` (default: jax.devices()[0]) from the
    generation table; `default` (v5e) when the kind is unknown. Keeps MFU
    honest across chip generations instead of hardcoding one part."""
    if device is None:
        import jax

        device = jax.devices()[0]
    return _lookup_by_device_kind(
        getattr(device, "device_kind", ""), _TPU_PEAK_FLOPS, default
    )


def get_system_info() -> Dict[str, Any]:
    """Host-side software/hardware summary (ref environment.py
    get_system_info)."""
    info: Dict[str, Any] = {
        "python_version": sys.version.split()[0],
        "platform": platform.platform(),
        "cpu_count": os.cpu_count(),
    }
    try:
        import jax

        info["jax_version"] = jax.__version__
    except Exception:  # pragma: no cover - jax is a hard dep in practice
        info["jax_version"] = None
    try:
        import flax

        info["flax_version"] = flax.__version__
    except Exception:  # pragma: no cover
        info["flax_version"] = None
    try:
        page = os.sysconf("SC_PAGE_SIZE")
        info["host_memory_gb"] = round(
            page * os.sysconf("SC_PHYS_PAGES") / 1e9, 2
        )
        info["host_memory_available_gb"] = round(
            page * os.sysconf("SC_AVPHYS_PAGES") / 1e9, 2
        )
    except (ValueError, OSError):  # pragma: no cover - non-POSIX
        pass
    return info


def _device_memory_gb(device) -> Optional[float]:
    """Best-effort per-device memory: live stats, else known HBM table."""
    try:
        stats = device.memory_stats()
        if stats and "bytes_limit" in stats:
            return round(stats["bytes_limit"] / 1e9, 2)
    except Exception:
        pass
    return _lookup_by_device_kind(
        getattr(device, "device_kind", ""), _TPU_HBM_GB, None
    )


def get_device_info() -> Dict[str, Any]:
    """Accelerator summary (ref environment.py CUDA introspection block)."""
    import jax

    devices = jax.devices()
    d0 = devices[0]
    info: Dict[str, Any] = {
        "platform": d0.platform,
        "device_count": len(devices),
        "local_device_count": jax.local_device_count(),
        "device_kind": getattr(d0, "device_kind", "unknown"),
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "memory_per_device_gb": _device_memory_gb(d0),
    }
    coords = getattr(d0, "coords", None)
    if coords is not None:
        info["topology_coords_present"] = True
        # Bounding box of chip coordinates ~ slice shape.
        all_coords = [d.coords for d in devices if hasattr(d, "coords")]
        if all_coords:
            dims = len(all_coords[0])
            info["topology_shape"] = tuple(
                max(c[i] for c in all_coords) + 1 for i in range(dims)
            )
    return info


def get_topology() -> Dict[str, Any]:
    """Process/host layout for multi-host planning (ref topology probing)."""
    import jax

    return {
        "process_count": jax.process_count(),
        "process_index": jax.process_index(),
        "devices_per_process": jax.local_device_count(),
        "global_devices": jax.device_count(),
    }


def estimate_training_memory_gb(config) -> Dict[str, float]:
    """Per-chip HBM need for a config under its parallelism settings."""
    est = config.memory_estimate_gb()
    model_shards = max(
        1,
        config.fsdp_parallel_size
        * max(1, config.tensor_parallel_size)
        * max(1, config.expert_parallel_size),
    )
    per_chip = {
        "params_gb": est["parameters_gb"] / model_shards,
        "optimizer_gb": est["optimizer_gb"] / model_shards,
        "activations_gb": est["activations_gb"],
        "total_gb": (est["parameters_gb"] + est["optimizer_gb"]) / model_shards
        + est["activations_gb"],
    }
    return {k: round(v, 3) for k, v in per_chip.items()}


def check_config_fits(config, n_devices: Optional[int] = None) -> Dict[str, Any]:
    """Does this config fit the detected hardware? (ref recommended-config
    validation). Returns {fits, per_chip_gb, available_gb, detail}."""
    dev = get_device_info()
    hbm = dev.get("memory_per_device_gb") or 16.0
    need = estimate_training_memory_gb(config)
    # config.max_memory_usage caps usable HBM (headroom for XLA scratch).
    budget = getattr(config, "max_memory_usage", 0.9)
    fits = need["total_gb"] <= hbm * budget
    return {
        "fits": fits,
        "per_chip_gb": need["total_gb"],
        "available_gb": hbm,
        "platform": dev["platform"],
        "device_count": n_devices or dev["device_count"],
        "detail": need,
    }


def recommend_preset(n_devices: Optional[int] = None) -> str:
    """Pick the largest preset that fits the detected fleet (ref
    environment.py recommended-config logic)."""
    from luminaai_tpu.config import ConfigPresets

    dev = get_device_info()
    n = n_devices or dev["device_count"]
    hbm = dev.get("memory_per_device_gb") or 16.0
    budget_gb = n * hbm * 0.92
    best = "debug"
    for name in ConfigPresets.available():
        cfg = ConfigPresets.get(name)
        total = cfg.memory_estimate_gb()
        need = total["parameters_gb"] + total["optimizer_gb"]
        if need <= budget_gb and cfg.estimate_parameters() > (
            ConfigPresets.get(best).estimate_parameters()
        ):
            best = name
    return best


_PROBE_CODE = """
import json, time
import jax, jax.numpy as jnp
t0 = time.perf_counter()
x = jnp.ones((512, 512), jnp.bfloat16)
float((x @ x).sum())
cold = time.perf_counter() - t0
t0 = time.perf_counter()
float((x @ x).sum())
warm = time.perf_counter() - t0
d = jax.devices()[0]
try:
    stats = d.memory_stats() or {}
except Exception:
    stats = {}
print(json.dumps({
    "platform": d.platform,
    "devices": jax.device_count(),
    "device_kind": getattr(d, "device_kind", "unknown"),
    "cold_matmul_s": round(cold, 2),
    "warm_matmul_s": round(warm, 4),
    "hbm_in_use_gb": (
        round(stats["bytes_in_use"] / 1e9, 3)
        if "bytes_in_use" in stats else None
    ),
    "hbm_limit_gb": (
        round(stats["bytes_limit"] / 1e9, 2)
        if "bytes_limit" in stats else None
    ),
}))
"""


def tpu_runtime_diagnostics(probe_timeout: int = 90) -> Dict[str, Any]:
    """Runtime probes for `cli diagnose` — the TPU counterpart of the
    reference's cuda_debug_script.py allocator/kernel diagnosis.

    Three findings an operator keeps rediscovering by hand here:
      - backend reachability, via a REAL matmul in a subprocess with a
        hard timeout (a dead tunnel HANGS rather than erroring, so an
        in-process probe would wedge the diagnosing tool itself);
      - HBM occupancy/limit from live memory_stats;
      - persistent XLA compile-cache state (entries, size, freshness —
        a cold cache explains a 'slow first step' report).
    """
    import glob
    import json as _json
    import subprocess
    import time as _time

    out: Dict[str, Any] = {}
    t0 = _time.monotonic()
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _PROBE_CODE],
            capture_output=True,
            text=True,
            timeout=probe_timeout,
        )
        dt = round(_time.monotonic() - t0, 1)
        if proc.returncode == 0:
            try:
                probe = _json.loads(proc.stdout.strip().splitlines()[-1])
            except (ValueError, IndexError):
                probe = {"raw": proc.stdout[-200:]}
            out["backend"] = {
                "status": "ok", "probe_seconds": dt, **probe,
            }
        else:
            err = (proc.stderr or "").strip().splitlines()
            out["backend"] = {
                "status": "error",
                "probe_seconds": dt,
                "last_error": err[-1][-200:] if err else f"rc={proc.returncode}",
            }
    except subprocess.TimeoutExpired:
        out["backend"] = {
            "status": "hung",
            "probe_seconds": probe_timeout,
            "hint": (
                "probe hung past the timeout — the dead-tunnel signature "
                "(a configured-but-unreachable TPU backend hangs on init); "
                "retry later or force CPU with PYTHONPATH= JAX_PLATFORMS=cpu"
            ),
        }

    cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if not cache_dir:
        # bench/sweep processes share this repo-local cache (bench_common).
        candidate = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)
            ))),
            ".jax_cache",
        )
        cache_dir = candidate if os.path.isdir(candidate) else None
    if cache_dir and os.path.isdir(cache_dir):
        # Stat each entry once, tolerating concurrent eviction (bench/
        # sweep processes share this dir and JAX rewrites entries).
        sizes, mtimes = [], []
        for e in glob.glob(os.path.join(cache_dir, "*")):
            try:
                st = os.stat(e)
            except OSError:
                continue
            sizes.append(st.st_size)
            mtimes.append(st.st_mtime)
        out["compile_cache"] = {
            "dir": cache_dir,
            "entries": len(sizes),
            "total_mb": round(sum(sizes) / 1e6, 1),
            "newest_age_s": (
                round(_time.time() - max(mtimes)) if mtimes else None
            ),
        }
    else:
        out["compile_cache"] = {
            "dir": None,
            "note": "no persistent compile cache configured "
                    "(set JAX_COMPILATION_CACHE_DIR)",
        }
    return out


def connectivity_probe(
    payload_mb: float = 4.0, iters: int = 5, registry=None
) -> Dict[str, Any]:
    """ICI/DCN connectivity probe for `cli diagnose` (the role of the
    reference's scripts/net.sh bandwidth/reachability check, TPU-side).

    Two findings an operator needs before debugging a slow or wedged
    multi-host job:

      - **per-host device visibility**: every process must see the same
        global device count and `process_count * local_device_count`
        must cover it — a host whose NICs came up without its ICI links
        shows up here, before a collective hangs;
      - **a small timed all-reduce per mesh axis**: `ici` (devices within
        this host's slice) and, when multiple processes exist, `dcn`
        (across hosts). A healthy axis completes in milliseconds;
        an axis that is orders of magnitude off its peers localizes the
        sick interconnect tier.

    Results are returned AND exported as `diagnose_*` gauges into the
    unified registry so a scraped `/metrics` carries the last probe.
    CPU-safe: on a single-host CPU backend the mesh degenerates to one
    `ici` axis of size (1..n_local) and the psum still executes — the
    numbers then validate the probe machinery, not an interconnect.

    Call only after backend reachability is established (cli diagnose's
    subprocess probe): initializing a dead TPU backend in-process hangs.
    """
    import time as _time

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    from luminaai_tpu.monitoring.telemetry import get_registry
    # The version-compat wrapper, NOT jax.experimental.shard_map: the
    # experimental module's signature drifted across the 0.4.x line and
    # broke on this container's jax (astlint rule LX001 pins the wrapper
    # as the one sanctioned entry point).
    from luminaai_tpu.parallel.mesh import shard_map

    registry = registry or get_registry()
    n_proc = jax.process_count()
    n_local = jax.local_device_count()
    n_global = jax.device_count()
    visibility: Dict[str, Any] = {
        "process_count": n_proc,
        "process_index": jax.process_index(),
        "local_device_count": n_local,
        "global_device_count": n_global,
        "platform": jax.devices()[0].platform,
        "device_kind": getattr(jax.devices()[0], "device_kind", "?"),
        # Every device must belong to exactly one process and the global
        # view must tile evenly across hosts; False here means a host
        # joined the job blind to part of the slice.
        "visibility_ok": n_global == n_proc * n_local,
    }
    out: Dict[str, Any] = {"visibility": visibility, "allreduce": {}}
    _export_visibility_gauges(registry, visibility)

    if n_global % n_proc != 0:
        # A degraded slice (a host lost part of its devices) cannot form
        # the dcn×ici grid — and is exactly what this probe exists to
        # surface. The visibility report above already says which host
        # count is wrong; skip the all-reduce instead of crashing past
        # the evidence.
        out["allreduce"]["skipped"] = (
            f"device grid is ragged ({n_global} devices across {n_proc} "
            "processes): cannot build a dcn×ici mesh — see visibility"
        )
        return out

    # dcn × ici factorization of the device grid: hosts on the slow axis,
    # local chips on the fast one. Single-host runs probe ici only.
    devices = np.array(jax.devices()).reshape(n_proc, n_global // n_proc)
    mesh = Mesh(devices, ("dcn", "ici"))
    n_elems = max(1, int(payload_mb * 1e6 / 4))  # fp32 words

    for axis in ("ici", "dcn") if n_proc > 1 else ("ici",):
        axis_size = mesh.shape[axis]

        @jax.jit
        def _allreduce(x, axis=axis):
            return shard_map(
                lambda v: jax.lax.psum(v, axis),
                mesh=mesh,
                in_specs=PartitionSpec(axis),
                out_specs=PartitionSpec(),
            )(x)

        # Pad the payload up to a multiple of the axis size so the
        # leading dim shards evenly on odd device counts.
        length = -(-n_elems // axis_size) * axis_size
        x = jax.device_put(
            jnp.ones((length,), jnp.float32),
            NamedSharding(mesh, PartitionSpec(axis)),
        )
        try:
            _allreduce(x).block_until_ready()  # compile
            t0 = _time.perf_counter()
            for _ in range(iters):
                y = _allreduce(x)
            y.block_until_ready()
            dt = (_time.perf_counter() - t0) / iters
        except Exception as e:  # probe must never wedge diagnose
            out["allreduce"][axis] = {
                "size": axis_size, "error": f"{type(e).__name__}: {e}"
            }
            continue
        payload_bytes = x.size * 4
        out["allreduce"][axis] = {
            "size": axis_size,
            "payload_mb": round(payload_bytes / 1e6, 2),
            "mean_seconds": round(dt, 6),
            # Algorithmic bandwidth: bytes reduced per second. A size-1
            # axis reports it for completeness, but it measures copy
            # speed, not an interconnect.
            "algo_gbps": round(payload_bytes / max(dt, 1e-9) / 1e9, 3),
        }

    ar_s = registry.gauge(
        "diagnose_allreduce_seconds",
        "Mean timed all-reduce per mesh axis at last diagnose",
        labelnames=("axis",),
    )
    ar_bw = registry.gauge(
        "diagnose_allreduce_gbps",
        "Algorithmic all-reduce bandwidth per mesh axis at last diagnose",
        labelnames=("axis",),
    )
    for axis, rec in out["allreduce"].items():
        if isinstance(rec, dict) and "mean_seconds" in rec:
            ar_s.labels(axis=axis).set(rec["mean_seconds"])
            ar_bw.labels(axis=axis).set(rec["algo_gbps"])
    return out


def _export_visibility_gauges(registry, visibility: Dict[str, Any]) -> None:
    """diagnose_* visibility gauges — exported BEFORE any mesh math so a
    degraded slice (the case the probe exists for) still reports. Names
    avoid the _count suffix: the registry reserves histogram exposition
    suffixes _bucket/_sum/_count for histogram families."""
    g = registry.gauge
    g("diagnose_processes", "Hosts in the job at last diagnose").set(
        visibility["process_count"]
    )
    g(
        "diagnose_local_devices", "Devices visible to this process"
    ).set(visibility["local_device_count"])
    g(
        "diagnose_global_devices", "Global devices at last diagnose"
    ).set(visibility["global_device_count"])
    g(
        "diagnose_device_visibility_ok",
        "1 when global devices == process_count * local devices",
    ).set(1.0 if visibility["visibility_ok"] else 0.0)


def format_diagnostics(include_accelerator: bool = True) -> str:
    """Human-readable diagnostics block (ref Main.py:619
    print_system_diagnostics).

    include_accelerator=False skips every jax touch: initializing a
    configured-but-unreachable TPU backend HANGS in-process, so callers
    that have just probed the backend as dead (cli diagnose) must be able
    to print host facts without wedging."""
    lines: List[str] = ["=" * 64, "SYSTEM DIAGNOSTICS", "=" * 64]
    sysinfo = get_system_info()
    lines.append("[host]")
    for k, v in sysinfo.items():
        lines.append(f"  {k}: {v}")
    if not include_accelerator:
        lines.append("[accelerator] skipped: backend probe did not answer")
        lines.append("=" * 64)
        return "\n".join(lines)
    try:
        dev = get_device_info()
        lines.append("[accelerator]")
        for k, v in dev.items():
            lines.append(f"  {k}: {v}")
        topo = get_topology()
        lines.append("[topology]")
        for k, v in topo.items():
            lines.append(f"  {k}: {v}")
    except Exception as e:  # backend can be unavailable (tunnel flake)
        lines.append(f"[accelerator] unavailable: {e}")
    lines.append("=" * 64)
    return "\n".join(lines)
