"""Environment detection: devices, topology, memory, recommended config.

Covers the reference environment module (ref: Src/Main_Scripts/utils/
environment.py — get_system_info, GPU/accelerator introspection, memory
estimates, recommended-config selection), re-targeted at JAX/TPU: the
accelerator story is `jax.devices()` + device memory_stats, topology is the
process/host layout JAX exposes, and the recommendation maps model memory
needs onto a mesh (fsdp/tp/ep) instead of CUDA settings.
"""

from __future__ import annotations

import os
import platform
import sys
from typing import Any, Dict, List, Optional

# Per-chip HBM for known TPU generations (GiB). Used when memory_stats()
# is unavailable (e.g. CPU hosts, some plugin backends).
_TPU_HBM_GB = {
    "v4": 32.0,
    "v5 lite": 16.0,
    "v5e": 16.0,
    "v5p": 95.0,
    "v6 lite": 32.0,
    "v6e": 32.0,
}

# Per-chip bf16 peak (FLOP/s) by generation — the MFU denominator.
# Public figures: v4 275T, v5e 197T, v5p 459T, v6e (Trillium) 918T.
_TPU_PEAK_FLOPS = {
    "v4": 275e12,
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6 lite": 918e12,
    "v6e": 918e12,
}


def _lookup_by_device_kind(kind: str, table: Dict[str, float], default):
    """Substring match of a device_kind against a generation table —
    shared by the HBM and peak-FLOPs lookups so they can't drift."""
    kind = kind.lower()
    for key, val in table.items():
        if key in kind:
            return val
    return default


def device_peak_flops(device=None, default: float = 197e12) -> float:
    """bf16 peak FLOP/s for `device` (default: jax.devices()[0]) from the
    generation table; `default` (v5e) when the kind is unknown. Keeps MFU
    honest across chip generations instead of hardcoding one part."""
    if device is None:
        import jax

        device = jax.devices()[0]
    return _lookup_by_device_kind(
        getattr(device, "device_kind", ""), _TPU_PEAK_FLOPS, default
    )


def get_system_info() -> Dict[str, Any]:
    """Host-side software/hardware summary (ref environment.py
    get_system_info)."""
    info: Dict[str, Any] = {
        "python_version": sys.version.split()[0],
        "platform": platform.platform(),
        "cpu_count": os.cpu_count(),
    }
    try:
        import jax

        info["jax_version"] = jax.__version__
    except Exception:  # pragma: no cover - jax is a hard dep in practice
        info["jax_version"] = None
    try:
        import flax

        info["flax_version"] = flax.__version__
    except Exception:  # pragma: no cover
        info["flax_version"] = None
    try:
        page = os.sysconf("SC_PAGE_SIZE")
        info["host_memory_gb"] = round(
            page * os.sysconf("SC_PHYS_PAGES") / 1e9, 2
        )
        info["host_memory_available_gb"] = round(
            page * os.sysconf("SC_AVPHYS_PAGES") / 1e9, 2
        )
    except (ValueError, OSError):  # pragma: no cover - non-POSIX
        pass
    return info


def _device_memory_gb(device) -> Optional[float]:
    """Best-effort per-device memory: live stats, else known HBM table."""
    try:
        stats = device.memory_stats()
        if stats and "bytes_limit" in stats:
            return round(stats["bytes_limit"] / 1e9, 2)
    except Exception:
        pass
    return _lookup_by_device_kind(
        getattr(device, "device_kind", ""), _TPU_HBM_GB, None
    )


def get_device_info() -> Dict[str, Any]:
    """Accelerator summary (ref environment.py CUDA introspection block)."""
    import jax

    devices = jax.devices()
    d0 = devices[0]
    info: Dict[str, Any] = {
        "platform": d0.platform,
        "device_count": len(devices),
        "local_device_count": jax.local_device_count(),
        "device_kind": getattr(d0, "device_kind", "unknown"),
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "memory_per_device_gb": _device_memory_gb(d0),
    }
    coords = getattr(d0, "coords", None)
    if coords is not None:
        info["topology_coords_present"] = True
        # Bounding box of chip coordinates ~ slice shape.
        all_coords = [d.coords for d in devices if hasattr(d, "coords")]
        if all_coords:
            dims = len(all_coords[0])
            info["topology_shape"] = tuple(
                max(c[i] for c in all_coords) + 1 for i in range(dims)
            )
    return info


def get_topology() -> Dict[str, Any]:
    """Process/host layout for multi-host planning (ref topology probing)."""
    import jax

    return {
        "process_count": jax.process_count(),
        "process_index": jax.process_index(),
        "devices_per_process": jax.local_device_count(),
        "global_devices": jax.device_count(),
    }


def estimate_training_memory_gb(config) -> Dict[str, float]:
    """Per-chip HBM need for a config under its parallelism settings."""
    est = config.memory_estimate_gb()
    model_shards = max(
        1,
        config.fsdp_parallel_size
        * max(1, config.tensor_parallel_size)
        * max(1, config.expert_parallel_size),
    )
    per_chip = {
        "params_gb": est["parameters_gb"] / model_shards,
        "optimizer_gb": est["optimizer_gb"] / model_shards,
        "activations_gb": est["activations_gb"],
        "total_gb": (est["parameters_gb"] + est["optimizer_gb"]) / model_shards
        + est["activations_gb"],
    }
    return {k: round(v, 3) for k, v in per_chip.items()}


def check_config_fits(config, n_devices: Optional[int] = None) -> Dict[str, Any]:
    """Does this config fit the detected hardware? (ref recommended-config
    validation). Returns {fits, per_chip_gb, available_gb, detail}."""
    dev = get_device_info()
    hbm = dev.get("memory_per_device_gb") or 16.0
    need = estimate_training_memory_gb(config)
    # config.max_memory_usage caps usable HBM (headroom for XLA scratch).
    budget = getattr(config, "max_memory_usage", 0.9)
    fits = need["total_gb"] <= hbm * budget
    return {
        "fits": fits,
        "per_chip_gb": need["total_gb"],
        "available_gb": hbm,
        "platform": dev["platform"],
        "device_count": n_devices or dev["device_count"],
        "detail": need,
    }


def recommend_preset(n_devices: Optional[int] = None) -> str:
    """Pick the largest preset that fits the detected fleet (ref
    environment.py recommended-config logic)."""
    from luminaai_tpu.config import ConfigPresets

    dev = get_device_info()
    n = n_devices or dev["device_count"]
    hbm = dev.get("memory_per_device_gb") or 16.0
    budget_gb = n * hbm * 0.92
    best = "debug"
    for name in ConfigPresets.available():
        cfg = ConfigPresets.get(name)
        total = cfg.memory_estimate_gb()
        need = total["parameters_gb"] + total["optimizer_gb"]
        if need <= budget_gb and cfg.estimate_parameters() > (
            ConfigPresets.get(best).estimate_parameters()
        ):
            best = name
    return best


_PROBE_CODE = """
import json, time
import jax, jax.numpy as jnp
t0 = time.perf_counter()
x = jnp.ones((512, 512), jnp.bfloat16)
float((x @ x).sum())
cold = time.perf_counter() - t0
t0 = time.perf_counter()
float((x @ x).sum())
warm = time.perf_counter() - t0
d = jax.devices()[0]
try:
    stats = d.memory_stats() or {}
except Exception:
    stats = {}
print(json.dumps({
    "platform": d.platform,
    "devices": jax.device_count(),
    "device_kind": getattr(d, "device_kind", "unknown"),
    "cold_matmul_s": round(cold, 2),
    "warm_matmul_s": round(warm, 4),
    "hbm_in_use_gb": (
        round(stats["bytes_in_use"] / 1e9, 3)
        if "bytes_in_use" in stats else None
    ),
    "hbm_limit_gb": (
        round(stats["bytes_limit"] / 1e9, 2)
        if "bytes_limit" in stats else None
    ),
}))
"""


def tpu_runtime_diagnostics(probe_timeout: int = 90) -> Dict[str, Any]:
    """Runtime probes for `cli diagnose` — the TPU counterpart of the
    reference's cuda_debug_script.py allocator/kernel diagnosis.

    Three findings an operator keeps rediscovering by hand here:
      - backend reachability, via a REAL matmul in a subprocess with a
        hard timeout (a dead tunnel HANGS rather than erroring, so an
        in-process probe would wedge the diagnosing tool itself);
      - HBM occupancy/limit from live memory_stats;
      - persistent XLA compile-cache state (entries, size, freshness —
        a cold cache explains a 'slow first step' report).
    """
    import glob
    import json as _json
    import subprocess
    import time as _time

    out: Dict[str, Any] = {}
    t0 = _time.monotonic()
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _PROBE_CODE],
            capture_output=True,
            text=True,
            timeout=probe_timeout,
        )
        dt = round(_time.monotonic() - t0, 1)
        if proc.returncode == 0:
            try:
                probe = _json.loads(proc.stdout.strip().splitlines()[-1])
            except (ValueError, IndexError):
                probe = {"raw": proc.stdout[-200:]}
            out["backend"] = {
                "status": "ok", "probe_seconds": dt, **probe,
            }
        else:
            err = (proc.stderr or "").strip().splitlines()
            out["backend"] = {
                "status": "error",
                "probe_seconds": dt,
                "last_error": err[-1][-200:] if err else f"rc={proc.returncode}",
            }
    except subprocess.TimeoutExpired:
        out["backend"] = {
            "status": "hung",
            "probe_seconds": probe_timeout,
            "hint": (
                "probe hung past the timeout — the dead-tunnel signature "
                "(a configured-but-unreachable TPU backend hangs on init); "
                "retry later or force CPU with PYTHONPATH= JAX_PLATFORMS=cpu"
            ),
        }

    cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if not cache_dir:
        # bench/sweep processes share this repo-local cache (bench_common).
        candidate = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)
            ))),
            ".jax_cache",
        )
        cache_dir = candidate if os.path.isdir(candidate) else None
    if cache_dir and os.path.isdir(cache_dir):
        # Stat each entry once, tolerating concurrent eviction (bench/
        # sweep processes share this dir and JAX rewrites entries).
        sizes, mtimes = [], []
        for e in glob.glob(os.path.join(cache_dir, "*")):
            try:
                st = os.stat(e)
            except OSError:
                continue
            sizes.append(st.st_size)
            mtimes.append(st.st_mtime)
        out["compile_cache"] = {
            "dir": cache_dir,
            "entries": len(sizes),
            "total_mb": round(sum(sizes) / 1e6, 1),
            "newest_age_s": (
                round(_time.time() - max(mtimes)) if mtimes else None
            ),
        }
    else:
        out["compile_cache"] = {
            "dir": None,
            "note": "no persistent compile cache configured "
                    "(set JAX_COMPILATION_CACHE_DIR)",
        }
    return out


def format_diagnostics(include_accelerator: bool = True) -> str:
    """Human-readable diagnostics block (ref Main.py:619
    print_system_diagnostics).

    include_accelerator=False skips every jax touch: initializing a
    configured-but-unreachable TPU backend HANGS in-process, so callers
    that have just probed the backend as dead (cli diagnose) must be able
    to print host facts without wedging."""
    lines: List[str] = ["=" * 64, "SYSTEM DIAGNOSTICS", "=" * 64]
    sysinfo = get_system_info()
    lines.append("[host]")
    for k, v in sysinfo.items():
        lines.append(f"  {k}: {v}")
    if not include_accelerator:
        lines.append("[accelerator] skipped: backend probe did not answer")
        lines.append("=" * 64)
        return "\n".join(lines)
    try:
        dev = get_device_info()
        lines.append("[accelerator]")
        for k, v in dev.items():
            lines.append(f"  {k}: {v}")
        topo = get_topology()
        lines.append("[topology]")
        for k, v in topo.items():
            lines.append(f"  {k}: {v}")
    except Exception as e:  # backend can be unavailable (tunnel flake)
        lines.append(f"[accelerator] unavailable: {e}")
    lines.append("=" * 64)
    return "\n".join(lines)
