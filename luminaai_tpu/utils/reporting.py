"""Data and training reports (ref: Src/Main_Scripts/utils/reporting.py).

Same two entry points as the reference — a dataset analysis report over
jsonl conversation files and a post-run training report over an experiment
directory — emitting self-contained HTML (parity) from the repo's own
validation (`data/processing.validate_data_comprehensive`) and metrics
formats (`monitoring/logger` jsonl, trainer summary json).
"""

from __future__ import annotations

import json
import logging
import os
from datetime import datetime
from pathlib import Path
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

_PAGE_STYLE = """
body { font-family: sans-serif; margin: 20px; }
.section { margin: 20px 0; padding: 15px; border: 1px solid #ddd; border-radius: 5px; }
.metric { display: inline-block; margin: 8px; padding: 8px 12px; background: #f5f5f5; border-radius: 3px; }
.error { color: #b00; }
table { border-collapse: collapse; width: 100%; }
th, td { border: 1px solid #ddd; padding: 6px 8px; text-align: left; }
th { background: #f2f2f2; }
"""


def _page(title: str, body: str) -> str:
    now = datetime.now().strftime("%Y-%m-%d %H:%M:%S")
    return (
        f"<!DOCTYPE html><html><head><title>{title}</title>"
        f"<style>{_PAGE_STYLE}</style></head><body>"
        f"<h1>{title}</h1><p>Generated on: {now}</p>{body}</body></html>"
    )


def _metric(label: str, value: Any) -> str:
    return f'<div class="metric">{label}: {value}</div>'


def create_data_summary_report(
    data_paths: List[str],
    tokenizer,
    output_path: str = "data_summary_report.html",
) -> str:
    """Dataset analysis report (ref reporting.py:11).

    Runs validate_data_comprehensive per file; renders file info, conversation
    stats, token stats, role distribution, and sample quality issues.
    """
    from luminaai_tpu.data.processing import validate_data_comprehensive

    sections = []
    for data_path in data_paths:
        logger.info("Analyzing %s...", data_path)
        stats = validate_data_comprehensive(data_path, tokenizer)
        tok = stats.get("token_stats", {})
        issues = stats.get("issues", {})
        checked = stats.get("checked", 0)
        valid = stats.get("valid", 0)

        try:
            st = os.stat(data_path)
            size_mb = st.st_size / 1e6
            modified = datetime.fromtimestamp(st.st_mtime).strftime(
                "%Y-%m-%d %H:%M:%S"
            )
        except OSError:
            size_mb, modified = 0.0, "Unknown"

        issue_rows = "".join(
            f"<tr><td>{kind}</td><td>{count:,}</td></tr>"
            for kind, count in sorted(issues.items())
        )
        issue_list = "".join(
            f'<li class="error">{kind}: {count}</li>'
            for kind, count in issues.items()
            if count
        )
        sections.append(
            f'<div class="section"><h2>Dataset: {os.path.basename(data_path)}</h2>'
            "<h3>File Information</h3>"
            + _metric("Size", f"{size_mb:.1f} MB")
            + _metric("Modified", modified)
            + "<h3>Conversation Statistics</h3>"
            + _metric("Checked", f"{checked:,}")
            + _metric("Valid Conversations", f"{valid:,}")
            + _metric(
                "Success Rate", f"{valid / checked:.2%}" if checked else "n/a"
            )
            + "<h3>Token Statistics</h3>"
            + _metric("Avg Tokens", f"{tok.get('mean', 0):.1f}")
            + _metric("P95 Tokens", f"{tok.get('p95', 0):,.0f}")
            + _metric("Max Tokens", f"{tok.get('max', 0):,}")
            + "<h3>Issue Breakdown</h3>"
            f"<table><tr><th>Issue</th><th>Count</th></tr>{issue_rows}</table>"
            f"<h3>Problems Found</h3><ul>{issue_list or '<li>none</li>'}</ul></div>"
        )

    html = _page("Dataset Analysis Report", "".join(sections))
    with open(output_path, "w") as f:
        f.write(html)
    logger.info("Data summary report saved: %s", output_path)
    return str(output_path)


def create_training_report(
    experiment_path: str, output_path: Optional[str] = None
) -> Optional[str]:
    """Post-run training report (ref reporting.py:96).

    Reads `training_summary.json` (written by the trainer/CLI) and the
    metrics jsonl; renders run summary, key config, health, and final
    metric values.
    """
    experiment_dir = Path(experiment_path)
    if output_path is None:
        output_path = experiment_dir / "training_report.html"

    summary_file = experiment_dir / "training_summary.json"
    if not summary_file.exists():
        logger.error("Training summary not found: %s", summary_file)
        return None
    summary = json.loads(summary_file.read_text())

    metrics: List[Dict[str, Any]] = []
    for candidate in (
        experiment_dir / "metrics.jsonl",
        experiment_dir / "logs" / "metrics.jsonl",
    ):
        if candidate.exists():
            with open(candidate) as f:
                metrics = [json.loads(line) for line in f if line.strip()]
            break

    body = ['<div class="section"><h3>Training Summary</h3>']
    for label, key, fmt in (
        ("Total Time", "total_training_time_hours", "{:.2f} h"),
        ("Total Epochs", "total_epochs", "{}"),
        ("Total Steps", "total_steps", "{}"),
        ("Best Eval Loss", "best_eval_loss", "{:.6f}"),
        ("Final Train Loss", "final_train_loss", "{:.6f}"),
    ):
        value = summary.get(key, summary.get("final_metrics", {}).get(key))
        if value is not None:
            body.append(_metric(label, fmt.format(value)))
    body.append("</div>")

    config = summary.get("model_config", summary.get("config", {}))
    if config:
        rows = "".join(
            f"<tr><td>{k}</td><td>{config[k]}</td></tr>"
            for k in (
                "hidden_size", "num_layers", "num_heads", "seq_length",
                "batch_size", "learning_rate", "num_epochs", "precision",
                "use_moe", "num_experts",
            )
            if k in config
        )
        body.append(
            '<div class="section"><h3>Model Configuration</h3>'
            f"<table><tr><th>Parameter</th><th>Value</th></tr>{rows}</table></div>"
        )

    health = summary.get("health_summary", {})
    if health:
        body.append(
            '<div class="section"><h3>Health Summary</h3>'
            + _metric("Status", health.get("status", "Unknown"))
            + _metric("Health Score", f"{health.get('health_score', 0):.2f}")
            + _metric("Alerts", health.get("total_alerts", 0))
            + "</div>"
        )

    if metrics:
        last = metrics[-1]
        rows = "".join(
            f"<tr><td>{k}</td><td>{v}</td></tr>"
            for k, v in sorted(last.items())
            if isinstance(v, (int, float))
        )
        body.append(
            f'<div class="section"><h3>Final Metrics (step {last.get("step", "?")},'
            f" {len(metrics)} records)</h3>"
            f"<table><tr><th>Metric</th><th>Value</th></tr>{rows}</table></div>"
        )

    html = _page(
        f"Training Report - {summary.get('experiment_name', experiment_dir.name)}",
        "".join(body),
    )
    with open(output_path, "w") as f:
        f.write(html)
    logger.info("Training report saved: %s", output_path)
    return str(output_path)
