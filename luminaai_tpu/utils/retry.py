"""Durable I/O: a reusable retry policy for flaky storage.

At production scale (ROADMAP: "millions of users"), transient storage
faults — a GCS 503 surfacing as `OSError`, an NFS server hiccup, a
momentary `ConnectionError` — are routine, and before this layer one of
them anywhere in `CheckpointManager.save`/`restore` or the data readers
killed the run. Every durable-I/O call site now routes through a
`RetryPolicy`: exponential backoff with jitter, a per-op deadline,
transient-vs-permanent error classification, and injectable clock/sleep
so tests drive the whole ladder without wall-clock sleeps.

Observability (docs/observability.md "Durable I/O"):
  - `io_retries_total{op}` — transient failures that were retried.
  - `io_failures_total{op}` — ops that exhausted the policy (or hit a
    permanent error) and raised to the caller.
  - `io_retry` flight events on the process recorder, one per retry,
    carrying op/attempt/delay/error.

Goodput: retry waits need no ledger plumbing of their own — the call
sites already run inside the trainer's open `checkpoint` / `data_wait`
goodput regions (PR 12), so backoff sleep accrues to the cause that was
already open. A storage blip therefore costs a visible, bounded retry
wait in the ledger instead of a restart.

Fault injection: `testing/faults.flaky_storage` installs a hook at this
seam (`set_fault_hook`) that raises transient errors for the first N
attempts — the whole retry ladder is exercised end to end through the
REAL call sites without monkeypatching `builtins.open`.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Any, Callable, Optional

logger = logging.getLogger(__name__)

__all__ = [
    "RetryPolicy",
    "TransientIOError",
    "default_classify",
    "default_policy",
    "set_default_policy",
    "set_fault_hook",
    "io_call",
]


class TransientIOError(OSError):
    """An error the caller KNOWS is transient (fault injectors raise
    this; wrappers around storage clients may too)."""


# OSError subclasses where a retry cannot change the outcome: the path
# is wrong, the file genuinely is a directory, the name already exists.
# PermissionError is permanent too — credential problems don't heal on
# a 50ms backoff, and retrying them just delays the actionable error.
_PERMANENT_OSERRORS = (
    FileNotFoundError,
    IsADirectoryError,
    NotADirectoryError,
    FileExistsError,
    PermissionError,
)


def default_classify(exc: BaseException) -> bool:
    """True when `exc` looks transient (worth retrying): OS-level I/O
    errors minus the permanent subclasses above. Everything else —
    corrupt-data ValueErrors, integrity failures, programming errors —
    is permanent by default: retrying a checksum mismatch just re-reads
    the same corrupt bytes."""
    if isinstance(exc, TransientIOError):
        return True
    if isinstance(exc, _PERMANENT_OSERRORS):
        return False
    # TimeoutError / ConnectionError / InterruptedError / BlockingIOError
    # are all OSError subclasses.
    return isinstance(exc, OSError)


# -- fault-injection seam (testing/faults.flaky_storage) -------------------
_fault_hook: Optional[Callable[[str], None]] = None
_hook_lock = threading.Lock()


def set_fault_hook(
    hook: Optional[Callable[[str], None]],
) -> Optional[Callable[[str], None]]:
    """Install a callable invoked with the op name at the START of every
    attempt; it may raise to simulate a storage fault. Returns the
    previous hook (restore it when done). Test-only seam."""
    global _fault_hook
    with _hook_lock:
        prev = _fault_hook
        _fault_hook = hook
    return prev


class RetryPolicy:
    """Exponential-backoff retry with jitter, deadline and classification.

    `call(fn, *args, op=..., **kwargs)` runs `fn` up to `max_attempts`
    times. A transient failure (per `classify`) sleeps
    `base_delay_s * 2**(attempt-1)` (capped at `max_delay_s`, jittered
    by ±`jitter` fraction) and tries again; a permanent failure or an
    exhausted ladder re-raises the original exception. `timeout_s`
    bounds the whole op including backoff waits: a retry whose delay
    would overrun the deadline fails immediately instead.

    Clock, sleep and the jitter RNG are injectable so tests assert the
    exact backoff sequence with zero wall-clock cost.
    """

    def __init__(
        self,
        max_attempts: int = 4,
        base_delay_s: float = 0.05,
        max_delay_s: float = 2.0,
        timeout_s: Optional[float] = None,
        jitter: float = 0.5,
        classify: Callable[[BaseException], bool] = default_classify,
        sleep: Callable[[float], None] = time.sleep,
        clock: Callable[[], float] = time.monotonic,
        rng: Optional[random.Random] = None,
        registry=None,
        recorder=None,
    ):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = int(max_attempts)
        self.base_delay_s = float(base_delay_s)
        self.max_delay_s = float(max_delay_s)
        self.timeout_s = timeout_s
        self.jitter = float(jitter)
        self.classify = classify
        self._sleep = sleep
        self._clock = clock
        self._rng = rng or random.Random()
        # None → resolve the process recorder at emit time, so a test's
        # set_recorder() swap is honored (the PR 12 identity lesson).
        self._recorder = recorder
        if registry is None:
            from luminaai_tpu.monitoring.telemetry import get_registry

            registry = get_registry()
        self._m_retries = registry.counter(
            "io_retries_total",
            "Transient storage-op failures absorbed by a retry, by op",
            labelnames=("op",),
        )
        self._m_failures = registry.counter(
            "io_failures_total",
            "Storage ops that raised to the caller (permanent error or "
            "retry ladder exhausted), by op",
            labelnames=("op",),
        )

    @classmethod
    def from_config(cls, config, **overrides) -> "RetryPolicy":
        """Build from the Config durable-I/O knobs (io_retries /
        io_retry_base_s / io_retry_max_s / io_timeout_s)."""
        kw: dict = dict(
            max_attempts=getattr(config, "io_retries", 4),
            base_delay_s=getattr(config, "io_retry_base_s", 0.05),
            max_delay_s=getattr(config, "io_retry_max_s", 2.0),
            timeout_s=getattr(config, "io_timeout_s", None),
        )
        kw.update(overrides)
        return cls(**kw)

    # -- execution --------------------------------------------------------
    def delay_for_attempt(self, attempt: int) -> float:
        """Backoff before retrying after failed attempt `attempt`
        (1-based): exponential from base, capped, then jittered."""
        d = min(self.max_delay_s, self.base_delay_s * (2 ** (attempt - 1)))
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        return max(0.0, d)

    def call(self, fn: Callable[..., Any], *args, op: str = "io", **kwargs):
        """Run `fn(*args, **kwargs)` under this policy. `op` is the
        bounded metric/event label (call sites use a fixed small set:
        checkpoint_save / checkpoint_restore / manifest_write /
        data_open / data_read / ...)."""
        deadline = (
            self._clock() + self.timeout_s
            if self.timeout_s is not None
            else None
        )
        attempt = 0
        while True:
            attempt += 1
            try:
                hook = _fault_hook
                if hook is not None:
                    hook(op)
                return fn(*args, **kwargs)
            except Exception as e:
                try:
                    transient = bool(self.classify(e))
                except Exception:  # a broken classifier never masks `e`
                    transient = False
                if not transient or attempt >= self.max_attempts:
                    self._m_failures.labels(op=op).inc()
                    raise
                delay = self.delay_for_attempt(attempt)
                if deadline is not None and self._clock() + delay > deadline:
                    self._m_failures.labels(op=op).inc()
                    logger.warning(
                        "%s: deadline (%.2fs) exhausted after %d attempt(s)",
                        op, self.timeout_s, attempt,
                    )
                    raise
                self._m_retries.labels(op=op).inc()
                self._emit_retry(op, attempt, delay, e)
                logger.warning(
                    "transient %s failure (attempt %d/%d): %s: %s; "
                    "retrying in %.3fs",
                    op, attempt, self.max_attempts,
                    type(e).__name__, str(e)[:200], delay,
                )
                self._sleep(delay)

    def wrap(self, fn: Callable[..., Any], op: str = "io"):
        """`fn` bound to this policy: `wrap(open, "data_open")(path)`."""

        def wrapped(*args, **kwargs):
            return self.call(fn, *args, op=op, **kwargs)

        wrapped.__name__ = getattr(fn, "__name__", "wrapped")
        return wrapped

    def _emit_retry(self, op, attempt, delay, exc) -> None:
        try:
            rec = self._recorder
            if rec is None:
                from luminaai_tpu.monitoring.events import get_recorder

                rec = get_recorder()
            rec.emit(
                "io_retry",
                op=op,
                attempt=attempt,
                delay_s=round(delay, 4),
                error=f"{type(exc).__name__}: {str(exc)[:160]}",
            )
        except Exception:  # pragma: no cover - telemetry must not kill I/O
            logger.debug("io_retry event emit failed", exc_info=True)


# -- process default --------------------------------------------------------
_default_policy: Optional[RetryPolicy] = None
_default_lock = threading.Lock()


def default_policy() -> RetryPolicy:
    """The process-wide policy data readers fall back to when the caller
    threads none through (checkpointing builds its own from Config)."""
    global _default_policy
    with _default_lock:
        if _default_policy is None:
            _default_policy = RetryPolicy()
        return _default_policy


def set_default_policy(policy: Optional[RetryPolicy]) -> Optional[RetryPolicy]:
    """Swap the process default (config wiring / tests). Returns the
    previous policy; pass it back to restore."""
    global _default_policy
    with _default_lock:
        prev = _default_policy
        _default_policy = policy
        return prev


def io_call(
    fn: Callable[..., Any],
    *args,
    op: str = "io",
    policy: Optional[RetryPolicy] = None,
    **kwargs,
):
    """One-shot retried call: `io_call(open, path, "rb", op="data_open")`."""
    return (policy or default_policy()).call(fn, *args, op=op, **kwargs)
