"""Multi-source pipeline + processing tests."""

import json

import pytest

from luminaai_tpu.data.multi_source import (
    MultiSourcePipeline,
    SourceProcessor,
    clean_gutenberg_text,
    clean_html_text,
    clean_wiki_text,
)
from luminaai_tpu.data.processing import (
    create_sample_data,
    process_oasst_data,
    validate_data_comprehensive,
)
from luminaai_tpu.data.tokenizer import ConversationTokenizer


def test_clean_wiki_text():
    raw = ("{{Infobox|x=1}} '''Python''' is a [[programming language|language]] "
           "created by [[Guido van Rossum]].<ref>cite</ref>\n== History ==\n"
           "It appeared in 1991.")
    out = clean_wiki_text(raw)
    assert "Infobox" not in out and "[[" not in out and "<ref>" not in out
    assert "Python is a language created by Guido van Rossum." in out
    assert "History" in out and "==" not in out


def test_clean_gutenberg_text():
    raw = ("junk header\n*** START OF THE PROJECT GUTENBERG EBOOK X ***\n"
           "Actual book text here.\n*** END OF THE PROJECT GUTENBERG EBOOK X ***\n"
           "license junk")
    out = clean_gutenberg_text(raw)
    assert out == "Actual book text here."


def test_clean_html_text():
    raw = "<p>Use <code>print()</code> here.</p><pre><code>x = 1</code></pre>&amp; more"
    out = clean_html_text(raw)
    assert "`print()`" in out and "```" in out and "& more" in out
    assert "<p>" not in out


def test_source_processor_shards(tmp_path):
    raw = tmp_path / "wiki_raw.jsonl"
    with raw.open("w") as f:
        for i in range(30):
            f.write(json.dumps({
                "text": f"'''Article {i}''' is about [[topic {i}]]. " * 20
            }) + "\n")
    proc = SourceProcessor("wikipedia")
    shards = proc.create_dataset_files(
        [str(raw)], str(tmp_path / "out"), num_files=2, mb_per_file=0.01
    )
    assert len(shards) == 2
    recs = [json.loads(l) for l in open(shards[0])]
    assert all(r["source"] == "wikipedia" for r in recs)
    assert "[[" not in recs[0]["text"]


def test_unknown_source_rejected():
    with pytest.raises(ValueError):
        SourceProcessor("tiktok")


def test_blend_respects_weights_and_exhaustion(tmp_path):
    shards = {}
    for name, n in (("wikipedia", 30), ("arxiv", 10)):
        p = tmp_path / f"{name}.jsonl"
        with p.open("w") as f:
            for i in range(n):
                f.write(json.dumps({"text": f"{name} doc {i}", "source": name}) + "\n")
        shards[name] = [str(p)]
    tok = ConversationTokenizer()
    pipe = MultiSourcePipeline(tok, {"wikipedia": 3.0, "arxiv": 1.0})
    docs = list(pipe.iter_blended(shards, seed=0))
    assert len(docs) == 40  # all docs surface even after a source empties
    srcs = [d["source"] for d in docs[:20]]
    assert srcs.count("wikipedia") > srcs.count("arxiv")

    cache = pipe.build_cache(shards, str(tmp_path / "blend"))
    assert cache.n_docs == 40


def test_oasst_processing_and_validation(tmp_path):
    raw = tmp_path / "oasst.jsonl"
    with raw.open("w") as f:
        f.write(json.dumps({"messages": [
            {"role": "prompter", "content": "hello"},
            {"role": "assistant", "content": "hi!"},
        ]}) + "\n")
        f.write(json.dumps({"messages": [
            {"role": "prompter", "content": "only one side"},
        ]}) + "\n")
        f.write("not json\n")
    out = tmp_path / "clean.jsonl"
    n = process_oasst_data(str(raw), str(out))
    assert n == 1
    rec = json.loads(out.read_text().splitlines()[0])
    assert rec["messages"][0]["role"] == "user"  # prompter normalized

    report = validate_data_comprehensive(str(out), ConversationTokenizer())
    assert report["valid"] == 1 and report["token_stats"]["mean"] > 0


def test_create_sample_data_roundtrip(tmp_path):
    p = tmp_path / "sample.jsonl"
    n = create_sample_data(str(p), num_conversations=12)
    assert n == 12
    tok = ConversationTokenizer()
    report = validate_data_comprehensive(str(p), tok)
    assert report["valid"] == 12 and report["issues"]["bad_json"] == 0
