"""Data acquisition tests (ref Dataset_download.py pipeline, offline)."""

import io
import json
from pathlib import Path

import pytest

from luminaai_tpu.data.acquisition import (
    DatasetDownloader,
    analyze_conversations,
    build_conversation_tree,
    extract_conversation_paths,
    fetch_raw,
    fetch_source,
    filter_quality_conversations,
    format_conversation,
    oasst_to_chat_format,
    save_conversations_with_size_limit,
)


def oasst_messages():
    """A 2-branch message tree: root → a1 → (u2 → a2), a1b."""
    return [
        {"message_id": "r", "parent_id": None, "role": "prompter",
         "text": "What is a TPU?", "lang": "en", "message_tree_id": "t1"},
        {"message_id": "a1", "parent_id": "r", "role": "assistant",
         "text": "A tensor processing unit: a matrix accelerator.",
         "lang": "en"},
        {"message_id": "a1b", "parent_id": "r", "role": "assistant",
         "text": "Google's custom ML chip.", "lang": "en"},
        {"message_id": "u2", "parent_id": "a1", "role": "prompter",
         "text": "How fast is it?", "lang": "en"},
        {"message_id": "a2", "parent_id": "u2", "role": "assistant",
         "text": "A v5e chip peaks near 200 bf16 TFLOPs.", "lang": "en"},
    ]


def test_tree_and_paths():
    message_map, roots = build_conversation_tree(oasst_messages())
    assert roots == ["r"]
    assert sorted(message_map["r"]["children"]) == ["a1", "a1b"]
    paths = extract_conversation_paths(message_map, "r")
    # Every ≥2-message prefix: r-a1, r-a1b, r-a1-u2, r-a1-u2-a2.
    assert len(paths) == 4
    assert max(len(p) for p in paths) == 4


def test_format_filter_and_chat_conversion():
    message_map, roots = build_conversation_tree(oasst_messages())
    paths = extract_conversation_paths(message_map, roots[0])
    formatted = [format_conversation(p) for p in paths]
    assert all(c["messages"][0]["role"] == "prompter" for c in formatted)
    kept = filter_quality_conversations(formatted)
    assert 0 < len(kept) <= len(formatted)
    chat = oasst_to_chat_format(kept[0])
    assert chat["messages"][0]["role"] == "user"
    stats = analyze_conversations(kept, "train")
    assert stats["count"] == len(kept) and stats["avg_turns"] >= 2


def test_filter_rejects_garbage():
    bad = [
        {"messages": [{"role": "assistant", "content": "no prompt first"}]},
        {"messages": [{"role": "prompter", "content": "x"},
                      {"role": "assistant", "content": ""}]},  # empty reply
        {"messages": [{"role": "prompter", "content": "hi"},
                      {"role": "prompter", "content": "hi again"}]},  # no asst
    ]
    assert filter_quality_conversations(bad) == []


def test_shard_writer_rotates(tmp_path):
    convs = [{"messages": [{"role": "user", "content": "x" * 500}]}] * 10
    files = save_conversations_with_size_limit(
        convs, str(tmp_path), max_mb_per_file=0.001  # 1KB → forces rotation
    )
    assert len(files) > 1
    total = sum(
        len(Path(f).read_text().splitlines()) for f in files
    )
    assert total == 10


def test_downloader_process_local_dump(tmp_path):
    dump = tmp_path / "raw.jsonl"
    with open(dump, "w") as f:
        for m in oasst_messages():
            f.write(json.dumps(m) + "\n")
    dl = DatasetDownloader(str(tmp_path / "out"))
    stats = dl.process_local_dump(str(dump), "train")
    assert stats["count"] > 0 and stats["files"]
    first = json.loads(Path(stats["files"][0]).read_text().splitlines()[0])
    assert first["messages"][0]["role"] == "user"
    # Output feeds the repo's own validator end-to-end.
    from luminaai_tpu.data.processing import validate_data_comprehensive
    from luminaai_tpu.data.tokenizer import ConversationTokenizer

    report = validate_data_comprehensive(
        stats["files"][0], ConversationTokenizer(model_name="byte")
    )
    assert report["valid"] > 0


def test_fetch_raw_offline_returns_none(tmp_path):
    def failing_opener(url):
        raise OSError("no route to host")

    out = fetch_raw(
        "https://example.com/x", str(tmp_path / "x"), _opener=failing_opener
    )
    assert out is None
    assert not (tmp_path / "x").exists()


def test_fetch_source_with_injected_opener(tmp_path):
    class FakeResp(io.BytesIO):
        def __enter__(self):
            return self

        def __exit__(self, *a):
            return False

    def opener(url):
        assert "wikimedia" in url
        return FakeResp(b"dump-bytes")

    out = fetch_source("wikipedia", str(tmp_path), _opener=opener)
    assert out and Path(out).read_bytes() == b"dump-bytes"
    with pytest.raises(ValueError):
        fetch_source("unknown_source", str(tmp_path))
