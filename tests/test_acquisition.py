"""Data acquisition tests (ref Dataset_download.py pipeline, offline)."""

import io
import json
from pathlib import Path

import pytest

from luminaai_tpu.data.acquisition import (
    DatasetDownloader,
    analyze_conversations,
    build_conversation_tree,
    extract_conversation_paths,
    fetch_raw,
    fetch_source,
    filter_quality_conversations,
    format_conversation,
    oasst_to_chat_format,
    save_conversations_with_size_limit,
)


def oasst_messages():
    """A 2-branch message tree: root → a1 → (u2 → a2), a1b."""
    return [
        {"message_id": "r", "parent_id": None, "role": "prompter",
         "text": "What is a TPU?", "lang": "en", "message_tree_id": "t1"},
        {"message_id": "a1", "parent_id": "r", "role": "assistant",
         "text": "A tensor processing unit: a matrix accelerator.",
         "lang": "en"},
        {"message_id": "a1b", "parent_id": "r", "role": "assistant",
         "text": "Google's custom ML chip.", "lang": "en"},
        {"message_id": "u2", "parent_id": "a1", "role": "prompter",
         "text": "How fast is it?", "lang": "en"},
        {"message_id": "a2", "parent_id": "u2", "role": "assistant",
         "text": "A v5e chip peaks near 200 bf16 TFLOPs.", "lang": "en"},
    ]


def test_tree_and_paths():
    message_map, roots = build_conversation_tree(oasst_messages())
    assert roots == ["r"]
    assert sorted(message_map["r"]["children"]) == ["a1", "a1b"]
    paths = extract_conversation_paths(message_map, "r")
    # Every ≥2-message prefix: r-a1, r-a1b, r-a1-u2, r-a1-u2-a2.
    assert len(paths) == 4
    assert max(len(p) for p in paths) == 4


def test_format_filter_and_chat_conversion():
    message_map, roots = build_conversation_tree(oasst_messages())
    paths = extract_conversation_paths(message_map, roots[0])
    formatted = [format_conversation(p) for p in paths]
    assert all(c["messages"][0]["role"] == "prompter" for c in formatted)
    kept = filter_quality_conversations(formatted)
    assert 0 < len(kept) <= len(formatted)
    chat = oasst_to_chat_format(kept[0])
    assert chat["messages"][0]["role"] == "user"
    stats = analyze_conversations(kept, "train")
    assert stats["count"] == len(kept) and stats["avg_turns"] >= 2


def test_filter_rejects_garbage():
    bad = [
        {"messages": [{"role": "assistant", "content": "no prompt first"}]},
        {"messages": [{"role": "prompter", "content": "x"},
                      {"role": "assistant", "content": ""}]},  # empty reply
        {"messages": [{"role": "prompter", "content": "hi"},
                      {"role": "prompter", "content": "hi again"}]},  # no asst
    ]
    assert filter_quality_conversations(bad) == []


def test_shard_writer_rotates(tmp_path):
    convs = [{"messages": [{"role": "user", "content": "x" * 500}]}] * 10
    files = save_conversations_with_size_limit(
        convs, str(tmp_path), max_mb_per_file=0.001  # 1KB → forces rotation
    )
    assert len(files) > 1
    total = sum(
        len(Path(f).read_text().splitlines()) for f in files
    )
    assert total == 10


def test_downloader_process_local_dump(tmp_path):
    dump = tmp_path / "raw.jsonl"
    with open(dump, "w") as f:
        for m in oasst_messages():
            f.write(json.dumps(m) + "\n")
    dl = DatasetDownloader(str(tmp_path / "out"))
    stats = dl.process_local_dump(str(dump), "train")
    assert stats["count"] > 0 and stats["files"]
    first = json.loads(Path(stats["files"][0]).read_text().splitlines()[0])
    assert first["messages"][0]["role"] == "user"
    # Output feeds the repo's own validator end-to-end.
    from luminaai_tpu.data.processing import validate_data_comprehensive
    from luminaai_tpu.data.tokenizer import ConversationTokenizer

    report = validate_data_comprehensive(
        stats["files"][0], ConversationTokenizer(model_name="byte")
    )
    assert report["valid"] > 0


class FakeResp(io.BytesIO):
    status = 206
    headers = {"ETag": 'W/"v1"'}

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


def test_fetch_raw_offline_returns_none(tmp_path):
    def failing_opener(url, headers):
        raise OSError("no route to host")

    out = fetch_raw(
        "https://example.com/x", str(tmp_path / "x"), _opener=failing_opener
    )
    assert out is None
    assert not (tmp_path / "x").exists()


def test_fetch_source_with_injected_opener(tmp_path):
    def opener(url, headers):
        assert "wikimedia" in url
        return FakeResp(b"dump-bytes")

    out = fetch_source("wikipedia", str(tmp_path), _opener=opener)
    assert out and Path(out).read_bytes() == b"dump-bytes"
    with pytest.raises(ValueError):
        fetch_source("unknown_source", str(tmp_path))


def test_fetch_source_url_construction_all_sources(tmp_path):
    """Every reference corpus has a working URL template (ref
    multi_source_dataset.py download_* across all eight processors)."""
    from luminaai_tpu.data.acquisition import SOURCE_URLS

    seen = {}

    def opener(url, headers):
        seen[len(seen)] = url
        return FakeResp(b"x")

    for source in SOURCE_URLS:
        out = fetch_source(source, str(tmp_path / source), _opener=opener)
        assert out is not None, source
    urls = list(seen.values())
    assert len(urls) == len(SOURCE_URLS)
    assert all(u.startswith("http") for u in urls), urls
    assert not any("{" in u for u in urls), urls  # templates fully filled


def test_fetch_raw_writes_checksum_and_verifies(tmp_path):
    import hashlib

    payload = b"corpus-bytes"
    good = hashlib.sha256(payload).hexdigest()

    def opener(url, headers):
        return FakeResp(payload)

    dest = tmp_path / "d.dat"
    out = fetch_raw("https://e.com/d", str(dest), _opener=opener,
                    expected_sha256=good)
    assert out and dest.read_bytes() == payload
    recorded = (tmp_path / "d.dat.sha256").read_text().split()[0]
    assert recorded == good

    # Mismatch: corrupt download is discarded, nothing clobbers dest2.
    from luminaai_tpu.data.acquisition import _part_path

    dest2 = tmp_path / "e.dat"
    out = fetch_raw("https://e.com/e", str(dest2), _opener=opener,
                    expected_sha256="0" * 64)
    assert out is None
    assert not dest2.exists()
    assert not Path(_part_path(str(dest2), "https://e.com/e")).exists()


def test_fetch_raw_resumes_partial_with_range(tmp_path):
    """A leftover partial resumes via HTTP Range and appends; a failed
    transfer keeps the partial for the next try. Partials are url-keyed,
    so a different url cannot splice onto this one."""
    from luminaai_tpu.data.acquisition import _part_path

    dest = tmp_path / "r.dat"
    part = Path(_part_path(str(dest), "https://e.com/r"))

    def dying_opener(url, headers):
        part_resp = FakeResp(b"first-")
        orig_read = part_resp.read

        def read(n):
            chunk = orig_read(n)
            if not chunk:
                raise OSError("connection reset")
            return chunk

        part_resp.read = read
        return part_resp

    assert fetch_raw("https://e.com/r", str(dest), _opener=dying_opener) is None
    assert part.read_bytes() == b"first-"  # partial kept

    ranges = []

    def resuming_opener(url, headers):
        ranges.append((headers.get("Range"), headers.get("If-Range")))
        return FakeResp(b"rest")

    # A DIFFERENT url ignores the other url's partial entirely.
    out2 = fetch_raw("https://e.com/other", str(tmp_path / "o.dat"),
                     _opener=resuming_opener)
    assert out2 and ranges == [(None, None)]

    ranges.clear()
    out = fetch_raw("https://e.com/r", str(dest), _opener=resuming_opener)
    assert out and dest.read_bytes() == b"first-rest"
    # Resume is validator-guarded: If-Range carries the ETag captured
    # when the partial started, so a changed remote serves whole.
    assert ranges == [("bytes=6-", 'W/"v1"')]
    # Streamed checksum over resumed bytes matches the whole file.
    import hashlib

    assert (tmp_path / "r.dat.sha256").read_text().split()[0] == (
        hashlib.sha256(b"first-rest").hexdigest()
    )


def test_fetch_raw_restarts_when_server_ignores_range(tmp_path):
    from luminaai_tpu.data.acquisition import _part_path

    dest = tmp_path / "s.dat"
    part = _part_path(str(dest), "https://e.com/s")
    Path(part).write_bytes(b"stale-half")
    Path(part + ".meta").write_text('W/"v1"')

    def full_body_opener(url, headers):
        resp = FakeResp(b"whole-file")
        resp.status = 200  # Range ignored / If-Range says remote changed
        return resp

    out = fetch_raw("https://e.com/s", str(dest), _opener=full_body_opener)
    assert out and dest.read_bytes() == b"whole-file"


def test_fetch_raw_discards_partial_without_validator(tmp_path):
    """A partial whose origin validator was never captured cannot be
    safely resumed (silent version splice) — refetch whole."""
    from luminaai_tpu.data.acquisition import _part_path

    dest = tmp_path / "n.dat"
    Path(_part_path(str(dest), "https://e.com/n")).write_bytes(b"orphan")
    sent = []

    def opener(url, headers):
        sent.append(headers.get("Range"))
        return FakeResp(b"complete")

    out = fetch_raw("https://e.com/n", str(dest), _opener=opener)
    assert out and dest.read_bytes() == b"complete"
    assert sent == [None]  # no Range without a validator


def test_fetch_raw_416_discards_stale_partial(tmp_path):
    """Range-not-satisfiable (remote shrank / partial complete) must not
    wedge: the stale partial is discarded and the fetch restarts whole."""
    import urllib.error

    from luminaai_tpu.data.acquisition import _part_path

    dest = tmp_path / "w.dat"
    part = _part_path(str(dest), "https://e.com/w")
    Path(part).write_bytes(b"toolongpartial")
    Path(part + ".meta").write_text('W/"v1"')
    calls = []

    def opener(url, headers):
        calls.append(headers.get("Range"))
        if headers.get("Range"):
            raise urllib.error.HTTPError(
                url, 416, "Range Not Satisfiable", {}, None
            )
        return FakeResp(b"fresh")

    out = fetch_raw("https://e.com/w", str(dest), _opener=opener)
    assert out and dest.read_bytes() == b"fresh"
    assert calls == ["bytes=14-", None]
