"""Goodput ledger, hang watchdog and step-time sentinel contracts
(docs/observability.md "Goodput & sentinels").

The ledger's headline invariant — causes PARTITION wall time, sum ==
elapsed — is pinned with an injected clock (exact) and end to end on a
real trainer (tolerance covers float rounding only). Resume replay is
attributed across a preempt/resume cycle from the existing faults
harness. The watchdog/sentinel robust-threshold math is unit-tested
here; the detect→dump→(abort|continue) end-to-end lives in
tests/test_resilience.py with the other fault-injection contracts.
"""

import glob
import json
import os
import time

import numpy as np
import pytest

from luminaai_tpu.config import Config
from luminaai_tpu.data.dataset import PrefetchLoader
from luminaai_tpu.monitoring.events import FlightRecorder
from luminaai_tpu.monitoring.goodput import CAUSES, GoodputLedger
from luminaai_tpu.monitoring.telemetry import MetricsRegistry
from luminaai_tpu.monitoring.watchdog import (
    HangWatchdog,
    RobustStats,
    StepTimeSentinel,
    host_step_skew,
)


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t

    def tick(self, dt):
        self.t += dt


# ---------------------------------------------------------------------------
# ledger arithmetic (injected clock: exact)
# ---------------------------------------------------------------------------
def test_ledger_partitions_wall_time_exactly():
    clk = FakeClock()
    led = GoodputLedger(clock=clk)
    led.start("idle")
    clk.tick(1.0)
    led.switch("productive")
    clk.tick(5.0)
    with led.region("checkpoint"):
        clk.tick(2.0)
    clk.tick(3.0)  # back in productive (region restored the cause)
    led.stop()
    secs = led.seconds()
    assert secs["idle"] == 1.0
    assert secs["productive"] == 8.0
    assert secs["checkpoint"] == 2.0
    assert sum(secs.values()) == led.elapsed() == 11.0
    assert led.fraction() == pytest.approx(8.0 / 11.0)
    snap = led.snapshot()
    assert snap["available"] and snap["partition_error_s"] == 0.0
    # Every canonical cause is present even at zero — the CI contract.
    assert set(snap["seconds"]) == set(CAUSES)


def test_ledger_reattribute_moves_open_accrual_and_clamps():
    clk = FakeClock()
    led = GoodputLedger(clock=clk)
    led.start("idle")
    led.switch("data_wait")
    clk.tick(4.0)
    # Move 3s of the open data_wait accrual to resume_replay.
    assert led.reattribute("resume_replay", 3.0) == 3.0
    # Asking for more than remains is clamped, never negative.
    assert led.reattribute("hang", 10.0) == 1.0
    clk.tick(2.0)
    led.stop()
    secs = led.seconds()
    assert secs["resume_replay"] == 3.0
    assert secs["hang"] == 1.0
    assert secs["data_wait"] == 2.0
    assert sum(secs.values()) == led.elapsed() == 6.0


def test_ledger_counters_and_gauge_export():
    reg = MetricsRegistry()
    clk = FakeClock()
    led = GoodputLedger(registry=reg, clock=clk)
    led.start("idle")
    led.switch("productive")
    clk.tick(3.0)
    led.switch("idle")
    clk.tick(1.0)
    led.stop()
    snap = reg.snapshot()
    assert snap["training_time_seconds_total"]["cause=productive"] == 3.0
    assert snap["training_goodput_fraction"] == pytest.approx(0.75)


def test_ledger_disabled_is_inert():
    led = GoodputLedger(enabled=False)
    led.start()
    led.switch("productive")
    with led.region("checkpoint"):
        pass
    led.stop()
    assert led.snapshot()["available"] is False


def test_ledger_rejects_unknown_cause():
    led = GoodputLedger(clock=FakeClock())
    led.start()
    with pytest.raises(ValueError):
        led.switch("coffee_break")


class TickingClock(FakeClock):
    """Advances on EVERY read — the adversarial schedule for a snapshot
    that read the clock twice (totals vs elapsed) would see."""

    def __call__(self):
        self.t += 0.25
        return self.t


def test_snapshot_reads_one_instant_even_under_clock_skew():
    """partition_error_s must be 0 even when every clock read advances
    time: the snapshot takes totals AND elapsed from ONE reading, so a
    descheduled reader can never fake a partition error (CI asserts
    < 0.05 on loaded runners)."""
    clk = TickingClock()
    led = GoodputLedger(clock=clk)
    led.start("productive")
    for _ in range(3):
        led.switch("data_wait")
        led.switch("productive")
    snap = led.snapshot()
    assert snap["partition_error_s"] == 0.0, snap
    assert led.fraction() <= 1.0


def test_ledger_restart_books_stopped_gap_as_idle():
    clk = FakeClock()
    led = GoodputLedger(clock=clk)
    led.start("productive")
    clk.tick(2.0)
    led.stop()
    clk.tick(5.0)  # between stop and restart: still elapsed wall time
    led.start("productive")
    clk.tick(1.0)
    led.stop()
    secs = led.seconds()
    assert secs["productive"] == 3.0
    assert secs["idle"] == 5.0
    assert sum(secs.values()) == led.elapsed() == 8.0


# ---------------------------------------------------------------------------
# robust stats + sentinel
# ---------------------------------------------------------------------------
def test_robust_stats_median_mad():
    st = RobustStats(window=16)
    for x in [1.0, 1.0, 1.0, 9.0]:
        st.add(x)
    assert st.median() == 1.0
    assert st.mad() == 0.0  # median of |x - 1| = [0,0,0,8] -> 0
    st.add(3.0)
    assert st.median() == 1.0
    assert st.mad() == 0.0


def test_sentinel_flags_spike_and_exports_gauges():
    reg = MetricsRegistry()
    rec = FlightRecorder()
    s = StepTimeSentinel(
        registry=reg, recorder=rec, prefix="train_step_seconds",
        program="train", k=4.0, warmup=5,
    )
    for _ in range(10):
        assert not s.observe(0.01)
    assert s.observe(0.5, step=11)  # 50x the median: anomalous
    evs = rec.snapshot(type="step_anomaly")
    assert evs and evs[0]["program"] == "train"
    assert evs[0]["seconds"] == pytest.approx(0.5)
    assert evs[0]["step"] == 11
    snap = reg.snapshot()
    assert snap["train_step_seconds_median"] == pytest.approx(0.01, rel=0.2)
    assert snap["step_time_anomalies_total"]["program=train"] == 1
    # Warmup: a fresh (reset) window cannot flag anything.
    s.reset()
    assert not s.observe(10.0)


def test_sentinel_not_fooled_by_noisy_window():
    """The MAD significance guard: in a widely-spread window a value
    k x median is NOT automatically an anomaly."""
    rng = np.random.RandomState(0)
    s = StepTimeSentinel(k=2.0, warmup=5, guard_sigmas=6.0)
    flagged = 0
    for _ in range(40):
        flagged += bool(s.observe(float(rng.uniform(0.01, 0.05))))
    assert flagged == 0


def test_host_step_skew_single_host_is_zero():
    reg = MetricsRegistry()
    assert host_step_skew(reg) == 0.0
    assert reg.snapshot()["host_step_skew_seconds"] == 0.0


# ---------------------------------------------------------------------------
# watchdog threshold mechanics (no trainer; injected clock + exit fn)
# ---------------------------------------------------------------------------
def test_watchdog_threshold_is_robust_and_warmup_aware():
    wd = HangWatchdog(
        kind="training", recorder=FlightRecorder(), k=10.0, floor_s=0.5,
        warmup=3,
    )
    wd.arm()
    assert wd.threshold_s() is None  # no intervals yet: cannot fire
    for _ in range(3):
        wd._stats.add(0.1)
    thr = wd.threshold_s()
    assert thr == pytest.approx(max(0.5, 10.0 * 0.1))
    wd.close()


def test_watchdog_fires_once_dumps_and_counts(tmp_path):
    reg = MetricsRegistry()
    rec = FlightRecorder()
    rec.emit("marker", x=1)
    exits = []
    wd = HangWatchdog(
        kind="training", registry=reg, recorder=rec,
        dump_dir=str(tmp_path), k=2.0, floor_s=0.15, warmup=2,
        poll_s=0.03, abort=False, exit_fn=exits.append,
    )
    wd.arm()
    for _ in range(4):
        time.sleep(0.02)
        wd.beat()
    time.sleep(0.6)  # stall: > floor, no beat arrives
    assert wd.fires == 1, wd.stats()  # fired exactly once per stall
    wd.beat()  # a beat re-enables firing for the NEXT stall
    wd.close()
    assert reg.snapshot()["training_hangs_total"] == 1
    evs = rec.snapshot(type="hang_suspected")
    assert evs and evs[0]["kind"] == "training"
    assert evs[0]["stalled_s"] > evs[0]["threshold_s"]
    dumps = glob.glob(str(tmp_path / "flightrec-*hang*.jsonl"))
    stacks = glob.glob(str(tmp_path / "stacks-*hang.txt"))
    assert dumps and stacks
    assert "thread" in open(stacks[0]).read()
    assert not exits  # abort off: the process keeps running


def test_watchdog_pause_excludes_slow_host_work():
    rec = FlightRecorder()
    wd = HangWatchdog(
        kind="training", recorder=rec, k=2.0, floor_s=0.1, warmup=2,
        poll_s=0.02,
    )
    wd.arm()
    for _ in range(3):
        time.sleep(0.02)
        wd.beat()
    with wd.pause():
        time.sleep(0.4)  # a blocking save this long must NOT fire
    time.sleep(0.05)
    wd.beat()
    wd.close()
    assert wd.fires == 0, wd.stats()
    assert not rec.snapshot(type="hang_suspected")


# ---------------------------------------------------------------------------
# trainer end-to-end (the faults-harness cycle)
# ---------------------------------------------------------------------------
def _tiny_cfg(out, **kw):
    base = dict(
        vocab_size=64, hidden_size=32, num_layers=1, num_heads=2,
        num_kv_heads=1, seq_length=16, batch_size=8,
        use_flash_attention=False, gradient_checkpointing=False,
        precision="fp32", max_steps=6, eval_every_n_batches=10**6,
        save_every_n_batches=10**6, health_check_interval=10,
        output_dir=str(out), learning_rate=1e-3,
    )
    base.update(kw)
    return Config(**base)


def _loader():
    def gen(epoch=0):
        rng = np.random.RandomState(epoch)
        for _ in range(50):
            yield {
                "input_ids": rng.randint(1, 60, size=(8, 16)).astype(
                    np.int32
                )
            }

    return PrefetchLoader(gen, prefetch=2)


def test_trainer_goodput_partitions_run_wall_clock(tmp_path):
    """Causes partition elapsed (tolerance = float rounding only),
    productive/compile/checkpoint all real, fraction in (0, 1], and the
    registry carries the counter + gauge series."""
    from luminaai_tpu.training.trainer import Trainer

    reg = MetricsRegistry()
    t = Trainer(
        _tiny_cfg(tmp_path), train_data=_loader(),
        checkpoint_dir=str(tmp_path / "ckpt"), registry=reg,
        recorder=FlightRecorder(),
    )
    s = t.train()
    t.close()
    gp = s["goodput"]
    assert gp["available"], gp
    assert 0.0 < gp["goodput_fraction"] <= 1.0, gp
    assert set(gp["seconds"]) == set(CAUSES), gp
    assert gp["partition_error_s"] < 0.01, gp
    assert gp["seconds"]["productive"] > 0
    assert gp["seconds"]["compile"] > 0
    assert gp["seconds"]["checkpoint"] > 0  # final forced save
    snap = reg.snapshot()
    assert snap["training_goodput_fraction"] > 0
    assert snap["training_time_seconds_total"]["cause=productive"] > 0
    # Sentinel gauges rode the same run (log cadence observations).
    assert snap["train_step_seconds_median"] > 0
    assert snap["host_step_skew_seconds"] == 0.0  # single host


@pytest.mark.faults
def test_resume_replay_attributed_across_preempt_resume(tmp_path):
    """The preempt/resume cycle from the faults harness: the interrupted
    run banks checkpoint time for its emergency save; the resumed run
    attributes restore to checkpoint and the loader fast-forward to
    resume_replay — and both ledgers still partition exactly."""
    from luminaai_tpu.testing.faults import preempt_at_step
    from luminaai_tpu.training.trainer import Trainer

    ckpt = str(tmp_path / "ckpt")
    t1 = Trainer(
        _tiny_cfg(tmp_path), train_data=_loader(), checkpoint_dir=ckpt,
        registry=MetricsRegistry(), recorder=FlightRecorder(),
    )
    with preempt_at_step(t1, 3):
        s1 = t1.train()
    t1.close()
    assert s1["preempted"]
    gp1 = s1["goodput"]
    assert gp1["seconds"]["checkpoint"] > 0, gp1  # blocking emergency save
    assert gp1["partition_error_s"] < 0.01, gp1

    t2 = Trainer(
        _tiny_cfg(tmp_path), train_data=_loader(), checkpoint_dir=ckpt,
        registry=MetricsRegistry(), recorder=FlightRecorder(),
    )
    assert t2.global_step == s1["final_step"]
    s2 = t2.train()
    t2.close()
    gp2 = s2["goodput"]
    assert s2["resumed_exact_data_state"]
    # Fast-forwarding 3 tiny in-memory batches takes tens of µs, which
    # the summary's 4-decimal rounding can flatten to 0.0 — assert on
    # the UNROUNDED ledger (plus any tail still banked in the loader,
    # in case the prefetch thread's last banking outran the final
    # per-batch drain).
    replay_s = t2.goodput.seconds()["resume_replay"]
    replay_s += t2.train_data.consume_resume_replay_seconds()
    assert replay_s > 0, (gp2, replay_s)
    assert gp2["seconds"]["checkpoint"] > 0, gp2  # the restore
    assert 0.0 < gp2["goodput_fraction"] <= 1.0, gp2
    assert gp2["partition_error_s"] < 0.01, gp2


def test_goodput_off_switch(tmp_path):
    from luminaai_tpu.training.trainer import Trainer

    reg = MetricsRegistry()
    t = Trainer(
        _tiny_cfg(tmp_path, goodput=False, watchdog=False,
                  step_anomaly=False, max_steps=2),
        train_data=_loader(), checkpoint_dir=str(tmp_path / "ckpt"),
        registry=reg, recorder=FlightRecorder(),
    )
    s = t.train()
    t.close()
    assert s["goodput"]["available"] is False
    assert t.watchdog is None
    # Sentinel fully off: no gauges registered, observe() inert.
    assert "train_step_seconds_median" not in reg.snapshot()
    assert not t._sentinel.observe(100.0)


# ---------------------------------------------------------------------------
# overhead (the sentinel A/B; performance_overhead.md row)
# ---------------------------------------------------------------------------
def test_ledger_and_beat_per_op_overhead_is_negligible():
    """Tier-1 microbench: the per-boundary cost is two clock reads + a
    lock — 10k switch/beat pairs well under 200ms keeps the sentinel
    layer invisible next to a multi-ms train step."""
    led = GoodputLedger()
    led.start("productive")
    wd = HangWatchdog(kind="training", recorder=FlightRecorder())
    wd.arm()
    t0 = time.perf_counter()
    for _ in range(10_000):
        with led.region("data_wait"):
            pass
        wd.beat()
    dt = time.perf_counter() - t0
    wd.close()
    assert dt < 1.0, f"sentinel layer per-op overhead too high: {dt:.3f}s"


@pytest.mark.slow
def test_watchdog_and_ledger_overhead_ab(tmp_path):
    """Trainer-level A/B: sentinels on (default) vs fully off. The on-
    run must stay within a generous budget of the off-run — the layer
    heartbeats at log cadence, so there is nothing per-step to pay."""
    from luminaai_tpu.training.trainer import Trainer

    def run(tag, **kw):
        t = Trainer(
            _tiny_cfg(tmp_path / tag, max_steps=30, **kw),
            train_data=_loader(),
            checkpoint_dir=str(tmp_path / tag / "ckpt"),
            registry=MetricsRegistry(), recorder=FlightRecorder(),
        )
        t0 = time.perf_counter()
        t.train()
        dt = time.perf_counter() - t0
        t.close()
        return dt

    run("warm")  # one throwaway run so compile caches are warm for both
    dt_off = run("off", goodput=False, watchdog=False, step_anomaly=False)
    dt_on = run("on")
    assert dt_on < dt_off * 1.5 + 0.5, (dt_on, dt_off)


# ---------------------------------------------------------------------------
# capture rung (scripts/capture_multichip.py)
# ---------------------------------------------------------------------------
def test_capture_next_index_numbering(tmp_path):
    import sys

    sys.path.insert(
        0, os.path.join(os.path.dirname(__file__), "..", "scripts")
    )
    from capture_multichip import next_capture_path

    assert next_capture_path(str(tmp_path)).endswith("MULTICHIP_r01.json")
    (tmp_path / "MULTICHIP_r07.json").write_text("{}")
    assert next_capture_path(str(tmp_path)).endswith("MULTICHIP_r08.json")


@pytest.mark.slow
def test_capture_multichip_records_both_dcn_paths(tmp_path):
    """The one-command ROADMAP item 3 capture: both probes' stage
    timings land in one MULTICHIP_r*.json (simulated dcn on the 8-CPU
    harness, flagged as such)."""
    import sys

    sys.path.insert(
        0, os.path.join(os.path.dirname(__file__), "..", "scripts")
    )
    from capture_multichip import main as capture_main

    out = str(tmp_path / "MULTICHIP_rXX.json")
    rc = capture_main(["--out", out, "--payload-mb", "0.25",
                       "--iters", "1", "--tag", "ci-cpu"])
    assert rc == 0
    rec = json.load(open(out))
    assert rec["ok"] and rec["tag"] == "ci-cpu"
    for path_name in ("expert_a2a", "grad_reduce"):
        stages = rec[path_name]["stages"]
        assert stages, rec[path_name]
        assert any("mean_seconds" in v for v in stages.values()), stages
        assert rec[path_name]["simulated_dcn"] is True


def test_prefetch_loader_banks_replay_on_early_termination():
    """Replay wall clock is banked even when the epoch ends (or the
    consumer walks away) BEFORE the skip counter reaches zero — the
    truncated-source resume case must not leave resume_replay at 0."""
    def gen(epoch=0):
        for i in range(3):  # shorter than the saved cursor below
            yield {"input_ids": np.zeros((1, 4), np.int32) + i}

    loader = PrefetchLoader(gen, prefetch=2)
    loader.load_state_dict({"epoch": 0, "batch_index": 10})
    assert list(loader) == []  # every batch consumed by the fast-forward
    assert loader.consume_resume_replay_seconds() > 0.0
    # Drained: a second consume returns 0.
    assert loader.consume_resume_replay_seconds() == 0.0
