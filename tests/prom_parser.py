"""Minimal Prometheus text-format (0.0.4) parser — test-side contract
check for the /metrics exposition. Deliberately dependency-free: the
point is proving our output round-trips through an INDEPENDENT reading
of the format rules, not through our own renderer's inverse."""

import re

_SAMPLE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r"\s+(?P<value>\S+)\s*$"
)
_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(v: str) -> str:
    return (
        v.replace(r"\n", "\n").replace(r"\"", '"').replace(r"\\", "\\")
    )


def _value(s: str) -> float:
    if s == "+Inf":
        return float("inf")
    if s == "-Inf":
        return float("-inf")
    return float(s)


def parse_prometheus_text(text: str):
    """Parse exposition text into
    {family: {"type": str|None, "help": str|None, "samples": [...]}} with
    each sample a (sample_name, labels_dict, value) triple. Histogram
    samples (`_bucket`/`_sum`/`_count` suffixes) attach to their family
    name. Raises ValueError on any line that is neither a comment, a
    blank, nor a well-formed sample — a strict parser is the contract.
    """
    families = {}

    def fam(name):
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[: -len(suffix)] in families:
                base = name[: -len(suffix)]
                break
        return families.setdefault(
            base, {"type": None, "help": None, "samples": []}
        )

    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            parts = line.split(" ", 3)
            if len(parts) < 3:
                raise ValueError(f"line {lineno}: malformed HELP: {line!r}")
            name = parts[2]
            families.setdefault(
                name, {"type": None, "help": None, "samples": []}
            )["help"] = parts[3] if len(parts) > 3 else ""
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ")
            if len(parts) != 4 or parts[3] not in (
                "counter", "gauge", "histogram", "summary", "untyped"
            ):
                raise ValueError(f"line {lineno}: malformed TYPE: {line!r}")
            families.setdefault(
                parts[2], {"type": None, "help": None, "samples": []}
            )["type"] = parts[3]
            continue
        if line.startswith("#"):
            continue  # free-form comment
        m = _SAMPLE.match(line)
        if m is None:
            raise ValueError(f"line {lineno}: malformed sample: {line!r}")
        labels = {}
        raw = m.group("labels")
        if raw:
            consumed = 0
            for lm in _LABEL.finditer(raw):
                labels[lm.group(1)] = _unescape(lm.group(2))
                consumed += 1
            if consumed != len([c for c in raw.split(",") if c.strip()]):
                raise ValueError(f"line {lineno}: malformed labels: {raw!r}")
        fam(m.group("name"))["samples"].append(
            (m.group("name"), labels, _value(m.group("value")))
        )
    return families


def check_histogram_wellformed(family_name: str, family: dict) -> None:
    """Assert-style invariants for one histogram family, per label set:
    buckets cumulative and nondecreasing in le order, +Inf present and
    equal to _count, _sum present."""
    by_labels = {}
    for name, labels, value in family["samples"]:
        key = tuple(
            sorted((k, v) for k, v in labels.items() if k != "le")
        )
        entry = by_labels.setdefault(
            key, {"buckets": [], "sum": None, "count": None}
        )
        if name.endswith("_bucket"):
            entry["buckets"].append((_value(labels["le"]), value))
        elif name.endswith("_sum"):
            entry["sum"] = value
        elif name.endswith("_count"):
            entry["count"] = value
        else:
            raise AssertionError(
                f"{family_name}: stray sample {name} in histogram family"
            )
    assert by_labels, f"{family_name}: histogram with no samples"
    for key, entry in by_labels.items():
        buckets = sorted(entry["buckets"])
        assert buckets, f"{family_name}{key}: no buckets"
        assert buckets[-1][0] == float("inf"), (
            f"{family_name}{key}: missing +Inf bucket"
        )
        counts = [c for _, c in buckets]
        assert all(
            a <= b for a, b in zip(counts, counts[1:])
        ), f"{family_name}{key}: bucket counts not cumulative: {counts}"
        assert entry["count"] == counts[-1], (
            f"{family_name}{key}: _count {entry['count']} != +Inf bucket "
            f"{counts[-1]}"
        )
        assert entry["sum"] is not None, f"{family_name}{key}: missing _sum"
