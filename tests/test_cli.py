"""CLI end-to-end coverage (ref Main.py surface): train/resume round-trip,
data utilities, diagnostics, presets, config plumbing."""

import json
from pathlib import Path

import numpy as np
import pytest

from luminaai_tpu.cli import build_parser, main


def run_cli(argv):
    return main(argv)


def test_parser_rejects_unknown_command():
    with pytest.raises(SystemExit):
        build_parser().parse_args(["frobnicate"])


def test_presets_listing(capsys):
    assert run_cli(["presets"]) == 0
    out = capsys.readouterr().out
    assert "debug" in out and "b300" in out

    assert run_cli(["presets", "--json"]) == 0
    data = json.loads(capsys.readouterr().out)
    assert data["b7"]["num_layers"] == 32


def test_diagnose_runs(capsys):
    assert run_cli(["diagnose"]) == 0
    out = capsys.readouterr().out
    assert "SYSTEM DIAGNOSTICS" in out
    assert "device_count: 8" in out  # conftest's virtual CPU mesh


def test_data_sample_writes_conversations(tmp_path):
    sample = tmp_path / "sample.jsonl"
    assert run_cli(["data", "sample", "--out", str(sample), "--count", "7"]) == 0
    lines = sample.read_text().strip().splitlines()
    assert len(lines) == 7
    assert all("messages" in json.loads(l) for l in lines)


def test_data_validate_reports_token_stats(tmp_path, capsys):
    sample = tmp_path / "s.jsonl"
    run_cli(["data", "sample", "--out", str(sample), "--count", "5"])
    capsys.readouterr()
    assert run_cli(["data", "validate", "--in", str(sample)]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["token_stats"]["max"] > 0


def test_train_resume_chat_roundtrip(tmp_path, capsys):
    """The flagship CLI flow: short synthetic train, resume continues from
    the checkpoint, chat loads it on a different device layout."""
    out_dir = str(tmp_path / "run")
    base = [
        "train", "--preset", "debug", "--synthetic", "--precision", "fp32",
        "--no-flash", "--lr", "1e-3", "--batch-size", "8",
        "--output-dir", out_dir, "--quiet", "--no-adaptive",
    ]
    assert run_cli(base + ["--steps", "6"]) == 0
    summary = json.loads((Path(out_dir) / "training_summary.json").read_text())
    assert summary["final_step"] == 6

    assert run_cli([
        "resume", "--preset", "debug", "--synthetic", "--precision", "fp32",
        "--no-flash", "--lr", "1e-3", "--batch-size", "8",
        "--output-dir", out_dir, "--quiet", "--no-adaptive", "--steps", "10",
    ]) == 0
    summary = json.loads((Path(out_dir) / "training_summary.json").read_text())
    assert summary["final_step"] == 10

    capsys.readouterr()
    assert run_cli([
        "chat", "--checkpoint", f"{out_dir}/checkpoints",
        "--prompt", "hello", "--max-new-tokens", "4",
    ]) == 0
    assert capsys.readouterr().out  # produced some text


def test_train_auto_epochs_with_packed_data(tmp_path, capsys):
    """--packed --auto-epochs: text jsonl → token cache → chinchilla step
    budget."""
    docs = tmp_path / "docs.jsonl"
    rng = np.random.RandomState(0)
    with docs.open("w") as f:
        for i in range(30):
            words = " ".join(
                "abcdefgh"[rng.randint(0, 8)] * rng.randint(1, 5)
                for _ in range(rng.randint(20, 60))
            )
            f.write(json.dumps({"text": words}) + "\n")
    out_dir = str(tmp_path / "run2")
    assert run_cli([
        "train", "--preset", "debug", "--data", str(docs), "--packed",
        "--auto-epochs", "--precision", "fp32", "--no-flash",
        "--batch-size", "8", "--steps", "4", "--output-dir", out_dir,
        "--quiet", "--no-adaptive",
    ]) == 0
    out = capsys.readouterr().out
    assert "chinchilla auto-budget" in out
    assert (Path(out_dir) / "training_summary.json").exists()


def test_config_file_roundtrip(tmp_path):
    from luminaai_tpu.config import ConfigPresets

    cfg = ConfigPresets.debug()
    cfg.learning_rate = 3.21e-4
    path = tmp_path / "cfg.json"
    cfg.save(str(path))
    from luminaai_tpu.cli import build_config

    args = build_parser().parse_args(
        ["train", "--config", str(path), "--synthetic", "--quiet"]
    )
    loaded = build_config(args)
    assert abs(loaded.learning_rate - 3.21e-4) < 1e-12


def test_data_acquire_local_dump(tmp_path, capsys):
    dump = tmp_path / "raw.jsonl"
    rows = [
        {"message_id": "r", "parent_id": None, "role": "prompter",
         "text": "hello there, what is jax?", "lang": "en"},
        {"message_id": "a", "parent_id": "r", "role": "assistant",
         "text": "JAX is a numerical computing library with autodiff.",
         "lang": "en"},
    ]
    with open(dump, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    out_dir = tmp_path / "out"
    assert run_cli([
        "data", "acquire", "--in", str(dump), "--out", str(out_dir)
    ]) == 0
    stats = json.loads(capsys.readouterr().out)
    assert stats["count"] == 1 and stats["files"]


def test_report_training_and_data(tmp_path, capsys):
    exp = tmp_path / "exp"
    exp.mkdir()
    (exp / "training_summary.json").write_text(json.dumps({
        "experiment_name": "cli-test", "total_steps": 5,
        "final_metrics": {"best_eval_loss": 3.0},
    }))
    assert run_cli(["report", "training", "--dir", str(exp)]) == 0
    assert "training report" in capsys.readouterr().out

    data = tmp_path / "d.jsonl"
    data.write_text(json.dumps({"messages": [
        {"role": "user", "content": "hi"},
        {"role": "assistant", "content": "hello!"},
    ]}) + "\n")
    out = tmp_path / "data_report.html"
    assert run_cli(["report", "data", "--out", str(out), str(data)]) == 0
    assert out.exists()

    # training report on a dir without a summary fails cleanly
    assert run_cli(["report", "training", "--dir", str(tmp_path / "nope")]) == 1


def test_evaluate_subcommand(tmp_path, capsys):
    # Train briefly, then evaluate the checkpoint on sample data.
    data = tmp_path / "conv.jsonl"
    run_cli(["data", "sample", "--out", str(data), "--count", "24"])
    capsys.readouterr()
    out_dir = tmp_path / "run"
    assert run_cli([
        "train", "--preset", "debug", "--data", str(data),
        "--steps", "3", "--output-dir", str(out_dir),
        "--no-adaptive", "--no-oom-protect", "--quiet",
        "--batch-size", "8",
    ]) == 0
    capsys.readouterr()
    assert run_cli([
        "evaluate", "--checkpoint", str(out_dir / "checkpoints"),
        "--data", str(data), "--batch-size", "8", "--max-batches", "2",
    ]) == 0
    result = json.loads(capsys.readouterr().out)
    assert result["tokens"] > 0 and result["perplexity"] > 1


def test_convert_checkpoint_layout_roundtrip(tmp_path, capsys):
    out_dir = tmp_path / "run"
    assert run_cli([
        "train", "--preset", "debug", "--synthetic", "--steps", "2",
        "--output-dir", str(out_dir), "--no-adaptive", "--no-oom-protect",
        "--quiet", "--batch-size", "8",
    ]) == 0
    capsys.readouterr()
    ckpt = str(out_dir / "checkpoints")
    scan_dir = tmp_path / "scanned"
    assert run_cli([
        "convert", "--checkpoint", ckpt, "--to", "scan", "--out",
        str(scan_dir),
    ]) == 0
    # Converting an already-scanned checkpoint is refused.
    again = tmp_path / "again"
    assert run_cli([
        "convert", "--checkpoint", str(scan_dir), "--to", "scan",
        "--out", str(again),
    ]) == 1
    # Same weights, identical logits across layouts.
    import jax
    import jax.numpy as jnp

    from luminaai_tpu.inference.chat import load_model_for_inference

    m1, p1, c1 = load_model_for_inference(ckpt)
    m2, p2, c2 = load_model_for_inference(str(scan_dir))
    assert c2.scan_layers and not c1.scan_layers
    ids = jnp.ones((1, 16), jnp.int32)
    l1, _ = m1.apply({"params": p1}, ids, deterministic=True)
    l2, _ = m2.apply({"params": p2}, ids, deterministic=True)
    assert float(jnp.abs(l1 - l2).max()) < 2e-2  # bf16 serving cast + scan op order


def test_data_blend_subcommand(tmp_path, capsys):
    for name, texts in {
        "wiki": ["wiki article one " * 30, "wiki article two " * 30],
        "web": ["web page " * 40],
    }.items():
        with open(tmp_path / f"{name}.jsonl", "w") as f:
            for t in texts:
                f.write(json.dumps({"text": t, "source": name}) + "\n")
    out = tmp_path / "blend.jsonl"
    assert run_cli([
        "data", "blend", "--out", str(out),
        "--sources",
        f"wiki=0.7={tmp_path}/wiki.jsonl",
        f"web=0.3={tmp_path}/web.jsonl",
    ]) == 0
    lines = [json.loads(l) for l in out.read_text().splitlines()]
    assert len(lines) == 3
    assert {l["source"] for l in lines} == {"wiki", "web"}
    # malformed spec fails cleanly
    assert run_cli(["data", "blend", "--sources", "bad-spec"]) == 2


def test_train_writes_experiment_metadata(tmp_path, capsys):
    out_dir = tmp_path / "run"
    assert run_cli([
        "train", "--preset", "debug", "--synthetic", "--steps", "2",
        "--output-dir", str(out_dir), "--no-adaptive", "--no-oom-protect",
        "--batch-size", "8",
    ]) == 0
    captured = capsys.readouterr().out
    assert "estimated training time" in captured
    meta = json.loads((out_dir / "experiment_metadata.json").read_text())
    assert meta["planned_steps"] == 2 and meta["total_params"] > 0


def test_finetune_adapter_and_chat(tmp_path, capsys):
    """PEFT flow: base train -> LoRA finetune -> chat with --adapter
    (docs/adapters.md; training/adapters.py)."""
    out_dir = str(tmp_path / "base")
    assert run_cli([
        "train", "--preset", "debug", "--synthetic", "--precision", "fp32",
        "--no-flash", "--lr", "1e-3", "--batch-size", "8",
        "--output-dir", out_dir, "--quiet", "--no-adaptive", "--steps", "4",
    ]) == 0

    data = tmp_path / "ft.jsonl"
    assert run_cli(["data", "sample", "--out", str(data), "--count", "24"]) == 0

    adapter_dir = str(tmp_path / "adapter")
    capsys.readouterr()
    assert run_cli([
        "finetune", "--checkpoint", f"{out_dir}/checkpoints",
        "--data", str(data), "--out", adapter_dir,
        "--rank", "4", "--steps", "3", "--batch-size", "4",
        "--merge-out", str(tmp_path / "merged"),
    ]) == 0
    out = capsys.readouterr().out
    assert "adapter saved" in out and "merged checkpoint" in out
    assert (Path(adapter_dir) / "adapter.npz").exists()
    assert (Path(adapter_dir) / "adapter.json").exists()

    capsys.readouterr()
    assert run_cli([
        "chat", "--checkpoint", f"{out_dir}/checkpoints",
        "--adapter", str(Path(adapter_dir) / "adapter"),
        "--prompt", "hi", "--max-new-tokens", "4",
    ]) == 0
    assert capsys.readouterr().out


def test_convert_int8_export_and_serve(tmp_path, capsys):
    """cli convert --to int8 writes a quantized serving checkpoint (ref
    trainer.py:681,712 GPTQ/quanto model saves): chat loads it directly
    (QuantizedTensor leaves rebuilt from the manifest, no re-quantization)
    and logits stay close to the source checkpoint's."""
    out_dir = tmp_path / "run"
    assert run_cli([
        "train", "--preset", "debug", "--synthetic", "--steps", "2",
        "--output-dir", str(out_dir), "--no-adaptive", "--no-oom-protect",
        "--quiet", "--batch-size", "8",
    ]) == 0
    capsys.readouterr()
    ckpt = str(out_dir / "checkpoints")
    q_dir = tmp_path / "int8"
    assert run_cli([
        "convert", "--checkpoint", ckpt, "--to", "int8", "--out",
        str(q_dir),
    ]) == 0
    assert "int8 serving export" in capsys.readouterr().out

    import jax
    import jax.numpy as jnp

    from luminaai_tpu.inference.chat import load_model_for_inference
    from luminaai_tpu.training.quantization import QuantizedTensor

    m1, p1, _ = load_model_for_inference(ckpt)
    m2, p2, c2 = load_model_for_inference(str(q_dir), allow_quantized=True)
    qleaves = [
        l for l in jax.tree_util.tree_leaves(
            p2, is_leaf=lambda x: isinstance(x, QuantizedTensor)
        )
        if isinstance(l, QuantizedTensor)
    ]
    assert qleaves, "no quantized tensors reconstructed"
    assert c2.quantization_method is None  # no double-quantize on load
    ids = jnp.ones((1, 16), jnp.int32)
    l1, _ = m1.apply({"params": p1}, ids, deterministic=True)
    l2, _ = m2.apply({"params": p2}, ids, deterministic=True)
    agree = float(
        (jnp.argmax(l1, -1) == jnp.argmax(l2, -1)).mean()
    )
    assert agree > 0.9, agree

    # The export is materially smaller on disk than the source.
    def tree_bytes(d):
        import os
        return sum(
            os.path.getsize(os.path.join(r, f))
            for r, _, fs in os.walk(d) for f in fs
        )
    assert tree_bytes(q_dir) < 0.75 * tree_bytes(out_dir / "checkpoints")


def test_int8_export_rejected_by_nonserving_consumers(tmp_path, capsys):
    """An int8 serving export must be refused (clearly, not corrupted)
    by convert/eval/finetune — only chat/serve may load it."""
    out_dir = tmp_path / "run"
    assert run_cli([
        "train", "--preset", "debug", "--synthetic", "--steps", "2",
        "--output-dir", str(out_dir), "--no-adaptive", "--no-oom-protect",
        "--quiet", "--batch-size", "8",
    ]) == 0
    q_dir = tmp_path / "int8"
    assert run_cli([
        "convert", "--checkpoint", str(out_dir / "checkpoints"),
        "--to", "int8", "--out", str(q_dir),
    ]) == 0
    capsys.readouterr()
    # Double-quantization refused.
    assert run_cli([
        "convert", "--checkpoint", str(q_dir), "--to", "int8",
        "--out", str(tmp_path / "again"),
    ]) == 1
    assert "SERVING checkpoint" in capsys.readouterr().err
    # Full-precision consumers refuse too.
    import pytest as _pytest

    from luminaai_tpu.inference.chat import load_model_for_inference

    with _pytest.raises(ValueError, match="SERVING checkpoint"):
        load_model_for_inference(str(q_dir), keep_master_dtype=True)
