"""Model unit tests (mirrors ref Src/tests/test_model.py strategy)."""

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from luminaai_tpu.config import Config, ConfigPresets
from luminaai_tpu.models.layers import RMSNorm, SwiGLU, apply_rope, rope_frequencies
from luminaai_tpu.models.transformer import LuminaTransformer, count_params


def tiny_config(**kw) -> Config:
    base = dict(
        vocab_size=256,
        hidden_size=64,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        seq_length=64,
        intermediate_size=128,
        use_moe=False,
        use_mod=False,
        gradient_checkpointing=False,
        use_flash_attention=False,
    )
    base.update(kw)
    return Config(**base)


@pytest.fixture
def rng():
    return jax.random.PRNGKey(0)


class TestRMSNorm:
    def test_normalizes(self, rng):
        x = jax.random.normal(rng, (2, 8, 64)) * 10.0
        norm = RMSNorm(dtype=jnp.float32)
        y, _ = norm.init_with_output(rng, x)
        rms = jnp.sqrt(jnp.mean(y**2, axis=-1))
        assert jnp.allclose(rms, 1.0, atol=1e-3)

    def test_dtype(self, rng):
        x = jax.random.normal(rng, (2, 8, 64), jnp.bfloat16)
        y, variables = RMSNorm(dtype=jnp.bfloat16).init_with_output(rng, x)
        assert y.dtype == jnp.bfloat16
        # params stay fp32 (mixed precision policy); unbox sharding metadata
        from flax.linen import meta

        scale = meta.unbox(variables["params"])["scale"]
        assert scale.dtype == jnp.float32


class TestRoPE:
    def test_rotation_preserves_norm(self, rng):
        cos, sin = rope_frequencies(64, 128)
        x = jax.random.normal(rng, (1, 128, 2, 64))
        y = apply_rope(x, cos, sin)
        assert jnp.allclose(
            jnp.linalg.norm(x, axis=-1), jnp.linalg.norm(y, axis=-1), atol=1e-4
        )

    def test_position_zero_identity(self, rng):
        cos, sin = rope_frequencies(64, 16)
        x = jax.random.normal(rng, (1, 1, 1, 64))
        y = apply_rope(x, cos, sin, positions=jnp.zeros((1, 1), jnp.int32))
        assert jnp.allclose(x, y, atol=1e-6)

    def test_relative_property(self, rng):
        # <R(p)q, R(p+k)k> depends only on offset k: shift both positions.
        d = 64
        cos, sin = rope_frequencies(d, 256)
        q = jax.random.normal(rng, (1, 1, 1, d))
        k = jax.random.normal(jax.random.fold_in(rng, 1), (1, 1, 1, d))
        def dot_at(p0, p1):
            qp = apply_rope(q, cos, sin, jnp.array([[p0]]))
            kp = apply_rope(k, cos, sin, jnp.array([[p1]]))
            return float(jnp.sum(qp * kp))
        assert dot_at(3, 7) == pytest.approx(dot_at(100, 104), abs=1e-3)

    def test_bf16_rotation_parity(self, rng):
        """rope_dtype='bf16' (the r6 flagship_tuned default) only changes
        the PRODUCT rounding: bf16 inputs/outputs are quantized either
        way, so the two rotations must agree to bf16 resolution — on the
        table path AND the explicit-positions path — and bf16 rotation
        must still preserve norms."""
        d, S = 64, 128
        cos, sin = rope_frequencies(d, S)
        x = jax.random.normal(rng, (2, S, 4, d)).astype(jnp.bfloat16)
        ref = apply_rope(x, cos, sin, compute_dtype=jnp.float32).astype(
            jnp.float32
        )
        out = apply_rope(x, cos, sin, compute_dtype=jnp.bfloat16).astype(
            jnp.float32
        )
        # |x| ~ N(0,1): 2 bf16 ulps of headroom at the observed scale.
        assert float(jnp.max(jnp.abs(out - ref))) < 0.06
        assert float(
            jnp.mean(jnp.abs(out - ref))
        ) < 0.01  # drift is rounding noise, not bias
        pos = jnp.broadcast_to(jnp.arange(S)[None], (2, S))
        out_pos = apply_rope(
            x, cos, sin, positions=pos, compute_dtype=jnp.bfloat16
        ).astype(jnp.float32)
        assert jnp.allclose(out_pos, out, atol=1e-6)
        assert jnp.allclose(
            jnp.linalg.norm(x.astype(jnp.float32), axis=-1),
            jnp.linalg.norm(out, axis=-1),
            rtol=0.05,
        )


class TestSwiGLU:
    def test_shape_and_grad(self, rng):
        x = jax.random.normal(rng, (2, 8, 64), jnp.float32)
        mod = SwiGLU(intermediate_size=128, dtype=jnp.float32)
        y, variables = mod.init_with_output(rng, x)
        assert y.shape == x.shape
        g = jax.grad(lambda p: mod.apply({"params": p}, x).sum())(variables["params"])
        assert all(jnp.isfinite(v).all() for v in jax.tree.leaves(g))


class TestTransformer:
    @pytest.mark.parametrize(
        "kw",
        [
            {},
            {"use_moe": True, "num_experts": 4, "moe_top_k": 2},
            {"use_mod": True, "mod_capacity_factor": 0.5},
            {
                "use_moe": True,
                "use_mod": True,
                "num_experts": 4,
                "moe_pattern": "sandwich",
                "dense_start_layers": 1,
                "dense_end_layers": 0,
                "num_layers": 3,
            },
        ],
        ids=["dense", "moe", "mod", "hybrid"],
    )
    def test_forward_backward(self, rng, kw):
        cfg = tiny_config(**kw)
        model = LuminaTransformer(cfg)
        ids = jax.random.randint(rng, (2, cfg.seq_length), 0, cfg.vocab_size)
        variables = model.init({"params": rng, "routing": rng}, ids)
        logits, aux = model.apply(
            variables, ids, deterministic=False, rngs={"routing": rng}
        )
        assert logits.shape == (2, cfg.seq_length, cfg.vocab_size)
        assert logits.dtype == jnp.float32
        assert jnp.isfinite(logits).all()
        assert jnp.isfinite(aux["aux_loss"])

        def loss_fn(params):
            lg, aux = model.apply(
                {"params": params}, ids, deterministic=False, rngs={"routing": rng}
            )
            return lg.astype(jnp.float32).mean() + aux["aux_loss"]

        grads = jax.grad(loss_fn)(variables["params"])
        assert all(jnp.isfinite(g).all() for g in jax.tree.leaves(grads))

    def test_remat_matches_no_remat(self, rng):
        cfg = tiny_config()
        ids = jax.random.randint(rng, (2, cfg.seq_length), 0, cfg.vocab_size)
        outs = []
        variables = None
        for remat in (False, True):
            c = dataclasses.replace(cfg, gradient_checkpointing=remat)
            model = LuminaTransformer(c)
            if variables is None:
                variables = model.init({"params": rng}, ids)
            logits, _ = model.apply(variables, ids)
            outs.append(logits)
        assert jnp.allclose(outs[0], outs[1], atol=1e-5)

    def test_param_count_matches_estimate(self, rng):
        cfg = tiny_config(use_moe=True, num_experts=4)
        model = LuminaTransformer(cfg)
        ids = jnp.zeros((1, 8), jnp.int32)
        variables = model.init({"params": rng, "routing": rng}, ids)
        actual = count_params(variables["params"])
        est = cfg.estimate_parameters()
        assert abs(actual - est) / actual < 0.02, (actual, est)

    def test_causality(self, rng):
        """Changing a future token must not change past logits."""
        cfg = tiny_config()
        model = LuminaTransformer(cfg)
        ids = jax.random.randint(rng, (1, cfg.seq_length), 0, cfg.vocab_size)
        variables = model.init({"params": rng}, ids)
        logits1, _ = model.apply(variables, ids)
        ids2 = ids.at[0, -1].set((ids[0, -1] + 1) % cfg.vocab_size)
        logits2, _ = model.apply(variables, ids2)
        assert jnp.allclose(logits1[0, :-1], logits2[0, :-1], atol=1e-5)


class TestKVCache:
    def test_incremental_decode_matches_full(self, rng):
        cfg = tiny_config()
        model = LuminaTransformer(cfg)
        S = 16
        ids = jax.random.randint(rng, (1, S), 0, cfg.vocab_size)
        variables = model.init({"params": rng}, ids)
        full_logits, _ = model.apply(variables, ids)

        caches = model.init_cache(1, S)
        step_logits = []
        for t in range(S):
            lg, caches, _ = model.apply(
                variables,
                ids[:, t : t + 1],
                positions=jnp.array([[t]]),
                kv_caches=caches,
                cache_index=t,
            )
            step_logits.append(lg[:, 0])
        inc = jnp.stack(step_logits, axis=1)
        assert jnp.allclose(full_logits, inc, atol=2e-2), (
            float(jnp.abs(full_logits - inc).max())
        )


class TestConfig:
    def test_presets_valid(self):
        for name in ConfigPresets.available():
            cfg = ConfigPresets.get(name)
            assert cfg.estimate_parameters() > 0

    def test_moe_patterns(self):
        cfg = tiny_config(
            use_moe=True, num_layers=6, moe_pattern="every_3rd", num_experts=4
        )
        assert [cfg.is_moe_layer(i) for i in range(6)] == [
            False, False, True, False, False, True,
        ]
        cfg2 = dataclasses.replace(cfg, moe_pattern="sandwich", dense_start_layers=2, dense_end_layers=2)
        assert [cfg2.is_moe_layer(i) for i in range(6)] == [
            False, False, True, True, False, False,
        ]

    def test_validation_errors(self):
        with pytest.raises(AssertionError):
            tiny_config(hidden_size=65)
        with pytest.raises(AssertionError):
            tiny_config(use_moe=True, moe_top_k=9, num_experts=4)
        with pytest.raises(AssertionError):
            tiny_config(use_mod=True, mod_capacity_factor=1.5)

    def test_roundtrip(self, tmp_path):
        cfg = ConfigPresets.debug()
        p = str(tmp_path / "c.yaml")
        cfg.save(p)
        cfg2 = Config.load(p)
        assert cfg.to_dict() == cfg2.to_dict()


def test_untied_embeddings_has_lm_head():
    """tie_word_embeddings=False adds an independent output head used by
    both the logits path and the fused-CE path."""
    import jax

    from tests.test_sharding import run_one_step, tiny_config

    cfg = tiny_config(tie_word_embeddings=False)
    from luminaai_tpu.models.transformer import LuminaTransformer

    model = LuminaTransformer(cfg)
    ids = jnp.ones((1, cfg.seq_length), jnp.int32)
    params = model.init(jax.random.key(0), ids)["params"]
    emb = params["embedder"]
    assert "lm_head" in emb and emb["lm_head"].value.shape == (
        cfg.vocab_size, cfg.hidden_size
    )
    _, m, _ = run_one_step(cfg)
    assert jnp.isfinite(float(m["loss"]))


def test_micro_batch_size_drives_accumulation():
    from tests.test_sharding import run_one_step, tiny_config

    cfg = tiny_config(micro_batch_size=2)  # batch 8 → accum 4
    assert cfg.gradient_accumulation_steps == 4
    base = tiny_config()
    _, m, _ = run_one_step(cfg)
    _, m0, _ = run_one_step(base)
    assert abs(float(m["ce_loss"]) - float(m0["ce_loss"])) < 5e-2


class TestRematPolicies:
    """Gradients must be identical across remat policies — they trade
    memory for recompute, never numerics (transformer.py REMAT_POLICIES)."""

    def test_policies_same_grads(self):
        rng = jax.random.PRNGKey(0)
        cfg = tiny_config(
            use_moe=True, num_experts=4, routing_noise_std=0.0,
            gradient_checkpointing=True,
        )
        ids = jax.random.randint(rng, (2, cfg.seq_length), 0, cfg.vocab_size)

        def grads_for(policy):
            c = dataclasses.replace(cfg, remat_policy=policy)
            model = LuminaTransformer(c)
            variables = model.init({"params": rng}, ids)

            def loss(p):
                lg, aux = model.apply({"params": p}, ids)
                return lg.astype(jnp.float32).mean() + aux["aux_loss"]

            return jax.grad(loss)(variables["params"])

        ref = grads_for("nothing_saveable")
        for policy in ("save_outs", "save_attn", "dots_saveable"):
            g = grads_for(policy)
            for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(g)):
                assert jnp.allclose(a, b, atol=1e-5), policy

    def test_save_attn_parity_with_flash_kernel(self):
        """save_attn's saved (out, lse) residuals come from checkpoint_name
        tags inside the flash custom_vjp fwd — parity must hold with the
        Pallas kernel actually on (interpret mode on CPU), where the saved
        residuals replace the recomputed forward in the backward pass."""
        rng = jax.random.PRNGKey(1)
        cfg = tiny_config(
            gradient_checkpointing=True,
            use_flash_attention=True,
            flash_block_q=128,
            flash_block_kv=128,
            seq_length=256,
            num_heads=2,
            num_kv_heads=1,
            hidden_size=128,  # head_dim 64: flash_eligible
        )
        ids = jax.random.randint(rng, (2, cfg.seq_length), 0, cfg.vocab_size)

        def grads_for(policy):
            c = dataclasses.replace(cfg, remat_policy=policy)
            model = LuminaTransformer(c)
            variables = model.init({"params": rng}, ids)

            def loss(p):
                lg, aux = model.apply({"params": p}, ids)
                return lg.astype(jnp.float32).mean() + aux["aux_loss"]

            return jax.grad(loss)(variables["params"])

        ref = grads_for("save_outs")
        g = grads_for("save_attn")
        for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(g)):
            assert jnp.allclose(a, b, atol=1e-6)


def test_attention_window_model_paths_agree():
    """config.attention_window: the flash kernel's block-skip banding and
    the XLA fallback's mask must implement the same window; a windowed
    model must differ from full causal."""
    import numpy as np

    cfg = tiny_config(
        use_flash_attention=True,
        flash_block_q=128,
        flash_block_kv=128,
        seq_length=256,
        num_heads=2,
        num_kv_heads=1,
        hidden_size=128,  # head_dim 64: flash_eligible
        attention_window=64,
        precision="fp32",  # sharp flash-vs-XLA comparison (bf16 noise
        # at early positions otherwise dominates the 2e-2 tolerance)
    )
    ids = jax.random.randint(
        jax.random.PRNGKey(0), (2, cfg.seq_length), 0, cfg.vocab_size
    )
    model = LuminaTransformer(cfg)
    params = model.init({"params": jax.random.PRNGKey(0)}, ids)["params"]
    flash_logits, _ = model.apply({"params": params}, ids)
    xla_cfg = dataclasses.replace(cfg, use_flash_attention=False)
    xla_logits, _ = LuminaTransformer(xla_cfg).apply({"params": params}, ids)
    np.testing.assert_allclose(
        np.asarray(flash_logits), np.asarray(xla_logits), atol=2e-2
    )
    full_cfg = dataclasses.replace(cfg, attention_window=None)
    full_logits, _ = LuminaTransformer(full_cfg).apply({"params": params}, ids)
    assert float(jnp.max(jnp.abs(flash_logits - full_logits))) > 1e-3
