"""Orchestrator, scaler and expert-evolution tests (SURVEY.md §4:
'orchestrator intervention fires on synthetic anomaly')."""

import numpy as np

import jax

from luminaai_tpu.config import Config
from luminaai_tpu.training.evolution import (
    evolution_feasible,
    grow_expert,
    num_experts_in,
    prune_expert,
)
from luminaai_tpu.training.orchestrator import (
    AdaptiveHyperparameterOptimizer,
    AdaptiveTrainingOrchestrator,
    ArchitectureEvolution,
    MetaLearningEngine,
    ProductionMonitoring,
    RealTimeAnalytics,
)
from luminaai_tpu.training.scaler import (
    ChinchillaScaler,
    ComputeEfficiencyTracker,
    ConvergenceDetector,
)
from luminaai_tpu.training.trainer import Trainer


def tiny_config(tmp, **kw) -> Config:
    base = dict(
        vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
        num_kv_heads=2, seq_length=64, batch_size=8,
        use_flash_attention=False, gradient_checkpointing=False,
        precision="fp32", max_steps=30, eval_every_n_batches=1000,
        save_every_n_batches=10, health_check_interval=5,
        intervention_cooldown_steps=10, output_dir=str(tmp),
    )
    base.update(kw)
    return Config(**base)


def patterned_data(cfg, n_batches=200):
    def gen():
        rng = np.random.RandomState(0)
        for _ in range(n_batches):
            starts = rng.randint(0, 32, size=(cfg.batch_size, 1))
            seq = (starts + np.arange(cfg.seq_length)) % 64 + 1
            yield {"input_ids": seq.astype(np.int32)}

    return gen


# -- analytics ------------------------------------------------------------
def test_analytics_detects_loss_spike_and_grad_explosion():
    a = RealTimeAnalytics()
    for i in range(60):
        a.observe(i, 1.0 + 0.001 * np.random.RandomState(i).randn(), 1.0)
    for i in range(60, 70):
        a.observe(i, 3.5, 500.0)
    types = {x["type"] for x in a.detect_anomalies()}
    assert "loss_spike" in types and "gradient_explosion" in types


def test_analytics_expert_collapse():
    a = RealTimeAnalytics()
    util = np.array([7.5, 0.001, 0.2, 0.3])
    for i in range(60):
        a.observe(i, 1.0, 1.0, util)
    assert any(x["type"] == "expert_collapse" for x in a.detect_anomalies())


def test_loss_dynamics_trend():
    a = RealTimeAnalytics()
    for i in range(100):
        a.observe(i, 5.0 - 0.03 * i, 1.0)
    insights = a.analyze_loss_dynamics()
    assert insights["trend_direction"] == "decreasing"


# -- hyperparameter optimizer ---------------------------------------------
def test_hyper_optimizer_divergence_cuts_lr():
    h = AdaptiveHyperparameterOptimizer(min_gap_steps=0)
    for i in range(20):
        h.observe(i, 1.0, 1.0)
    for i in range(20, 26):
        h.observe(i, 2.5, 1.0)
    prop = h.propose(26)
    assert prop is not None and prop["action"] == "decrease"


def test_hyper_optimizer_plateau_raises_lr():
    h = AdaptiveHyperparameterOptimizer(min_gap_steps=0)
    for i in range(25):
        h.observe(i, 1.8, 1.0)
    prop = h.propose(25)
    assert prop is not None and prop["action"] == "increase"


# -- architecture evolution ------------------------------------------------
def test_evolution_prune_on_dead_expert():
    e = ArchitectureEvolution(window=5)
    util = np.array([2.0, 0.01, 1.0, 1.0])
    for _ in range(5):
        e.observe(util, drop_rate=0.0)
    prop = e.propose()
    assert prop["action"] == "prune_expert" and prop["expert_idx"] == 1


def test_evolution_add_on_capacity_pressure():
    e = ArchitectureEvolution(window=5)
    util = np.ones(4)
    for _ in range(5):
        e.observe(util, drop_rate=0.3)
    assert e.propose()["action"] == "add_expert"


# -- expert param surgery --------------------------------------------------
def moe_params(E=4, H=8, F=16):
    key = jax.random.key(0)
    return {
        "layer_0": {
            "moe": {
                "router": jax.random.normal(key, (H, E)),
                "wi": jax.random.normal(key, (E, H, 2 * F)),  # lumina: disable=LX005 -- deterministic fixture params, reuse intended
                "wo": jax.random.normal(key, (E, F, H)),  # lumina: disable=LX005 -- deterministic fixture params, reuse intended
            },
            "ffn": {"kernel": jax.random.normal(key, (H, H))},  # lumina: disable=LX005 -- deterministic fixture params, reuse intended
        }
    }


def test_grow_and_prune_expert_shapes():
    p = moe_params(E=4)
    grown = grow_expert(p, jax.random.key(1))
    assert num_experts_in(grown) == 5
    assert grown["layer_0"]["moe"]["wi"].shape[0] == 5
    # Non-MoE params untouched.
    assert grown["layer_0"]["ffn"]["kernel"].shape == (8, 8)
    pruned = prune_expert(grown, 2)
    assert num_experts_in(pruned) == 4
    # New expert starts near the mean of the others.
    mean_wi = p["layer_0"]["moe"]["wi"].mean(axis=0)
    np.testing.assert_allclose(
        grown["layer_0"]["moe"]["wi"][4], mean_wi, atol=0.1
    )


def test_evolution_feasibility_gates():
    cfg = Config(use_moe=True, num_experts=8, expert_parallel_size=4,
                 hidden_size=64, num_heads=4, num_kv_heads=2, vocab_size=128)
    ok, why = evolution_feasible(cfg, 9)
    assert not ok and "divisible" in why
    ok, _ = evolution_feasible(cfg, 12)
    assert ok


def test_trainer_evolve_experts_end_to_end(tmp_path):
    cfg = tiny_config(tmp_path, use_moe=True, num_experts=4, max_steps=2)
    t = Trainer(cfg, train_data=patterned_data(cfg),
                checkpoint_dir=str(tmp_path / "ckpt"))
    batch = t._put(next(patterned_data(cfg)()))
    t.state, m1 = t.train_step(t.state, batch)
    step_before = int(t.state.step)
    assert t.evolve_experts("add_expert", reason="test")
    assert cfg.num_experts == 5
    # Optimizer re-init must NOT reset schedule counts (warmup would replay).
    counts = [
        l for p, l in jax.tree_util.tree_flatten_with_path(t.state.opt_state)[0]
        if getattr(p[-1], "name", None) == "count"
    ]
    assert counts and all(int(c) == step_before for c in counts)
    t.state, m2 = t.train_step(t.state, batch)  # recompiled step runs
    assert np.isfinite(float(m2["loss"]))
    assert t.evolve_experts("prune_expert", expert_idx=4, reason="test")
    assert cfg.num_experts == 4
    t.close()


# -- orchestrated training -------------------------------------------------
def test_orchestrator_intervenes_on_synthetic_anomaly(tmp_path):
    """Feed the orchestrator a fabricated divergence; LR override fires."""
    # max_steps=200 keeps the fabricated steps inside the schedule body
    # (LR interventions are gated off during warmup and terminal decay).
    cfg = tiny_config(tmp_path, enable_adaptive_lr=True,
                      min_override_threshold=0.2, max_steps=200)
    t = Trainer(cfg, train_data=patterned_data(cfg),
                checkpoint_dir=str(tmp_path / "ckpt"))
    orch = AdaptiveTrainingOrchestrator(t)
    for i in range(5, 105, 5):
        loss = 1.0 if i < 75 else 4.0  # divergence at the end
        orch.on_metrics(i, {"loss": loss, "grad_norm": 1.0})
    applied = [d for d in orch.decisions if d.applied]
    assert applied, "no intervention fired on synthetic divergence"
    assert t._lr_override is not None and t._lr_override < cfg.learning_rate


def test_orchestrated_run_end_to_end(tmp_path):
    cfg = tiny_config(tmp_path, max_steps=12, health_check_interval=4)
    t = Trainer(cfg, train_data=patterned_data(cfg),
                eval_data=patterned_data(cfg, n_batches=2),
                checkpoint_dir=str(tmp_path / "ckpt"))
    orch = AdaptiveTrainingOrchestrator(t)
    summary = orch.run()
    assert summary["final_step"] == 12
    assert "adaptive_decisions" in summary
    # Meta-learning recorded the run.
    meta2 = MetaLearningEngine(f"{cfg.output_dir}/meta_history.jsonl")
    assert len(meta2.runs) == 1
    sugg = meta2.suggest_hyperparameters(cfg)
    assert sugg == {} or "learning_rate" in sugg
    t.close()


def test_trajectory_prediction_classes():
    """predict_training_trajectory buckets by loss slope (ref
    orchestrator.py:253)."""
    a = RealTimeAnalytics()
    assert a.predict_training_trajectory() is None  # cold start
    for i in range(20):
        a.observe(i, 3.0 - 0.05 * i, 1.0)
    t = a.predict_training_trajectory()
    assert t["prediction"] == "healthy_convergence"
    a = RealTimeAnalytics()
    for i in range(20):
        a.observe(i, 1.5, 1.0)
    assert a.predict_training_trajectory()["prediction"] == "plateau"
    a = RealTimeAnalytics()
    for i in range(20):
        a.observe(i, 1.0 + 0.01 * i, 1.0)
    t = a.predict_training_trajectory()
    assert t["prediction"] == "potential_divergence"
    assert t["suggested_action"] == "reduce_lr_or_add_regularization"


def test_orchestrator_fires_expert_dropout_on_collapse(tmp_path):
    """Synthetic expert collapse → expert_dropout intervention (ref
    trainer.py:1495); the rebuilt step must run with the dropout mask."""
    cfg = tiny_config(
        tmp_path, use_moe=True, num_experts=4, max_steps=400,
        min_override_threshold=0.2, enable_adaptive_lr=False,
    )
    t = Trainer(cfg, train_data=patterned_data(cfg),
                checkpoint_dir=str(tmp_path / "ckpt"))
    orch = AdaptiveTrainingOrchestrator(t)
    collapsed = np.array([3.2, 0.01, 0.4, 0.39])
    for i in range(5, 305, 5):
        orch.on_metrics(
            i, {"loss": 1.0, "grad_norm": 1.0,
                "expert_utilization": collapsed, "moe_drop_rate": 0.0},
        )
    fired = [d for d in orch.decisions if d.kind == "expert_dropout" and d.applied]
    assert fired, [d.to_dict() for d in orch.decisions]
    assert cfg.expert_dropout_rate == 0.1
    batch = t._put(next(patterned_data(cfg)()))
    t.state, m = t.train_step(t.state, batch)
    assert np.isfinite(float(m["loss"]))
    # Collapse persisting WITH dropout on falls back to clip tightening.
    for i in range(305, 505, 5):
        orch.on_metrics(
            i, {"loss": 1.0, "grad_norm": 1.0,
                "expert_utilization": collapsed, "moe_drop_rate": 0.0},
        )
    assert any(d.kind == "clip_tighten" and d.applied for d in orch.decisions)
    # Once routing recovers and stays healthy, the orchestrator reverts the
    # dropout it enabled (it must not perturb healthy routing forever).
    healthy = np.array([1.1, 0.9, 1.0, 1.0])
    for i in range(505, 905, 5):
        orch.on_metrics(
            i, {"loss": 1.0, "grad_norm": 1.0,
                "expert_utilization": healthy, "moe_drop_rate": 0.0},
        )
    assert cfg.expert_dropout_rate == 0.0, [
        d.to_dict() for d in orch.decisions
    ]
    t.close()


def test_orchestrator_raises_weight_decay_on_loss_creep(tmp_path):
    """Slow sustained loss rise (no spike) → weight_decay intervention (ref
    trainer.py:1792); optimizer state must survive the tx rebuild."""
    cfg = tiny_config(
        tmp_path, max_steps=1000, min_override_threshold=0.2,
        enable_adaptive_lr=False, enable_batch_size_optimization=False,
    )
    t = Trainer(cfg, train_data=patterned_data(cfg),
                checkpoint_dir=str(tmp_path / "ckpt"))
    batch = t._put(next(patterned_data(cfg)()))
    t.state, _ = t.train_step(t.state, batch)  # materialize opt state
    wd0 = cfg.weight_decay
    orch = AdaptiveTrainingOrchestrator(t)
    for i in range(5, 505, 5):
        # +0.002/observation: too slow for the spike/divergence rules, but a
        # clearly positive slope for the trajectory classifier.
        orch.on_metrics(i, {"loss": 1.0 + 0.002 * (i // 5), "grad_norm": 1.0})
    fired = [d for d in orch.decisions if d.kind == "weight_decay" and d.applied]
    assert fired, [d.to_dict() for d in orch.decisions]
    assert cfg.weight_decay > wd0
    t.state, m = t.train_step(t.state, batch)  # rebuilt step + carried state
    assert np.isfinite(float(m["loss"]))
    t.close()


def test_orchestrator_schedules_mod_capacity_by_phase(tmp_path):
    """Phase-scheduled MoD compute ratio (ref Main.py
    mod_capacity_adaptation + trainer.py:1559 adjust_mod_capacity): the
    orchestrator walks the early/mid/late schedule as steps cross the
    1/3 and 2/3 boundaries, one recompile per boundary, and the rebuilt
    step runs."""
    cfg = tiny_config(
        tmp_path, use_mod=True, use_moe=False, max_steps=300,
        min_override_threshold=0.2, enable_adaptive_lr=False,
        enable_mod_capacity_adaptation=True,
        mod_capacity_factor=0.7,  # already at the early-phase target
    )
    t = Trainer(cfg, train_data=patterned_data(cfg),
                checkpoint_dir=str(tmp_path / "ckpt"))
    orch = AdaptiveTrainingOrchestrator(t)
    for i in range(5, 300, 5):
        orch.on_metrics(i, {"loss": 1.0, "grad_norm": 1.0})
    fired = [d for d in orch.decisions if d.kind == "mod_capacity" and d.applied]
    targets = [d.params["new_value"] for d in fired]
    assert targets == [0.5, 0.3], [d.to_dict() for d in orch.decisions]
    assert cfg.mod_capacity_factor == 0.3
    batch = t._put(next(patterned_data(cfg)()))
    t.state, m = t.train_step(t.state, batch)
    assert np.isfinite(float(m["loss"]))
    stats = t.mod_statistics()
    assert stats["configured_capacity"] == 0.3
    t.close()


# -- scaler ----------------------------------------------------------------
def test_chinchilla_plan():
    cfg = Config(hidden_size=64, num_layers=2, num_heads=4, num_kv_heads=2,
                 vocab_size=128, batch_size=8, seq_length=64,
                 use_chinchilla_scaling=True)
    plan = ChinchillaScaler(cfg).plan(dataset_tokens=1_000_000)
    assert plan.optimal_tokens == int(20.0 * cfg.estimate_parameters())
    assert plan.recommended_steps == plan.optimal_tokens // (8 * 64)
    sc = ChinchillaScaler(cfg)
    steps = sc.apply()
    assert cfg.max_steps == steps


def test_convergence_detector():
    d = ConvergenceDetector(patience=3, min_steps=0)
    assert not d.update(2.0, 10)
    assert not d.update(1.5, 20)
    assert not d.update(1.501, 30)
    assert not d.update(1.502, 40)
    assert d.update(1.503, 50)  # 3rd stale


def test_efficiency_tracker_mfu():
    tr = ComputeEfficiencyTracker(active_params=1_000_000, n_chips=1,
                                  peak_flops=100e12)
    s = tr.record(tokens=10_000, seconds=1.0)
    # 6*1e6*1e4 = 6e10 FLOPs in 1s → 0.06% of 100 TFLOPs.
    assert abs(s["mfu"] - 6e-4) < 1e-6


# -- production monitoring --------------------------------------------------
def test_production_monitoring_drift_and_safety():
    p = ProductionMonitoring()
    ref = ["the cat sat on the mat"] * 10
    same = p.monitor_semantic_drift(["the cat sat on the mat"], ref)
    assert same is None
    drifted = p.monitor_semantic_drift(
        ["zx qv wk jj pq mm nn oo"] * 5, ref
    )
    assert drifted is not None and drifted["alert"] == "semantic_drift"
    flags = p.track_safety_metrics(["please give me your credit card number"])
    assert flags and flags[0]["metric"] == "flagged_content"


# -- adaptive curriculum (ref chinchilla_scaler.py:155) ---------------------
def test_adaptive_curriculum_signal_moves():
    from luminaai_tpu.training.scaler import AdaptiveCurriculum

    c = AdaptiveCurriculum()
    assert c.difficulty() == 0.3  # cold start (ref default)
    # Fast learning: loss drops 0.05/update → velocity well above 0.01.
    for i in range(20):
        c.update(6.0 - 0.05 * i)
    assert c.difficulty() > 0.8
    # Plateau: velocity ~0 → difficulty falls back toward easy data.
    for _ in range(20):
        c.update(5.0)
    assert c.difficulty() <= 0.5
    # Regression (loss rising) pushes below the neutral 0.5.
    for i in range(20):
        c.update(5.0 + 0.02 * i)
    assert c.difficulty() < 0.5


def test_orchestrator_curriculum_decision_reaches_loader(tmp_path):
    class CurriculumLoader:
        def __init__(self, fn):
            self.fn = fn
            self.received = []

        def __call__(self):
            return self.fn()

        def set_difficulty(self, d):
            self.received.append(d)
            return True

    cfg = tiny_config(
        tmp_path, enable_adaptive_curriculum=True, max_steps=200,
        min_override_threshold=0.2,
        # Mute the competing deciders so the curriculum block is reached.
        enable_adaptive_lr=False, enable_architecture_evolution=False,
        enable_moe_routing_optimization=False, enable_adaptive_wd=False,
    )
    loader = CurriculumLoader(patterned_data(cfg))
    t = Trainer(cfg, train_data=loader,
                checkpoint_dir=str(tmp_path / "ckpt"))
    orch = AdaptiveTrainingOrchestrator(t)
    for i in range(5, 105, 5):
        # Fast-decreasing loss → velocity 0.05/update → difficulty 0.9.
        orch.on_metrics(i, {"loss": 6.0 - 0.05 * i / 5, "grad_norm": 1.0})
    fired = [d for d in orch.decisions if d.kind == "curriculum"]
    assert fired and fired[0].applied
    # Cold start applies the warmup default (0.3); once the velocity
    # window fills, the fast-learning signal re-aims difficulty high.
    assert loader.received and loader.received[-1] > 0.8
    assert any(
        iv["kind"] == "curriculum" for iv in t._interventions
    )
    t.close()
