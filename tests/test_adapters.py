"""PEFT: LoRA adapters + soft-prompt tuning (ref docs/adapters.md)."""


import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from luminaai_tpu.config import Config
from luminaai_tpu.models.transformer import LuminaTransformer
from luminaai_tpu.training.adapters import (
    LoRASpec,
    init_lora_params,
    init_soft_prompt,
    load_lora,
    lora_param_count,
    make_lora_train_step,
    make_prompt_tuning_step,
    merge_lora,
    prepend_soft_prompt,
    save_lora,
)


def tiny_config(**kw) -> Config:
    base = dict(
        vocab_size=256,
        hidden_size=64,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        seq_length=64,
        intermediate_size=128,
        use_flash_attention=False,
        gradient_checkpointing=False,
        precision="fp32",
        routing_noise_std=0.0,
    )
    base.update(kw)
    return Config(**base)


@pytest.fixture(scope="module")
def setup():
    cfg = tiny_config()
    model = LuminaTransformer(cfg)
    ids = jnp.asarray(
        np.random.RandomState(0).randint(1, 256, (2, cfg.seq_length)),
        jnp.int32,
    )
    params = model.init(jax.random.key(0), ids)["params"]
    return cfg, model, params, ids


class TestLoRA:
    def test_zero_init_is_identity(self, setup):
        cfg, model, params, ids = setup
        spec = LoRASpec(rank=4)
        lora = init_lora_params(params, spec, jax.random.key(1))
        merged = merge_lora(params, lora, spec)
        base_out, _ = model.apply({"params": params}, ids)
        lora_out, _ = model.apply({"params": merged}, ids)
        np.testing.assert_allclose(
            np.asarray(base_out), np.asarray(lora_out), atol=1e-6
        )

    def test_param_count_is_small(self, setup):
        cfg, model, params, ids = setup
        lora = init_lora_params(params, LoRASpec(rank=4), jax.random.key(1))
        total = sum(p.size for p in jax.tree.leaves(params))
        assert lora_param_count(lora) < 0.1 * total

    def test_targets_cover_attention_and_ffn(self, setup):
        cfg, model, params, ids = setup
        lora = init_lora_params(params, LoRASpec(rank=4), jax.random.key(1))
        paths = list(lora)
        assert any("attention/wq" in p for p in paths)
        assert any("attention/wo" in p for p in paths)
        assert any("ffn/wi" in p for p in paths)

    def test_moe_experts_optional(self):
        cfg = tiny_config(use_moe=True, num_experts=4, moe_top_k=2)
        model = LuminaTransformer(cfg)
        ids = jnp.ones((1, cfg.seq_length), jnp.int32)
        params = model.init(jax.random.key(0), ids)["params"]
        spec = LoRASpec(rank=2, target_patterns=(r"attention/", r"moe/"))
        lora = init_lora_params(params, spec, jax.random.key(1))
        moe_paths = [p for p in lora if "/moe/" in p]
        assert moe_paths, "expert kernels not matched"
        # per-expert factors carry the leading E axis
        a = lora[moe_paths[0]]["a"]
        assert a.ndim == 3 and a.shape[0] == cfg.num_experts
        merged = merge_lora(params, lora, spec)
        out, _ = model.apply({"params": merged}, ids)
        assert jnp.isfinite(out).all()

    def test_training_moves_loss_base_frozen(self, setup):
        cfg, model, params, ids = setup
        spec = LoRASpec(rank=4, alpha=8.0)
        lora = init_lora_params(params, spec, jax.random.key(1))
        tx = optax.adam(1e-2)
        step = make_lora_train_step(cfg, model, params, spec, tx)
        carry = (lora, tx.init(lora))
        batch = {"input_ids": ids}
        losses = []
        for i in range(10):
            carry, metrics = step(carry, batch, jax.random.key(i))
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0], losses
        # b started at zero and must have moved
        moved = any(
            float(jnp.abs(ab["b"]).max()) > 0 for ab in carry[0].values()
        )
        assert moved

    def test_save_load_roundtrip(self, setup, tmp_path):
        cfg, model, params, ids = setup
        spec = LoRASpec(rank=4, alpha=32.0)
        lora = init_lora_params(params, spec, jax.random.key(1))
        path = str(tmp_path / "adapter.npz")
        save_lora(path, lora, spec)
        lora2, spec2 = load_lora(path)
        assert spec2 == spec
        for k in lora:
            for sub in ("a", "b"):
                np.testing.assert_array_equal(
                    np.asarray(lora[k][sub]), np.asarray(lora2[k][sub])
                )


class TestSoftPrompt:
    def test_prepend_shapes_and_identity_of_suffix(self, setup):
        cfg, model, params, ids = setup
        prompt = init_soft_prompt({"embedder": params["embedder"]}, 8,
                                  jax.random.key(2))
        assert prompt.shape == (8, cfg.hidden_size)
        logits, _ = prepend_soft_prompt(model, params, prompt, ids)
        assert logits.shape == (ids.shape[0], ids.shape[1], cfg.vocab_size)

    def test_prompt_tuning_reduces_loss(self, setup):
        cfg, model, params, ids = setup
        prompt = init_soft_prompt({"embedder": params["embedder"]}, 4,
                                  jax.random.key(2))
        tx = optax.adam(5e-2)
        step = make_prompt_tuning_step(cfg, model, params, tx)
        carry = (prompt, tx.init(prompt))
        batch = {"input_ids": ids}
        losses = []
        for _ in range(10):
            carry, metrics = step(carry, batch)
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0], losses
        assert not np.allclose(np.asarray(carry[0]), np.asarray(prompt))


class TestScannedLayout:
    def test_lora_on_scanned_params(self):
        """scan_layers=True params carry a leading L axis (nn.scan
        variable_axes) — factors must split at the true in/out boundary,
        with per-layer lead dims, and zero-init must stay an identity."""
        cfg = tiny_config(scan_layers=True, num_layers=4)
        model = LuminaTransformer(cfg)
        ids = jnp.asarray(
            np.random.RandomState(0).randint(1, 256, (2, cfg.seq_length)),
            jnp.int32,
        )
        params = model.init(jax.random.key(0), ids)["params"]
        spec = LoRASpec(rank=4)
        lora = init_lora_params(params, spec, jax.random.key(1))
        # factors carry the scan-layer lead axis; adapter stays small
        wq_key = next(p for p in lora if p.endswith("attention/wq"))
        assert lora[wq_key]["a"].shape[0] == cfg.num_layers
        assert lora[wq_key]["a"].shape[1:] == (cfg.hidden_size, 4)
        total = sum(p.size for p in jax.tree.leaves(params))
        assert lora_param_count(lora) < 0.15 * total
        merged = merge_lora(params, lora, spec)
        base_out, _ = model.apply({"params": params}, ids)
        lora_out, _ = model.apply({"params": merged}, ids)
        np.testing.assert_allclose(
            np.asarray(base_out), np.asarray(lora_out), atol=1e-6
        )

    def test_mismatched_adapter_rejected(self):
        cfg = tiny_config()
        model = LuminaTransformer(cfg)
        ids = jnp.ones((1, cfg.seq_length), jnp.int32)
        params = model.init(jax.random.key(0), ids)["params"]
        spec = LoRASpec(rank=2)
        lora = init_lora_params(params, spec, jax.random.key(1))
        bogus = {f"nonexistent/{k}": v for k, v in lora.items()}
        with pytest.raises(ValueError, match="does not match"):
            merge_lora(params, bogus, spec)
