"""HTTP serving surface (ref Dockerfile.backend Flask-on-:5001 contract)."""

import json
import threading
import urllib.error
import urllib.request
from http.server import ThreadingHTTPServer

import pytest

from luminaai_tpu.config import Config
from luminaai_tpu.serving.server import ChatServer


class FakeTokenizerBackend:
    def encode(self, text):
        return [ord(c) % 250 for c in text]


class FakeTokenizer:
    backend = FakeTokenizerBackend()

    def decode(self, tokens):
        return "tok:" + ",".join(str(t) for t in tokens)


class FakeEngine:
    """Engine double mirroring GenerationEngine's contract: generate /
    generate_batch map token ids -> (token ids, stats); encode_chat maps
    messages -> prompt ids; .tokenizer does the text round-trip."""

    def __init__(self):
        self.config = Config(
            vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
            num_kv_heads=2, seq_length=64, use_flash_attention=False,
        )
        self.tokenizer = FakeTokenizer()
        self.batch_sizes = []

    def generate(self, prompt_tokens, **kw):
        toks = list(prompt_tokens)[:3]
        return toks, {"tokens_generated": len(toks), "stopped": "eos"}

    def generate_batch(self, prompts, **kw):
        self.batch_sizes.append(len(prompts))
        return [self.generate(p, **kw) for p in prompts]

    def encode_chat(self, messages):
        return self.tokenizer.backend.encode(messages[-1]["content"])

    def chat_response(self, messages):
        reply, stats = self.generate(self.encode_chat(messages))
        return self.tokenizer.decode(reply), stats

    def generate_stream(self, prompt_tokens, **kw):
        toks, stats = self.generate(prompt_tokens, **kw)
        yield from toks
        yield stats


@pytest.fixture()
def server_url():
    srv = ChatServer(FakeEngine())
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), srv.make_handler())
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}", srv
    httpd.shutdown()
    httpd.server_close()


def _post(url, path, body, token=None):
    req = urllib.request.Request(
        url + path, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json",
                 **({"Authorization": f"Bearer {token}"} if token else {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get(url, path):
    with urllib.request.urlopen(url + path, timeout=10) as r:
        return r.status, json.loads(r.read())


def test_chat_page_served(server_url):
    """GET / serves the built-in chat UI (the ref Electron app's role)."""
    url, _ = server_url
    with urllib.request.urlopen(url + "/", timeout=10) as r:
        assert r.status == 200
        assert r.headers.get("Content-Type", "").startswith("text/html")
        page = r.read().decode()
    assert "/v1/chat" in page and "text/event-stream" in page


def test_health(server_url):
    url, _ = server_url
    code, body = _get(url, "/health")
    assert code == 200 and body["status"] == "ok"
    assert body["model"]["hidden_size"] == 64


def test_generate_and_stats(server_url):
    url, srv = server_url
    code, body = _post(url, "/v1/generate", {"prompt": "hiya"})
    assert code == 200 and body["text"].startswith("tok:")
    assert body["tokens"] == 3
    code, body = _post(url, "/v1/chat", {"message": "yo"})
    # Chat rides the same batched path: encode_chat -> generate -> decode.
    assert code == 200 and body["reply"] == "tok:121,111"
    code, body = _get(url, "/stats")
    assert body["requests"] == 2 and body["tokens_out"] == 5


def test_bad_requests(server_url):
    url, _ = server_url
    assert _post(url, "/v1/generate", {})[0] == 400
    assert _post(url, "/nope", {})[0] == 404
    code, body = _get(url, "/stats")  # GET unknown POST-only route
    assert code == 200


def test_generation_overrides_are_scoped(server_url):
    url, srv = server_url
    base = srv.engine.config.max_new_tokens
    code, _ = _post(url, "/v1/generate",
                    {"prompt": "x", "max_new_tokens": 7})
    assert code == 200
    assert srv.engine.config.max_new_tokens == base  # restored


class TestSecure:
    @pytest.fixture()
    def secure_url(self, tmp_path, monkeypatch):
        monkeypatch.chdir(tmp_path)  # SecurityManager persists users.json
        srv = ChatServer(
            FakeEngine(), secure=True, bootstrap_user=("operator", "hunter22x")
        )
        httpd = ThreadingHTTPServer(("127.0.0.1", 0), srv.make_handler())
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        yield f"http://127.0.0.1:{httpd.server_address[1]}", srv
        httpd.shutdown()
        httpd.server_close()

    def test_auth_flow(self, secure_url):
        url, _ = secure_url
        assert _post(url, "/v1/chat", {"message": "hi"})[0] == 401
        code, body = _post(url, "/v1/auth",
                           {"user": "operator", "password": "wrong1234"})
        assert code == 401
        code, body = _post(url, "/v1/auth",
                           {"user": "operator", "password": "hunter22x"})
        assert code == 200 and body["token"]
        token = body["token"]
        code, body = _post(url, "/v1/chat", {"message": "hi"}, token=token)
        assert code == 200 and body["reply"]

    def test_input_validation(self, secure_url):
        url, _ = secure_url
        code, body = _post(url, "/v1/auth",
                           {"user": "operator", "password": "hunter22x"})
        token = body["token"]
        code, body = _post(url, "/v1/chat", {"message": "   "}, token=token)
        assert code == 400


def _post_sse(url, path, body, timeout=10):
    """POST with stream:true; return (content_type, list of data frames)."""
    req = urllib.request.Request(
        url + path, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        ctype = r.headers.get("Content-Type", "")
        raw = r.read().decode()
    frames = [
        line[len("data: "):]
        for line in raw.split("\n")
        if line.startswith("data: ")
    ]
    return ctype, frames


def test_streaming_sse(server_url):
    """stream:true responds as text/event-stream: one token frame per
    generated token (deltas concatenate to the final text), a done frame
    with the same schema as the non-streaming reply, then [DONE]."""
    url, srv = server_url
    _, ref = _post(url, "/v1/generate", {"prompt": "hi"})
    ctype, frames = _post_sse(url, "/v1/generate",
                              {"prompt": "hi", "stream": True})
    assert ctype.startswith("text/event-stream")
    assert frames[-1] == "[DONE]"
    events = [json.loads(f) for f in frames[:-1]]
    toks = [e for e in events if "token" in e]
    done = events[-1]
    assert done.get("done") is True
    assert len(toks) == done["tokens"] == ref["tokens"]
    assert done["text"] == ref["text"]
    assert done["stopped"] == ref["stopped"]
    # /v1/chat streams with the reply key.
    _, frames = _post_sse(url, "/v1/chat",
                          {"message": "yo", "stream": True})
    done = json.loads(frames[-2])
    assert done["done"] is True and done["reply"].startswith("tok:")
    # Stats count streamed requests/tokens too (3 requests: the non-stream
    # reference + two streams; "hi"→2 tokens ×2 + "yo"→2 tokens).
    _, stats = _get(url, "/stats")
    assert stats["requests"] >= 3 and stats["tokens_out"] >= 6


def test_streaming_errors(server_url):
    url, _ = server_url
    code, body = _post(url, "/v1/generate", {"stream": True})  # no prompt
    assert code == 400


def test_streaming_multibyte_delta_hold():
    """A multi-byte codepoint split across tokens must not bake U+FFFD
    into the delta stream: the partial decode is held and flushed at the
    next clean boundary, so concatenated deltas == final text."""

    class ByteTokenizerBackend:
        def encode(self, text):
            return list(text.encode())

    class ByteTokenizer:
        backend = ByteTokenizerBackend()

        def decode(self, tokens):
            return bytes(tokens).decode("utf-8", errors="replace")

    class ByteEngine(FakeEngine):
        def __init__(self):
            super().__init__()
            self.tokenizer = ByteTokenizer()

        def generate_stream(self, prompt_tokens, **kw):
            out = list("héllo".encode())  # é = 2 bytes, split mid-stream
            yield from out
            yield {"tokens_generated": len(out), "stopped": "eos"}

    srv = ChatServer(ByteEngine())
    events = list(srv._stream_events([1], {}, "text"))
    done = events[-1]
    deltas = "".join(e["delta"] for e in events[:-1])
    assert done["text"] == "héllo"
    assert deltas == done["text"]
    # The held frame emitted an empty delta, not a replacement char.
    assert all("�" not in e["delta"] for e in events[:-1])


def test_streaming_midflight_error_emits_error_frame(server_url):
    """An engine exception after frames have been sent must surface as an
    SSE error frame + [DONE], never a second HTTP status line inside the
    open stream body."""

    class ExplodingEngine(FakeEngine):
        def generate_stream(self, prompt_tokens, **kw):
            yield int(prompt_tokens[0])
            raise RuntimeError("device fell over")

    srv = ChatServer(ExplodingEngine())
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), srv.make_handler())
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        url = f"http://127.0.0.1:{httpd.server_address[1]}"
        ctype, frames = _post_sse(url, "/v1/generate",
                                  {"prompt": "x", "stream": True})
        assert ctype.startswith("text/event-stream")
        assert frames[-1] == "[DONE]"
        err = json.loads(frames[-2])
        assert "device fell over" in err["error"]
        json.loads(frames[0])  # the pre-error token frame is parseable
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_stream_concurrency_cap():
    """Streams bypass the MicroBatcher, so a slot semaphore caps them:
    over the limit → 503; slots release on completion AND on a close
    before the first event (the leak path)."""
    import time as _time

    class SlowEngine(FakeEngine):
        def generate_stream(self, prompt_tokens, **kw):
            yield 1
            _time.sleep(0.5)
            yield 2
            yield {"tokens_generated": 2, "stopped": "eos"}

    srv = ChatServer(SlowEngine(), max_streams=1)
    err1, ev1 = srv.start_stream("/v1/generate", {"prompt": "a"}, None)
    assert err1 is None
    err2, ev2 = srv.start_stream("/v1/generate", {"prompt": "b"}, None)
    assert err2 is not None and err2[0] == 503
    # Closing BEFORE the first next() must still release the slot.
    ev1.close()
    err3, ev3 = srv.start_stream("/v1/generate", {"prompt": "c"}, None)
    assert err3 is None
    # Draining to exhaustion releases too.
    list(ev3)
    err4, ev4 = srv.start_stream("/v1/generate", {"prompt": "d"}, None)
    assert err4 is None
    ev4.close()


def test_stream_tail_flush_on_done_frame():
    """A stream ending mid-codepoint flushes the held tokens as the done
    frame's delta, so concatenated deltas still reproduce the text."""

    class ByteTokenizerBackend:
        def encode(self, text):
            return list(text.encode())

    class ByteTokenizer:
        backend = ByteTokenizerBackend()

        def decode(self, tokens):
            return bytes(tokens).decode("utf-8", errors="replace")

    class TruncatedEngine(FakeEngine):
        def __init__(self):
            super().__init__()
            self.tokenizer = ByteTokenizer()

        def generate_stream(self, prompt_tokens, **kw):
            out = list("hé".encode())[:-1] + [0xC3]  # ends mid-codepoint
            yield from out
            yield {"tokens_generated": len(out), "stopped": "length"}

    srv = ChatServer(TruncatedEngine())
    events = list(srv._stream_events([1], {}, "text"))
    done = events[-1]
    deltas = "".join(e["delta"] for e in events)
    assert done["text"] == deltas  # tail flushed via done frame's delta
    assert done["delta"] != ""


def test_speculative_request_path():
    """{"speculative": true} on a greedy request runs the engine's
    speculative path (stats surfaced); sampling requests silently fall
    back to the batched path; engines without the method fall back."""

    class SpecEngine(FakeEngine):
        def __init__(self):
            super().__init__()
            self.spec_calls = 0

        def _resolve_gen_key(self, mnt, temp, top_p, top_k, rep):
            return (int(mnt or 8), float(0.0 if temp is None else temp),
                    0, 1.0, 1.0)

        def generate_speculative(self, prompt_tokens, max_new_tokens=None):
            self.spec_calls += 1
            toks = list(prompt_tokens)[:3]
            return toks, {
                "tokens_generated": len(toks), "stopped": "eos",
                "verify_calls": 2, "tokens_per_verify": 1.5,
            }

    eng = SpecEngine()
    srv = ChatServer(eng)
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), srv.make_handler())
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    url = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        # Greedy + speculative: engine path used, stats in the reply.
        code, body = _post(url, "/v1/generate",
                           {"prompt": "hiya", "temperature": 0,
                            "speculative": True})
        assert code == 200 and eng.spec_calls == 1
        assert body["speculative"]["verify_calls"] == 2
        assert body["text"].startswith("tok:")
        # Sampling + speculative: silently rides the batcher.
        code, body = _post(url, "/v1/generate",
                           {"prompt": "hiya", "temperature": 0.7,
                            "speculative": True})
        assert code == 200 and eng.spec_calls == 1
        assert "speculative" not in body
        # Stats counted both.
        _, stats = _get(url, "/stats")
        assert stats["requests"] == 2
    finally:
        httpd.shutdown()
        httpd.server_close()

    # Engine without the method: plain fallback, no error.
    srv2 = ChatServer(FakeEngine())
    code, body = srv2._run_model(
        "/v1/generate", {"prompt": "hiya", "speculative": True}
    )
    assert code == 200 and "speculative" not in body

    # Slots exhausted: falls back to the batched path, never 503s — the
    # hint must not make a servable request fail.
    eng3 = SpecEngine()
    srv3 = ChatServer(eng3, max_streams=1)
    assert srv3._stream_slots.acquire(blocking=False)  # hog the slot
    code, body = srv3._run_model(
        "/v1/generate",
        {"prompt": "hiya", "temperature": 0, "speculative": True},
    )
    assert code == 200 and "speculative" not in body
    assert eng3.spec_calls == 0


def test_aborted_stream_still_counted():
    """Closing the event generator early (client disconnect) still books
    the streamed tokens into /stats."""
    srv = ChatServer(FakeEngine())
    gen = srv._stream_events([1, 2, 3, 4], {}, "text")
    next(gen)
    next(gen)
    gen.close()
    assert srv.requests == 1
    assert srv.tokens_out == 2


def test_streaming_unsupported_engine():
    eng = FakeEngine()
    del type(eng).generate_stream  # class attr removal affects this type
    try:
        srv = ChatServer(eng)
        httpd = ThreadingHTTPServer(("127.0.0.1", 0), srv.make_handler())
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        url = f"http://127.0.0.1:{httpd.server_address[1]}"
        code, body = _post(url, "/v1/generate",
                           {"prompt": "x", "stream": True})
        assert code == 501
        httpd.shutdown()
        httpd.server_close()
    finally:
        FakeEngine.generate_stream = _FAKE_STREAM_BACKUP


_FAKE_STREAM_BACKUP = FakeEngine.generate_stream


def test_override_clamps(server_url):
    url, srv = server_url
    code, body = _post(url, "/v1/generate",
                       {"prompt": "x", "max_new_tokens": 10**9,
                        "temperature": 99, "top_p": 5})
    assert code == 200  # clamped, not refused
    code, body = _post(url, "/v1/generate",
                       {"prompt": "x", "max_new_tokens": "lots"})
    assert code == 400


def test_health_with_query_string(server_url):
    url, _ = server_url
    code, body = _get(url, "/health?probe=1")
    assert code == 200 and body["status"] == "ok"


def test_malformed_chat_messages(server_url):
    url, _ = server_url
    code, body = _post(url, "/v1/chat", {"messages": [{"content": "hi"}]})
    assert code == 400 and "role" in body["error"]


def test_concurrent_requests_ride_one_batch():
    """N clients in flight together must be served by batched decode
    (MicroBatcher groups same-param requests within the window)."""
    srv = ChatServer(FakeEngine(), batch_window_ms=300, max_batch=8)
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), srv.make_handler())
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    url = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        codes = []
        lock = threading.Lock()

        def hit(i):
            code, body = _post(url, "/v1/generate", {"prompt": f"hey{i}"})
            with lock:
                codes.append(code)

        threads = [
            threading.Thread(target=hit, args=(i,)) for i in range(6)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert codes == [200] * 6
        assert max(srv.engine.batch_sizes, default=1) >= 2, (
            srv.engine.batch_sizes
        )
        _, stats = _get(url, "/stats")
        assert stats["max_batch_seen"] >= 2
        assert stats["requests"] == 6
    finally:
        httpd.shutdown()
        httpd.server_close()


# -- continuous batching ---------------------------------------------------
class FakeStepper:
    """Hermetic StepwiseDecoder double: deterministic token streams
    (prompt[0], prompt[0]+1, ...) over a real PagedKVPool's slot
    accounting, so scheduler logic (admission, eviction, reuse ordering,
    cancellation) is testable without jax."""

    def __init__(self, num_slots=2, slot_tokens=64):
        from luminaai_tpu.inference.kv_pool import PagedKVPool

        self.num_slots = num_slots
        self.slot_tokens = slot_tokens
        self.pool = PagedKVPool(None, num_slots, 1, slot_tokens)
        self.steps = 0
        self._active = [False] * num_slots
        self._next = [0] * num_slots

    def has_free_slot(self):
        return self.pool.has_free()

    def acquire_slot(self):
        return self.pool.alloc()

    def release_slot(self, slot):
        self._active[slot] = False
        self.pool.free(slot)

    def lane_full(self, slot):
        return False

    def prefill_into_slot(self, slot, prompt, max_new_tokens=1,
                          sample_key=None, seed=None):
        first = int(prompt[0])
        self._active[slot] = max_new_tokens > 1
        self._next[slot] = first + 1
        self.pool.lengths[slot] = len(prompt)
        return {"token": first, "prompt_tokens": len(prompt),
                "is_stop": False}

    def decode_step(self, sample_key=None):
        import time as _time

        import numpy as np

        _time.sleep(0.01)  # a "device step": keeps admission ordering real
        toks = np.zeros((self.num_slots,), np.int64)
        eos = np.zeros((self.num_slots,), bool)
        produced = np.asarray(self._active, bool).copy()
        for s in range(self.num_slots):
            if self._active[s]:
                toks[s] = self._next[s]
                self._next[s] += 1
        self.steps += 1
        return toks, produced, eos


class FakeContinuousEngine(FakeEngine):
    """FakeEngine + the step-wise API surface ChatServer auto-detects."""

    def __init__(self):
        super().__init__()
        self.stepper = FakeStepper(num_slots=2)

    def _resolve_gen_key(self, mnt, temp, top_p, top_k, rep):
        return (
            int(mnt or 3),
            float(0.0 if temp is None else temp),
            int(top_k or 0),
            float(1.0 if top_p is None else top_p),
            float(1.0 if rep is None else rep),
        )

    def make_stepwise(self, **kw):
        return self.stepper


def test_paged_pool_free_list_never_double_allocates():
    """The slot free-list is the continuous scheduler's safety invariant:
    exhaustion raises (never hands out a live slot), free() of a
    non-allocated slot raises, and reuse is counted."""
    from luminaai_tpu.inference.kv_pool import PagedKVPool

    pool = PagedKVPool(None, num_slots=3, pages=4, page_size=16)
    assert pool.slot_tokens == 64
    got = [pool.alloc() for _ in range(3)]
    assert sorted(got) == [0, 1, 2]  # each slot handed out exactly once
    with pytest.raises(RuntimeError):
        pool.alloc()
    with pytest.raises(ValueError):
        pool.free(99)
    pool.lengths[got[0]] = 17
    pool.free(got[0])
    assert pool.lengths[got[0]] == 0  # length reset on free
    again = pool.alloc()
    assert again == got[0]
    assert pool.reuses == 1
    with pytest.raises(RuntimeError):
        pool.alloc()  # still exhausted: no phantom slots appeared
    pool.free(again)
    with pytest.raises(ValueError):
        pool.free(again)  # double-free rejected


def test_continuous_scheduler_admits_mid_decode():
    """A queued request must join the running decode in a freed slot
    BEFORE the longest in-flight request completes (step-level
    admission), and every request's tokens must be its own stream."""
    from luminaai_tpu.serving.server import ContinuousScheduler

    stepper = FakeStepper(num_slots=2)
    sched = ContinuousScheduler(FakeContinuousEngine(), decoder=stepper)
    results = {}
    lock = threading.Lock()

    def hit(name, first_tok, max_new):
        out = sched.submit([first_tok], {"max_new_tokens": max_new})
        with lock:
            results[name] = out

    ta = threading.Thread(target=hit, args=("a", 100, 3))
    tb = threading.Thread(target=hit, args=("b", 200, 40))
    ta.start()
    tb.start()
    import time as _time

    _time.sleep(0.05)  # let a/b occupy both slots so c queues
    tc = threading.Thread(target=hit, args=("c", 300, 3))
    tc.start()
    for t in (ta, tb, tc):
        t.join(timeout=30)
    assert set(results) == {"a", "b", "c"}
    toks_a, stats_a = results["a"]
    toks_b, stats_b = results["b"]
    toks_c, stats_c = results["c"]
    assert toks_a == [100, 101, 102]
    assert toks_b == list(range(200, 240))
    assert toks_c == [300, 301, 302]
    # c rode a freed slot while b was still decoding.
    assert stats_c["admitted_step"] < stats_b["finished_step"]
    assert stepper.pool.reuses >= 1
    assert sched.max_batch_seen == 2


def test_continuous_scheduler_switches_sampling_keys():
    """Mismatched sampling params cannot share one traced decode step;
    they park, the active generation drains, and the scheduler switches —
    every request completes."""
    from luminaai_tpu.serving.server import ContinuousScheduler

    sched = ContinuousScheduler(
        FakeContinuousEngine(), decoder=FakeStepper(num_slots=2)
    )
    results = []
    lock = threading.Lock()

    def hit(i):
        out = sched.submit(
            [50 + i], {"max_new_tokens": 4, "temperature": 0.1 * (i % 2)}
        )
        with lock:
            results.append((i, out))

    threads = [threading.Thread(target=hit, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert len(results) == 4
    for i, (toks, stats) in results:
        assert toks == [50 + i, 51 + i, 52 + i, 53 + i]
    assert sched.batches >= 2  # at least one key switch


def test_continuous_stream_cancel_frees_slot():
    """Closing a continuous SSE stream flags the lane cancelled; the
    scheduler frees its slot at the next step instead of decoding for a
    gone client."""
    from luminaai_tpu.serving.server import ContinuousScheduler

    stepper = FakeStepper(num_slots=1)
    sched = ContinuousScheduler(FakeContinuousEngine(), decoder=stepper)
    gen = sched.submit_stream([70], {"max_new_tokens": 10_000})
    assert next(gen) == 70
    gen.close()
    import time as _time

    deadline = _time.time() + 5.0
    while _time.time() < deadline and not stepper.pool.has_free():
        _time.sleep(0.01)
    assert stepper.pool.has_free(), "cancelled stream never freed its slot"
    # The freed slot is immediately serviceable.
    toks, stats = sched.submit([80], {"max_new_tokens": 2})
    assert toks == [80, 81]


def test_continuous_server_http_end_to_end():
    """ChatServer auto-detects the step-wise engine API: generation and
    SSE ride the continuous scheduler, /stats reports it."""
    eng = FakeContinuousEngine()
    srv = ChatServer(eng)
    assert srv.continuous
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), srv.make_handler())
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    url = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        code, body = _post(url, "/v1/generate",
                           {"prompt": "abc", "max_new_tokens": 3})
        assert code == 200
        assert body["text"] == "tok:97,98,99"  # ord('a'), +1, +2
        assert body["stopped"] == "length"
        ctype, frames = _post_sse(
            url, "/v1/generate",
            {"prompt": "abc", "max_new_tokens": 3, "stream": True},
        )
        assert ctype.startswith("text/event-stream")
        assert frames[-1] == "[DONE]"
        events = [json.loads(f) for f in frames[:-1]]
        toks = [e["token"] for e in events if "token" in e]
        assert toks == [97, 98, 99]
        assert events[-1]["done"] is True
        assert events[-1]["text"] == "tok:97,98,99"
        _, stats = _get(url, "/stats")
        assert stats["scheduler"] == "continuous"
        assert stats["requests"] == 2
        assert stats["kv_pool"]["num_slots"] == 2
        assert stats["decode_steps"] >= 2
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_legacy_engine_falls_back_to_micro_batcher():
    """Engines without the step-wise API keep the MicroBatcher path, and
    continuous=False forces it even when the API exists."""
    from luminaai_tpu.serving.server import MicroBatcher

    srv = ChatServer(FakeEngine())
    assert not srv.continuous and isinstance(srv.batcher, MicroBatcher)
    srv2 = ChatServer(FakeContinuousEngine(), continuous=False)
    assert not srv2.continuous and isinstance(srv2.batcher, MicroBatcher)


def test_mismatched_params_requeue_not_starve():
    """Requests with different sampling params fall into separate batches
    but all complete."""
    srv = ChatServer(FakeEngine(), batch_window_ms=100, max_batch=8)
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), srv.make_handler())
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    url = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        codes = []
        lock = threading.Lock()

        def hit(i):
            code, _ = _post(
                url, "/v1/generate",
                {"prompt": "z", "temperature": 0.1 * (i % 2)},
            )
            with lock:
                codes.append(code)

        threads = [
            threading.Thread(target=hit, args=(i,)) for i in range(4)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert codes == [200] * 4
    finally:
        httpd.shutdown()
        httpd.server_close()


# -- telemetry: /healthz, /metrics, parity, overhead ------------------------
def test_healthz_warming_then_ready():
    """/healthz is the READINESS probe: 503 while the engine is
    compiling/warming (so the Dockerfile HEALTHCHECK holds traffic),
    200 with scheduler state once serving."""
    srv = ChatServer(FakeContinuousEngine())
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), srv.make_handler())
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    url = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        srv._ready.clear()  # simulate mid-compile
        code, body = _post(url, "/healthz", {})  # POST -> 404 route check
        assert code == 404
        try:
            _get(url, "/healthz")
            assert False, "expected 503"
        except urllib.error.HTTPError as e:
            assert e.code == 503
            assert json.loads(e.read())["status"] == "warming"
        # /health (liveness) stays 200 while warming; only readiness gates.
        code, _ = _get(url, "/health")
        assert code == 200
        srv.mark_ready()
        code, body = _get(url, "/healthz?probe=1")
        assert code == 200 and body["status"] == "ok"
        assert body["scheduler"] == "continuous"
        assert body["active_lanes"] == 0
        assert body["queue_depth"] == 0
        assert body["slots_free"] == 2
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_healthz_warmup_flow_marks_ready():
    """warmup=True starts not-ready, drives a generation through the real
    batcher path in the background, and flips the gate when it completes."""
    import time as _time

    srv = ChatServer(FakeContinuousEngine(), warmup=True)
    assert srv._ready.wait(timeout=10), "warmup never marked ready"
    assert srv.batcher.requests_served >= 1  # warmup used the real path
    code, body = srv.handle("GET", "/healthz", {}, None)
    assert code == 200 and body["status"] == "ok"
    assert "warmup_error" not in body
    _time.sleep(0)


def test_healthz_warmup_failure_still_serves():
    """A broken warmup must not brick the server: the gate opens anyway
    and the failure is surfaced in the health payload."""

    class BrokenPrefill(FakeStepper):
        def prefill_into_slot(self, *a, **kw):
            raise RuntimeError("compile exploded")

    eng = FakeContinuousEngine()
    eng.stepper = BrokenPrefill(num_slots=2)
    srv = ChatServer(eng, warmup=True)
    assert srv._ready.wait(timeout=10)
    code, body = srv.handle("GET", "/healthz", {}, None)
    assert code == 200
    assert "compile exploded" in body.get("warmup_error", "")


def test_healthz_micro_batcher_state():
    srv = ChatServer(FakeEngine())
    code, body = srv.handle("GET", "/healthz", {}, None)
    assert code == 200
    assert body["scheduler"] == "micro_batch"
    assert body["queue_depth"] == 0


def test_metrics_endpoint_round_trips_and_covers_serving():
    """GET /metrics on a running server returns valid Prometheus text
    exposition (independent minimal parser) including the serving
    histograms (TTFT, per-token decode), KV-pool gauges, and — with a
    colocated training monitor on the same registry — training series.
    The acceptance-criterion test for the unified sink."""
    from luminaai_tpu.monitoring.logger import TrainingHealthMonitor
    from luminaai_tpu.monitoring.telemetry import MetricsRegistry
    from prom_parser import check_histogram_wellformed, parse_prometheus_text

    registry = MetricsRegistry()
    srv = ChatServer(FakeContinuousEngine(), registry=registry)
    # Training flows into the SAME registry (the unified-sink contract).
    monitor = TrainingHealthMonitor(registry=registry)
    monitor.log_step(5, {"loss": 2.0, "grad_norm": 0.5})
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), srv.make_handler())
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    url = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        # Generate some traffic: one batched request + one SSE stream.
        code, _ = _post(url, "/v1/generate",
                        {"prompt": "abc", "max_new_tokens": 3})
        assert code == 200
        _post_sse(url, "/v1/generate",
                  {"prompt": "abd", "max_new_tokens": 3, "stream": True})
        with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
            assert r.status == 200
            ctype = r.headers.get("Content-Type", "")
            text = r.read().decode()
        assert ctype.startswith("text/plain")
        assert "version=0.0.4" in ctype

        families = parse_prometheus_text(text)  # strict: raises on junk
        for name, fam in families.items():
            assert fam["type"] is not None, f"{name} missing TYPE"
            # A labeled family with no children yet (e.g. an alert
            # counter before any alert) legally renders TYPE-only.

        # Serving histograms saw the traffic.
        for hist in ("serve_ttft_seconds", "serve_token_latency_seconds",
                     "serve_prefill_seconds", "serve_queue_wait_seconds",
                     "serve_decode_step_seconds",
                     "serve_stream_duration_seconds"):
            assert families[hist]["type"] == "histogram", hist
            check_histogram_wellformed(hist, families[hist])
        ttft_count = [
            v for (n, l, v) in families["serve_ttft_seconds"]["samples"]
            if n.endswith("_count")
        ]
        assert ttft_count == [2]  # both requests measured

        # KV-pool gauges are exported.
        for g in ("kv_pool_slots_in_use", "kv_pool_slots_free",
                  "kv_pool_pages_in_use", "kv_pool_fragmentation_rows"):
            assert families[g]["type"] == "gauge", g
        (_, _, free), = families["kv_pool_slots_free"]["samples"]
        assert free == 2  # all slots released after completion

        # Training series ride the same exposition.
        (_, _, loss), = families["training_loss"]["samples"]
        assert loss == 2.0
        assert families["training_health_score"]["type"] == "gauge"

        # HTTP counter carries route/code labels.
        http = {
            (l["route"], l["code"]): v
            for (_, l, v) in families["serve_http_requests_total"]["samples"]
        }
        assert http[("/v1/generate", "200")] == 2
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_decode_parity_with_telemetry_on_off():
    """Telemetry must be observation-only: the exact token streams come
    out of the continuous scheduler with recording on and off (the
    acceptance-criterion parity check)."""
    from luminaai_tpu.monitoring.telemetry import MetricsRegistry
    from luminaai_tpu.serving.server import ContinuousScheduler

    outs = {}
    for on in (True, False):
        sched = ContinuousScheduler(
            FakeContinuousEngine(),
            decoder=FakeStepper(num_slots=2),
            registry=MetricsRegistry(),
            telemetry=on,
        )
        results = {}
        lock = threading.Lock()

        def hit(name, first_tok, max_new, sched=sched, results=results,
                lock=lock):
            out = sched.submit([first_tok], {"max_new_tokens": max_new})
            with lock:
                results[name] = out[0]

        threads = [
            threading.Thread(target=hit, args=(f"r{i}", 100 + 10 * i, 3 + i))
            for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        outs[on] = results
    assert outs[True] == outs[False]
    for i in range(4):
        first = 100 + 10 * i
        assert outs[True][f"r{i}"] == list(range(first, first + 3 + i))


@pytest.mark.slow
def test_telemetry_overhead_within_budget():
    """Scheduler A/B with metrics on vs off: recording must stay inside
    budget. The fake stepper does no sleeping, so the workload is almost
    PURE scheduler overhead — the harshest possible ratio; the real
    decode step is orders of magnitude heavier."""
    import time as _time

    from luminaai_tpu.monitoring.telemetry import MetricsRegistry
    from luminaai_tpu.serving.server import ContinuousScheduler

    class FastStepper(FakeStepper):
        def decode_step(self, sample_key=None):
            import numpy as np

            toks = np.zeros((self.num_slots,), np.int64)
            eos = np.zeros((self.num_slots,), bool)
            produced = np.asarray(self._active, bool).copy()
            for s in range(self.num_slots):
                if self._active[s]:
                    toks[s] = self._next[s]
                    self._next[s] += 1
            self.steps += 1
            return toks, produced, eos

    def run_once(telemetry_on):
        sched = ContinuousScheduler(
            FakeContinuousEngine(),
            decoder=FastStepper(num_slots=4),
            registry=MetricsRegistry(),
            telemetry=telemetry_on,
        )
        t0 = _time.perf_counter()
        threads = [
            threading.Thread(
                target=sched.submit,
                args=([50 + i], {"max_new_tokens": 500}),
            )
            for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        return _time.perf_counter() - t0

    # Interleave and take mins to shed scheduler-timing noise.
    on = min(run_once(True) for _ in range(3))
    off = min(run_once(False) for _ in range(3))
    # Budget: recording may cost at most 50% on a zero-work decode step
    # plus a 20ms absolute floor for timer jitter.
    assert on <= off * 1.5 + 0.02, (on, off)


def test_speculative_stream_path():
    """{"speculative": true} on an SSE request composes the draft/verify
    loop with the streaming contract (VERDICT r5 #5 slice): greedy
    streams ride generate_stream_speculative (done frame carries the
    acceptance stats), sampled streams silently use the plain stream,
    slot exhaustion falls back rather than failing, and the slot
    releases on drain."""

    class SpecStreamEngine(FakeEngine):
        def __init__(self):
            super().__init__()
            self.spec_streams = 0
            self.plain_streams = 0

        def _resolve_gen_key(self, mnt, temp, top_p, top_k, rep):
            return (int(mnt or 8), float(0.0 if temp is None else temp),
                    0, 1.0, 1.0)

        def generate_stream(self, prompt_tokens, **kw):
            self.plain_streams += 1
            yield from (1, 2, 3)
            yield {"tokens_generated": 3, "stopped": "length"}

        def generate_stream_speculative(self, prompt_tokens,
                                        max_new_tokens=None,
                                        timeout_s=None):
            self.spec_streams += 1
            yield from (1, 2, 3)
            yield {"tokens_generated": 3, "stopped": "eos",
                   "verify_calls": 2, "tokens_per_verify": 1.5}

    eng = SpecStreamEngine()
    srv = ChatServer(eng, max_streams=1)

    # Greedy + speculative: the draft/verify stream serves the SSE.
    err, ev = srv.start_stream(
        "/v1/generate",
        {"prompt": "abcabc", "temperature": 0, "speculative": True},
        None,
    )
    assert err is None
    events = list(ev)
    assert eng.spec_streams == 1 and eng.plain_streams == 0
    assert [e["token"] for e in events[:-1]] == [1, 2, 3]
    done = events[-1]
    assert done["done"] and done["stopped"] == "eos"
    assert done["speculative"]["verify_calls"] == 2

    # Slot released on drain: a second speculative stream gets it back.
    err, ev = srv.start_stream(
        "/v1/generate",
        {"prompt": "abcabc", "temperature": 0, "speculative": True},
        None,
    )
    assert err is None
    list(ev)
    assert eng.spec_streams == 2

    # Sampled + speculative: silently the plain stream (hint ignored).
    err, ev = srv.start_stream(
        "/v1/generate",
        {"prompt": "abcabc", "temperature": 0.7, "speculative": True},
        None,
    )
    assert err is None
    events = list(ev)
    assert eng.plain_streams == 1 and eng.spec_streams == 2
    assert "speculative" not in events[-1]

    # Slot hogged: the hint falls back to the plain stream, never 503s
    # for a request the normal path could serve (legacy mode also caps
    # plain streams by the same semaphore, so this would 503 — but the
    # SPECULATIVE branch itself must not consume the last slot).
    assert srv._stream_slots.acquire(blocking=False)
    err, ev = srv.start_stream(
        "/v1/generate",
        {"prompt": "abcabc", "temperature": 0, "speculative": True},
        None,
    )
    # Legacy mode still needs a slot for the plain stream -> 503 here is
    # the pre-existing cap behavior, not a speculative failure.
    assert err is not None and err[0] == 503
    assert eng.spec_streams == 2
    srv._stream_slots.release()


def test_engine_stream_speculative_matches_greedy_stream():
    """generate_stream_speculative must reproduce generate_stream's
    greedy token sequence exactly on a real (tiny) model, and its
    blocking collector (generate_speculative) must agree with both."""
    import jax
    import jax.numpy as jnp
    from flax import linen as nn

    from luminaai_tpu.config import Config
    from luminaai_tpu.inference.generate import GenerationEngine
    from luminaai_tpu.models.transformer import LuminaTransformer

    class _Tok:
        eos_token_id = 1
        pad_token_id = 0
        im_end = 2

        class backend:
            @staticmethod
            def encode(text):
                return [3 + (ord(c) % 60) for c in text]

        @staticmethod
        def decode(tokens):
            return " ".join(str(t) for t in tokens)

    cfg = Config(
        vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
        num_kv_heads=1, seq_length=128, use_flash_attention=False,
        precision="fp32", gradient_checkpointing=False, max_new_tokens=16,
    )
    model = LuminaTransformer(cfg)
    params = model.init(jax.random.key(0), jnp.ones((1, 8), jnp.int32))[
        "params"
    ]
    params = jax.tree.map(
        lambda x: x.unbox() if isinstance(x, nn.meta.AxisMetadata) else x,
        params,
        is_leaf=lambda x: isinstance(x, nn.meta.AxisMetadata),
    )
    engine = GenerationEngine(model, params, _Tok(), cfg)
    # Repetitive prompt so the n-gram index actually drafts.
    prompt = [5, 6, 7, 8, 5, 6, 7, 8, 5, 6, 7, 8]

    ref = [
        t for t in engine.generate_stream(
            prompt, max_new_tokens=12, temperature=0.0,
            repetition_penalty=1.0, seed=0,
        )
        if not isinstance(t, dict)
    ]
    streamed, stats = [], None
    for item in engine.generate_stream_speculative(
        prompt, max_new_tokens=12, seed=0
    ):
        if isinstance(item, dict):
            stats = item
        else:
            streamed.append(item)
    assert streamed == ref, (streamed, ref)
    assert stats["verify_calls"] >= 1
    blocking, bstats = engine.generate_speculative(
        prompt, max_new_tokens=12, seed=0
    )
    assert blocking == ref
    assert bstats["tokens_generated"] == len(ref)


def test_speculative_stream_honors_request_deadline():
    """Speculative streams run outside the continuous scheduler's lane
    eviction, so the engine's decode loop enforces the per-request
    deadline: an expired timeout ends the stream with stopped='timeout'
    instead of holding its slot for the full token budget."""

    class DeadlineEngine(FakeEngine):
        def __init__(self):
            super().__init__()
            self.seen_timeout = None

        def _resolve_gen_key(self, mnt, temp, top_p, top_k, rep):
            return (int(mnt or 8), float(0.0 if temp is None else temp),
                    0, 1.0, 1.0)

        def generate_stream_speculative(self, prompt_tokens,
                                        max_new_tokens=None,
                                        timeout_s=None):
            self.seen_timeout = timeout_s
            yield 1
            yield {"tokens_generated": 1,
                   "stopped": "timeout" if timeout_s else "length"}

    eng = DeadlineEngine()
    srv = ChatServer(eng, request_timeout_s=2.5)
    err, ev = srv.start_stream(
        "/v1/generate",
        {"prompt": "abc", "temperature": 0, "speculative": True},
        None,
    )
    assert err is None
    events = list(ev)
    assert eng.seen_timeout == 2.5
    assert events[-1]["stopped"] == "timeout"


def test_speculative_stream_window_degrade_keeps_deadline():
    """When the rolling-window cache leaves no verify slack (k < 2), the
    speculative stream degrades to the plain greedy stream — but must
    NOT drop the per-request deadline on the way (the serving layer
    routed it outside the scheduler's eviction on that promise)."""
    import jax
    import jax.numpy as jnp
    from flax import linen as nn

    from luminaai_tpu.config import Config
    from luminaai_tpu.inference.generate import GenerationEngine
    from luminaai_tpu.models.transformer import LuminaTransformer

    class _Tok:
        eos_token_id = 1
        pad_token_id = 0
        im_end = 2

        class backend:
            @staticmethod
            def encode(text):
                return [3 + (ord(c) % 60) for c in text]

        @staticmethod
        def decode(tokens):
            return " ".join(str(t) for t in tokens)

    # window % 128 == 0 -> rolling slack slots - w + 1 == 1 < 2: the
    # draft can't fit, generate_stream_speculative degrades.
    cfg = Config(
        vocab_size=128, hidden_size=32, num_layers=1, num_heads=2,
        num_kv_heads=1, seq_length=512, attention_window=128,
        use_flash_attention=False, precision="fp32",
        gradient_checkpointing=False, max_new_tokens=8,
    )
    model = LuminaTransformer(cfg)
    params = model.init(jax.random.key(0), jnp.ones((1, 8), jnp.int32))[
        "params"
    ]
    params = jax.tree.map(
        lambda x: x.unbox() if isinstance(x, nn.meta.AxisMetadata) else x,
        params,
        is_leaf=lambda x: isinstance(x, nn.meta.AxisMetadata),
    )
    engine = GenerationEngine(model, params, _Tok(), cfg)
    prompt = [5, 6, 7, 8] * 3

    # Expired deadline: the degraded stream stops early with 'timeout'.
    items = list(engine.generate_stream_speculative(
        prompt, max_new_tokens=8, seed=0, timeout_s=0.0
    ))
    stats = items[-1]
    assert isinstance(stats, dict)
    assert stats["stopped"] == "timeout"
    assert stats["tokens_generated"] < 8

    # No deadline: same degrade path runs to completion.
    items = list(engine.generate_stream_speculative(
        prompt, max_new_tokens=8, seed=0
    ))
    assert items[-1]["stopped"] in ("eos", "length")


# ---------------------------------------------------------------------------
# tenant QoS: fair-share admission + token-bucket gate + identity hygiene
# ---------------------------------------------------------------------------
def test_wrr_dequeue_interleaves_tenants():
    """Weighted round-robin dequeue: a hot tenant's flood alternates
    with other tenants' requests instead of draining first; weights
    grant extra dequeues per rotation (priority lanes)."""
    from luminaai_tpu.serving.server import ContinuousScheduler

    sched = ContinuousScheduler(
        FakeContinuousEngine(), decoder=FakeStepper(num_slots=2),
        tenant_weights={"vip": 2},
    )
    # The worker thread is parked in q.get(); the tenant queues are
    # worker-side state we can drive directly for a deterministic
    # dequeue-order check.
    def req(tenant, i):
        r = sched._make_request([i], {"tenant": tenant}, stream=False)
        return r

    for i in range(4):
        sched._enqueue_tenant(req("hot", i))
    for i in range(2):
        sched._enqueue_tenant(req("cold", 10 + i))
    for i in range(2):
        sched._enqueue_tenant(req("vip", 20 + i))
    order = []
    while True:
        nxt = sched._next_queued()
        if nxt is None:
            break
        order.append(nxt.tenant)
    assert len(order) == 8
    # One rotation serves every tenant before hot's flood repeats: both
    # cold requests and both vip requests land in the first 2 rotations.
    assert order.index("cold") < 3
    assert order[:5].count("hot") <= 2
    # vip (weight 2) drains both its requests inside one rotation.
    first_vip = order.index("vip")
    assert order[first_vip + 1] == "vip" or order.count("vip") == 2
    assert sched.queue_depth() == 0


def test_fair_share_keeps_starved_tenant_draining():
    """Acceptance: under an injected hot-tenant flood, the starved
    tenant's queue keeps draining — its requests complete before the
    flood's tail."""
    import time as _time

    from luminaai_tpu.serving.server import ContinuousScheduler

    sched = ContinuousScheduler(
        FakeContinuousEngine(), decoder=FakeStepper(num_slots=1)
    )
    done = []
    lock = threading.Lock()

    def hit(tenant, tok, budget):
        sched.submit([tok], {"max_new_tokens": budget, "tenant": tenant})
        with lock:
            done.append(tenant)

    # A blocker occupies the single slot while the flood + starved
    # tenant enqueue behind it.
    blocker = threading.Thread(target=hit, args=("hot", 50, 60))
    blocker.start()
    _time.sleep(0.1)
    threads = [
        threading.Thread(target=hit, args=("hot", 100 + i, 3))
        for i in range(6)
    ] + [
        threading.Thread(target=hit, args=("starved", 200 + i, 3))
        for i in range(2)
    ]
    for t in threads:
        t.start()
    for t in [blocker] + threads:
        t.join(timeout=60)
    assert len(done) == 9
    # Both starved completions land before the flood's tail: WRR admits
    # starved's requests in the first rotations after the blocker.
    last_starved = max(i for i, t in enumerate(done) if t == "starved")
    assert last_starved <= 6, done


def test_tenant_token_bucket_gate_429s_and_recovers():
    srv = ChatServer(FakeEngine(), tenant_rate_per_s=100.0, tenant_burst=2)
    # Deterministic clock for the bucket.
    now = [0.0]
    srv.tenant_bucket.clock = lambda: now[0]
    srv.tenant_bucket._buckets.clear()
    ok1 = srv.handle("POST", "/v1/generate", {"prompt": "a"}, None)
    ok2 = srv.handle("POST", "/v1/generate", {"prompt": "b"}, None)
    limited = srv.handle("POST", "/v1/generate", {"prompt": "c"}, None)
    assert ok1[0] == 200 and ok2[0] == 200
    assert limited[0] == 429
    assert "retry_after" in limited[1]
    now[0] += 1.0  # 100 tokens/s refill
    assert srv.handle("POST", "/v1/generate", {"prompt": "d"}, None)[0] == 200


def test_secure_gate_limiter_keys_are_hashed_tenants():
    """Satellite: the gate's limiter state is keyed by tenant_hash, so
    raw usernames never appear in limiter keys."""
    from luminaai_tpu.security.auth import tenant_hash

    srv = ChatServer(
        FakeEngine(), secure=True,
        bootstrap_user=("alice", "correct-horse1"),
        users_path="/dev/null",
    )
    code, payload = srv.handle(
        "POST", "/v1/auth",
        {"user": "alice", "password": "correct-horse1"}, None,
    )
    assert code == 200
    token = payload["token"]
    code, _ = srv.handle("POST", "/v1/chat", {"message": "hi"}, token)
    assert code == 200
    keys = list(srv.limiter._events)
    assert keys, "limiter recorded nothing"
    assert all(ident == tenant_hash("alice") for ident, _ in keys)
    assert all(ident != "alice" for ident, _ in keys)


def test_microbatcher_fallback_tenant_accounting_parity():
    """Satellite: identity riders thread through MicroBatcher.submit —
    per-tenant /metrics series and lifecycle events match the
    continuous path for the same workload."""
    from luminaai_tpu.monitoring.events import FlightRecorder
    from luminaai_tpu.monitoring.telemetry import MetricsRegistry

    workload = [{"prompt": "hello"}, {"prompt": "worlds"}]

    def run(engine, continuous):
        reg = MetricsRegistry()
        rec = FlightRecorder(capacity=256)
        srv = ChatServer(
            engine, continuous=continuous, registry=reg, recorder=rec
        )
        for body in workload:
            code, payload = srv.handle(
                "POST", "/v1/generate", dict(body), None
            )
            assert code == 200
            assert payload["request_id"]
            assert payload["tenant"] == "anon"
        snap = reg.snapshot()
        return {
            "requests": snap["tenant_requests_total"].get("tenant=anon"),
            "tokens_in": snap["tenant_tokens_in_total"].get("tenant=anon"),
            "tokens_out": snap["tenant_tokens_out_total"].get(
                "tenant=anon"
            ),
        }, rec

    cont, _ = run(FakeContinuousEngine(), True)
    legacy, rec = run(FakeEngine(), False)
    assert cont == legacy
    # The fallback path emits the same lifecycle spine, tagged with its
    # scheduler (riders stripped in submit, never reaching the engine).
    admitted = rec.snapshot(type="request_admitted")
    completed = rec.snapshot(type="request_completed")
    assert len(admitted) == 2 and len(completed) == 2
    assert all(e["scheduler"] == "micro_batch" for e in admitted)
    assert all(e.get("tenant") == "anon" for e in completed)
