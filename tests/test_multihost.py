"""Multi-host (multi-process) distributed training test.

SURVEY §2 'Multi-host awareness': round-1 review called this path untested
"unavoidably" — it isn't. Two OS processes, each with 4 virtual CPU
devices, form one 8-device global mesh through jax.distributed (the same
coordination path a TPU pod uses, minus ICI): initialize_multihost brings
up the runtime, build_mesh sees 8 global devices, and a data-parallel
train step runs with XLA's cross-process collectives. Both workers must
report the same finite loss.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

WORKER = textwrap.dedent("""
    import os, sys
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4"
    )
    import jax
    jax.config.update("jax_platforms", "cpu")

    coordinator, pid, mode = sys.argv[1], int(sys.argv[2]), sys.argv[3]

    from luminaai_tpu.config import Config
    from luminaai_tpu.models.transformer import LuminaTransformer
    from luminaai_tpu.parallel.mesh import build_mesh, initialize_multihost
    from luminaai_tpu.parallel.sharding import init_sharded_state
    from luminaai_tpu.parallel.train_step import make_train_step
    from luminaai_tpu.training.optimizer import make_optimizer, make_schedule

    extra = (
        # 1F1B pipeline stages SPANNING the process boundary: every tick's
        # activation/cotangent ppermute is a cross-process collective.
        dict(pipeline_parallel_size=2, scan_layers=True)
        if mode == "pipe"
        else dict(fsdp_parallel_size=2)
    )
    cfg = Config(
        vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
        num_kv_heads=1, seq_length=32, batch_size=8,
        use_flash_attention=False, gradient_checkpointing=False,
        precision="fp32",
        multihost=True, coordinator_address=coordinator,
        num_processes=2, process_id=pid, **extra,
    )
    initialize_multihost(cfg)
    assert jax.device_count() == 8, jax.device_count()
    assert jax.process_count() == 2

    import jax.numpy as jnp
    import numpy as np

    model = LuminaTransformer(cfg)
    schedule = make_schedule(cfg, 10)
    tx = make_optimizer(cfg, 10, schedule)
    mesh = build_mesh(cfg)
    state, shardings = init_sharded_state(
        cfg, model, tx, mesh, jax.random.key(0)
    )
    step = make_train_step(cfg, model, shardings, mesh, schedule, tx)

    from jax.sharding import NamedSharding
    from luminaai_tpu.parallel.sharding import batch_spec

    bsharding = NamedSharding(mesh, batch_spec())
    if mode == "data":
        # Production multi-host input path: this host's PackedDataset
        # shard (docs pid::2 of the shared cache — nothing else is read)
        # -> put_process_local_batch assembly -> sharded train step.
        from luminaai_tpu.data.dataset import PackedDataset, TokenCache
        from luminaai_tpu.training.trainer import put_process_local_batch

        cache = TokenCache(sys.argv[4]).open()
        ds = PackedDataset(
            cache, cfg.batch_size, cfg.seq_length, pad_id=0,
            process_index=pid, process_count=2,
        )
        local = next(iter(ds))
        assert local["input_ids"].shape == (
            cfg.batch_size // 2, cfg.seq_length
        ), local["input_ids"].shape
        # Reads only its shard: every real token comes from docs pid::2.
        shard_tokens = set()
        for d in range(pid, cache.n_docs, 2):
            shard_tokens |= set(
                np.asarray(
                    cache.tokens[cache.offsets[d]:cache.offsets[d+1]]
                ).tolist()
            )
        real = local["input_ids"][local["loss_mask"] > 0]
        assert set(real.tolist()) <= shard_tokens, "host read foreign docs"
        batch = put_process_local_batch(local, bsharding, cfg.batch_size)
    else:
        # Each process feeds its LOCAL shard of the global batch via
        # make_array_from_process_local_data (the multi-host input
        # pattern).
        global_ids = np.random.RandomState(0).randint(
            1, cfg.vocab_size, size=(cfg.batch_size, cfg.seq_length)
        ).astype(np.int32)
        batch = {
            "input_ids": jax.make_array_from_process_local_data(
                bsharding, global_ids  # full array; jax slices per process
            )
        }
    state, metrics = step(state, batch)
    loss = float(metrics["loss"])
    assert np.isfinite(loss), loss
    print(f"WORKER{pid} loss {loss:.6f}", flush=True)
""")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _build_cache(tmp_path):
    """Shared token cache the 'data'-mode workers shard between them."""
    from luminaai_tpu.data.dataset import TokenCache

    rng = __import__("numpy").random.RandomState(7)
    docs = [
        rng.randint(1, 128, size=rng.randint(10, 60)).tolist()
        for _ in range(40)
    ]
    stem = str(tmp_path / "mhcache")
    TokenCache(stem).build(iter(docs))
    return stem


@pytest.mark.parametrize("mode", ["fsdp", "pipe", "data"])
def test_two_process_distributed_train_step(tmp_path, mode):
    coordinator = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    argv_tail = [_build_cache(tmp_path)] if mode == "data" else []
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", WORKER, coordinator, str(pid), mode]
            + argv_tail,
            env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multihost worker timed out")
        outs.append(out)
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"
    losses = []
    for out in outs:
        for line in out.splitlines():
            if line.startswith("WORKER"):
                losses.append(float(line.split()[-1]))
    assert len(losses) == 2
    # Replicated loss scalar: both processes computed the same global value.
    assert abs(losses[0] - losses[1]) < 1e-6, losses

    if mode == "data":
        # Training-loss parity vs single-process: assemble the same global
        # batch (concat of the two host shards) in THIS process and run
        # the identical step on the local 8-device mesh.
        import jax
        import numpy as np

        from jax.sharding import NamedSharding
        from luminaai_tpu.config import Config
        from luminaai_tpu.data.dataset import PackedDataset, TokenCache
        from luminaai_tpu.models.transformer import LuminaTransformer
        from luminaai_tpu.parallel.mesh import build_mesh
        from luminaai_tpu.parallel.sharding import batch_spec, init_sharded_state
        from luminaai_tpu.parallel.train_step import make_train_step
        from luminaai_tpu.training.optimizer import make_optimizer, make_schedule

        cfg = Config(
            vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
            num_kv_heads=1, seq_length=32, batch_size=8,
            use_flash_attention=False, gradient_checkpointing=False,
            precision="fp32", fsdp_parallel_size=2,
        )
        cache = TokenCache(argv_tail[0]).open()
        shards = [
            next(iter(PackedDataset(
                cache, cfg.batch_size, cfg.seq_length, pad_id=0,
                process_index=q, process_count=2,
            )))
            for q in range(2)
        ]
        batch_np = {
            k: np.concatenate([s[k] for s in shards]) for k in shards[0]
        }
        model = LuminaTransformer(cfg)
        schedule = make_schedule(cfg, 10)
        tx = make_optimizer(cfg, 10, schedule)
        mesh = build_mesh(cfg)
        state, shardings = init_sharded_state(
            cfg, model, tx, mesh, jax.random.key(0)
        )
        step = make_train_step(cfg, model, shardings, mesh, schedule, tx)
        bsharding = NamedSharding(mesh, batch_spec())
        batch = {
            k: jax.device_put(v, bsharding) for k, v in batch_np.items()
        }
        _, metrics = step(state, batch)
        ref_loss = float(metrics["loss"])
        assert abs(losses[0] - ref_loss) < 1e-4, (losses, ref_loss)
