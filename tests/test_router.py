"""Resilient serving-plane router (luminaai_tpu/serving/router.py).

Every failure contract here runs on an injectable clock + in-memory
transport — NO wall-clock sleeps: probes, breaker cooldowns and shed
windows advance by `clock.advance()`, and the router's backoff sleep is
a no-op recorder. The handful of real-HTTP tests at the bottom exercise
the socket seam (ChatServer replicas, the router's own HTTP surface,
the kill_replica injector) with fast local connections only.
"""

import json
import threading
import urllib.error
import urllib.request
from http.server import ThreadingHTTPServer
from types import SimpleNamespace

import pytest

from luminaai_tpu.cli import main
from luminaai_tpu.monitoring.events import FlightRecorder, filter_events
from luminaai_tpu.monitoring.telemetry import MetricsRegistry
from luminaai_tpu.monitoring.top import render_top
from luminaai_tpu.serving.router import CircuitBreaker, Router
from luminaai_tpu.serving.server import REQUEST_ID_RX, ChatServer
from luminaai_tpu.testing.faults import kill_replica, replica_5xx_burst
from tests.test_serving import FakeEngine, _get, _post, _post_sse


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += float(dt)


class SimReplica:
    """One in-memory ChatServer as the FakeTransport sees it: scripted
    health, 5xx/shed bursts, death, and SSE frame plans."""

    def __init__(self, name):
        self.name = name
        self.alive = True
        self.status = "ok"
        self.fail_next = 0       # POSTs answered 500
        self.shed_next = 0       # POSTs answered 503
        self.retry_after = 7
        self.posts = 0
        self.stream_frames = 3   # tokens before the done frame
        self.stream_die_after = None  # frames yielded before death

    def request(self, method, path, body, headers):
        if not self.alive:
            raise ConnectionRefusedError(f"{self.name} is dead")
        if method == "GET" and path == "/healthz":
            return 200, {}, {"status": self.status}
        if method == "GET":
            return 404, {}, {"error": "no route"}
        self.posts += 1
        if self.shed_next > 0:
            self.shed_next -= 1
            return 503, {}, {"error": "shedding",
                            "retry_after": self.retry_after}
        if self.fail_next > 0:
            self.fail_next -= 1
            return 500, {}, {"error": "boom"}
        return 200, {}, {
            "text": f"ok:{self.name}", "tokens": 3,
            "request_id": (headers or {}).get("X-Request-Id"),
        }

    def stream(self, path, body, headers):
        if not self.alive:
            raise ConnectionRefusedError(f"{self.name} is dead")
        if self.shed_next > 0:
            self.shed_next -= 1
            return 503, {}, {"error": "shedding",
                            "retry_after": self.retry_after}, None

        def frames():
            for i in range(self.stream_frames):
                if (self.stream_die_after is not None
                        and i >= self.stream_die_after):
                    raise ConnectionError(f"{self.name} died mid-stream")
                yield json.dumps({"token": i, "replica": self.name})
            yield json.dumps({"done": True, "replica": self.name})

        return 200, {}, None, frames()


class FakeTransport:
    """Routes transport calls to SimReplicas by URL."""

    def __init__(self, sims):
        self.by_url = {f"http://sim/{s.name}": s for s in sims}

    def endpoints(self):
        return [(s.name, url) for url, s in self.by_url.items()]

    def request(self, base_url, method, path, body=None, headers=None,
                timeout_s=None, cancel=None):
        return self.by_url[base_url].request(method, path, body, headers)

    def stream(self, base_url, path, body, headers=None, timeout_s=None):
        return self.by_url[base_url].stream(path, body, headers)


def make_router(n=2, **kw):
    sims = [SimReplica(f"r{i}") for i in range(n)]
    transport = FakeTransport(sims)
    clock = FakeClock()
    sleeps = []
    recorder = FlightRecorder(capacity=512)
    kw.setdefault("breaker_failures", 3)
    kw.setdefault("breaker_cooldown_s", 5.0)
    kw.setdefault("max_failovers", n - 1)
    router = Router(
        transport.endpoints(), transport=transport,
        registry=MetricsRegistry(), recorder=recorder,
        clock=clock, sleep=sleeps.append, **kw,
    )
    return SimpleNamespace(router=router, sims=sims, clock=clock,
                           sleeps=sleeps, recorder=recorder)


def metric_line(registry, prefix):
    for line in registry.render_prometheus().splitlines():
        if line.startswith(prefix):
            return float(line.rsplit(" ", 1)[1])
    return None


# -- circuit breaker FSM ----------------------------------------------------

def test_breaker_consecutive_failures_open_halfopen_close():
    clock = FakeClock()
    seen = []
    b = CircuitBreaker("r0", failures=3, cooldown_s=5.0, clock=clock,
                       on_transition=lambda bk, o, n, r: seen.append((o, n)))
    for _ in range(2):
        b.record_failure()
    assert b.state == "closed" and b.allow()
    b.record_failure()
    assert b.state == "open" and not b.allow()
    clock.advance(4.9)
    assert not b.allow()  # cooldown not elapsed
    clock.advance(0.2)
    assert b.allow()      # the ONE half-open probe
    assert b.state == "half_open"
    assert not b.allow()  # slot already owned
    b.record_success()
    assert b.state == "closed" and b.allow()
    assert seen == [("closed", "open"), ("open", "half_open"),
                    ("half_open", "closed")]


def test_breaker_halfopen_failure_reopens_and_probe_rearms():
    clock = FakeClock()
    b = CircuitBreaker("r0", failures=1, cooldown_s=5.0, clock=clock)
    b.record_failure()
    clock.advance(5.1)
    assert b.allow() and b.state == "half_open"
    b.record_failure()
    assert b.state == "open" and not b.allow()
    # A probe lost without a verdict re-arms after another cooldown.
    clock.advance(5.1)
    assert b.allow() and b.state == "half_open"
    clock.advance(5.1)
    assert b.allow()  # prior probe presumed lost: slot re-armed


def test_breaker_error_rate_opens_without_consecutive_run():
    b = CircuitBreaker("r0", failures=5, error_rate=0.5, min_requests=8,
                       clock=FakeClock())
    for _ in range(4):  # alternate ok/fail: never 5 consecutive
        b.record_success()
        b.record_failure()
    assert b.state == "open"


def test_breaker_trip_forces_open():
    b = CircuitBreaker("r0", failures=3, clock=FakeClock())
    b.trip("probe failed: ConnectionRefusedError")
    assert b.state == "open" and not b.allow()


# -- dispatch: affinity, failover, shed -------------------------------------

def test_affinity_stable_per_prompt_and_spreads_across_prompts():
    env = make_router(n=3)
    key = env.router._affinity_key("/v1/generate", {"prompt": "shared sys"})
    heads = {env.router._ordered(key)[0].name for _ in range(10)}
    assert len(heads) == 1  # same prompt, same head, every time
    spread = {
        env.router._ordered(
            env.router._affinity_key("/v1/generate", {"prompt": f"p{i}"})
        )[0].name
        for i in range(24)
    }
    assert len(spread) > 1  # distinct prompts land on distinct replicas


def test_failover_on_dead_replica_is_invisible_to_client():
    env = make_router(n=2)
    env.sims[0].alive = False
    for i in range(6):
        status, payload = env.router.dispatch(
            "/v1/generate", {"prompt": f"p{i}"})
        assert status == 200
        assert payload["text"] == "ok:r1"
    failovers = env.recorder.snapshot(type="router_failover")
    assert failovers and all(
        e["to_replica"] == "r1" and e["kind"] == "request"
        for e in failovers
    )
    # Backoff between candidates went through the injected sleep.
    assert env.sleeps and all(s >= 0 for s in env.sleeps)


def test_shed_is_a_routing_signal_not_a_client_error():
    env = make_router(n=2)
    env.sims[0].shed_next = 1
    env.sims[0].retry_after = 7
    # Pick a prompt whose affine head is the shedding replica.
    prompt = next(
        f"p{i}" for i in range(64)
        if env.router._ordered(env.router._affinity_key(
            "/v1/generate", {"prompt": f"p{i}"}))[0].name == "r0"
    )
    status, payload = env.router.dispatch(
        "/v1/generate", {"prompt": prompt})
    assert status == 200 and payload["text"] == "ok:r1"
    assert metric_line(env.router.registry,
                       'router_sheds_total{replica="r0"}') == 1
    # r0 is now on shed-cooldown: the next request skips it WITHOUT
    # contacting it, and the breaker is untouched (shed != failure).
    posts_before = env.sims[0].posts
    status, _ = env.router.dispatch("/v1/generate", {"prompt": prompt})
    assert status == 200 and env.sims[0].posts == posts_before
    assert env.router.replicas[0].breaker.state == "closed"
    # Cooldown expires on the injected clock: r0 serves again.
    env.clock.advance(7.1)
    status, payload = env.router.dispatch(
        "/v1/generate", {"prompt": prompt})
    assert status == 200 and payload["text"] == "ok:r0"


def test_all_shedding_returns_503_with_max_retry_after():
    env = make_router(n=2)
    env.sims[0].shed_next = 1
    env.sims[0].retry_after = 7
    env.sims[1].shed_next = 1
    env.sims[1].retry_after = 3
    status, payload = env.router.dispatch("/v1/generate", {"prompt": "x"})
    assert status == 503
    assert payload["retry_after"] == 7  # the max, so clients back off enough
    assert payload["request_id"]
    assert env.recorder.snapshot(type="router_shed_all")
    assert metric_line(env.router.registry,
                       "router_shed_returned_total") == 1


def test_5xx_burst_opens_breaker_then_failover_serves():
    env = make_router(n=2)
    env.sims[0].fail_next = 10
    prompt = next(
        f"p{i}" for i in range(64)
        if env.router._ordered(env.router._affinity_key(
            "/v1/generate", {"prompt": f"p{i}"}))[0].name == "r0"
    )
    for _ in range(5):
        status, _ = env.router.dispatch("/v1/generate", {"prompt": prompt})
        assert status == 200  # every 5xx absorbed by failover
    assert env.router.replicas[0].breaker.state == "open"
    assert env.recorder.snapshot(type="breaker_open")
    # Once open, r0 is skipped: its POST count stops moving.
    posts = env.sims[0].posts
    env.router.dispatch("/v1/generate", {"prompt": prompt})
    assert env.sims[0].posts == posts


# -- THE acceptance contract ------------------------------------------------

@pytest.mark.faults
def test_acceptance_kill_one_of_two_replicas_zero_client_5xx():
    """ISSUE 19 acceptance: two replicas, one dies mid-load. The router
    completes in-flight survivor streams, opens the dead replica's
    breaker within one probe round, serves every subsequent request with
    zero client-visible 5xx, and walks half-open → closed when the
    replica returns. Injected clock + transport: no wall-clock sleeps."""
    env = make_router(n=2, breaker_cooldown_s=5.0)
    router, clock = env.router, env.clock
    router.probe_all()
    assert [r.status for r in router.replicas] == ["ok", "ok"]

    # Warm traffic over both replicas.
    for i in range(8):
        status, _ = router.dispatch("/v1/generate", {"prompt": f"warm{i}"})
        assert status == 200

    # An in-flight stream pinned to the survivor (r1): start it, then
    # kill r0 mid-consumption.
    survivor_prompt = next(
        f"s{i}" for i in range(64)
        if router._ordered(router._affinity_key(
            "/v1/chat", {"message": f"s{i}", "stream": True}))[0].name == "r1"
    )
    err, frames = router.open_stream(
        "/v1/chat", {"message": survivor_prompt, "stream": True})
    assert err is None
    it = iter(frames)
    first = json.loads(next(it))
    assert first["replica"] == "r1"

    env.sims[0].alive = False  # SIGKILL equivalent: connections refused

    # The survivor's in-flight stream drains to completion.
    rest = [json.loads(f) for f in it]
    assert rest[-1]["done"] is True
    assert all(f["replica"] == "r1" for f in rest[:-1])

    # One probe round opens the dead replica's breaker (trip: a refused
    # TCP endpoint needs no statistical evidence).
    router.probe_all()
    assert router.replicas[0].breaker.state == "open"
    assert router.replicas[0].status == "down"
    opens = env.recorder.snapshot(type="breaker_open")
    assert opens and opens[-1]["replica"] == "r0"
    assert metric_line(router.registry,
                       'router_breaker_state{replica="r0"}') == 2

    # Every subsequent request lands 200 — zero client-visible 5xx.
    for i in range(10):
        clock.advance(0.3)  # stay inside the cooldown: r0 never probed
        status, payload = router.dispatch(
            "/v1/generate", {"prompt": f"post-kill {i}"})
        assert status == 200 and payload["text"] == "ok:r1"
    # Streams too.
    err, frames = router.open_stream(
        "/v1/generate", {"prompt": "post-kill stream", "stream": True})
    assert err is None
    assert json.loads(list(frames)[-1])["done"] is True

    # Replica returns: after the cooldown the next probe walks the
    # breaker half-open → closed and traffic reaches r0 again.
    env.sims[0].alive = True
    clock.advance(5.1)
    router.probe_all()
    assert router.replicas[0].breaker.state == "closed"
    assert router.replicas[0].status == "ok"
    types = [e["type"] for e in env.recorder.snapshot()
             if e["type"].startswith("breaker_")]
    assert types[-2:] == ["breaker_half_open", "breaker_close"]
    assert metric_line(router.registry,
                       'router_breaker_state{replica="r0"}') == 0
    status, _ = router.dispatch("/v1/generate", {"prompt": "recovered"})
    assert status == 200


# -- streams ----------------------------------------------------------------

@pytest.mark.faults
def test_stream_pre_first_token_fails_over_transparently():
    env = make_router(n=2)
    env.sims[0].stream_die_after = 0  # dies before the first frame
    prompt = next(
        f"p{i}" for i in range(64)
        if env.router._ordered(env.router._affinity_key(
            "/v1/generate", {"prompt": f"p{i}"}))[0].name == "r0"
    )
    err, frames = env.router.open_stream(
        "/v1/generate", {"prompt": prompt, "stream": True})
    assert err is None
    out = [json.loads(f) for f in frames]
    # No error frame: the client sees a clean stream from the survivor.
    assert out[-1]["done"] is True
    assert all(f.get("replica") == "r1" for f in out)
    fo = env.recorder.snapshot(type="router_failover")
    assert fo and fo[-1]["kind"] == "stream"


@pytest.mark.faults
def test_stream_mid_generation_surfaces_error_frame_with_request_id():
    env = make_router(n=2)
    env.sims[0].stream_die_after = 2  # two tokens reach the client first
    prompt = next(
        f"p{i}" for i in range(64)
        if env.router._ordered(env.router._affinity_key(
            "/v1/generate", {"prompt": f"p{i}"}))[0].name == "r0"
    )
    rid = "req-mid-stream-1"
    err, frames = env.router.open_stream(
        "/v1/generate", {"prompt": prompt, "stream": True},
        headers={"X-Request-Id": rid})
    assert err is None
    out = [json.loads(f) for f in frames]
    # Replaying elsewhere would duplicate the two delivered tokens, so
    # the death surfaces as an error frame carrying the original id.
    assert [f.get("token") for f in out[:2]] == [0, 1]
    assert out[-1]["error"] and out[-1]["request_id"] == rid
    assert metric_line(env.router.registry,
                       "router_stream_errors_total") == 1
    ev = env.recorder.snapshot(type="router_stream_error")
    assert ev and ev[-1]["request_id"] == rid


def test_stream_all_shedding_returns_503():
    env = make_router(n=2)
    env.sims[0].shed_next = 1
    env.sims[1].shed_next = 1
    err, frames = env.router.open_stream(
        "/v1/generate", {"prompt": "x", "stream": True})
    assert frames is None
    status, payload = err
    assert status == 503 and payload["retry_after"] >= 1


# -- hedging ----------------------------------------------------------------

class BlockingTransport(FakeTransport):
    """r0 blocks POSTs until released — the hedge must win."""

    def __init__(self, sims, slow_name):
        super().__init__(sims)
        self.slow_name = slow_name
        self.release = threading.Event()

    def request(self, base_url, method, path, body=None, headers=None,
                timeout_s=None, cancel=None):
        sim = self.by_url[base_url]
        if method == "POST" and sim.name == self.slow_name:
            self.release.wait(timeout=5.0)
        return sim.request(method, path, body, headers)


def test_hedged_dispatch_second_replica_wins():
    sims = [SimReplica("r0"), SimReplica("r1")]
    transport = BlockingTransport(sims, slow_name="r0")
    recorder = FlightRecorder(capacity=128)
    router = Router(
        transport.endpoints(), transport=transport,
        registry=MetricsRegistry(), recorder=recorder,
        sleep=lambda dt: None, hedge=True, hedge_delay_s=0.005,
        hedge_budget=1.0,
    )
    prompt = next(
        f"p{i}" for i in range(64)
        if router._ordered(router._affinity_key(
            "/v1/generate", {"prompt": f"p{i}"}))[0].name == "r0"
    )
    try:
        status, payload = router.dispatch(
            "/v1/generate", {"prompt": prompt, "max_new_tokens": 8})
        assert status == 200 and payload["text"] == "ok:r1"
    finally:
        transport.release.set()
    assert metric_line(router.registry, "router_hedges_total") == 1
    assert metric_line(router.registry, "router_hedge_wins_total") == 1
    ev = recorder.snapshot(type="router_hedge")
    assert ev and ev[-1]["primary"] == "r0" and ev[-1]["hedge"] == "r1"


def test_hedge_budget_and_eligibility_bounds():
    env = make_router(n=2, hedge=True, hedge_budget=0.1,
                      hedge_max_tokens=32)
    r = env.router
    # Streams and long generations never hedge.
    assert not r._hedge_eligible({"stream": True})
    assert not r._hedge_eligible({"max_new_tokens": 64})
    # Budget 0.1: hedges may never exceed 10% of non-stream traffic, so
    # cold traffic can't hedge at all — no tail-chasing under no load.
    assert not r._hedge_eligible({"max_new_tokens": 8})
    with r._stats_lock:
        r._nonstream_total = 9
    assert r._hedge_eligible({"max_new_tokens": 8})
    # After one hedge, another 10% of traffic must accrue first.
    with r._stats_lock:
        r._hedges_fired = 1
        r._nonstream_total = 15
    assert not r._hedge_eligible({"max_new_tokens": 8})
    with r._stats_lock:
        r._nonstream_total = 40
    assert r._hedge_eligible({"max_new_tokens": 8})
    # A hedge partner is only ever a closed-breaker, unshedded replica —
    # peeked, never consuming a half-open probe slot.
    r.replicas[1].breaker.trip("dead")
    order = r._ordered("k")
    primary = r.replicas[0] if order[0] is r.replicas[0] else r.replicas[1]
    assert r._hedge_partner(order, order[0]) is None


# -- fleet / health surfaces ------------------------------------------------

def test_healthz_aggregate_degraded_and_down():
    env = make_router(n=2)
    env.router.probe_all()
    code, payload = env.router.health_payload()
    assert (code, payload["status"]) == (200, "ok")
    env.sims[0].alive = False
    env.router.probe_all()
    code, payload = env.router.health_payload()
    # One dead replica degrades the plane but must NOT pull it from
    # rotation: the survivor is still serving.
    assert (code, payload["status"]) == (200, "degraded")
    assert payload["available"] == 1 and payload["breakers_open"] == 1
    env.sims[1].alive = False
    env.router.probe_all()
    code, payload = env.router.health_payload()
    assert (code, payload["status"]) == (503, "down")


def test_fleet_payload_and_top_render():
    env = make_router(n=2)
    env.router.probe_all()
    env.router.dispatch("/v1/generate", {"prompt": "x"})
    env.sims[1].alive = False
    env.router.probe_all()
    fleet = env.router.fleet_payload()
    assert fleet["status"] == "degraded"
    by_name = {r["replica"]: r for r in fleet["replicas"]}
    assert by_name["r1"]["breaker"] == "open"
    assert by_name["r1"]["status"] == "down"
    assert by_name["r0"]["breaker"] == "closed"
    frame = render_top({"series": {}}, source="router", fleet=fleet)
    assert "fleet — degraded (1/2 available" in frame
    assert "! r1" in frame.replace("!  r1", "! r1")  # open breaker flagged
    assert "(no series" not in frame  # router mode: fleet replaces rows


# -- real HTTP: ChatServer replicas behind the router -----------------------

@pytest.fixture()
def fleet_url():
    """Two real ChatServer replicas + the router's own HTTP surface,
    all in-process on loopback."""
    servers, httpds, urls = [], [], []
    for _ in range(2):
        srv = ChatServer(FakeEngine(), registry=MetricsRegistry(),
                         recorder=FlightRecorder(capacity=512))
        httpd = ThreadingHTTPServer(("127.0.0.1", 0), srv.make_handler())
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        servers.append(srv)
        httpds.append(httpd)
        urls.append(f"http://127.0.0.1:{httpd.server_address[1]}")
    recorder = FlightRecorder(capacity=512)
    router = Router(
        [("r0", urls[0]), ("r1", urls[1])],
        registry=MetricsRegistry(), recorder=recorder,
        sleep=lambda dt: None, max_failovers=1,
        breaker_cooldown_s=5.0,
    )
    rhttpd = ThreadingHTTPServer(("127.0.0.1", 0), router.make_handler())
    threading.Thread(target=rhttpd.serve_forever, daemon=True).start()
    yield SimpleNamespace(
        url=f"http://127.0.0.1:{rhttpd.server_address[1]}",
        router=router, servers=servers, httpds=httpds,
        replica_urls=urls, recorder=recorder,
    )
    for h in [rhttpd] + httpds:
        h.shutdown()
        h.server_close()


def test_router_http_surface_end_to_end(fleet_url):
    f = fleet_url
    code, body = _post(f.url, "/v1/generate", {"prompt": "hiya"})
    assert code == 200 and body["text"].startswith("tok:")
    assert REQUEST_ID_RX.fullmatch(body["request_id"])
    code, body = _post(f.url, "/v1/chat", {"message": "yo"})
    assert code == 200 and body["reply"].startswith("tok:")
    ctype, frames = _post_sse(f.url, "/v1/generate",
                              {"prompt": "hi", "stream": True})
    assert ctype.startswith("text/event-stream")
    assert frames[-1] == "[DONE]"
    assert json.loads(frames[-2])["done"] is True
    code, health = _get(f.url, "/healthz")
    assert code == 200 and health["status"] == "ok"
    code, fleet = _get(f.url, "/fleet")
    assert code == 200 and len(fleet["replicas"]) == 2
    with urllib.request.urlopen(f.url + "/metrics", timeout=10) as r:
        text = r.read().decode()
    assert "router_requests_total" in text
    assert "router_breaker_state" in text
    assert _post(f.url, "/nope", {})[0] == 404
    assert _get(f.url, "/healthz?verbose=1")[0] == 200


@pytest.mark.faults
def test_real_5xx_burst_opens_breaker_then_probe_recovers(fleet_url):
    """satellite 1: the replica_5xx_burst injector drives the breaker
    open over real HTTP, and a probe after the (fake-clock) cooldown
    walks it half-open → closed."""
    f = fleet_url
    clock = FakeClock()
    # Re-arm every breaker on the fake clock so recovery needs no sleep.
    for rep in f.router.replicas:
        rep.breaker._clock = clock
    head = f.router._ordered(
        f.router._affinity_key("/v1/generate", {"prompt": "burst"}))[0]
    victim = f.servers[f.replica_urls.index(head.url)]
    with replica_5xx_burst(victim, times=8) as hits:
        for _ in range(5):
            code, _ = _post(f.url, "/v1/generate", {"prompt": "burst"})
            assert code == 200  # failover absorbs every injected 500
    assert hits["calls"] >= 3
    assert head.breaker.state == "open"
    assert f.recorder.snapshot(type="breaker_open")
    # Burst exhausted + cooldown elapsed: one probe round recovers.
    clock.advance(5.1)
    f.router.probe_once(head)
    assert head.breaker.state == "closed"
    code, _ = _post(f.url, "/v1/generate", {"prompt": "burst"})
    assert code == 200


@pytest.mark.faults
def test_request_id_correlates_router_and_replica_rings(fleet_url, tmp_path,
                                                        capsys):
    """satellite 2: one X-Request-Id threads client → router → replica;
    `lumina events --request <id>` joins both flight rings."""
    f = fleet_url
    rid = "req-corr-42"
    # Kill one replica so the router books a failover event for this id.
    dead = f.router.replicas[0]
    dead_idx = f.replica_urls.index(dead.url)
    f.httpds[dead_idx].shutdown()
    f.httpds[dead_idx].server_close()
    prompt = next(
        f"p{i}" for i in range(64)
        if f.router._ordered(f.router._affinity_key(
            "/v1/generate", {"prompt": f"p{i}"}))[0] is dead
    )
    req = urllib.request.Request(
        f.url + "/v1/generate",
        data=json.dumps({"prompt": prompt}).encode(),
        headers={"Content-Type": "application/json", "X-Request-Id": rid},
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        assert r.status == 200
        assert r.headers.get("X-Request-Id") == rid
        body = json.loads(r.read())
    assert body["request_id"] == rid

    survivor = f.servers[1 - dead_idx]
    router_ev = filter_events(f.recorder.snapshot(), request=rid)
    replica_ev = filter_events(survivor.recorder.snapshot(), request=rid)
    assert any(e["type"] == "router_failover" for e in router_ev)
    assert any(e["type"] == "request_received" for e in replica_ev)

    # The CLI joins the two rings from their dumps.
    d_router = tmp_path / "router"
    d_replica = tmp_path / "replica"
    f.recorder.dump_to_dir(str(d_router), reason="test")
    survivor.recorder.dump_to_dir(str(d_replica), reason="test")
    assert main(["events", "--request", rid, "--json",
                 str(d_router), str(d_replica)]) == 0
    lines = [json.loads(ln) for ln in
             capsys.readouterr().out.strip().splitlines()]
    types = {e["type"] for e in lines}
    assert "router_failover" in types and "request_received" in types
    assert all(e["request_id"] == rid for e in lines)


def test_invalid_inbound_request_id_is_replaced(fleet_url):
    f = fleet_url
    req = urllib.request.Request(
        f.url + "/v1/generate",
        data=json.dumps({"prompt": "x"}).encode(),
        headers={"Content-Type": "application/json",
                 "X-Request-Id": "bad id!! with spaces"},
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        body = json.loads(r.read())
    assert body["request_id"] != "bad id!! with spaces"
    assert REQUEST_ID_RX.fullmatch(body["request_id"])


@pytest.mark.faults
def test_kill_replica_injector_refuses_new_connections(fleet_url):
    f = fleet_url
    victim = SimpleNamespace(httpd=f.httpds[0], url=f.replica_urls[0])
    kill_replica(victim)
    # Depending on backlog timing the client sees refused (URLError) or
    # reset (the kernel RSTs connections queued before the close).
    with pytest.raises((urllib.error.URLError, ConnectionResetError)):
        urllib.request.urlopen(f.replica_urls[0] + "/healthz", timeout=2)
    # The prober sees the dead endpoint and trips the breaker in ONE round.
    f.router.probe_all()
    assert f.router.replicas[0].breaker.state == "open"
    assert f.router.replicas[0].status == "down"
    # The plane keeps serving through the survivor.
    for i in range(4):
        code, _ = _post(f.url, "/v1/generate", {"prompt": f"after {i}"})
        assert code == 200


def test_lumina_top_renders_router_fleet(fleet_url, capsys):
    """satellite 4: `lumina top --url <router>` detects the /fleet shape
    and renders the per-replica table."""
    f = fleet_url
    f.router.probe_all()
    assert main(["top", "--url", f.url, "--once"]) == 0
    out = capsys.readouterr().out
    assert "fleet — ok (2/2 available" in out
    assert "r0" in out and "r1" in out
    assert main(["top", "--url", f.url, "--once", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["fleet"]["replicas"][0]["breaker"] == "closed"


# -- CLI wiring -------------------------------------------------------------

def test_cli_route_and_serve_replicas_parse():
    from luminaai_tpu.cli import _fleet_child_argv, build_parser

    p = build_parser()
    args = p.parse_args([
        "route", "--replica", "http://a:1", "--replica", "http://b:2",
        "--breaker-failures", "4", "--hedge", "--port", "8123",
    ])
    assert args.replicas == ["http://a:1", "http://b:2"]
    assert args.breaker_failures == 4 and args.hedge and args.port == 8123
    args = p.parse_args(["serve", "--replicas", "3"])
    assert args.replicas == 3
    # Fleet children inherit the serve argv minus the fleet/port flags.
    argv = ["serve", "--replicas", "3", "--port", "8000", "--continuous"]
    child = _fleet_child_argv(argv, 8001)
    assert "--replicas" not in child
    assert child[-2:] == ["--port", "8001"] and "--continuous" in child


def test_cli_route_rejects_duplicate_replicas(capsys):
    assert main(["route", "--replica", "http://a:1",
                 "--replica", "http://a:1/"]) == 2
    assert "duplicate" in capsys.readouterr().err


# -- bench contract ---------------------------------------------------------

@pytest.mark.faults
def test_router_bench_smoke_contract(capsys):
    """satellite 5: `bench.py --smoke-router` emits one JSON line whose
    extras.router pins failover + breaker behavior for the CI CHECK."""
    import bench

    bench._router_bench_main(smoke=True)
    out = capsys.readouterr().out.strip().splitlines()
    doc = json.loads(out[-1])
    assert doc["metric"] == "router_tokens_per_sec_2replica"
    assert "error" not in doc
    r = doc["extras"]["router"]
    assert r["replicas"] == 2
    assert r["failovers"] >= 1
    assert r["post_kill_success_rate"] == 1.0
    assert r["breaker_opened"] is True
    assert r["routed_ok"] == r["routed_requests"]
    # ISSUE 20: the shared-prefix rung prices cache-on vs cache-off.
    ps = doc["extras"]["page_share"]
    assert "error" not in ps, ps
    assert ps["cross_replica_hit_rate"] > 0
    assert ps["remote_hit_admissions"] >= 1 and ps["pull_failures"] == 0
    assert ps["prefill_tokens_cache_on"] < ps["prefill_tokens_cache_off"]
    assert ps["prefill_seconds_cache_off"] > 0


# -- fleet page index (ISSUE 20: cross-replica page sharing) ----------------

def test_page_report_registered_replicas_only_then_fifo_cap():
    """Only registered replica URLs are indexed (an unknown reporter
    could otherwise poison every lookup); the index is FIFO-bounded;
    last reporter wins per key."""
    env = make_router(n=2, page_index_capacity=3)
    out = env.router.handle_page_report(
        {"replica": "http://evil/x", "keys": ["k1"]})
    assert out == {"indexed": 0, "known": False}
    assert env.router.handle_page_lookup({"keys": ["k1"]})["owner"] is None
    out = env.router.handle_page_report(
        {"replica": "http://sim/r0", "keys": ["k1", "k2"]})
    assert out == {"indexed": 2, "known": True}
    # Last reporter wins: r1 re-reports k2.
    env.router.handle_page_report(
        {"replica": "http://sim/r1", "keys": ["k2"]})
    assert env.router.handle_page_lookup(
        {"keys": ["k2"]})["owner"] == "http://sim/r1"
    # Beyond capacity the OLDEST key falls out, never the newest.
    env.router.handle_page_report(
        {"replica": "http://sim/r0", "keys": ["k3", "k4"]})
    assert env.router.handle_page_lookup({"keys": ["k1"]})["owner"] is None
    assert env.router.handle_page_lookup(
        {"keys": ["k4"]})["owner"] == "http://sim/r0"
    assert metric_line(env.router.registry, "router_page_index_keys") == 3
    assert metric_line(
        env.router.registry, "router_page_reports_total") == 5


def test_page_lookup_contiguous_prefix_have_offset_and_health():
    """Lookup names one owner for a contiguous run from `have`, skips
    the asker, and never points a puller at a replica the router would
    not route a request to."""
    env = make_router(n=2)
    env.router.handle_page_report(
        {"replica": "http://sim/r0", "keys": ["a", "b", "c"]})
    # The covered prefix stops at the first key the owner lacks.
    res = env.router.handle_page_lookup(
        {"keys": ["a", "b", "zz"], "exclude": "http://sim/r1"})
    assert res["owner"] == "http://sim/r0" and res["keys"] == ["a", "b"]
    # have>0: the asker's resident prefix is covered without ownership
    # checks (it will not pull those), extension stays contiguous.
    res = env.router.handle_page_lookup({"keys": ["a", "b", "c"], "have": 1})
    assert res["owner"] == "http://sim/r0"
    assert res["keys"] == ["a", "b", "c"]
    assert env.router.handle_page_lookup(
        {"keys": ["a"], "have": 5})["owner"] is None
    # The asker never pulls from itself.
    assert env.router.handle_page_lookup(
        {"keys": ["a"], "exclude": "http://sim/r0"})["owner"] is None
    # Unhealthy owners are invisible: down status, then open breaker.
    r0 = env.router.replicas[0]
    r0.status = "down"
    assert env.router.handle_page_lookup({"keys": ["a"]})["owner"] is None
    r0.status = "ok"
    r0.breaker.trip("probe failed")
    assert env.router.handle_page_lookup({"keys": ["a"]})["owner"] is None
    # ...and the half-open probe slot is NOT consumed by lookups.
    env.clock.advance(6.0)
    assert env.router.handle_page_lookup({"keys": ["a"]})["owner"] is None
    assert r0.breaker.state == "open"  # lookup never called allow()
    assert r0.breaker.allow()  # the probe slot is still armed


def test_fleet_payload_shared_index_columns():
    env = make_router(n=2)
    env.router.handle_page_report(
        {"replica": "http://sim/r0", "keys": ["a", "b"]})
    by = {r["replica"]: r for r in env.router.fleet_payload()["replicas"]}
    assert by["r0"]["shared_pages"] == 2 and by["r0"]["page_reports"] == 2
    assert by["r1"]["shared_pages"] == 0 and by["r1"]["page_reports"] == 0


def test_page_index_http_routes(fleet_url):
    f = fleet_url
    key = "ab" * 32
    code, body = _post(f.url, "/pages/report",
                       {"replica": f.replica_urls[0], "keys": [key]})
    assert code == 200 and body == {"indexed": 1, "known": True}
    code, body = _post(f.url, "/pages/lookup",
                       {"keys": [key], "exclude": f.replica_urls[1]})
    assert code == 200
    assert body["owner"] == f.replica_urls[0] and body["keys"] == [key]
