"""Test harness: force an 8-device virtual CPU mesh.

The container's sitecustomize registers a tunneled TPU ('axon') backend and
pins JAX_PLATFORMS=axon; tests must run on a virtual 8-device CPU mesh
instead (sharding coverage without 8 real chips), so override both before
any backend is initialized.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
# Child processes spawned by tests (backend probes, dryrun workers, bench
# children) must ALSO land on CPU: they re-run the container sitecustomize
# from PYTHONPATH, which pins the tunneled TPU backend and can HANG a
# probe against a dead tunnel. Normalize the inheritable env here — the
# in-process jax.config.update below doesn't reach subprocesses.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PYTHONPATH", None)

import jax

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# Fast-tier support: the files below hold the mesh-heavy / multi-process /
# end-to-end tests that dominate suite wall-clock (pipeline parity grids,
# two-OS-process multihost runs, full trainer loops). The DEFAULT run is
# unchanged — full coverage — but `pytest -q -m "not slow"` gives a
# fast iteration tier, and multi-core machines can add `-n auto`
# (pytest-xdist) for parallel full runs.
_SLOW_FILES = {
    "test_pipeline.py",
    "test_multihost.py",
    "test_trainer.py",
    "test_sharding.py",
    "test_ring_attention.py",
    "test_scan_layers.py",
    "test_orchestrator.py",
    "test_adaptive.py",
    "test_cli.py",
    "test_adapters.py",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.fspath.basename in _SLOW_FILES:
            item.add_marker(pytest.mark.slow)


@pytest.fixture(scope="session", autouse=True)
def _assert_cpu_mesh():
    devices = jax.devices()
    assert devices[0].platform == "cpu" and len(devices) == 8, devices
