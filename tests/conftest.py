"""Test harness: force an 8-device virtual CPU mesh.

The container's sitecustomize registers a tunneled TPU ('axon') backend and
pins JAX_PLATFORMS=axon; tests must run on a virtual 8-device CPU mesh
instead (sharding coverage without 8 real chips), so override both before
any backend is initialized.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _assert_cpu_mesh():
    devices = jax.devices()
    assert devices[0].platform == "cpu" and len(devices) == 8, devices
