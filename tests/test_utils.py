"""utils package tests: reporting, profiling, environment (SURVEY §2/§5)."""

import json
import time
from pathlib import Path

import jax.numpy as jnp

from luminaai_tpu.utils.profiling import (
    SectionTimer,
    StepTimer,
    annotate,
    profile_function,
    profiling_context,
)
from luminaai_tpu.utils.reporting import (
    create_data_summary_report,
    create_training_report,
)


def test_profile_function_records_synced_timings():
    @profile_function
    def work(x):
        return jnp.sum(x * x)

    out = work(jnp.arange(128, dtype=jnp.float32))
    assert float(out) > 0
    s = work.summary()
    assert s["count"] == 1 and s["mean_s"] > 0


def test_step_timer_window_and_summary():
    timer = StepTimer()
    timer.start()
    val = jnp.ones((8,)).sum()
    time.sleep(0.01)
    w = timer.stop(n_steps=2, n_tokens=1000, sync=val)
    assert w["seconds"] >= 0.01
    assert w["tokens_per_sec"] > 0
    s = timer.summary()
    assert s["windows"] == 1 and s["steps"] == 2


def test_section_timer():
    timer = SectionTimer()
    with timer.section("io"):
        time.sleep(0.005)
    with timer.section("io"):
        pass
    s = timer.summary()
    assert s["io"]["count"] == 2 and s["io"]["total_s"] >= 0.005


def test_profiling_context_noop_and_annotate():
    with profiling_context(None):  # disabled: must be a clean no-op
        with annotate("label"):
            x = jnp.ones(4) + 1
    assert float(x.sum()) == 8.0


def test_profiling_context_writes_trace(tmp_path):
    trace_dir = tmp_path / "trace"
    with profiling_context(str(trace_dir)):
        jnp.ones((64, 64)).sum().block_until_ready()
    assert any(trace_dir.rglob("*")), "no trace output written"


def test_training_report(tmp_path):
    exp = tmp_path / "exp"
    exp.mkdir()
    (exp / "training_summary.json").write_text(json.dumps({
        "experiment_name": "unit",
        "total_training_time_hours": 0.5,
        "total_epochs": 1,
        "total_steps": 100,
        "final_metrics": {"best_eval_loss": 2.5},
        "model_config": {"hidden_size": 64, "num_layers": 2},
        "health_summary": {"status": "healthy", "health_score": 0.9},
    }))
    with open(exp / "metrics.jsonl", "w") as f:
        f.write(json.dumps({"step": 100, "loss": 2.6}) + "\n")
    out = create_training_report(str(exp))
    html = Path(out).read_text()
    assert "unit" in html and "2.5" in html and "hidden_size" in html


def test_training_report_missing_summary(tmp_path):
    assert create_training_report(str(tmp_path)) is None


def test_data_summary_report(tmp_path):
    data = tmp_path / "data.jsonl"
    with open(data, "w") as f:
        for i in range(3):
            f.write(json.dumps({"messages": [
                {"role": "user", "content": f"hello {i}"},
                {"role": "assistant", "content": "hi there"},
            ]}) + "\n")

    from luminaai_tpu.data.tokenizer import ConversationTokenizer

    tok = ConversationTokenizer(model_name="byte")
    out = create_data_summary_report(
        [str(data)], tok, output_path=str(tmp_path / "report.html")
    )
    html = Path(out).read_text()
    assert "data.jsonl" in html and "Issue Breakdown" in html


def test_trainer_profile_window(tmp_path):
    """config.profile_start_step captures a device trace mid-run."""
    from luminaai_tpu.training.trainer import Trainer
    from tests.test_orchestrator import patterned_data, tiny_config

    cfg = tiny_config(
        tmp_path, max_steps=6, profile_start_step=2, profile_num_steps=2,
    )
    t = Trainer(cfg, train_data=patterned_data(cfg),
                checkpoint_dir=str(tmp_path / "ckpt"))
    t.train()
    t.close()
    profile_dir = Path(cfg.output_dir) / "profile"
    assert profile_dir.exists() and any(profile_dir.rglob("*"))


def test_tpu_runtime_diagnostics_cpu_backend():
    """Probe runs a real matmul in a subprocess (CPU here), reports
    status/timings, and inspects the compile-cache state."""
    from luminaai_tpu.utils.environment import tpu_runtime_diagnostics

    rt = tpu_runtime_diagnostics(probe_timeout=120)
    assert rt["backend"]["status"] == "ok", rt
    assert rt["backend"]["platform"] == "cpu"
    assert rt["backend"]["devices"] >= 1
    assert rt["backend"]["cold_matmul_s"] >= 0
    assert "compile_cache" in rt


def test_tpu_runtime_diagnostics_hung_probe(monkeypatch):
    """A wedged backend (dead-tunnel signature) is reported as hung with
    the recovery hint, not by hanging the diagnosing tool."""
    import subprocess as sp

    from luminaai_tpu.utils import environment

    def fake_run(*a, timeout=None, **k):
        raise sp.TimeoutExpired(a[0], timeout)

    monkeypatch.setattr(sp, "run", fake_run)
    rt = environment.tpu_runtime_diagnostics(probe_timeout=5)
    assert rt["backend"]["status"] == "hung"
    assert "tunnel" in rt["backend"]["hint"]


def test_device_peak_flops_table():
    from luminaai_tpu.utils.environment import device_peak_flops

    class D:
        def __init__(self, kind):
            self.device_kind = kind

    assert device_peak_flops(D("TPU v5 lite")) == 197e12
    assert device_peak_flops(D("TPU v5p")) == 459e12
    assert device_peak_flops(D("TPU v6e")) == 918e12
    assert device_peak_flops(D("cpu")) == 197e12  # unknown → default
    assert device_peak_flops(D("cpu"), default=1.0) == 1.0
