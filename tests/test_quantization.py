"""Weight-only quantization tests (ref trainer.py:575 QuantizationManager)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from luminaai_tpu.config import Config
from luminaai_tpu.models.transformer import LuminaTransformer
from luminaai_tpu.training.quantization import (
    QuantizationManager,
    QuantizedTensor,
    dequantize_tree,
    quantize_array,
    quantize_tree,
)


def tiny_config(**kw) -> Config:
    base = dict(
        vocab_size=256,
        hidden_size=64,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        seq_length=64,
        batch_size=2,
        use_flash_attention=False,
        gradient_checkpointing=False,
        precision="fp32",
    )
    base.update(kw)
    return Config(**base)


@pytest.mark.parametrize("bits", [8, 4])
def test_quantize_roundtrip_error(bits):
    w = jnp.asarray(np.random.RandomState(0).randn(128, 64), jnp.float32) * 0.02
    qt = quantize_array(w, bits=bits)
    deq = qt.dequantize(jnp.float32)
    assert deq.shape == w.shape
    # Per-channel symmetric: error bounded by scale/2 per element.
    rel = float(jnp.abs(deq - w).max() / jnp.abs(w).max())
    assert rel < (0.01 if bits == 8 else 0.12), rel


def test_int4_packs_two_per_byte():
    w = jnp.ones((16, 64), jnp.float32)
    qt = quantize_array(w, bits=4)
    assert qt.q.shape == (16, 32)  # packed along last axis
    assert qt.q.dtype == jnp.int8


def test_int4_odd_axis_padding():
    w = jnp.asarray(np.random.RandomState(1).randn(8, 63), jnp.float32)
    qt = quantize_array(w, bits=4)
    deq = qt.dequantize(jnp.float32)
    assert deq.shape == w.shape


def test_quantize_tree_skips_small_and_norms():
    params = {
        "attn": {"wq": jnp.ones((64, 128)), "scale": jnp.ones((64, 128))},
        "norm": {"scale": jnp.ones((128,))},
        "tiny": {"w": jnp.ones((2, 2))},
    }
    qtree, info = quantize_tree(params, bits=8, min_size=1024)
    assert isinstance(qtree["attn"]["wq"], QuantizedTensor)
    assert not isinstance(qtree["attn"]["scale"], QuantizedTensor)  # name skip
    assert not isinstance(qtree["norm"]["scale"], QuantizedTensor)
    assert not isinstance(qtree["tiny"]["w"], QuantizedTensor)  # size skip
    assert info["quantized_leaves"] == 1


def test_manager_validation():
    with pytest.raises(ValueError):
        QuantizationManager(tiny_config(quantization_method="gguf"))
    with pytest.raises(ValueError):
        QuantizationManager(
            tiny_config(quantization_method="int8", quantization_bits=3)
        )
    m = QuantizationManager(tiny_config())
    assert not m.enabled
    m = QuantizationManager(
        tiny_config(quantization_method="int4", quantization_bits=8)
    )
    assert m.bits == 4  # method/bits kept consistent


def test_quantized_model_forward_close_and_generates():
    cfg = tiny_config(quantization_method="int8")
    model = LuminaTransformer(cfg)
    ids = jnp.asarray(
        np.random.RandomState(0).randint(1, 256, (2, 32)), jnp.int32
    )
    params = model.init(jax.random.key(0), ids)["params"]
    logits, _ = model.apply({"params": params}, ids, deterministic=True)

    manager = QuantizationManager(cfg)
    qparams = manager.quantize_for_inference(params)
    assert manager.is_quantized
    assert manager.quantization_info["compression"] > 1.5
    deq = manager.materialize(qparams, jnp.float32)
    qlogits, _ = model.apply({"params": deq}, ids, deterministic=True)
    # int8 weight-only: logits shift a little; argmax should mostly agree.
    agree = float(
        (jnp.argmax(logits, -1) == jnp.argmax(qlogits, -1)).mean()
    )
    assert agree > 0.9, agree

    from luminaai_tpu.data.tokenizer import ConversationTokenizer
    from luminaai_tpu.inference.generate import GenerationEngine

    tok = ConversationTokenizer(model_name="byte")
    # The engine wires quantization itself from config.quantization_method.
    engine = GenerationEngine(model, params, tok, config=cfg)
    assert engine.quantization_info.get("quantized_leaves", 0) > 0
    out_ids, stats = engine.generate(
        [1, 2, 3], max_new_tokens=5, temperature=0.0, seed=0
    )
    assert len(out_ids) >= 1
    assert all(0 <= t < cfg.vocab_size for t in out_ids)
