"""Weight-only quantization tests (ref trainer.py:575 QuantizationManager)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from luminaai_tpu.config import Config
from luminaai_tpu.models.transformer import LuminaTransformer
from luminaai_tpu.training.quantization import (
    QuantizationManager,
    QuantizedTensor,
    quantize_array,
    quantize_tree,
)


def tiny_config(**kw) -> Config:
    base = dict(
        vocab_size=256,
        hidden_size=64,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        seq_length=64,
        batch_size=2,
        use_flash_attention=False,
        gradient_checkpointing=False,
        precision="fp32",
    )
    base.update(kw)
    return Config(**base)


@pytest.mark.parametrize("bits", [8, 4])
def test_quantize_roundtrip_error(bits):
    w = jnp.asarray(np.random.RandomState(0).randn(128, 64), jnp.float32) * 0.02
    qt = quantize_array(w, bits=bits)
    deq = qt.dequantize(jnp.float32)
    assert deq.shape == w.shape
    # Per-channel symmetric: error bounded by scale/2 per element.
    rel = float(jnp.abs(deq - w).max() / jnp.abs(w).max())
    assert rel < (0.01 if bits == 8 else 0.12), rel


def test_int4_packs_two_per_byte():
    w = jnp.ones((16, 64), jnp.float32)
    qt = quantize_array(w, bits=4)
    assert qt.q.shape == (16, 32)  # packed along last axis
    assert qt.q.dtype == jnp.int8


def test_int4_odd_axis_padding():
    w = jnp.asarray(np.random.RandomState(1).randn(8, 63), jnp.float32)
    qt = quantize_array(w, bits=4)
    deq = qt.dequantize(jnp.float32)
    assert deq.shape == w.shape


def test_quantize_tree_skips_small_and_norms():
    params = {
        "attn": {"wq": jnp.ones((64, 128)), "scale": jnp.ones((64, 128))},
        "norm": {"scale": jnp.ones((128,))},
        "tiny": {"w": jnp.ones((2, 2))},
    }
    qtree, info = quantize_tree(params, bits=8, min_size=1024)
    assert isinstance(qtree["attn"]["wq"], QuantizedTensor)
    assert not isinstance(qtree["attn"]["scale"], QuantizedTensor)  # name skip
    assert not isinstance(qtree["norm"]["scale"], QuantizedTensor)
    assert not isinstance(qtree["tiny"]["w"], QuantizedTensor)  # size skip
    assert info["quantized_leaves"] == 1


def test_manager_validation():
    with pytest.raises(ValueError):
        QuantizationManager(tiny_config(quantization_method="gguf"))
    with pytest.raises(ValueError):
        QuantizationManager(
            tiny_config(quantization_method="int8", quantization_bits=3)
        )
    m = QuantizationManager(tiny_config())
    assert not m.enabled
    m = QuantizationManager(
        tiny_config(quantization_method="int4", quantization_bits=8)
    )
    assert m.bits == 4  # method/bits kept consistent


def test_quantized_model_forward_close_and_generates():
    cfg = tiny_config(quantization_method="int8")
    model = LuminaTransformer(cfg)
    ids = jnp.asarray(
        np.random.RandomState(0).randint(1, 256, (2, 32)), jnp.int32
    )
    params = model.init(jax.random.key(0), ids)["params"]
    logits, _ = model.apply({"params": params}, ids, deterministic=True)

    manager = QuantizationManager(cfg)
    qparams = manager.quantize_for_inference(params)
    assert manager.is_quantized
    assert manager.quantization_info["compression"] > 1.5
    deq = manager.materialize(qparams, jnp.float32)
    qlogits, _ = model.apply({"params": deq}, ids, deterministic=True)
    # int8 weight-only: logits shift a little; argmax should mostly agree.
    agree = float(
        (jnp.argmax(logits, -1) == jnp.argmax(qlogits, -1)).mean()
    )
    assert agree > 0.9, agree

    from luminaai_tpu.data.tokenizer import ConversationTokenizer
    from luminaai_tpu.inference.generate import GenerationEngine

    tok = ConversationTokenizer(model_name="byte")
    # The engine wires quantization itself from config.quantization_method.
    engine = GenerationEngine(model, params, tok, config=cfg)
    assert engine.quantization_info.get("quantized_leaves", 0) > 0
    out_ids, stats = engine.generate(
        [1, 2, 3], max_new_tokens=5, temperature=0.0, seed=0
    )
    assert len(out_ids) >= 1
    assert all(0 <= t < cfg.vocab_size for t in out_ids)


# ---------------------------------------------------------------------------
# int8 COMPUTE path (W8A8, ops/quantized.py) — ref trainer.py:658 kernel swap
# ---------------------------------------------------------------------------
def _relerr(a, b):
    return float(jnp.abs(a - b).max() / (jnp.abs(b).max() + 1e-9))


def test_int8_project_matches_dequant_matmul():
    from luminaai_tpu.ops.quantized import int8_project

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(4, 7, 64), jnp.float32)
    # 2D weight [K, N]
    w2 = jnp.asarray(rng.randn(64, 96), jnp.float32) * 0.02
    qt2 = quantize_array(w2, bits=8, axis=(0,))
    y = int8_project(x, qt2, jnp.float32)
    ref = x @ qt2.dequantize(jnp.float32)
    assert y.shape == (4, 7, 96)
    assert _relerr(y, ref) < 0.02, _relerr(y, ref)
    # 3D weight [K, h, d] (attention projection shape)
    w3 = jnp.asarray(rng.randn(64, 4, 16), jnp.float32) * 0.02
    qt3 = quantize_array(w3, bits=8, axis=(0,))
    y3 = int8_project(x, qt3, jnp.float32)
    ref3 = jnp.einsum("bsk,khd->bshd", x, qt3.dequantize(jnp.float32))
    assert y3.shape == (4, 7, 4, 16)
    assert _relerr(y3, ref3) < 0.02


def test_int8_attend_and_out_proj_match():
    from luminaai_tpu.ops.quantized import int8_attend, int8_out_proj

    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(2, 5, 64), jnp.float32)
    emb = jnp.asarray(rng.randn(256, 64), jnp.float32) * 0.02
    qe = quantize_array(emb, bits=8, axis=(-1,))
    y = int8_attend(x, qe, jnp.float32)
    ref = jnp.einsum("bsk,vk->bsv", x, qe.dequantize(jnp.float32))
    assert y.shape == (2, 5, 256)
    assert _relerr(y, ref) < 0.02

    out = jnp.asarray(rng.randn(2, 5, 4, 16), jnp.float32)
    wo = jnp.asarray(rng.randn(4, 16, 64), jnp.float32) * 0.02
    qo = quantize_array(wo, bits=8, axis=(0, 1))
    y2 = int8_out_proj(out, qo, jnp.float32)
    ref2 = jnp.einsum("bshk,hkd->bsd", out, qo.dequantize(jnp.float32))
    assert _relerr(y2, ref2) < 0.02


def test_int8_expert_matches():
    from luminaai_tpu.ops.quantized import int8_expert

    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(8, 2, 16, 64), jnp.float32)
    w = jnp.asarray(rng.randn(8, 64, 32), jnp.float32) * 0.02
    qt = quantize_array(w, bits=8, axis=(1,))
    y = int8_expert(x, qt, jnp.float32)
    ref = jnp.einsum("egch,ehf->egcf", x, qt.dequantize(jnp.float32))
    assert y.shape == (8, 2, 16, 32)
    assert _relerr(y, ref) < 0.02


def test_int8_embed_rows_match():
    from luminaai_tpu.ops.quantized import embed_rows

    rng = np.random.RandomState(3)
    emb = jnp.asarray(rng.randn(128, 64), jnp.float32) * 0.02
    qe = quantize_array(emb, bits=8, axis=(-1,))
    toks = jnp.asarray(rng.randint(0, 128, (2, 9)), jnp.int32)
    rows = embed_rows(qe, toks, jnp.float32)
    ref = jnp.take(qe.dequantize(jnp.float32), toks, axis=0)
    np.testing.assert_allclose(np.asarray(rows), np.asarray(ref), atol=1e-5)


def test_quantize_for_serving_axes_and_roles():
    from luminaai_tpu.training.quantization import quantize_for_serving

    cfg = tiny_config(use_moe=True, num_experts=4, moe_top_k=2)
    model = LuminaTransformer(cfg)
    ids = jnp.ones((1, 32), jnp.int32)
    params = model.init(jax.random.key(0), ids)["params"]
    qp, info = quantize_for_serving(params, min_size=1024)
    assert info["quantized_leaves"] > 0
    flat = jax.tree_util.tree_flatten_with_path(
        qp, is_leaf=lambda x: isinstance(x, QuantizedTensor)
    )[0]
    for path, leaf in flat:
        keys = tuple(
            p.key for p in path if isinstance(p, jax.tree_util.DictKey)
        )
        name = keys[-1]
        if not isinstance(leaf, QuantizedTensor):
            assert name in ("scale", "bias", "router") or leaf.size < 1024, keys
            continue
        # Scale must be reduced over the CONTRACTION axes of each role.
        if name == "embedding":
            assert leaf.scale.shape == (leaf.orig_shape[0], 1)
        elif name in ("wq", "wk", "wv"):
            assert leaf.scale.shape == (1,) + leaf.orig_shape[1:]
        elif name == "wi":  # moe [E, H, 2F]
            assert leaf.scale.shape == (
                leaf.orig_shape[0], 1, leaf.orig_shape[2]
            )
        elif name == "wo":
            if any("moe" in k for k in keys):
                assert leaf.scale.shape == (
                    leaf.orig_shape[0], 1, leaf.orig_shape[2]
                )
            else:  # attention [heads, d, H]
                assert leaf.scale.shape == (1, 1, leaf.orig_shape[2])


def test_quantize_for_serving_idempotent():
    """Re-quantizing a tree that already holds QuantizedTensor leaves
    (chat/serve --quantize int8 pointed at an int8 serving export) must
    pass them through unchanged — not nest QT(q=QT(...)) and explode at
    trace time in int8_project (ADVICE r4 medium)."""
    from luminaai_tpu.training.quantization import quantize_for_serving

    cfg = tiny_config(use_moe=True, num_experts=4, moe_top_k=2,
                      routing_noise_std=0.0)
    model = LuminaTransformer(cfg)
    ids = jnp.asarray(
        np.random.RandomState(0).randint(1, 256, (2, 32)), jnp.int32
    )
    params = model.init(jax.random.key(0), ids)["params"]
    qp1, info1 = quantize_for_serving(params, min_size=1024)
    qp2, info2 = quantize_for_serving(qp1, min_size=1024)
    assert info2["quantized_leaves"] == info1["quantized_leaves"]
    flat1 = jax.tree_util.tree_leaves(
        qp1, is_leaf=lambda x: isinstance(x, QuantizedTensor)
    )
    flat2 = jax.tree_util.tree_leaves(
        qp2, is_leaf=lambda x: isinstance(x, QuantizedTensor)
    )
    for a, b in zip(flat1, flat2):
        if isinstance(a, QuantizedTensor):
            assert b is a  # passed through, not re-quantized
            assert not isinstance(a.q, QuantizedTensor)
    # The re-quantized tree still traces and runs the int8 path.
    qlogits, _ = model.apply({"params": qp2}, ids, deterministic=True)
    assert bool(jnp.isfinite(qlogits).all())
    # quantize_tree (storage path) is idempotent the same way.
    qt1, i1 = quantize_tree(params, bits=8, min_size=1024)
    qt2, i2 = quantize_tree(qt1, bits=8, min_size=1024)
    assert i2["quantized_leaves"] == i1["quantized_leaves"]
    # A DIFFERENT bit-width re-quantizes (round-trips through bf16)
    # instead of passing mismatched leaves through under the new label.
    qt4, i4 = quantize_tree(qt1, bits=4, min_size=1024)
    four_bit = [
        l for l in jax.tree_util.tree_leaves(
            qt4, is_leaf=lambda x: isinstance(x, QuantizedTensor)
        ) if isinstance(l, QuantizedTensor)
    ]
    assert four_bit and all(l.bits == 4 for l in four_bit)
    # Storage-layout trees fed to quantize_for_serving get re-quantized
    # into the serving (contraction-axis) layout, then trace fine.
    qs, _ = quantize_for_serving(qt1, min_size=1024)
    slogits, _ = model.apply({"params": qs}, ids, deterministic=True)
    assert bool(jnp.isfinite(slogits).all())


def test_quantized_axis_always_tuple():
    """QuantizedTensor.axis is canonically a tuple for every entry path
    (int axis, negative axis, tuple, int4), so consumers never branch on
    int-vs-tuple (ADVICE r4)."""
    w = jnp.asarray(np.random.RandomState(0).randn(16, 64), jnp.float32)
    assert quantize_array(w, bits=8, axis=-1).axis == (1,)
    assert quantize_array(w, bits=8, axis=0).axis == (0,)
    assert quantize_array(w, bits=8, axis=(0, 1)).axis == (0, 1)
    assert quantize_array(w, bits=4, axis=-1).axis == (1,)
    # int4 dequantize still un-packs correctly through the tuple axis.
    qt = quantize_array(w, bits=4, axis=0)
    assert qt.dequantize(jnp.float32).shape == w.shape


def test_int8_layout_mismatch_raises_valueerror():
    """Layout contract violations raise ValueError (asserts are stripped
    under python -O and would silently produce wrong logits)."""
    from luminaai_tpu.ops.quantized import int8_project

    w = jnp.asarray(np.random.RandomState(0).randn(64, 32), jnp.float32)
    qt_wrong = quantize_array(w, bits=8, axis=-1)  # kernel wants axis 0
    x = jnp.ones((2, 64), jnp.float32)
    with pytest.raises(ValueError, match="quantized over axes"):
        int8_project(x, qt_wrong, jnp.float32)


@pytest.mark.parametrize("use_moe", [False, True])
def test_int8_compute_model_forward_close(use_moe):
    """End-to-end quality delta: the model applied with QuantizedTensor
    leaves (real int8 dots at every quantization-aware call site) stays
    close to the fp32 forward — and actually runs the int8 path (pinned
    by the serving-layout scale shapes above)."""
    from luminaai_tpu.training.quantization import quantize_for_serving

    cfg = tiny_config(
        use_moe=use_moe, num_experts=4, moe_top_k=2,
        routing_noise_std=0.0,
    )
    model = LuminaTransformer(cfg)
    ids = jnp.asarray(
        np.random.RandomState(0).randint(1, 256, (2, 32)), jnp.int32
    )
    params = model.init(jax.random.key(0), ids)["params"]
    logits, _ = model.apply({"params": params}, ids, deterministic=True)
    qp, _ = quantize_for_serving(params, min_size=1024)
    qlogits, _ = model.apply({"params": qp}, ids, deterministic=True)
    assert qlogits.shape == logits.shape
    agree = float(
        (jnp.argmax(logits, -1) == jnp.argmax(qlogits, -1)).mean()
    )
    assert agree > 0.9, agree


def test_int8_scan_layers_falls_back_to_storage_path():
    """Scanned checkpoints stack layer params on a leading L axis; the
    int8 compute layout's static contraction axes can't survive nn.scan
    slicing, so serving must fall back to the layout-agnostic
    storage-only quantization — and still generate."""
    cfg = tiny_config(quantization_method="int8", scan_layers=True)
    model = LuminaTransformer(cfg)
    ids = jnp.ones((1, 8), jnp.int32)
    from flax.linen import meta

    params = meta.unbox(model.init(jax.random.key(0), ids)["params"])

    from luminaai_tpu.data.tokenizer import ConversationTokenizer
    from luminaai_tpu.inference.generate import GenerationEngine

    tok = ConversationTokenizer(model_name="byte")
    engine = GenerationEngine(model, params, tok, config=cfg)
    assert engine.quantization_info.get("mode") != "int8_compute"
    assert not any(
        isinstance(l, QuantizedTensor)
        for l in jax.tree_util.tree_leaves(
            engine.params,
            is_leaf=lambda x: isinstance(x, QuantizedTensor),
        )
    )
    out_ids, _ = engine.generate(
        [1, 2, 3], max_new_tokens=4, temperature=0.0, seed=0
    )
    assert len(out_ids) >= 1
