"""Trainable byte-level BPE (data/bpe.py + native/bpe.cpp)."""

import json

import numpy as np
import pytest

from luminaai_tpu.data.bpe import (
    BPETokenizer,
    _merge_loop_python,
    pretokenize,
    train_bpe,
)

CORPUS = [
    "the quick brown fox jumps over the lazy dog",
    "the lazy dog sleeps while the quick fox runs",
    "pack my box with five dozen liquor jugs",
    "how vexingly quick daft zebras jump!",
] * 8


def test_roundtrip_exact():
    tok = train_bpe(CORPUS, vocab_size=300)
    for text in CORPUS + ["completely unseen text, with punctuation?!",
                          "unicode: éè 中文 \U0001f600"]:
        assert tok.decode(tok.encode(text)) == text


def test_compresses_vs_bytes():
    tok = train_bpe(CORPUS, vocab_size=400)
    text = CORPUS[0]
    assert len(tok.encode(text)) < 0.7 * len(text.encode())


def test_merges_never_cross_pretokens():
    tok = train_bpe(CORPUS, vocab_size=300)
    # every learned token's bytes must sit inside one pretoken
    for tid in range(256, tok.n_vocab):
        piece = tok._bytes[tid].decode("utf-8", errors="replace")
        assert len(pretokenize(piece)) <= 1 or piece.startswith(" "), piece


def test_native_matches_python():
    from luminaai_tpu.native import bpe_train_native, native_available

    if not native_available():
        pytest.skip("no native toolchain")
    words = {}
    for text in CORPUS:
        for w in pretokenize(text):
            words[w] = words.get(w, 0) + 1
    seqs = [list(w.encode()) for w in words]
    counts = list(words.values())
    flat = np.asarray([t for w in seqs for t in w], dtype=np.int32)
    offsets = np.zeros(len(seqs) + 1, dtype=np.int64)
    np.cumsum([len(w) for w in seqs], out=offsets[1:])
    native = bpe_train_native(
        flat, offsets, np.asarray(counts, dtype=np.int64), 64
    )
    python = _merge_loop_python([list(w) for w in seqs], counts, 64)
    assert [tuple(r) for r in native.tolist()] == python


def test_save_load_and_backend(tmp_path):
    tok = train_bpe(CORPUS, vocab_size=300)
    path = str(tmp_path / "tok.json")
    tok.save(path)
    tok2 = BPETokenizer.load(path)
    assert tok2.encode(CORPUS[0]) == tok.encode(CORPUS[0])

    from luminaai_tpu.data.tokenizer import ConversationTokenizer

    ct = ConversationTokenizer(model_name=f"bpe:{path}")
    assert ct.backend.name == "bpe"
    enc = ct.encode_conversation(
        {"messages": [{"role": "user", "content": "the quick brown fox"}]}
    )
    assert len(enc["input_ids"]) > 0


def test_train_stops_when_exhausted():
    # tiny corpus cannot support 10k merges; trainer must stop, not loop
    tok = train_bpe(["ab ab ab"], vocab_size=10_000)
    assert tok.n_vocab < 300


def test_cli_train_tokenizer(tmp_path, capsys):
    from luminaai_tpu.cli import main as cli_main

    data = tmp_path / "c.jsonl"
    with open(data, "w") as f:
        for text in CORPUS:
            f.write(json.dumps({"messages": [
                {"role": "user", "content": text}]}) + "\n")
    out = str(tmp_path / "tok.json")
    assert cli_main([
        "data", "train-tokenizer", "--in", str(data), "--out", out,
        "--vocab-size", "300",
    ]) == 0
    assert "trained 300-token BPE" in capsys.readouterr().out
    assert BPETokenizer.load(out).n_vocab == 300
